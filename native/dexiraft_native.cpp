// Native data-path primitives for dexiraft_tpu.
//
// The reference keeps its data pipeline in Python workers
// (core/datasets.py + torch DataLoader, 4 forked workers); its only native
// code is the CUDA correlation kernel. Here the decode hot path is native
// instead: C ABI decoders for the Middlebury .flo and binary PPM formats,
// plus thread-pooled batch variants that decode a whole training batch in
// one GIL-free call (Python threads serialize on the interpreter lock;
// these do not).
//
// Build: g++ -O3 -shared -fPIC -pthread (driven by dexiraft_tpu/data/native.py).
// Every function returns 0 on success, negative errno-style codes otherwise.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr float kFloMagic = 202021.25f;  // 'PIEH'

struct File {
  FILE* f;
  explicit File(const char* path) : f(std::fopen(path, "rb")) {}
  ~File() {
    if (f) std::fclose(f);
  }
};

int read_flo_into(const char* path, float* out, int64_t cap, int* w, int* h) {
  File file(path);
  if (!file.f) return -1;
  float magic;
  int32_t dims[2];
  if (std::fread(&magic, 4, 1, file.f) != 1 || magic != kFloMagic) return -2;
  if (std::fread(dims, 4, 2, file.f) != 2) return -2;
  const int64_t n = int64_t(dims[0]) * dims[1] * 2;
  if (n <= 0 || n > (int64_t(1) << 31)) return -2;
  if (w) *w = dims[0];
  if (h) *h = dims[1];
  if (!out) return 0;  // dims-only query
  if (n > cap) return -3;
  if (std::fread(out, 4, size_t(n), file.f) != size_t(n)) return -2;
  return 0;
}

// binary PPM (P6, maxval 255): the FlyingChairs image format
int read_ppm_into(const char* path, uint8_t* out, int64_t cap, int* w, int* h) {
  File file(path);
  if (!file.f) return -1;
  char tag[3] = {0};
  if (std::fscanf(file.f, "%2s", tag) != 1 || std::strcmp(tag, "P6") != 0)
    return -2;
  // header fields with '#' comment lines allowed between tokens
  int vals[3], got = 0;
  while (got < 3) {
    int c = std::fgetc(file.f);
    if (c == EOF) return -2;
    if (c == '#') {
      while (c != '\n' && c != EOF) c = std::fgetc(file.f);
    } else if (c >= '0' && c <= '9') {
      std::ungetc(c, file.f);
      if (std::fscanf(file.f, "%d", &vals[got++]) != 1) return -2;
    }
  }
  if (vals[2] != 255) return -4;
  std::fgetc(file.f);  // single whitespace after maxval
  const int64_t n = int64_t(vals[0]) * vals[1] * 3;
  if (w) *w = vals[0];
  if (h) *h = vals[1];
  if (!out) return 0;
  if (n > cap) return -3;
  if (std::fread(out, 1, size_t(n), file.f) != size_t(n)) return -2;
  return 0;
}

template <typename Fn>
void parallel_for(int n, int nthreads, Fn fn) {
  if (nthreads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> next{0};
  auto worker = [&] {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) fn(i);
  };
  std::vector<std::thread> pool;
  const int k = std::min(nthreads, n);
  pool.reserve(size_t(k));
  for (int t = 0; t < k; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

int drn_read_flo(const char* path, float* out, int64_t cap, int* w, int* h) {
  return read_flo_into(path, out, cap, w, h);
}

int drn_read_ppm(const char* path, uint8_t* out, int64_t cap, int* w, int* h) {
  return read_ppm_into(path, out, cap, w, h);
}

// Batch decode into a contiguous (n, h, w, 2) float buffer; every file must
// match the given dims (FlyingChairs is uniform 384x512). Returns 0 or the
// first failing file's negative code.
int drn_read_flo_batch(const char** paths, int n, float* out, int w, int h,
                       int nthreads) {
  std::atomic<int> status{0};
  const int64_t per = int64_t(w) * h * 2;
  parallel_for(n, nthreads, [&](int i) {
    int fw = 0, fh = 0;
    int rc = read_flo_into(paths[i], out + per * i, per, &fw, &fh);
    if (rc == 0 && (fw != w || fh != h)) rc = -5;
    int expected = 0;
    if (rc != 0) status.compare_exchange_strong(expected, rc);
  });
  return status.load();
}

int drn_read_ppm_batch(const char** paths, int n, uint8_t* out, int w, int h,
                       int nthreads) {
  std::atomic<int> status{0};
  const int64_t per = int64_t(w) * h * 3;
  parallel_for(n, nthreads, [&](int i) {
    int fw = 0, fh = 0;
    int rc = read_ppm_into(paths[i], out + per * i, per, &fw, &fh);
    if (rc == 0 && (fw != w || fh != h)) rc = -5;
    int expected = 0;
    if (rc != 0) status.compare_exchange_strong(expected, rc);
  });
  return status.load();
}

}  // extern "C"
