"""On-chip profile of the v5 eval-forward PRELUDE — the part that gates
the end-to-end headline (VERDICT r1: ~104 ms measured against a ~4 ms
ideal-MXU floor, i.e. ~4% MXU efficiency, cause unprofiled).

Times each prelude component standalone at its production shape
(B=2: both frames batched through one DexiNed call; 440x1024 input),
in the production dtype (bf16 under mixed precision), RTT-corrected like
bench.py. The UpConv stages are timed in BOTH transposed-conv
implementations ("transpose" = lax.conv_transpose on the input-dilated
signal; "subpixel" = the numerically identical phase decomposition,
models/dexined.py:SubpixelConvTranspose) — the A/B that decides
config.dexined_upconv's default.

Usage: python scripts/prelude_profile.py [--cpu] [--fp32]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--fp32", action="store_true",
                    help="profile in fp32 instead of the production bf16")
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    dt = jnp.float32 if args.fp32 else jnp.bfloat16
    print(f"platform={platform} dtype={dt.__name__}", flush=True)

    from dexiraft_tpu.models.dexined import (
        DenseBlock,
        DexiNed,
        DoubleConvBlock,
        SingleConvBlock,
        UpConvBlock,
    )
    from dexiraft_tpu.models.extractor import BasicEncoder
    from dexiraft_tpu.ops.corr import build_corr_pyramid

    trivial = jax.jit(lambda x: jnp.sum(x))
    float(trivial(jnp.ones((8, 8))))

    def rtt(reps=4):
        t0 = time.perf_counter()
        for _ in range(reps):
            float(trivial(jnp.ones((8, 8))))
        return (time.perf_counter() - t0) / reps

    results = {}

    def bench(name, module, shapes, method=None):
        """Init `module` on random inputs of `shapes`, time jitted apply."""
        keys = jax.random.split(jax.random.PRNGKey(0), len(shapes))
        xs = [jax.random.normal(k, s, jnp.float32) for k, s in zip(keys, shapes)]
        try:
            variables = jax.jit(lambda *a: module.init(
                jax.random.PRNGKey(1), *a))(*xs)

            @jax.jit
            def fwd(*a):
                out = module.apply(variables, *a)
                leaves = jax.tree_util.tree_leaves(out)
                return sum(jnp.sum(l.astype(jnp.float32)) for l in leaves)

            gflop = None
            try:
                cost = fwd.lower(*xs).compile().cost_analysis()
                if cost and cost.get("flops"):
                    gflop = cost["flops"] / 1e9
            except Exception:
                pass  # cost model optional; timings are the point

            float(fwd(*xs))  # compile
            floor = rtt()
            t0 = time.perf_counter()
            for _ in range(args.reps):
                float(fwd(*xs))
            raw = (time.perf_counter() - t0) / args.reps
            dtc = raw - floor if raw > floor else raw
            results[name] = dtc
            eff = (f"  {gflop:8.1f} GFLOP -> {gflop / dtc / 1e3:6.2f} TFLOP/s"
                   if gflop else "")
            print(f"{name:>28s}: {dtc * 1e3:8.2f} ms   "
                  f"(raw {raw * 1e3:.2f}, rtt {floor * 1e3:.2f}){eff}",
                  flush=True)
        except Exception as e:
            print(f"{name:>28s}: FAILED {type(e).__name__}: {e}", flush=True)

    B = 2  # both frames in one batched DexiNed call (models/raft.py:190)
    H, W = 440, 1024

    # --- the full embedded-DexiNed forward, both upconv impls ---
    for impl in ("transpose", "subpixel"):
        bench(f"dexined_full[{impl}]",
              DexiNed(dtype=dt, upconv=impl), [(B, H, W, 3)])

    # --- DexiNed internals at production shapes ---
    bench("stem_double(3->32->64,s2)",
          DoubleConvBlock(32, 64, stride=2, dtype=dt), [(B, H, W, 3)])
    bench("block2_double(64->128)",
          DoubleConvBlock(128, use_act=False, dtype=dt),
          [(B, H // 2, W // 2, 64)])
    bench("dense3(2x256@110x256)", DenseBlock(2, 256, dtype=dt),
          [(B, H // 4, W // 4, 128), (B, H // 4, W // 4, 256)])
    bench("dense4(3x512@55x128)", DenseBlock(3, 512, dtype=dt),
          [(B, H // 8, W // 8, 256), (B, H // 8, W // 8, 512)])
    bench("dense5(3x512@28x64)", DenseBlock(3, 512, dtype=dt),
          [(B, 28, 64, 512), (B, 28, 64, 512)])
    bench("dense6(3x256@28x64)", DenseBlock(3, 256, dtype=dt),
          [(B, 28, 64, 512), (B, 28, 64, 256)])
    for impl in ("transpose", "subpixel"):
        bench(f"up1_b1[{impl}]", UpConvBlock(1, dtype=dt, upconv=impl),
              [(B, H // 2, W // 2, 64)])
        bench(f"up1_b2[{impl}]", UpConvBlock(1, dtype=dt, upconv=impl),
              [(B, H // 2, W // 2, 128)])
        bench(f"up2_b3[{impl}]", UpConvBlock(2, dtype=dt, upconv=impl),
              [(B, H // 4, W // 4, 256)])
        bench(f"up3_b4[{impl}]", UpConvBlock(3, dtype=dt, upconv=impl),
              [(B, H // 8, W // 8, 512)])
        bench(f"up4_b5[{impl}]", UpConvBlock(4, dtype=dt, upconv=impl),
              [(B, 28, 64, 512)])
        bench(f"up4_b6[{impl}]", UpConvBlock(4, dtype=dt, upconv=impl),
              [(B, 28, 64, 256)])
    bench("fusion_cat_1x1(6ch)", SingleConvBlock(1, use_bn=False, dtype=dt),
          [(B, H, W, 6)])

    # --- the RAFT side of the prelude, for scale ---
    bench("fnet(basic,instance)@full",
          BasicEncoder(output_dim=256, norm_fn="instance", dtype=dt),
          [(B, H, W, 3)])
    bench("cnet(basic,batch)@full",
          BasicEncoder(output_dim=256, norm_fn="batch", dtype=dt),
          [(B, H, W, 3)])

    @jax.jit
    def vol(f1, f2):
        pyr = build_corr_pyramid(f1, f2, num_levels=4, radius=4)
        return sum(jnp.sum(v) for v in pyr.levels)

    f1 = jax.random.normal(jax.random.PRNGKey(2), (1, H // 8, W // 8, 256))
    float(vol(f1, f1))
    floor = rtt()
    t0 = time.perf_counter()
    for _ in range(args.reps):
        float(vol(f1, f1))
    raw = (time.perf_counter() - t0) / args.reps
    print(f"{'corr_pyramid_build':>28s}: "
          f"{(raw - floor if raw > floor else raw) * 1e3:8.2f} ms", flush=True)

    # --- the refinement-loop components at loop shapes (B=2: the dual
    # streams share one batch; 55x128 = 440x1024 at 1/8) ---
    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.update import BasicUpdateBlock
    from dexiraft_tpu.ops.grid import coords_grid

    h8, w8 = H // 8, W // 8
    bench("update_block(GRU+heads)", BasicUpdateBlock(hidden_dim=128, dtype=dt),
          [(2, h8, w8, 128), (2, h8, w8, 128), (2, h8, w8, 324),
           (2, h8, w8, 2)])

    for impl in ("allpairs", "local"):
        cfg = raft_v5(mixed_precision=not args.fp32, corr_impl=impl)
        f1 = jax.random.normal(jax.random.PRNGKey(3), (2, h8, w8, 256))
        f2 = jax.random.normal(jax.random.PRNGKey(4), (2, h8, w8, 256))

        @jax.jit
        def lookup_once(f1, f2):
            if impl == "allpairs":
                pyr = build_corr_pyramid(f1, f2, 4, 4)
            else:
                from dexiraft_tpu.ops.local_corr import build_local_corr
                pyr = build_local_corr(f1, f2, 4, 4, row_chunk=8)
            coords = coords_grid(2, h8, w8) + 1.3
            return jnp.sum(pyr(coords))

        try:
            float(lookup_once(f1, f2))
            floor = rtt()
            t0 = time.perf_counter()
            for _ in range(args.reps):
                float(lookup_once(f1, f2))
            raw = (time.perf_counter() - t0) / args.reps
            dtc = raw - floor if raw > floor else raw
            print(f"{'build+lookup[' + impl + ']':>28s}: {dtc * 1e3:8.2f} ms",
                  flush=True)
        except Exception as e:
            print(f"{'build+lookup[' + impl + ']':>28s}: FAILED {e}",
                  flush=True)

    ups = [k for k in results if k.startswith("up") and "[" in k]
    t_total = sum(v for k, v in results.items()
                  if k.startswith("up") and "transpose" in k)
    s_total = sum(v for k, v in results.items()
                  if k.startswith("up") and "subpixel" in k)
    print(f"\nupconv stages total: transpose {t_total * 1e3:.2f} ms, "
          f"subpixel {s_total * 1e3:.2f} ms ({len(ups)} timed)", flush=True)
    if "dexined_full[transpose]" in results and "dexined_full[subpixel]" in results:
        print(f"dexined full: transpose "
              f"{results['dexined_full[transpose]'] * 1e3:.2f} ms, subpixel "
              f"{results['dexined_full[subpixel]'] * 1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
