"""Cross-stack validation of TRAINED weights (VERDICT r4 next-7).

Promotes the random-weights eval-stack parity (tests/test_eval_stack_parity)
to trained weights: restore a train_demo checkpoint, export the flax
params to a torch state_dict (interop/torch_convert.export_raft_state_dict),
load them into the ACTUAL reference torch model, and run both stacks over
the same OOD held-out set train_demo validates on. Reports per-stack EPE
and the cross-stack flow agreement — if the reference's own forward
reproduces our held-out EPE with our trained weights, the accuracy claim
no longer rests on our stack grading its own homework.

Reference anchors: raft_1.py (v1/small forward), raft.py (v5),
evaluate.py:22-54 (EPE accumulation semantics re-derived here).

Usage:
  python scripts/trained_crossstack.py --ckpt_dir logs/v1_cpu_r5_ckpt \
      --variant small [--n_batches 8] [--iters 12]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))
sys.path.insert(0, osp.dirname(osp.abspath(__file__)))

import jax
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--variant", default="small",
                    help="'small' (v1-small demo) or 'v5'")
    # defaults match train_demo's held-out evaluation (iters=24 at
    # scripts/train_demo.py full_heldout_epe; 32 batches = the r5 CPU
    # run's --heldout_batches) so ours_epe is directly comparable to
    # the training transcript's heldout_full_epe
    ap.add_argument("--iters", type=int, default=24)
    ap.add_argument("--n_batches", type=int, default=32)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--size", type=int, nargs=2, default=(192, 256))
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import torch

    from dexiraft_tpu import config as cfg_mod
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.interop.torch_convert import export_raft_state_dict
    from dexiraft_tpu.train.checkpoint import restore_checkpoint
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_eval_step

    from train_demo import make_heldout  # same OOD generator/seed

    h, w = args.size
    small = args.variant == "small"
    if small:
        cfg = cfg_mod.raft_v1(small=True)
    else:
        cfg = getattr(cfg_mod, f"raft_{args.variant}")()
    tc = TrainConfig(name="xstack", num_steps=1, batch_size=args.batch,
                     image_size=(h, w), iters=args.iters)

    # ---- restore the trained flax state ----
    template = create_state(jax.random.PRNGKey(0), cfg, tc)
    state = restore_checkpoint(args.ckpt_dir, template)
    step = int(state.step)
    print(f"# restored step {step} from {args.ckpt_dir}", file=sys.stderr)
    variables = {"params": state.params,
                 **({"batch_stats": state.batch_stats}
                    if state.batch_stats else {})}

    # ---- reference torch model with OUR trained weights ----
    from dexiraft_tpu.interop.reference import (_import_from, REF_CORE,
                                                build_reference_v5)

    if small:
        TorchRAFT = _import_from(REF_CORE, "raft_1").RAFT
        tm = TorchRAFT(argparse.Namespace(
            small=True, dropout=0.0, mixed_precision=False,
            alternate_corr=False))
        tm.eval()
    else:
        tm = build_reference_v5()
    sd = export_raft_state_dict(variables, tm.state_dict(), small=small)
    tm.load_state_dict({k: torch.from_numpy(np.asarray(v))
                        for k, v in sd.items()})

    # ---- the same OOD held-out set train_demo reports on ----
    heldout = make_heldout(args.n_batches, args.batch, h, w)

    ours_fn = make_eval_step(cfg, iters=args.iters)

    ours_epe, ref_epe, xmax = [], [], 0.0
    for bi, b in enumerate(heldout):
        _, up = ours_fn(variables, b["image1"], b["image2"])
        ours = jax.device_get(up)

        t1 = torch.from_numpy(
            np.asarray(b["image1"]).transpose(0, 3, 1, 2)).contiguous()
        t2 = torch.from_numpy(
            np.asarray(b["image2"]).transpose(0, 3, 1, 2)).contiguous()
        with torch.no_grad():
            _, tup = tm(t1, t2, iters=args.iters, test_mode=True)
        ref = tup.numpy().transpose(0, 2, 3, 1)

        gt = np.asarray(b["flow"])
        ours_epe.append(np.sqrt(((ours - gt) ** 2).sum(-1)).mean())
        ref_epe.append(np.sqrt(((ref - gt) ** 2).sum(-1)).mean())
        bdelta = float(np.abs(ours - ref).max())
        xmax = max(xmax, bdelta)
        print(f"# batch {bi}: ours {ours_epe[-1]:.3f}  "
              f"torch-ref {ref_epe[-1]:.3f}  max|Δflow| {bdelta:.3e}",
              file=sys.stderr)

    rec = {
        "metric": f"trained_crossstack_epe@{h}x{w}x{args.iters}it",
        "variant": args.variant,
        "ckpt_step": step,
        "samples": args.n_batches * args.batch,
        "ours_epe": round(float(np.mean(ours_epe)), 4),
        "torch_ref_epe": round(float(np.mean(ref_epe)), 4),
        "cross_stack_max_flow_delta": xmax,
    }
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
