"""End-to-end training demo on real hardware with exact ground truth.

The reference's de-facto regression record is its training transcripts
(logs/*.out, SURVEY.md §4); datasets are not mounted here, so this demo
trains on procedurally generated pairs with EXACT ground-truth flow:
image2 is a smooth random texture, the flow field is a smooth random
warp, and image1[x] = image2[x + flow[x]] by bilinear sampling — the
flow supervision is correct by construction. EPE dropping from the
~flow-magnitude level toward zero demonstrates the whole training path
(model, sequence loss, OneCycle/AdamW, bf16 policy) learning on-chip.

Writes a reference-style transcript to logs/train_demo_<platform>.log.

Usage: python scripts/train_demo.py [--steps 300] [--batch 4]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from scipy import ndimage


def smooth_noise(rng, shape, grid=8, lo=0.0, hi=1.0):
    """Low-frequency noise: coarse grid upsampled with cubic zoom."""
    h, w = shape
    coarse = rng.uniform(lo, hi, (grid, grid))
    return ndimage.zoom(coarse, (h / grid, w / grid), order=3)[:h, :w]


# training-distribution generator parameters; the held-out set below
# deliberately uses NONE of these values
TRAIN_TEX_GRID, TRAIN_FLOW_GRID, TRAIN_MAX_DISP = 24, 6, 6.0


def make_pair(rng, h, w, max_disp=TRAIN_MAX_DISP, tex_grid=TRAIN_TEX_GRID,
              flow_grid=TRAIN_FLOW_GRID):
    """(image1, image2, flow) with image1[x] = image2[x + flow[x]]."""
    img2 = np.stack([smooth_noise(rng, (h, w), grid=tex_grid, lo=0, hi=255)
                     for _ in range(3)], axis=-1)
    flow = np.stack([smooth_noise(rng, (h, w), grid=flow_grid,
                                  lo=-max_disp, hi=max_disp)
                     for _ in range(2)], axis=-1)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    sample_y = yy + flow[..., 1]
    sample_x = xx + flow[..., 0]
    img1 = np.stack([
        ndimage.map_coordinates(img2[..., c], [sample_y, sample_x],
                                order=1, mode="nearest")
        for c in range(3)], axis=-1)
    return img1, img2, flow


def make_batch(rng, batch, h, w, **pair_kw):
    i1, i2, fl = zip(*[make_pair(rng, h, w, **pair_kw)
                       for _ in range(batch)])
    return {
        "image1": jnp.asarray(np.stack(i1), jnp.float32),
        "image2": jnp.asarray(np.stack(i2), jnp.float32),
        "flow": jnp.asarray(np.stack(fl), jnp.float32),
        "valid": jnp.ones((batch, h, w), jnp.float32),
    }


# held-out generator parameters: textures both coarser and finer than
# training's grid=24, motion fields smoother and rougher than grid=6,
# magnitudes above and below max_disp=6 — every (tex, flow, disp) tuple
# is outside the training distribution, so a falling held-out EPE means
# the model learned warped-texture MATCHING, not the training pool
HELDOUT_SPECS = ((12, 4, 8.0), (48, 9, 8.0), (12, 9, 4.0), (48, 4, 4.0))


def make_heldout(n_batches, batch, h, w, seed=990801):
    """OOD held-out set: fresh RNG stream AND generator parameters
    disjoint from training's (VERDICT r3 item 4: >=128 samples, unseen
    textures, unseen motion-field parameters)."""
    rng = np.random.default_rng(seed)
    return [make_batch(rng, batch, h, w,
                       tex_grid=tg, flow_grid=fg, max_disp=md)
            for i in range(n_batches)
            for tg, fg, md in [HELDOUT_SPECS[i % len(HELDOUT_SPECS)]]]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--size", type=int, nargs=2, default=(192, 256))
    ap.add_argument("--pool", type=int, default=16,
                    help="distinct pre-uploaded batches cycled during "
                         "training (keeps the tunnel out of the step loop)")
    ap.add_argument("--heldout_batches", type=int, default=64,
                    help="held-out batches (x --batch = samples; min 1 — "
                         "batch 0 doubles as the cheap probe); the set "
                         "is OOD by construction (unseen texture/motion "
                         "generator parameters, fresh RNG stream)")
    ap.add_argument("--heldout_every", type=int, default=150,
                    help="evaluate the FULL held-out set every N steps "
                         "(<=0 disables the in-loop full evals; the "
                         "25-step cadence uses a 1-batch probe)")
    ap.add_argument("--log", default=None)
    ap.add_argument("--variant", default="small",
                    help="'small' (RAFT-small v1, the quick demo) or any "
                         "config factory name: v1..v5. v5 is the 42.6M "
                         "flagship — trained with remat (required at "
                         "realistic geometry, docs/perf.md) and a lower "
                         "lr, proving the dual-stream model converges "
                         "end-to-end on one chip")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon site hook "
                         "re-pins JAX_PLATFORMS, so the env var alone "
                         "does not stick; config.update does)")
    ap.add_argument("--ckpt_dir", default=None,
                    help="checkpoint every --ckpt_every steps and resume "
                         "from the latest step on restart — a multi-hour "
                         "CPU transcript must survive session kills "
                         "(train/checkpoint.py round-trips opt state + "
                         "step, so OneCycle continues, not restarts)")
    ap.add_argument("--ckpt_every", type=int, default=25)
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu import config as cfg_mod
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    platform = jax.devices()[0].platform
    h, w = args.size
    log_path = args.log or osp.join(
        osp.dirname(osp.dirname(osp.abspath(__file__))),
        "logs", f"train_demo_{args.variant}_{platform}.log"
        if args.variant != "small" else f"train_demo_{platform}.log")
    import os

    start_step = 0
    if args.ckpt_dir:
        from dexiraft_tpu.train.checkpoint import latest_step

        if osp.isdir(args.ckpt_dir):
            start_step = latest_step(args.ckpt_dir) or 0

    os.makedirs(osp.dirname(log_path), exist_ok=True)
    # resuming appends: the transcript stays one continuous record
    log_f = open(log_path, "a" if start_step else "w")

    def log(msg):
        print(msg)
        print(msg, file=log_f, flush=True)

    mixed = platform == "tpu"
    if args.variant == "small":
        cfg = cfg_mod.raft_v1(small=True, mixed_precision=mixed)
        lr = 4e-4
        name = "RAFT-small v1"
    else:
        factory = getattr(cfg_mod, f"raft_{args.variant}")
        cfg = factory(mixed_precision=mixed, remat=True)
        lr = 2e-4  # the reference's chairs-stage lr (train_standard.sh)
        name = f"RAFT {args.variant} (remat)"
    tc = TrainConfig(name="demo", num_steps=args.steps,
                     batch_size=args.batch, image_size=(h, w),
                     iters=12, lr=lr, wdecay=1e-5)
    log(f"# train_demo: {name}, platform={platform}, "
        f"batch={args.batch}, {h}x{w}, iters=12, steps={args.steps}, "
        f"synthetic warped-texture pairs (exact GT)")

    t0 = time.perf_counter()
    state = create_state(jax.random.PRNGKey(1234), cfg, tc)
    step_fn = make_train_step(cfg, tc)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    log(f"# {n_params} parameters; init {time.perf_counter() - t0:.1f}s")

    rng = np.random.default_rng(1234)
    pool = [make_batch(rng, args.batch, h, w) for _ in range(args.pool)]
    heldout = make_heldout(max(args.heldout_batches, 1), args.batch, h, w)
    val_batch = heldout[0]  # the cheap 25-step probe
    ho_mag = float(np.mean([np.linalg.norm(np.asarray(b["flow"]), axis=-1)
                            .mean() for b in heldout]))
    log(f"# held-out set: {len(heldout) * args.batch} samples, "
        f"OOD generator params {HELDOUT_SPECS} vs train "
        f"{(TRAIN_TEX_GRID, TRAIN_FLOW_GRID, TRAIN_MAX_DISP)}, "
        f"mean |flow| {ho_mag:.3f}")

    # held-out probe: the in-loop loss cycles over the recycled pool
    # batches, so consecutive log lines are not comparable — the fixed
    # held-out EPE is the monotone signal a transcript reader needs
    from dexiraft_tpu.models.raft import RAFT

    model = RAFT(cfg)

    @jax.jit
    def val_epe(params, batch_stats, batch):
        _, flow_up = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image1"], batch["image2"], iters=24,
            train=False, test_mode=True)
        return jnp.mean(jnp.linalg.norm(flow_up - batch["flow"], axis=-1))

    def full_heldout_epe(state):
        return float(np.mean([float(jax.device_get(
            val_epe(state.params, state.batch_stats, b)))
                              for b in heldout]))

    if start_step:
        from dexiraft_tpu.train.checkpoint import restore_checkpoint

        state = restore_checkpoint(args.ckpt_dir, state, step=start_step)
        log(f"# resumed from {args.ckpt_dir} at step {start_step} "
            f"(opt state + OneCycle step restored)")
        loop_from = start_step + 1
    else:
        t0 = time.perf_counter()
        probe0 = float(jax.device_get(
            val_epe(state.params, state.batch_stats, val_batch)))
        log(f"# probe compile+eval {time.perf_counter() - t0:.1f}s "
            f"(untrained probe epe {probe0:.3f})")
        t0 = time.perf_counter()
        full0 = full_heldout_epe(state)
        log(f"# untrained heldout_full_epe {full0:.3f} "
            f"({len(heldout) * args.batch} samples, "
            f"{time.perf_counter() - t0:.0f}s)")
        t0 = time.perf_counter()
        state, metrics = step_fn(state, pool[0])
        float(jax.device_get(metrics["loss"]))
        log(f"# compile+first step {time.perf_counter() - t0:.1f}s")
        loop_from = 1

    # the probe evals run inside the loop but are excluded from the
    # steps/s denominator — the printed rate stays a TRAINING
    # throughput, comparable with earlier transcripts of this script
    t0 = time.perf_counter()
    eval_s = 0.0
    for i in range(loop_from, args.steps):
        state, metrics = step_fn(state, pool[i % args.pool])
        if i % 25 == 0 or i == args.steps - 1:
            # drain the async train stream FIRST (the loss fetch is the
            # sync point) so pending train steps accrue to train time,
            # not to the eval window measured next
            loss_v = float(jax.device_get(metrics["loss"]))
            epe_v = float(jax.device_get(metrics["epe"]))
            te = time.perf_counter()
            train_elapsed = te - t0 - eval_s  # before this eval's cost
            probe_epe = float(jax.device_get(
                val_epe(state.params, state.batch_stats, val_batch)))
            eval_s += time.perf_counter() - te
            # rate over steps run in THIS process — on resume, dividing
            # the global index by post-restart elapsed would inflate it
            log(f"[{i:5d}] loss {loss_v:7.3f}  "
                f"epe {epe_v:6.3f}  "
                f"heldout_epe {probe_epe:6.3f}  "
                f"{(i - loop_from + 1) / train_elapsed:5.2f} steps/s")
        if args.heldout_every > 0 and i % args.heldout_every == 0:
            te = time.perf_counter()
            full = full_heldout_epe(state)
            eval_s += time.perf_counter() - te
            log(f"[{i:5d}] heldout_full_epe {full:6.3f}  "
                f"({len(heldout) * args.batch} OOD samples)")
        if args.ckpt_dir and (i % args.ckpt_every == 0
                              or i == args.steps - 1):
            from dexiraft_tpu.train.checkpoint import save_checkpoint

            save_checkpoint(args.ckpt_dir, state, step=i)

    final_full = full_heldout_epe(state)
    log(f"# held-out synthetic val: EPE {final_full:.3f} over "
        f"{len(heldout) * args.batch} OOD samples "
        f"(unseen textures AND unseen motion-field parameters, "
        f"mean |flow| {ho_mag:.3f})")
    log_f.close()


if __name__ == "__main__":
    main()
