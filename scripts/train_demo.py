"""End-to-end training demo on real hardware with exact ground truth.

The reference's de-facto regression record is its training transcripts
(logs/*.out, SURVEY.md §4); datasets are not mounted here, so this demo
trains on procedurally generated pairs with EXACT ground-truth flow:
image2 is a smooth random texture, the flow field is a smooth random
warp, and image1[x] = image2[x + flow[x]] by bilinear sampling — the
flow supervision is correct by construction. EPE dropping from the
~flow-magnitude level toward zero demonstrates the whole training path
(model, sequence loss, OneCycle/AdamW, bf16 policy) learning on-chip.

Writes a reference-style transcript to logs/train_demo_<platform>.log.

Usage: python scripts/train_demo.py [--steps 300] [--batch 4]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from scipy import ndimage


def smooth_noise(rng, shape, grid=8, lo=0.0, hi=1.0):
    """Low-frequency noise: coarse grid upsampled with cubic zoom."""
    h, w = shape
    coarse = rng.uniform(lo, hi, (grid, grid))
    return ndimage.zoom(coarse, (h / grid, w / grid), order=3)[:h, :w]


def make_pair(rng, h, w, max_disp=6.0):
    """(image1, image2, flow) with image1[x] = image2[x + flow[x]]."""
    img2 = np.stack([smooth_noise(rng, (h, w), grid=24, lo=0, hi=255)
                     for _ in range(3)], axis=-1)
    flow = np.stack([smooth_noise(rng, (h, w), grid=6,
                                  lo=-max_disp, hi=max_disp)
                     for _ in range(2)], axis=-1)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    sample_y = yy + flow[..., 1]
    sample_x = xx + flow[..., 0]
    img1 = np.stack([
        ndimage.map_coordinates(img2[..., c], [sample_y, sample_x],
                                order=1, mode="nearest")
        for c in range(3)], axis=-1)
    return img1, img2, flow


def make_batch(rng, batch, h, w):
    i1, i2, fl = zip(*[make_pair(rng, h, w) for _ in range(batch)])
    return {
        "image1": jnp.asarray(np.stack(i1), jnp.float32),
        "image2": jnp.asarray(np.stack(i2), jnp.float32),
        "flow": jnp.asarray(np.stack(fl), jnp.float32),
        "valid": jnp.ones((batch, h, w), jnp.float32),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--size", type=int, nargs=2, default=(192, 256))
    ap.add_argument("--pool", type=int, default=16,
                    help="distinct pre-uploaded batches cycled during "
                         "training (keeps the tunnel out of the step loop)")
    ap.add_argument("--log", default=None)
    ap.add_argument("--variant", default="small",
                    help="'small' (RAFT-small v1, the quick demo) or any "
                         "config factory name: v1..v5. v5 is the 42.6M "
                         "flagship — trained with remat (required at "
                         "realistic geometry, docs/perf.md) and a lower "
                         "lr, proving the dual-stream model converges "
                         "end-to-end on one chip")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon site hook "
                         "re-pins JAX_PLATFORMS, so the env var alone "
                         "does not stick; config.update does)")
    ap.add_argument("--ckpt_dir", default=None,
                    help="checkpoint every --ckpt_every steps and resume "
                         "from the latest step on restart — a multi-hour "
                         "CPU transcript must survive session kills "
                         "(train/checkpoint.py round-trips opt state + "
                         "step, so OneCycle continues, not restarts)")
    ap.add_argument("--ckpt_every", type=int, default=25)
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu import config as cfg_mod
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    platform = jax.devices()[0].platform
    h, w = args.size
    log_path = args.log or osp.join(
        osp.dirname(osp.dirname(osp.abspath(__file__))),
        "logs", f"train_demo_{args.variant}_{platform}.log"
        if args.variant != "small" else f"train_demo_{platform}.log")
    import os

    start_step = 0
    if args.ckpt_dir:
        from dexiraft_tpu.train.checkpoint import latest_step

        if osp.isdir(args.ckpt_dir):
            start_step = latest_step(args.ckpt_dir) or 0

    os.makedirs(osp.dirname(log_path), exist_ok=True)
    # resuming appends: the transcript stays one continuous record
    log_f = open(log_path, "a" if start_step else "w")

    def log(msg):
        print(msg)
        print(msg, file=log_f, flush=True)

    mixed = platform == "tpu"
    if args.variant == "small":
        cfg = cfg_mod.raft_v1(small=True, mixed_precision=mixed)
        lr = 4e-4
        name = "RAFT-small v1"
    else:
        factory = getattr(cfg_mod, f"raft_{args.variant}")
        cfg = factory(mixed_precision=mixed, remat=True)
        lr = 2e-4  # the reference's chairs-stage lr (train_standard.sh)
        name = f"RAFT {args.variant} (remat)"
    tc = TrainConfig(name="demo", num_steps=args.steps,
                     batch_size=args.batch, image_size=(h, w),
                     iters=12, lr=lr, wdecay=1e-5)
    log(f"# train_demo: {name}, platform={platform}, "
        f"batch={args.batch}, {h}x{w}, iters=12, steps={args.steps}, "
        f"synthetic warped-texture pairs (exact GT)")

    t0 = time.perf_counter()
    state = create_state(jax.random.PRNGKey(1234), cfg, tc)
    step_fn = make_train_step(cfg, tc)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    log(f"# {n_params} parameters; init {time.perf_counter() - t0:.1f}s")

    rng = np.random.default_rng(1234)
    pool = [make_batch(rng, args.batch, h, w) for _ in range(args.pool)]
    val_batch = make_batch(np.random.default_rng(99), args.batch, h, w)

    # held-out probe: the in-loop loss cycles over the recycled pool
    # batches, so consecutive log lines are not comparable — the fixed
    # held-out EPE is the monotone signal a transcript reader needs
    from dexiraft_tpu.models.raft import RAFT

    model = RAFT(cfg)

    @jax.jit
    def val_epe(params, batch_stats, batch):
        _, flow_up = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image1"], batch["image2"], iters=24,
            train=False, test_mode=True)
        return jnp.mean(jnp.linalg.norm(flow_up - batch["flow"], axis=-1))

    if start_step:
        from dexiraft_tpu.train.checkpoint import restore_checkpoint

        state = restore_checkpoint(args.ckpt_dir, state, step=start_step)
        log(f"# resumed from {args.ckpt_dir} at step {start_step} "
            f"(opt state + OneCycle step restored)")
        loop_from = start_step + 1
    else:
        t0 = time.perf_counter()
        heldout = float(val_epe(state.params, state.batch_stats, val_batch))
        log(f"# probe compile+eval {time.perf_counter() - t0:.1f}s "
            f"(untrained heldout_epe {heldout:.3f})")
        t0 = time.perf_counter()
        state, metrics = step_fn(state, pool[0])
        float(metrics["loss"])
        log(f"# compile+first step {time.perf_counter() - t0:.1f}s")
        loop_from = 1

    # the probe evals run inside the loop but are excluded from the
    # steps/s denominator — the printed rate stays a TRAINING
    # throughput, comparable with earlier transcripts of this script
    t0 = time.perf_counter()
    eval_s = 0.0
    heldout = None
    for i in range(loop_from, args.steps):
        state, metrics = step_fn(state, pool[i % args.pool])
        if i % 25 == 0 or i == args.steps - 1:
            # drain the async train stream FIRST (the loss fetch is the
            # sync point) so pending train steps accrue to train time,
            # not to the eval window measured next
            loss_v = float(metrics["loss"])
            epe_v = float(metrics["epe"])
            te = time.perf_counter()
            train_elapsed = te - t0 - eval_s  # before this eval's cost
            heldout = float(val_epe(state.params, state.batch_stats,
                                    val_batch))
            eval_s += time.perf_counter() - te
            # rate over steps run in THIS process — on resume, dividing
            # the global index by post-restart elapsed would inflate it
            log(f"[{i:5d}] loss {loss_v:7.3f}  "
                f"epe {epe_v:6.3f}  "
                f"heldout_epe {heldout:6.3f}  "
                f"{(i - loop_from + 1) / train_elapsed:5.2f} steps/s")
        if args.ckpt_dir and (i % args.ckpt_every == 0
                              or i == args.steps - 1):
            from dexiraft_tpu.train.checkpoint import save_checkpoint

            save_checkpoint(args.ckpt_dir, state, step=i)

    if heldout is None:  # resumed at/after the last step: loop was empty
        heldout = float(val_epe(state.params, state.batch_stats, val_batch))
    mag = float(jnp.mean(jnp.linalg.norm(val_batch["flow"], axis=-1)))
    log(f"# held-out synthetic val: EPE {heldout:.3f} (mean |flow| {mag:.3f})")
    log_f.close()


if __name__ == "__main__":
    main()
