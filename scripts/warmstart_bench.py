"""Sequential (video-mode) inference throughput with warm start.

The submission path (create_sintel_submission, evaluate.py:22-54) chains
frames: each forward starts from the previous frame's low-res flow,
forward-splatted to the new frame. The reference pays a device->host->
device scipy round-trip per frame for that splat (core/utils/utils.py:
26-54); here the whole chain — forward, on-device forward_interpolate,
next forward — stays on device. This measures per-frame latency in that
regime for the flagship v5 at Sintel eval size.

Usage: python scripts/warmstart_bench.py [--frames 8] [--iters 32]
       [--corr_impl local] [--cpu]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

HEIGHT, WIDTH = 440, 1024


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--frames", type=int, default=8)
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--corr_impl", default="local")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.eval.interpolate import forward_interpolate
    from dexiraft_tpu.models.raft import RAFT

    platform = jax.devices()[0].platform
    print(f"platform={platform} frames={args.frames} iters={args.iters} "
          f"corr_impl={args.corr_impl}", file=sys.stderr)

    cfg = raft_v5(mixed_precision=(platform == "tpu"),
                  corr_impl=args.corr_impl)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    init = jax.jit(lambda r, a, b: model.init(r, a, b, iters=1, train=False))
    variables = jax.block_until_ready(init(rng, small, small))
    print("init done", file=sys.stderr)

    @jax.jit
    def frame_step(variables, a, b, flow_prev):
        """One video frame: warm-started forward + next frame's seed.
        Returns (seed for next frame, checksum of the full-res flow).
        variables is an argument (not a closure) so the weights aren't
        baked into the lowered computation — the make_eval_step pattern."""
        low, up = model.apply(variables, a, b, iters=args.iters,
                              train=False, test_mode=True,
                              flow_init=flow_prev)
        # forward_interpolate is unbatched (H, W, 2), like the
        # submission loop's flow_low[0] usage (eval/submission.py)
        return forward_interpolate(low[0])[None], jnp.sum(up)

    keys = jax.random.split(jax.random.PRNGKey(1), args.frames + 1)
    frames = [jax.random.uniform(k, (1, HEIGHT, WIDTH, 3), jnp.float32,
                                 0, 255) for k in keys]
    seed = jnp.zeros((1, HEIGHT // 8, WIDTH // 8, 2), jnp.float32)

    # compile + warmup
    t0 = time.perf_counter()
    seed_w, s = frame_step(variables, frames[0], frames[1], seed)
    float(jax.device_get(s))
    print(f"compile+first frame {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    t0 = time.perf_counter()
    seed = seed_w
    acc = 0.0
    for i in range(args.frames):
        seed, s = frame_step(variables, frames[i], frames[i + 1], seed)
    acc = float(jax.device_get(s))
    # ONE sync at the end: frames chain through `seed`,
    # so fetching the last checksum bounds the whole pipeline (per-frame
    # fetches would add one tunnel RTT each)
    dt = (time.perf_counter() - t0) / args.frames
    print(f"warm-start sequential: {dt * 1e3:.1f} ms/frame "
          f"({1.0 / dt:.2f} FPS at {HEIGHT}x{WIDTH}, {args.iters} iters, "
          f"checksum finite={acc == acc})")


if __name__ == "__main__":
    main()
