"""Measured baseline anchor: reference torch v5 vs our flax v5, same CPU.

The 320 iters/s denominator in bench.py is an estimate (upstream RAFT's
~10 FPS at 1088x436 on a 1080Ti x 32 iters) because the reference records
no throughput numbers anywhere (BASELINE.md). No CUDA GPU exists in this
environment, so the reference's CUDA path cannot be timed — but its torch
code CAN be timed on this host's CPU against our stack at identical
geometry, in the same process, under the same load. That ratio is a
measured, like-for-like anchor for "how does the framework compare to the
reference on the same silicon" — it complements (not replaces) the
on-chip vs-estimate headline.

Workload: v5 test-mode forward, iters as given (default 6 to match
bench.py's CPU fallback), geometry 224x512 (same). Reference classes are
imported from /root/reference verbatim; the embedded DexiNed checkpoint
load is fed a random state dict (no checkpoints ship in the reference).

Writes a JSON line; tee it into logs/torch_cpu_anchor.log and cite in
docs/perf.md.
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys
import time

import numpy as np

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--height", type=int, default=224)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--train", action="store_true",
                    help="anchor the full TRAINING step instead of the "
                         "test-mode forward: sequence loss + backward + "
                         "grad-clip + AdamW on both sides, identical "
                         "hyperparameters (the reference's chairs-stage "
                         "recipe at the demo geometry)")
    args = ap.parse_args()
    h, w, iters = args.height, args.width, args.iters
    if args.train:
        return train_anchor(args)

    import torch

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    rng = np.random.default_rng(0)
    im1 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    im2 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)

    # ---- reference torch path ----
    from dexiraft_tpu.interop.reference import build_reference_v5

    tm = build_reference_v5()
    t1 = torch.from_numpy(im1.transpose(0, 3, 1, 2))
    t2 = torch.from_numpy(im2.transpose(0, 3, 1, 2))
    with torch.no_grad():
        tm(t1, t2, iters=iters, test_mode=True)  # warm (autotune etc.)
        t0 = time.perf_counter()
        for _ in range(args.reps):
            tm(t1, t2, iters=iters, test_mode=True)
        torch_s = (time.perf_counter() - t0) / args.reps
    print(f"[anchor] torch forward {torch_s * 1e3:.0f} ms", file=sys.stderr)

    # ---- our path, same process/load ----
    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT

    cfg = raft_v5(mixed_precision=False)
    model = RAFT(cfg)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, h, w, 3)), jnp.zeros((1, h, w, 3)),
                           iters=1, train=False)
    fwd = jax.jit(lambda v, a, b: model.apply(
        v, a, b, iters=iters, train=False, test_mode=True))
    j1, j2 = jnp.asarray(im1), jnp.asarray(im2)
    jax.block_until_ready(fwd(variables, j1, j2))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(args.reps):
        jax.block_until_ready(fwd(variables, j1, j2))
    jax_s = (time.perf_counter() - t0) / args.reps
    print(f"[anchor] flax forward {jax_s * 1e3:.0f} ms", file=sys.stderr)

    print(json.dumps({
        "metric": f"cpu_anchor_v5_forward@{h}x{w}x{iters}it",
        "torch_ms": round(torch_s * 1e3, 1),
        "flax_ms": round(jax_s * 1e3, 1),
        "torch_iters_per_sec": round(iters / torch_s, 3),
        "flax_iters_per_sec": round(iters / jax_s, 3),
        "flax_over_torch": round(torch_s / jax_s, 3),
        "host": "2-core CPU (build container)",
    }), flush=True)


def train_anchor(args):
    """Full training step, torch reference vs flax, same CPU.

    Both sides run: forward with per-iteration outputs -> the
    gamma-weighted sequence loss (train.py:42-73 semantics, re-derived)
    -> backward -> grad-clip 1.0 -> AdamW(lr 2e-4, wd 1e-5). No AMP on
    either side (CPU), no remat on ours (the reference stores all
    activations, so the fair memory/compute tradeoff is store-all).
    """
    h, w, iters = args.height, args.width, args.iters

    import torch

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    rng = np.random.default_rng(0)
    im1 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    im2 = rng.uniform(0, 255, (1, h, w, 3)).astype(np.float32)
    gt = rng.normal(0, 3, (1, h, w, 2)).astype(np.float32)

    # ---- reference torch training step ----
    from dexiraft_tpu.interop.reference import build_reference_v5

    tm = build_reference_v5()
    tm.train()
    opt = torch.optim.AdamW(tm.parameters(), lr=2e-4, weight_decay=1e-5)
    t1 = torch.from_numpy(im1.transpose(0, 3, 1, 2))
    t2 = torch.from_numpy(im2.transpose(0, 3, 1, 2))
    tgt = torch.from_numpy(gt.transpose(0, 3, 1, 2))
    tvalid = torch.ones(1, h, w)

    def torch_seq_loss(preds):
        # gamma-weighted L1 over iteration outputs, masked by
        # valid & |gt|<400 (train.py:42-73), gamma=0.8
        mag = torch.sum(tgt ** 2, dim=1).sqrt()
        valid = (tvalid >= 0.5) & (mag < 400)
        loss = 0.0
        n = len(preds)
        for i, p in enumerate(preds):
            w_i = 0.8 ** (n - i - 1)
            loss = loss + w_i * (valid[:, None] * (p - tgt).abs()).mean()
        return loss

    def torch_step():
        preds = tm(t1, t2, iters=iters)
        loss = torch_seq_loss(preds)
        opt.zero_grad()
        loss.backward()
        torch.nn.utils.clip_grad_norm_(tm.parameters(), 1.0)
        opt.step()
        return float(loss)

    torch_step()  # warm
    t0 = time.perf_counter()
    for _ in range(args.reps):
        torch_step()
    torch_s = (time.perf_counter() - t0) / args.reps
    print(f"[anchor] torch train step {torch_s * 1e3:.0f} ms",
          file=sys.stderr)

    # ---- our training step, same process/load ----
    from dexiraft_tpu.config import TrainConfig, raft_v5
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg = raft_v5(mixed_precision=False)  # remat off: store-all like torch
    tc = TrainConfig(name="anchor", num_steps=100, batch_size=1,
                     image_size=(h, w), iters=iters, lr=2e-4, wdecay=1e-5,
                     clip=1.0)
    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    step_fn = make_train_step(cfg, tc)
    batch = {"image1": jnp.asarray(im1), "image2": jnp.asarray(im2),
             "flow": jnp.asarray(gt), "valid": jnp.ones((1, h, w))}
    state, metrics = step_fn(state, batch)  # compile + warm
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(args.reps):
        state, metrics = step_fn(state, batch)
        float(jax.device_get(metrics["loss"]))  # explicit sync (JL007)
    jax_s = (time.perf_counter() - t0) / args.reps
    print(f"[anchor] flax train step {jax_s * 1e3:.0f} ms", file=sys.stderr)

    print(json.dumps({
        "metric": f"cpu_anchor_v5_trainstep@{h}x{w}x{iters}it",
        "torch_ms": round(torch_s * 1e3, 1),
        "flax_ms": round(jax_s * 1e3, 1),
        "flax_over_torch_train": round(torch_s / jax_s, 3),
        "host": "2-core CPU (build container)",
    }), flush=True)


if __name__ == "__main__":
    main()
