"""On-hardware smoke: run after the TPU tunnel recovers (cannot run under
the CPU-pinned test suite).

  python scripts/tpu_smoke.py            # all stages
  python scripts/tpu_smoke.py pallas     # just the kernel parity

Stages:
  pallas   compile + parity of the Pallas local-corr kernel vs the XLA
           gather path on the real chip (the interpret-mode tests cover
           numerics; this covers Mosaic compilation)
  train    one jitted v1-small train step on synthetic data
  forward  flagship v5 test-mode forward at 440x1024 (bench shape)
"""

from __future__ import annotations

import os.path as osp
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# repo root on sys.path: bench.py lives there (outside the package) and
# `python scripts/tpu_smoke.py` only adds scripts/ itself
sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))


def stage_pallas() -> None:
    from dexiraft_tpu.ops.local_corr import local_corr_level
    from dexiraft_tpu.ops.pallas_corr import pallas_local_corr_level

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    b, h, w, c = 1, 55, 128, 256  # Sintel eval shape at 1/8
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    coords = (jnp.stack([xs, ys], -1)[None]
              + jax.random.uniform(k3, (b, h, w, 2), jnp.float32, -3, 3))

    # XLA-formulation reference; the Pallas kernel's first compile and
    # parity check happen inside the block-size sweep below (no
    # duplicate Mosaic compile — cold compiles dominate queue cost)
    ref = jax.block_until_ready(
        jax.jit(lambda a, b_, c_: local_corr_level(a, b_, c_, 4, row_chunk=8))(
            f1, f2, coords))

    # timing via scalar fetch: block_until_ready does not reliably block
    # through the relay tunnel (verify SKILL.md), so reduce to one value
    # on device and float() it — and subtract the adjacent RTT floor
    import os

    trivial = jax.jit(lambda x: jnp.sum(x))
    float(jax.device_get(trivial(jnp.ones((8, 8)))))

    def rtt(n=4):
        t0 = time.perf_counter()
        for _ in range(n):
            # explicit scalar fetch = the sync (jaxlint JL007)
            float(jax.device_get(trivial(jnp.ones((8, 8)))))
        return (time.perf_counter() - t0) / n

    def timed(fn, reps=10):
        float(jax.device_get(fn(f1, f2, coords)))  # compile + warm
        floor = rtt()
        t0 = time.perf_counter()
        for _ in range(reps):
            float(jax.device_get(fn(f1, f2, coords)))
        dt = (time.perf_counter() - t0) / reps
        if dt <= floor:
            # an RTT spike during the floor sample would otherwise
            # publish a ~0 ms nonsense win — report uncorrected instead
            print(f"  WARNING: dt {dt * 1e3:.2f} ms <= rtt floor "
                  f"{floor * 1e3:.2f} ms; reporting uncorrected")
            return dt
        return dt - floor

    # two kernel shapes (ops/pallas_corr.py): "loop" = per-pixel
    # slice+reduce; "batched" = copy loop + one vectorized block reduce
    # (the r4 VERDICT's find-the-regime ask). Block sizes differ because
    # batched stages (P, k, k, C) patches in VMEM.
    sweep = {"loop": (128, 256, 512), "batched": (16, 32, 64)}
    results = {}
    parity_failures = []
    try:
        for variant, blocks in sweep.items():
            os.environ["DEXIRAFT_PALLAS_VARIANT"] = variant
            for blk in blocks:
                os.environ["DEXIRAFT_PALLAS_PIXEL_BLOCK"] = str(blk)
                # parity FIRST at this config — Mosaic layout bugs are
                # block-size-dependent, so a timing may only count for a
                # config whose values were checked on this very chip
                try:
                    # fresh jit per sweep config ON PURPOSE: the env vars
                    # above change the traced kernel, so a hoisted wrapper
                    # would serve a stale executable
                    out_blk = jax.jit(  # jaxlint: disable=JL009
                        lambda a, b_, c_: pallas_local_corr_level(
                            a, b_, c_, 4))(f1, f2, coords)
                except Exception as e:
                    # a VMEM-overflow compile failure on one config must
                    # not kill the rest of the sweep — but it is only a
                    # skipped config, never a parity verdict
                    print(f"  pallas {variant}/block={blk}: compile "
                          f"FAILED ({type(e).__name__}: {str(e)[:200]})")
                    continue
                try:
                    np.testing.assert_allclose(
                        np.asarray(out_blk), np.asarray(ref),
                        rtol=2e-3, atol=2e-3)
                except AssertionError as e:
                    # WRONG VALUES on chip: finish the sweep for
                    # information, but the stage must fail at the end
                    parity_failures.append((variant, blk))
                    print(f"  pallas {variant}/block={blk}: PARITY "
                          f"MISMATCH ({str(e)[:200]})")
                    continue
                fn = jax.jit(lambda a, b_, c_: jnp.sum(  # jaxlint: disable=JL009
                    pallas_local_corr_level(a, b_, c_, 4)))
                results[(variant, blk)] = timed(fn)
                print(f"  pallas {variant}/block={blk}: "
                      f"{results[(variant, blk)] * 1e3:.2f} ms "
                      f"(parity ok)")
    finally:
        # a mid-sweep failure must not leak the tuning knobs to later
        # stages or callers that catch the exception
        os.environ.pop("DEXIRAFT_PALLAS_PIXEL_BLOCK", None)
        os.environ.pop("DEXIRAFT_PALLAS_VARIANT", None)
    if results:
        best = min(results, key=results.get)
        dt_p = results[best]
        fn2 = jax.jit(lambda a, b_, c_: jnp.sum(
            local_corr_level(a, b_, c_, 4, row_chunk=8)))
        dt_x = timed(fn2)
        print(f"pallas best {dt_p * 1e3:.2f} ms "
              f"({best[0]}/block={best[1]}) vs xla-formulation "
              f"{dt_x * 1e3:.2f} ms per level-0 lookup")
    if parity_failures:
        raise RuntimeError(f"pallas parity FAILED for {parity_failures}")
    if not results:
        raise RuntimeError("every pallas config failed to compile")
    print("PALLAS PARITY OK (all compiled configs)")


def stage_train() -> None:
    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg = raft_v1(small=True, mixed_precision=True)
    tc = TrainConfig(num_steps=10, batch_size=2, image_size=(64, 64), iters=4)
    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    step = make_train_step(cfg, tc)
    rng = np.random.default_rng(0)
    batch = {
        "image1": rng.uniform(0, 255, (2, 64, 64, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (2, 64, 64, 3)).astype(np.float32),
        "flow": rng.normal(0, 1, (2, 64, 64, 2)).astype(np.float32),
        "valid": np.ones((2, 64, 64), np.float32),
    }
    t0 = time.perf_counter()
    state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    print(f"TRAIN STEP OK loss={loss:.3f} "
          f"(compile+run {time.perf_counter() - t0:.1f}s)")


def stage_forward() -> None:
    import os

    import bench

    # run the measurement body directly: this process already holds the
    # single TPU claim, so letting bench.main() act as the watchdog
    # PARENT (BENCH_CHILD unset) would spawn probe + measurement
    # subprocesses that can never acquire the device — the forward
    # number would silently become a CPU-fallback record
    prev = os.environ.get("BENCH_CHILD")
    os.environ["BENCH_CHILD"] = "1"
    try:
        bench.main()
    finally:
        if prev is None:
            os.environ.pop("BENCH_CHILD", None)
        else:
            os.environ["BENCH_CHILD"] = prev


STAGES = {"pallas": stage_pallas, "train": stage_train,
          "forward": stage_forward}


if __name__ == "__main__":
    # default = pallas + train only: the queue always lands the official
    # bench (job 1) before this job, so the "forward" stage would re-run
    # the whole 4-config sweep inside a scarce heal window for nothing.
    # Ask for it explicitly (`tpu_smoke.py forward`) when wanted.
    wanted = sys.argv[1:] or ["pallas", "train"]
    print(f"devices: {jax.devices()}")
    for name in wanted:
        print(f"--- {name} ---")
        STAGES[name]()
