"""On-hardware smoke: run after the TPU tunnel recovers (cannot run under
the CPU-pinned test suite).

  python scripts/tpu_smoke.py            # all stages
  python scripts/tpu_smoke.py pallas     # just the kernel parity

Stages:
  pallas   compile + parity of the Pallas local-corr kernel vs the XLA
           gather path on the real chip (the interpret-mode tests cover
           numerics; this covers Mosaic compilation)
  train    one jitted v1-small train step on synthetic data
  forward  flagship v5 test-mode forward at 440x1024 (bench shape)
"""

from __future__ import annotations

import os.path as osp
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# repo root on sys.path: bench.py lives there (outside the package) and
# `python scripts/tpu_smoke.py` only adds scripts/ itself
sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))


def stage_pallas() -> None:
    from dexiraft_tpu.ops.local_corr import local_corr_level
    from dexiraft_tpu.ops.pallas_corr import pallas_local_corr_level

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    b, h, w, c = 1, 55, 128, 256  # Sintel eval shape at 1/8
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    coords = (jnp.stack([xs, ys], -1)[None]
              + jax.random.uniform(k3, (b, h, w, 2), jnp.float32, -3, 3))

    t0 = time.perf_counter()
    out_pallas = jax.block_until_ready(
        jax.jit(lambda a, b_, c_: pallas_local_corr_level(a, b_, c_, 4))(
            f1, f2, coords))
    print(f"pallas compile+run: {time.perf_counter() - t0:.1f}s")
    ref = jax.block_until_ready(
        jax.jit(lambda a, b_, c_: local_corr_level(a, b_, c_, 4, row_chunk=8))(
            f1, f2, coords))
    np.testing.assert_allclose(np.asarray(out_pallas), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)

    reps = 10
    t0 = time.perf_counter()
    fn = jax.jit(lambda a, b_, c_: pallas_local_corr_level(a, b_, c_, 4))
    for _ in range(reps):
        jax.block_until_ready(fn(f1, f2, coords))
    dt_p = (time.perf_counter() - t0) / reps
    fn2 = jax.jit(lambda a, b_, c_: local_corr_level(a, b_, c_, 4, row_chunk=8))
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn2(f1, f2, coords))
    dt_x = (time.perf_counter() - t0) / reps
    print(f"PALLAS PARITY OK  pallas {dt_p * 1e3:.2f} ms vs "
          f"xla-gather {dt_x * 1e3:.2f} ms per level-0 lookup")


def stage_train() -> None:
    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg = raft_v1(small=True, mixed_precision=True)
    tc = TrainConfig(num_steps=10, batch_size=2, image_size=(64, 64), iters=4)
    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    step = make_train_step(cfg, tc)
    rng = np.random.default_rng(0)
    batch = {
        "image1": rng.uniform(0, 255, (2, 64, 64, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (2, 64, 64, 3)).astype(np.float32),
        "flow": rng.normal(0, 1, (2, 64, 64, 2)).astype(np.float32),
        "valid": np.ones((2, 64, 64), np.float32),
    }
    t0 = time.perf_counter()
    state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    print(f"TRAIN STEP OK loss={loss:.3f} "
          f"(compile+run {time.perf_counter() - t0:.1f}s)")


def stage_forward() -> None:
    import bench

    bench.main()


STAGES = {"pallas": stage_pallas, "train": stage_train,
          "forward": stage_forward}


if __name__ == "__main__":
    wanted = sys.argv[1:] or list(STAGES)
    print(f"devices: {jax.devices()}")
    for name in wanted:
        print(f"--- {name} ---")
        STAGES[name]()
