"""A/B the corr_lookup formulation on the real chip at Sintel eval shape.

One script, three experiment rounds (formerly lookup_ab.py / lookup_ab2.py
/ lookup_ab3.py — consolidated; the per-round output formats are pinned,
logs/ carries records in them):

  --variant 1   formulation A/B:
    matmul    one-hot separable matmul (current corr_lookup)
    matmul16  same but the volume stored bf16 (halved HBM traffic)
    batched   both streams' lookups through ONE set of einsums
    batched16 the whole lookup in bf16 (hats + volume), fp32 accumulate

  --variant 2   second round — where do the 2.9 ms/iter go?
    current/xfirst/fused   contraction-order A/B on interp_window
    build_only             just the one-hot A matrices each iteration
    mm_only                pre-built A matrices, only the matmuls
    blockdiag              all 4 levels through ONE block-diagonal matmul

  --variant 3   bf16 inputs for the on-demand (local) corr path
    fp32/bf16/bf16_all timing + max|delta| accuracy bound per variant

  --variant 4   the three lookup FORMULATIONS head-to-head (ISSUE 12):
    allpairs   materialized volume + one-hot matmul lookup (corr_lookup)
    pallas     per-pixel slice kernel (pallas_local_corr_level)
    flash      flash-blocked kernel — fmap2 row-block-streamed from HBM,
               partial-volume MXU matmuls, no materialized volume
    On the CPU fallback the Pallas legs run in interpreter mode at a
    reduced geometry/iteration count (printed) — code-path proof only.

Each timed run is 32 chained 2-stream lookups inside one scan
(carry-dependent so iterations cannot be collapsed), one scalar out =
one tunnel round-trip.
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.corr import (
    CorrPyramid,
    _axis_interp_matrix,
    avg_pool_2x2,
    build_corr_pyramid,
    corr_lookup,
)
from dexiraft_tpu.ops.grid import coords_grid

H8, W8, C = 55, 128, 256
ITERS = 32
RADIUS = R = 4
WIN = 2 * R + 1
B3 = 2  # variant-3 dual-stream batch


def _print_rtt() -> float:
    t = jax.jit(lambda x: jnp.sum(x))
    float(t(jnp.ones((8, 8))))
    t0 = time.perf_counter()
    for _ in range(3):
        float(t(jnp.ones((8, 8))))
    rtt = (time.perf_counter() - t0) / 3
    print(f"       rtt: {rtt * 1e3:8.1f} ms")
    return rtt


# ---------------------------------------------------------------------------
# variant 1: lookup formulation A/B (original lookup_ab.py)
# ---------------------------------------------------------------------------

def slice_lookup(pyramid: CorrPyramid, coords: jax.Array) -> jax.Array:
    r = pyramid.radius
    b, h, w = pyramid.batch, pyramid.ht, pyramid.wd
    win = 2 * r + 1
    k = 2 * r + 2
    pad = k
    flat = coords.reshape(b * h * w, 2).astype(jnp.float32)
    out = []
    for i, corr in enumerate(pyramid.levels):
        hl, wl = corr.shape[1], corr.shape[2]
        c = flat / (2.0 ** i)
        x = jnp.clip(c[:, 0], -(r + 1.0), wl - 1 + r + 1.0)
        y = jnp.clip(c[:, 1], -(r + 1.0), hl - 1 + r + 1.0)
        x0 = jnp.floor(x)
        y0 = jnp.floor(y)
        fx = (x - x0)[:, None, None]
        fy = (y - y0)[:, None, None]
        sx = x0.astype(jnp.int32) + (r + 2)
        sy = y0.astype(jnp.int32) + (r + 2)
        volp = jnp.pad(corr[..., 0], ((0, 0), (pad, pad), (pad, pad)))

        patch = jax.vmap(
            lambda v, py, px: jax.lax.dynamic_slice(v, (py, px), (k, k))
        )(volp, sy, sx)  # (N, k, k)

        tl = patch[:, 0:win, 0:win]
        tr = patch[:, 0:win, 1:win + 1]
        bl = patch[:, 1:win + 1, 0:win]
        br = patch[:, 1:win + 1, 1:win + 1]
        o = ((1 - fy) * (1 - fx) * tl + (1 - fy) * fx * tr
             + fy * (1 - fx) * bl + fy * fx * br)
        out.append(o.swapaxes(1, 2).reshape(b, h, w, win * win))
    return jnp.concatenate(out, axis=-1)


def bench(name, lookup, cast=lambda x: x):
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (1, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (1, H8, W8, C))

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, RADIUS)
        pyr2 = build_corr_pyramid(f2, f1, 4, RADIUS)
        pyr = pyr.replace(levels=tuple(cast(l) for l in pyr.levels))
        pyr2 = pyr2.replace(levels=tuple(cast(l) for l in pyr2.levels))
        coords = coords_grid(1, H8, W8)

        def body(co, _):
            s = lookup(pyr, co) + lookup(pyr2, co)
            co = co + 0.01 * s.mean(axis=-1, keepdims=True)
            return co, None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    float(run(f1, f2))
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        float(run(f1, f2))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, "
          f"{dt / ITERS * 1e3:6.2f} ms/iter")


def bench_batched(name, adt):
    """Both streams' lookups through ONE set of einsums: pyramids built
    from batch-2 fmaps (N doubles, matmul count halves); optionally the
    whole lookup in bf16 (one-hot A and volume) with fp32 accumulate."""
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (2, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (2, H8, W8, C))

    def lookup(pyr, coords):
        r, b, h, w = pyr.radius, pyr.batch, pyr.ht, pyr.wd
        win = 2 * r + 1
        flat = coords.reshape(b * h * w, 2).astype(jnp.float32)
        out = []
        for i, corr in enumerate(pyr.levels):
            hl, wl = corr.shape[1], corr.shape[2]
            center = flat / (2.0 ** i)
            ax = _axis_interp_matrix(center[:, 0], r, wl).astype(adt)
            ay = _axis_interp_matrix(center[:, 1], r, hl).astype(adt)
            vol = corr[..., 0].astype(adt)
            rows = jnp.einsum("nby,nyx->nbx", ay, vol,
                              preferred_element_type=jnp.float32).astype(adt)
            window = jnp.einsum("nax,nbx->nab", ax, rows,
                                preferred_element_type=jnp.float32)
            out.append(window.reshape(b, h, w, win * win))
        return jnp.concatenate(out, axis=-1).astype(jnp.float32)

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, RADIUS)  # batch-2 = 2 streams
        coords = coords_grid(2, H8, W8)

        def body(co, _):
            s = lookup(pyr, co)
            co = co + 0.01 * s.mean(axis=-1, keepdims=True)
            return co, None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    float(run(f1, f2))
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        float(run(f1, f2))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, "
          f"{dt / ITERS * 1e3:6.2f} ms/iter")


def main_v1():
    _print_rtt()
    bench("matmul", corr_lookup)
    bench("matmul16", corr_lookup,
          cast=lambda l: l.astype(jnp.bfloat16))
    bench_batched("batched", jnp.float32)
    bench_batched("batched16", jnp.bfloat16)


# ---------------------------------------------------------------------------
# variant 2: second-round lookup experiments (original lookup_ab2.py)
# ---------------------------------------------------------------------------

def _pyr2():
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (2, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (2, H8, W8, C))
    return f1, f2


def _time(name, run, *args):
    float(run(*args))
    t0 = time.perf_counter()
    for _ in range(3):
        float(run(*args))
    dt = (time.perf_counter() - t0) / 3
    print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, {dt / ITERS * 1e3:6.2f} ms/iter")


def bench_lookup(name, level_fn):
    f1, f2 = _pyr2()

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, R)
        coords = coords_grid(2, H8, W8)

        def body(co, _):
            flat = co.reshape(-1, 2)
            out = []
            for i, corr in enumerate(pyr.levels):
                out.append(level_fn(corr[..., 0], flat / (2.0 ** i)))
            s = jnp.concatenate(out, axis=-1).reshape(2, H8, W8, -1)
            return co + 0.01 * s.mean(axis=-1, keepdims=True), None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    _time(name, run, f1, f2)


def lvl_current(vol, centers):
    ay = _axis_interp_matrix(centers[:, 1], R, vol.shape[1])
    ax = _axis_interp_matrix(centers[:, 0], R, vol.shape[2])
    rows = jnp.einsum("nby,nyx->nbx", ay, vol,
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nax,nbx->nab", ax, rows,
                      preferred_element_type=jnp.float32).reshape(
        vol.shape[0], WIN * WIN)


def lvl_xfirst(vol, centers):
    ay = _axis_interp_matrix(centers[:, 1], R, vol.shape[1])
    ax = _axis_interp_matrix(centers[:, 0], R, vol.shape[2])
    cols = jnp.einsum("nax,nyx->nay", ax, vol,
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nby,nay->nab", ay, cols,
                      preferred_element_type=jnp.float32).reshape(
        vol.shape[0], WIN * WIN)


def lvl_fused(vol, centers):
    ay = _axis_interp_matrix(centers[:, 1], R, vol.shape[1])
    ax = _axis_interp_matrix(centers[:, 0], R, vol.shape[2])
    return jnp.einsum("nby,nyx,nax->nab", ay, vol, ax,
                      preferred_element_type=jnp.float32).reshape(
        vol.shape[0], WIN * WIN)


def bench_build_only():
    f1, f2 = _pyr2()

    @jax.jit
    def run(f1, f2):
        coords = coords_grid(2, H8, W8)
        sizes = [(H8, W8), (27, 64), (13, 32), (6, 16)]

        def body(co, _):
            flat = co.reshape(-1, 2)
            acc = 0.0
            for i, (hl, wl) in enumerate(sizes):
                c = flat / (2.0 ** i)
                ay = _axis_interp_matrix(c[:, 1], R, hl)
                ax = _axis_interp_matrix(c[:, 0], R, wl)
                acc = acc + ay.sum() + ax.sum()
            return co + 1e-9 * acc, None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    _time("build_only", run, f1, f2)


def bench_mm_only():
    f1, f2 = _pyr2()

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, R)
        coords = coords_grid(2, H8, W8)
        flat = coords.reshape(-1, 2)
        mats = []
        for i, corr in enumerate(pyr.levels):
            c = flat / (2.0 ** i)
            mats.append((_axis_interp_matrix(c[:, 1], R, corr.shape[1]),
                         _axis_interp_matrix(c[:, 0], R, corr.shape[2])))

        def body(carry, _):
            acc = carry
            outs = []
            for (ay, ax), corr in zip(mats, pyr.levels):
                vol = corr[..., 0] + acc  # keep iteration-dependent
                rows = jnp.einsum("nby,nyx->nbx", ay, vol,
                                  preferred_element_type=jnp.float32)
                w = jnp.einsum("nax,nbx->nab", ax, rows,
                               preferred_element_type=jnp.float32)
                outs.append(w.sum())
            return acc + 1e-9 * sum(outs), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), None, length=ITERS)
        return acc

    _time("mm_only", run, f1, f2)


def bench_blockdiag():
    """All 4 levels' y-einsums fused into ONE batched matmul against a
    block-diagonal concatenated volume (built once, loop-invariant);
    probes whether per-matmul-instance overhead dominates."""
    f1, f2 = _pyr2()
    sizes = [(55, 128), (27, 64), (13, 32), (6, 16)]
    yoff = [0, 55, 82, 95]
    xoff = [0, 128, 192, 224]
    ktot, xtot = 101, 240

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, R)
        n = 2 * H8 * W8
        vol_cat = jnp.zeros((n, ktot, xtot), jnp.float32)
        for lvl, corr in enumerate(pyr.levels):
            hl, wl = sizes[lvl]
            vol_cat = jax.lax.dynamic_update_slice(
                vol_cat, corr[..., 0], (0, yoff[lvl], xoff[lvl]))
        coords = coords_grid(2, H8, W8)

        def hats(flat):
            ays, axs = [], []
            for lvl in range(4):
                c = flat / (2.0 ** lvl)
                hl, wl = sizes[lvl]
                ays.append(_axis_interp_matrix(c[:, 1], R, hl))
                axs.append(_axis_interp_matrix(c[:, 0], R, wl))
            # place each level's hat into its global K/X range
            ay = jnp.zeros((flat.shape[0], 4, WIN, ktot), jnp.float32)
            ax = jnp.zeros((flat.shape[0], 4, WIN, xtot), jnp.float32)
            for lvl in range(4):
                hl, wl = sizes[lvl]
                ay = ay.at[:, lvl, :, yoff[lvl]:yoff[lvl] + hl].set(ays[lvl])
                ax = ax.at[:, lvl, :, xoff[lvl]:xoff[lvl] + wl].set(axs[lvl])
            return ay.reshape(-1, 4 * WIN, ktot), ax

        def body(co, _):
            flat = co.reshape(-1, 2)
            ay, ax = hats(flat)
            rows = jnp.einsum("nby,nyx->nbx", ay, vol_cat,
                              preferred_element_type=jnp.float32)
            rows = rows.reshape(-1, 4, WIN, xtot)
            w = jnp.einsum("nlax,nlbx->nlab", ax, rows,
                           preferred_element_type=jnp.float32)
            s = w.reshape(2, H8, W8, -1)
            return co + 0.01 * s.mean(axis=-1, keepdims=True), None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    _time("blockdiag", run, f1, f2)


def main_v2():
    _print_rtt()
    bench_lookup("current", lvl_current)
    bench_lookup("xfirst", lvl_xfirst)
    bench_lookup("fused", lvl_fused)
    bench_build_only()
    bench_mm_only()
    bench_blockdiag()


# ---------------------------------------------------------------------------
# variant 3: bf16 inputs for the on-demand path (original lookup_ab3.py)
# ---------------------------------------------------------------------------
# The local path recomputes the all-pairs block f1·f2ᵀ every iteration —
# MXU FLOPs, not HBM reads, so input precision is the lever: fp32 matmuls
# on TPU run as multi-pass bf16 decompositions, while native bf16 inputs
# with fp32 accumulation (preferred_element_type) are one pass.

def _fmaps3():
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (B3, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (B3, H8, W8, C))
    return f1, f2


def local_level(f1, f2, centers, in_dtype, hat_dtype):
    """One level of the on-demand lookup at the given precisions."""
    b, h, w, c = f1.shape
    n = b * h * w
    q = f1.reshape(b, h * w, c).astype(in_dtype)
    t = f2.reshape(b, -1, c).astype(in_dtype)
    vol = jnp.einsum("bnd,bmd->bnm", q, t,
                     preferred_element_type=jnp.float32)
    vol = (vol / jnp.sqrt(jnp.float32(c))).reshape(n, f2.shape[1], f2.shape[2])
    ay = _axis_interp_matrix(centers[:, 1], R, f2.shape[1]).astype(hat_dtype)
    ax = _axis_interp_matrix(centers[:, 0], R, f2.shape[2]).astype(hat_dtype)
    win = jnp.einsum("nby,nyx,nax->nab", ay, vol.astype(hat_dtype), ax,
                     preferred_element_type=jnp.float32)
    return win.reshape(n, WIN * WIN)


def make_run(in_dtype, hat_dtype):
    @jax.jit
    def run(f1, f2):
        pyr2 = [f2]
        for _ in range(3):
            pyr2.append(avg_pool_2x2(pyr2[-1]))
        coords = coords_grid(B3, H8, W8)

        def body(co, _):
            flat = co.reshape(-1, 2)
            out = [local_level(f1, lvl, flat / (2.0 ** i), in_dtype, hat_dtype)
                   for i, lvl in enumerate(pyr2)]
            s = jnp.concatenate(out, axis=-1).reshape(B3, H8, W8, -1)
            return co + 0.01 * s.mean(axis=-1, keepdims=True), None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    return run


def main_v3():
    f1, f2 = _fmaps3()
    rtt = _print_rtt()

    # accuracy bound: one lookup at identity coords, each variant vs fp32
    flat = coords_grid(B3, H8, W8).reshape(-1, 2)
    ref = local_level(f1, f2, flat, jnp.float32, jnp.float32)
    for name, dts in [("bf16", (jnp.bfloat16, jnp.float32)),
                      ("bf16_all", (jnp.bfloat16, jnp.bfloat16))]:
        d = jnp.max(jnp.abs(local_level(f1, f2, flat, *dts) - ref))
        r = jnp.max(jnp.abs(ref))
        print(f"{name:>10s}: max|delta| {float(d):.4f} on max|corr| {float(r):.2f}")

    for name, dts in [("fp32", (jnp.float32, jnp.float32)),
                      ("bf16", (jnp.bfloat16, jnp.float32)),
                      ("bf16_all", (jnp.bfloat16, jnp.bfloat16))]:
        run = make_run(*dts)
        float(run(f1, f2))
        t0 = time.perf_counter()
        for _ in range(3):
            float(run(f1, f2))
        raw = (time.perf_counter() - t0) / 3
        # floor guard (same rule as bench.py): the RTT floor is measured
        # once and the tunnel latency drifts — never print a negative or
        # near-zero corrected time, fall back to the raw number
        dt = raw - rtt if raw > rtt else raw
        print(f"{name:>10s}: {dt * 1e3:8.1f} ms total "
              f"(raw {raw * 1e3:.1f}), {dt / ITERS * 1e3:6.2f} ms/iter")


# ---------------------------------------------------------------------------
# variant 4: the three formulations head-to-head (ISSUE 12)
# ---------------------------------------------------------------------------
# allpairs amortizes one volume build over the loop but streams the
# O(N^2) volume from HBM every lookup; per-pixel pallas avoids the
# volume but is gather-shaped; flash-blocked recomputes the needed
# partial-volume blocks as MXU matmuls with only the fmaps in HBM.

def main_v4():
    import os

    from dexiraft_tpu.ops.local_corr import build_local_corr

    on_tpu = jax.devices()[0].platform == "tpu"
    h8, w8, iters = (H8, W8, ITERS) if on_tpu else (16, 32, 4)
    if not on_tpu:
        # interpreter-mode kernels at the full geometry are debug-speed
        # (the per-pixel kernel loops 7040 slices per level per iter) —
        # the CPU leg proves the code paths, not the ordering
        os.environ.setdefault("DEXIRAFT_PALLAS_INTERPRET", "1")
        print(f"cpu fallback: reduced geometry {h8}x{w8}, {iters} iters "
              "— code-path proof only, interpret-mode kernels",
              file=sys.stderr)
    _print_rtt()

    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (1, h8, w8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (1, h8, w8, C))

    def run_for(make_lookup):
        @jax.jit
        def run(f1, f2):
            lkp, lkp2 = make_lookup(f1, f2)
            coords = coords_grid(1, h8, w8)

            def body(co, _):
                s = lkp(co) + lkp2(co)
                co = co + 0.01 * s.mean(axis=-1, keepdims=True)
                return co, None

            co, _ = jax.lax.scan(body, coords, None, length=iters)
            return jnp.sum(co)

        return run

    def time_leg(name, make_lookup):
        run = run_for(make_lookup)
        float(run(f1, f2))
        t0 = time.perf_counter()
        reps = 3 if on_tpu else 1
        for _ in range(reps):
            float(run(f1, f2))
        dt = (time.perf_counter() - t0) / reps
        print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, "
              f"{dt / iters * 1e3:6.2f} ms/iter")

    time_leg("allpairs", lambda a, b: (build_corr_pyramid(a, b, 4, RADIUS),
                                       build_corr_pyramid(b, a, 4, RADIUS)))
    time_leg("pallas", lambda a, b: (
        build_local_corr(a, b, 4, RADIUS, kernel="pallas"),
        build_local_corr(b, a, 4, RADIUS, kernel="pallas")))
    time_leg("flash", lambda a, b: (
        build_local_corr(a, b, 4, RADIUS, kernel="flash"),
        build_local_corr(b, a, 4, RADIUS, kernel="flash")))


def main():
    ap = argparse.ArgumentParser(
        "lookup_ab", description="corr-lookup A/B experiment rounds")
    ap.add_argument("--variant", type=int, choices=[1, 2, 3, 4], default=1,
                    help="1 = formulation A/B, 2 = contraction-order / "
                         "instance-overhead round, 3 = bf16-input round, "
                         "4 = allpairs vs per-pixel pallas vs "
                         "flash-blocked")
    args = ap.parse_args()
    print(f"platform={jax.devices()[0].platform}", file=sys.stderr)
    {1: main_v1, 2: main_v2, 3: main_v3, 4: main_v4}[args.variant]()


if __name__ == "__main__":
    main()
