"""A/B the corr_lookup formulation on the real chip at Sintel eval shape.

  matmul    one-hot separable matmul (current corr_lookup)
  matmul16  same but the volume stored bf16 (halved HBM traffic)
  slice     vmapped dynamic_slice (2r+2)^2 patch + corner blend (the
            pallas index-prep in pure XLA)

Each runs 32 chained 2-stream lookups inside one scan (carry-dependent so
iterations cannot be collapsed), one scalar out = one tunnel round-trip.
"""

from __future__ import annotations

import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.corr import CorrPyramid, build_corr_pyramid, corr_lookup
from dexiraft_tpu.ops.grid import coords_grid

H8, W8, C = 55, 128, 256
ITERS = 32
RADIUS = 4


def slice_lookup(pyramid: CorrPyramid, coords: jax.Array) -> jax.Array:
    r = pyramid.radius
    b, h, w = pyramid.batch, pyramid.ht, pyramid.wd
    win = 2 * r + 1
    k = 2 * r + 2
    pad = k
    flat = coords.reshape(b * h * w, 2).astype(jnp.float32)
    out = []
    for i, corr in enumerate(pyramid.levels):
        hl, wl = corr.shape[1], corr.shape[2]
        c = flat / (2.0 ** i)
        x = jnp.clip(c[:, 0], -(r + 1.0), wl - 1 + r + 1.0)
        y = jnp.clip(c[:, 1], -(r + 1.0), hl - 1 + r + 1.0)
        x0 = jnp.floor(x)
        y0 = jnp.floor(y)
        fx = (x - x0)[:, None, None]
        fy = (y - y0)[:, None, None]
        sx = x0.astype(jnp.int32) + (r + 2)
        sy = y0.astype(jnp.int32) + (r + 2)
        volp = jnp.pad(corr[..., 0], ((0, 0), (pad, pad), (pad, pad)))

        patch = jax.vmap(
            lambda v, py, px: jax.lax.dynamic_slice(v, (py, px), (k, k))
        )(volp, sy, sx)  # (N, k, k)

        tl = patch[:, 0:win, 0:win]
        tr = patch[:, 0:win, 1:win + 1]
        bl = patch[:, 1:win + 1, 0:win]
        br = patch[:, 1:win + 1, 1:win + 1]
        o = ((1 - fy) * (1 - fx) * tl + (1 - fy) * fx * tr
             + fy * (1 - fx) * bl + fy * fx * br)
        out.append(o.swapaxes(1, 2).reshape(b, h, w, win * win))
    return jnp.concatenate(out, axis=-1)


def bench(name, lookup, cast=lambda x: x):
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (1, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (1, H8, W8, C))

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, RADIUS)
        pyr2 = build_corr_pyramid(f2, f1, 4, RADIUS)
        pyr = pyr.replace(levels=tuple(cast(l) for l in pyr.levels))
        pyr2 = pyr2.replace(levels=tuple(cast(l) for l in pyr2.levels))
        coords = coords_grid(1, H8, W8)

        def body(co, _):
            s = lookup(pyr, co) + lookup(pyr2, co)
            co = co + 0.01 * s.mean(axis=-1, keepdims=True)
            return co, None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    float(run(f1, f2))
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        float(run(f1, f2))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, "
          f"{dt / ITERS * 1e3:6.2f} ms/iter")


def main():
    print(f"platform={jax.devices()[0].platform}", file=sys.stderr)
    t = jax.jit(lambda x: jnp.sum(x))
    float(t(jnp.ones((8, 8))))
    t0 = time.perf_counter()
    for _ in range(3):
        float(t(jnp.ones((8, 8))))
    print(f"       rtt: {(time.perf_counter() - t0) / 3 * 1e3:8.1f} ms")

    bench("matmul", corr_lookup)
    bench("matmul16", corr_lookup,
          cast=lambda l: l.astype(jnp.bfloat16))
    bench_batched("batched", jnp.float32)
    bench_batched("batched16", jnp.bfloat16)


def bench_batched(name, adt):
    """Both streams' lookups through ONE set of einsums: pyramids built
    from batch-2 fmaps (N doubles, matmul count halves); optionally the
    whole lookup in bf16 (one-hot A and volume) with fp32 accumulate."""
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (2, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (2, H8, W8, C))

    from dexiraft_tpu.ops.corr import _axis_interp_matrix

    def lookup(pyr, coords):
        r, b, h, w = pyr.radius, pyr.batch, pyr.ht, pyr.wd
        win = 2 * r + 1
        flat = coords.reshape(b * h * w, 2).astype(jnp.float32)
        out = []
        for i, corr in enumerate(pyr.levels):
            hl, wl = corr.shape[1], corr.shape[2]
            center = flat / (2.0 ** i)
            ax = _axis_interp_matrix(center[:, 0], r, wl).astype(adt)
            ay = _axis_interp_matrix(center[:, 1], r, hl).astype(adt)
            vol = corr[..., 0].astype(adt)
            rows = jnp.einsum("nby,nyx->nbx", ay, vol,
                              preferred_element_type=jnp.float32).astype(adt)
            window = jnp.einsum("nax,nbx->nab", ax, rows,
                                preferred_element_type=jnp.float32)
            out.append(window.reshape(b, h, w, win * win))
        return jnp.concatenate(out, axis=-1).astype(jnp.float32)

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, RADIUS)  # batch-2 = 2 streams
        coords = coords_grid(2, H8, W8)

        def body(co, _):
            s = lookup(pyr, co)
            co = co + 0.01 * s.mean(axis=-1, keepdims=True)
            return co, None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    float(run(f1, f2))
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        float(run(f1, f2))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, "
          f"{dt / ITERS * 1e3:6.2f} ms/iter")


if __name__ == "__main__":
    main()
