"""Component microbenchmark on the real chip — where does the forward go?

Times, with the float-sync pattern (block_until_ready does not reliably
block through the relay tunnel):
  rtt          scalar fetch on a trivial jitted fn (the measurement floor)
  volume       all-pairs matmul + pyramid (x2 streams)
  dexi_b_bf16  the shipped DexiNed prelude (one batched bf16 call)
  enc_x4       4 encoder passes at eval res
  lookup32     32 chained corr_lookup calls (both streams, carry-dependent)
  lkp32_<dt>   the same loop with the pyramid stored fp32/bf16/int8
               (--corr_dtype sweep; each line also reports the estimated
               correlation bytes each lookup streams from HBM — the
               quantization win made legible even on the CPU fallback)
  flash32_<dt> the same chained loop through the flash-blocked kernel
               (ops/pallas_corr.py, ISSUE 12): no materialized volume —
               its bytes column is the O(fmaps) streaming BOUND, vs the
               O(N^2) volume bytes of lkp32. Interpreter-mode
               (debug-speed) on the CPU fallback; with lookup_ab
               --variant 4 the pinned records now cover all three
               formulations (allpairs / per-pixel pallas / flash)
  forward      the full v5 test-mode forward (sanity: ~ sum of the above)
  fwd_iter1    iters=1 forward -> per-iteration + prelude split
  fwd_sp_unr4  candidate config: scan_unroll=4 (XLA software pipelining)

Run:  python scripts/micro_bench.py [--impl allpairs]
                                    [--corr_dtype {fp32,bf16,int8,all}]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

HEIGHT, WIDTH = 440, 1024
ITERS = 32


_RTT = [0.0]


def timeit(name, fn, *args, reps=3, strict=False):
    """fn must return a pytree; it is reduced to ONE device scalar inside
    jit so the sync fetch costs exactly one tunnel round-trip.

    strict=True arms guards.strict_mode around the post-warmup reps (the
    PR 5 steady-state contract): a retrace or implicit transfer inside
    the timed window fails the run instead of deflating the number."""
    reduced = jax.jit(
        lambda *a: jax.tree_util.tree_reduce(
            lambda acc, x: acc + jnp.sum(x).astype(jnp.float32),
            fn(*a), jnp.float32(0)))
    float(jax.device_get(reduced(*args)))  # compile + warmup
    import contextlib

    from dexiraft_tpu.analysis import guards

    ctx = (guards.strict_mode(label=f"micro_bench:{name}") if strict
           else contextlib.nullcontext())
    with ctx:
        t0 = time.perf_counter()
        for _ in range(reps):
            # explicit scalar fetch = the sync (jaxlint JL007)
            float(jax.device_get(reduced(*args)))
        dt = (time.perf_counter() - t0) / reps
    print(f"{name:>11s}: {dt * 1e3:8.1f} ms   (-rtt {max(dt - _RTT[0], 0) * 1e3:8.1f} ms)")
    return dt


def corr_bytes_per_lookup(batch: int, h8: int, w8: int, num_levels: int,
                          corr_dtype: str) -> int:
    """Estimated bytes ONE all-pairs corr_lookup streams from HBM: every
    pyramid level is read once per lookup by the windowing matmuls
    (interp_window is volume-streaming by construction — docs/perf.md).
    Level dims floor-halve exactly like build_corr_pyramid's VALID pool."""
    from dexiraft_tpu.ops.quant import corr_dtype_bytes

    n = batch * h8 * w8
    total = 0
    hl, wl = h8, w8
    for _ in range(num_levels):
        total += n * hl * wl * corr_dtype_bytes(corr_dtype)
        hl, wl = hl // 2, wl // 2
    return total


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--impl", default="allpairs")
    ap.add_argument("--corr_dtype", default="all",
                    choices=["fp32", "bf16", "int8", "all"],
                    help="pyramid storage precision(s) for the lkp32 "
                         "sweep ('all' = sweep the three)")
    ap.add_argument("--corr_sweep_only", action="store_true",
                    help="run rtt + the corr_dtype lookup sweep and exit "
                         "— the CPU-fallback A/B (the full component "
                         "profile costs minutes off-chip)")
    args = ap.parse_args()

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT
    from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
    from dexiraft_tpu.ops.grid import coords_grid

    print(f"platform={jax.devices()[0].platform}", file=sys.stderr)

    # --- RTT floor ---
    _RTT[0] = timeit("rtt", lambda x: x, jnp.ones((8, 8)))

    h8, w8, c = HEIGHT // 8, WIDTH // 8, 256
    kf1, kf2 = jax.random.split(jax.random.PRNGKey(0))
    f1 = jax.random.normal(kf1, (1, h8, w8, c), jnp.float32)
    f2 = jax.random.normal(kf2, (1, h8, w8, c))

    # --- volume build (both streams, all levels) ---
    def volume(f1, f2):
        p1 = build_corr_pyramid(f1, f2, 4, 4)
        p2 = build_corr_pyramid(f2, f1, 4, 4)
        return p1.levels + p2.levels

    if not args.corr_sweep_only:
        timeit("volume", volume, f1, f2)

    # --- pyramid storage-precision sweep (ISSUE 8): 32 chained 2-stream
    # lookups with the volume stored fp32/bf16/int8, timed inside a
    # strict steady-state window (a retrace or implicit transfer FAILS
    # the run), plus the bytes each lookup streams — the quantization
    # lever is bandwidth, so the bytes column is the prediction and the
    # ms column the measurement ---
    dtypes = (("fp32", "bf16", "int8") if args.corr_dtype == "all"
              else (args.corr_dtype,))
    t_by_dtype = {}
    for dt in dtypes:
        def lookup32_q(f1, f2, dt=dt):
            pyr = build_corr_pyramid(f1, f2, 4, 4, dtype=dt)
            pyr2 = build_corr_pyramid(f2, f1, 4, 4, dtype=dt)
            coords = coords_grid(1, h8, w8)

            def body(co, _):
                s = corr_lookup(pyr, co)
                s2 = corr_lookup(pyr2, co)
                co = co + 0.01 * (s.mean(axis=-1, keepdims=True)
                                  + s2.mean(axis=-1, keepdims=True))
                return co, None

            co, _ = jax.lax.scan(body, coords, None, length=ITERS)
            return co

        t_q = timeit(f"lkp32_{dt}", lookup32_q, f1, f2, strict=True)
        t_by_dtype[dt] = t_q
        mb = 2 * corr_bytes_per_lookup(1, h8, w8, 4, dt) / 1e6  # 2 streams
        print(f"  -> {dt}: {mb:8.1f} MB corr bytes/lookup, "
              f"{t_q / ITERS * 1e3:6.1f} ms/iter "
              f"({mb / max(t_q / ITERS, 1e-9) / 1e3:6.2f} GB/s implied)")

    # --- the flash-blocked formulation at the same dtypes (ISSUE 12):
    # fmap2 stays in HBM and streams in row blocks, so the volume bytes
    # above disappear entirely — the printed bound is the whole fmap
    # set, the most a lookup can stream. Off-TPU the kernel runs in
    # interpreter mode (debug-speed; timings prove the path is
    # compile-flat and transfer-clean, nothing more) ---
    import os

    from dexiraft_tpu.ops.quant import corr_dtype_bytes
    from dexiraft_tpu.ops.local_corr import build_local_corr

    if jax.devices()[0].platform != "tpu":
        os.environ.setdefault("DEXIRAFT_PALLAS_INTERPRET", "1")
    for dt in dtypes:
        def flash32_q(f1, f2, dt=dt):
            lc = build_local_corr(f1, f2, 4, 4, dtype=dt, kernel="flash")
            lc2 = build_local_corr(f2, f1, 4, 4, dtype=dt, kernel="flash")
            coords = coords_grid(1, h8, w8)

            def body(co, _):
                s = lc(co)
                s2 = lc2(co)
                co = co + 0.01 * (s.mean(axis=-1, keepdims=True)
                                  + s2.mean(axis=-1, keepdims=True))
                return co, None

            co, _ = jax.lax.scan(body, coords, None, length=ITERS)
            return co

        t_f = timeit(f"flash32_{dt}", flash32_q, f1, f2, strict=True)
        n = h8 * w8
        pyr_cells = sum((n >> (2 * i)) * c for i in range(4))
        # fmap1 is read fp32; the fmap2 pyramid streams in the storage
        # dtype — and only the row blocks the windows touch, so this is
        # an upper bound, not an estimate
        mb = 2 * (n * c * 4 + pyr_cells * corr_dtype_bytes(dt)) / 1e6
        print(f"  -> {dt}: <= {mb:6.1f} MB fmap bytes/lookup "
              f"(O(fmaps) bound — no volume), "
              f"{t_f / ITERS * 1e3:6.1f} ms/iter")
    if args.corr_sweep_only:
        return

    # --- DexiNed + encoders at eval res ---
    # (the historical fp32 two-call "dexined_x2" comparison is gone: its
    # conv_transpose graph at full 440x1024 compiled for >20 min on-chip
    # and timed the whole job out, 2026-08-02 queue run. The shipped
    # config is the batched bf16 call below; the transpose-vs-subpixel
    # A/B lives in prelude_profile.py and the bench 4-config sweep.)
    from dexiraft_tpu.models.dexined import DexiNed

    dimg = jnp.zeros((1, 64, 64, 3), jnp.float32)
    big = jax.random.uniform(jax.random.PRNGKey(3),
                             (1, HEIGHT, WIDTH, 3), jnp.float32, -1, 1)

    # the shipped v5 configuration: ONE batched call, bf16 body
    dexi16 = DexiNed(dtype=jnp.bfloat16, upconv="subpixel")
    dvars16 = jax.jit(lambda r, x: dexi16.init(r, x, train=False))(
        jax.random.PRNGKey(2), dimg)

    def dexined_batched_bf16(a):
        both = jnp.concatenate([a, -a], axis=0)
        return dexi16.apply(dvars16, both, train=False)[-1]

    timeit("dexi_b_bf16", dexined_batched_bf16, big)

    from dexiraft_tpu.models.extractor import Encoder

    enc = Encoder(256, "instance", 0.0, jnp.bfloat16)
    evars = jax.jit(lambda r, x: enc.init(r, x, train=False))(
        jax.random.PRNGKey(4), jnp.zeros((1, 64, 64, 3), jnp.bfloat16))

    def enc4(a):
        x = a.astype(jnp.bfloat16)
        return [enc.apply(evars, x, train=False) for _ in range(4)]

    timeit("enc_x4", enc4, big)

    # --- 32 chained lookups (2 streams): identical to the sweep's fp32
    # leg, so reuse its timing when it ran instead of compiling and
    # measuring the same scan twice ---
    if "fp32" in t_by_dtype:
        t_lookup = t_by_dtype["fp32"]
        print(f"{'lookup32':>11s}: = lkp32_fp32 ({t_lookup * 1e3:8.1f} ms)")
    else:
        @jax.jit
        def lookup32(f1, f2):
            pyr = build_corr_pyramid(f1, f2, 4, 4)
            pyr2 = build_corr_pyramid(f2, f1, 4, 4)
            coords = coords_grid(1, h8, w8)

            def body(carry, _):
                co = carry
                s = corr_lookup(pyr, co)
                s2 = corr_lookup(pyr2, co)
                co = co + 0.01 * (s.mean(axis=-1, keepdims=True)
                                  + s2.mean(axis=-1, keepdims=True))
                return co, None

            co, _ = jax.lax.scan(body, coords, None, length=ITERS)
            return co

        t_lookup = timeit("lookup32", lookup32, f1, f2)

    # --- full forward ---
    from dexiraft_tpu.config import raft_v5

    cfg = raft_v5(mixed_precision=True, corr_impl=args.impl)
    model = RAFT(cfg)
    img = jnp.zeros((1, 64, 64, 3), jnp.float32)
    init = jax.jit(lambda r, a, b: model.init(r, a, b, iters=1, train=False))
    variables = init(jax.random.PRNGKey(0), img, img)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    im1 = jax.random.uniform(k1, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)
    im2 = jax.random.uniform(k2, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)

    @jax.jit
    def fwd(a, b):
        low, up = model.apply(variables, a, b, iters=ITERS, train=False,
                              test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    t_fwd = timeit("forward", fwd, im1, im2)

    # --- prelude: everything before the loop (iters=1 minus 1 lookup) ---
    @jax.jit
    def fwd1(a, b):
        low, up = model.apply(variables, a, b, iters=1, train=False,
                              test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    t_one = timeit("fwd_iter1", fwd1, im1, im2)
    per_iter = (t_fwd - t_one) / (ITERS - 1)
    print(f"  -> per-iteration cost {per_iter * 1e3:6.1f} ms; "
          f"prelude+1 {t_one * 1e3:.1f} ms; "
          f"lookup32/iter {t_lookup / ITERS * 1e3:6.1f} ms")

    # --- candidate shipping config: subpixel upconv (now the default)
    # + 4x unrolled scan (XLA can software-pipeline consecutive
    # refinement iterations) ---
    cfg_u = raft_v5(mixed_precision=True, corr_impl=args.impl,
                    scan_unroll=4)
    model_u = RAFT(cfg_u)

    @jax.jit
    def fwd_u(a, b):
        low, up = model_u.apply(variables, a, b, iters=ITERS, train=False,
                                test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    timeit("fwd_sp_unr4", fwd_u, im1, im2)


if __name__ == "__main__":
    main()
