#!/bin/bash
# Run a CPU-only workload in the background WITHOUT polluting on-chip
# timing: while the TPU queue has a job in flight (its RTT-differenced
# timings are host-sensitive on this 2-core box), the workload is
# SIGSTOPped; it resumes when the chip job finishes. Safe because the
# workload is CPU-only — stopping it cannot wedge the relay tunnel.
#
# Usage: bash scripts/cpu_bg_run.sh <queue_log> <cmd...>

set -u
QLOG=$1; shift
nice -n 19 "$@" &
PID=$!
# never leave the child frozen: if this wrapper dies (TERM/INT/exit)
# while the workload is SIGSTOPped, resume it on the way out
trap 'kill -CONT "$PID" 2>/dev/null' EXIT

queue_busy() {
  [ -f "$QLOG" ] || return 1
  # a queue that died mid-job leaves a dangling 'start' line — only
  # trust it while a queue process is actually alive
  pgrep -f "tpu_queue.sh" >/dev/null || return 1
  local s d
  s=$(grep -n ' start ' "$QLOG" | tail -1 | cut -d: -f1)
  d=$(grep -n ' done ' "$QLOG" | tail -1 | cut -d: -f1)
  [ -n "$s" ] && [ "${d:-0}" -lt "$s" ]
}

stopped=0
while kill -0 "$PID" 2>/dev/null; do
  if queue_busy; then
    [ "$stopped" -eq 0 ] && kill -STOP "$PID" && stopped=1
  else
    [ "$stopped" -eq 1 ] && kill -CONT "$PID" && stopped=0
  fi
  sleep 30
done
wait "$PID"
