"""Training-step throughput on the real chip — async pipeline edition.

Chairs-stage geometry (train_standard.sh: batch 10 crop 368x496 on 2
GPUs -> 5/GPU; here per-chip batch 6, iters 12) for the flagship v5.
The step is driven the way train_cli drives it: batches flow through
the device-side double-buffered prefetcher (data/prefetch.py), the
precision policy and gradient accumulation run inside the one jitted
step, and the persistent XLA compile cache (default logs/xla_cache/)
makes the second launch skip the compile entirely.

Emits ONE JSON record: steps/s, pixel-iters/s (the tokens/s analog:
batch*H*W*iters per second), prefetch-stall time (≈0 after warmup when
the host keeps ahead), whole-step FLOPs + MFU, and compile time (watch
it collapse on the second identical launch).

Compute sharding (`--compute_sharding halo` + `--seq N`): runs the
explicit shard_map spatial partitioning (parallel/halo.py) instead of
the GSPMD gather-fence step — rows shard over the mesh's seq axis with
ppermute halo exchange, params stay fsdp-sharded through compute via
per-block all-gather. The record gains memory_analysis columns
(argument/temp bytes per device) so the fence-vs-halo A/B shows the
activation and peak-params HBM win, and `--mem_only` emits the same
columns as a JSON record without executing. `--remat` selects the
rematerialization policy (none | dots_saveable | per_iter; TrainConfig
.remat) for both step modes.

Usage: python scripts/train_bench.py [--variant v1|v5] [--batch 6]
           [--accum 2] [--precision bf16] [--prefetch 2] [--steps 8]
           [--remat none|dots_saveable|per_iter] [--fsdp 2] [--seq 2]
           [--compute_sharding fence|halo] [--freeze_bn] [--mem_only]
           [--no_compile_cache] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

# --host_devices N must take effect BEFORE jax's backend initializes
# (same dance as scripts/shard_audit.py): it forces N virtual host
# devices so the fsdp A/B runs on a laptop/CI box without a TPU.
for _i, _arg in enumerate(sys.argv):
    if _arg == "--host_devices" and _i + 1 < len(sys.argv):
        _n = sys.argv[_i + 1]
    elif _arg.startswith("--host_devices="):
        _n = _arg.split("=", 1)[1]
    else:
        continue
    _flags = os.environ.get("XLA_FLAGS", "")
    if _n.isdigit() and \
            "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_n}")
    break  # a malformed value falls through to argparse's own refusal

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from dexiraft_tpu.train_cli import fsdp_arg

    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="v5")
    ap.add_argument("--batch", type=int, default=6,
                    help="TOTAL batch per step (= accum * microbatch)")
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--size", type=int, nargs=2, default=(368, 496))
    ap.add_argument("--accum", type=int, default=1,
                    help="gradient-accumulation microbatch count "
                         "(lax.scan inside the jitted step)")
    ap.add_argument("--precision", choices=["fp32", "bf16"], default="fp32",
                    help="bf16 = bf16 compute/activations, fp32 master "
                         "weights and optimizer")
    ap.add_argument("--prefetch", type=int, default=2,
                    help="device-prefetch depth (2 = double buffering; "
                         "0 disables)")
    ap.add_argument("--steps", type=int, default=5,
                    help="timed steady-state steps")
    ap.add_argument("--remat", default="none",
                    choices=["none", "dots_saveable", "per_iter"],
                    help="rematerialization policy (TrainConfig.remat): "
                         "per_iter recomputes each RAFT iteration in the "
                         "backward (the old --remat flag), dots_saveable "
                         "keeps matmul/conv outputs but recomputes "
                         "elementwise chains")
    ap.add_argument("--remat_lookup", action="store_true")
    ap.add_argument("--corr_impl", default="allpairs",
                    choices=["allpairs", "local", "pallas", "flash"])
    ap.add_argument("--corr_dtype", choices=["fp32", "bf16"], default="fp32",
                    help="correlation-pyramid storage precision (int8 is "
                         "inference-only, so not offered here)")
    ap.add_argument("--fused_update", action="store_true",
                    help="fused Pallas lookup+update step kernel "
                         "(requires --corr_impl flash or pallas)")
    ap.add_argument("--compile_cache_dir", default=None,
                    help="persistent XLA cache dir "
                         "(default logs/xla_cache)")
    ap.add_argument("--no_compile_cache", action="store_true",
                    help="skip the persistent compile cache (cold "
                         "compile every launch)")
    ap.add_argument("--mem_only", action="store_true",
                    help="compile-only: print the executable's "
                         "memory_analysis and exit WITHOUT executing. "
                         "This is how the no-remat OOM proof is "
                         "captured — actually running an OOM-bound "
                         "step can wedge the relay tunnel")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (shakeout while the "
                         "tunnel is down; config.update beats the "
                         "axon site-hook pin)")
    ap.add_argument("--fsdp", default=None, type=fsdp_arg,
                    help="shard params + optimizer state over the "
                         "mesh's fsdp axis ('auto' or an integer; see "
                         "train --fsdp). Enables the mesh path: the "
                         "step runs with pinned state shardings and "
                         "the record's state_bytes_per_device shows "
                         "the storage win; 1 = replicated mesh "
                         "baseline for the A/B")
    ap.add_argument("--compute_sharding", default="fence",
                    choices=["fence", "halo"],
                    help="'fence' = GSPMD step with one-shot entry "
                         "all-gather of fsdp params; 'halo' = explicit "
                         "shard_map spatial partitioning over the seq "
                         "axis with per-conv halo exchange and per-block "
                         "param gather (needs --seq >= 2; v1/fp32 only, "
                         "see parallel/halo.check_halo_support)")
    ap.add_argument("--seq", type=int, default=None,
                    help="shard image rows N-way over a mesh 'seq' axis "
                         "(needs an explicit integer --fsdp; use "
                         "--fsdp 1 for seq-only). Height must divide by "
                         "8*N for --compute_sharding halo")
    ap.add_argument("--freeze_bn", action="store_true",
                    help="freeze BatchNorm stats (TrainConfig.freeze_bn "
                         "— post-chairs stages do; required by halo on "
                         "non-small variants)")
    ap.add_argument("--host_devices", type=int, default=None,
                    help="force N virtual host devices (CPU) so the "
                         "fsdp A/B runs without a TPU; must be the "
                         "first jax-visible setting, handled before "
                         "import")
    args = ap.parse_args()
    if args.fused_update and args.corr_impl not in ("pallas", "flash"):
        ap.error("--fused_update requires --corr_impl flash or pallas")
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu import config as C
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.data.prefetch import prefetch_to_device
    from dexiraft_tpu.profiling import ThroughputReport, enable_persistent_cache
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    # --fsdp enables the mesh path: state stored sharded between steps
    # (parallel/layout.state_sharding), gathered inside the step's
    # fences (or per block inside the halo body); --fsdp 1 is the
    # replicated-mesh baseline of the A/B. --seq adds the spatial axis
    # halo compute sharding partitions over.
    mesh = None
    fsdp_live = False
    if args.seq is not None and args.seq > 1:
        from dexiraft_tpu.parallel.layout import LAYOUT, make_mesh_fsdp

        if not isinstance(args.fsdp, int):
            ap.error("--seq needs an explicit integer --fsdp "
                     "(--fsdp 1 for a (data, seq)-shaped budget)")
        budget = len(jax.devices()) // (args.fsdp * args.seq)
        if budget < 1:
            ap.error(f"mesh fsdp={args.fsdp} x seq={args.seq} needs "
                     f"{args.fsdp * args.seq} devices, have "
                     f"{len(jax.devices())} (pass --host_devices N)")
        n_data = max(n for n in range(1, budget + 1)
                     if args.batch % n == 0)
        mesh = make_mesh_fsdp(n_data, args.fsdp, args.seq)
        fsdp_live = LAYOUT.has_fsdp(mesh)
        print(f"mesh: {dict(mesh.shape)}", file=sys.stderr)
    elif args.fsdp is not None:
        from dexiraft_tpu.parallel.layout import LAYOUT, make_train_mesh

        mesh = make_train_mesh(args.batch, fsdp=args.fsdp)
        fsdp_live = LAYOUT.has_fsdp(mesh)
        print(f"mesh: {dict(mesh.shape)}", file=sys.stderr)
    if args.compute_sharding == "halo" and (args.seq or 0) < 2:
        ap.error("--compute_sharding halo needs --seq >= 2 (the halo "
                 "step partitions rows over the mesh's seq axis)")

    cache_dir = None
    if not args.no_compile_cache and fsdp_live:
        # a DESERIALIZED (persistent-cache-hit) executable of the
        # donated fsdp step segfaults this backend on its second call
        # (jax 0.4.37 CPU; bisected in the fsdp PR — cold cache writes
        # and uncached compiles are clean, any warm hit crashes), so
        # fsdp benches run uncached until upstream fixes the cache path
        print("fsdp: persistent compile cache disabled (cache-hit fsdp "
              "executables crash this backend; see docs/perf.md "
              "'Sharded state (fsdp)')", file=sys.stderr)
    elif not args.no_compile_cache:
        cache_dir = enable_persistent_cache(args.compile_cache_dir)
        print(f"compile cache: {cache_dir}", file=sys.stderr)

    # model compute dtype follows the training-policy flag, so the
    # fp32-vs-bf16 A/B compares genuinely different programs (the step
    # forces mixed_precision=True itself when precision=bf16)
    cfg = getattr(C, f"raft_{args.variant}")(
        mixed_precision=args.precision == "bf16",
        remat_lookup=args.remat_lookup, corr_impl=args.corr_impl,
        corr_dtype=args.corr_dtype, fused_update=args.fused_update)
    h, w = args.size
    tc = TrainConfig(name="bench", num_steps=1000, batch_size=args.batch,
                     image_size=(h, w), iters=args.iters, lr=4e-4,
                     precision=args.precision, accum_steps=args.accum,
                     prefetch_depth=args.prefetch, remat=args.remat,
                     freeze_bn=args.freeze_bn)
    print(f"platform={jax.devices()[0].platform} variant={args.variant} "
          f"batch={args.batch} {h}x{w} iters={args.iters} "
          f"precision={args.precision} accum={args.accum} "
          f"prefetch={args.prefetch} remat={args.remat} "
          f"compute_sharding={args.compute_sharding}", file=sys.stderr)

    t0 = time.perf_counter()
    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    if mesh is not None:
        from dexiraft_tpu.parallel.layout import shard_state

        state = shard_state(state, mesh)
    step_fn = make_train_step(cfg, tc, mesh=mesh,
                              compute_sharding=args.compute_sharding)
    init_s = time.perf_counter() - t0
    print(f"init {init_s:.1f}s", file=sys.stderr)

    def mem_fields(compiled_exe):
        """memory_analysis of the per-device compiled module — the HBM
        columns of the record. argument bytes carry the fsdp storage
        win (params arrive sharded), temp bytes carry the halo
        activation win (spatial slabs shard over seq) AND the per-block
        gather win (peak gathered params = one block, not the tree).
        Best-effort: absent on backends without the analysis."""
        try:
            mem = compiled_exe.memory_analysis()
        except Exception as e:
            print(f"memory_analysis unavailable: {e}", file=sys.stderr)
            return {}
        out = {}
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "alias_size_in_bytes",
                     "generated_code_size_in_bytes"):
            v = getattr(mem, attr, None)
            if v is not None:
                out[attr.replace("_size_in_bytes",
                                 "_bytes_per_device")] = int(v)
        total = (out.get("argument_bytes_per_device", 0)
                 + out.get("output_bytes_per_device", 0)
                 + out.get("temp_bytes_per_device", 0)
                 - out.get("alias_bytes_per_device", 0))
        out["hbm_bytes_per_device"] = total
        return out

    def host_batches():
        # a PRE-DECODED pool, cycled: the real Loader hands over batches
        # its worker pool already decoded, so next() is instant — an
        # in-line rng.uniform per yield would charge synchronous numpy
        # time to the "prefetch stall" metric and muddy the acceptance
        # signal (any residual stall must be transfer-side)
        rng = np.random.default_rng(0)
        pool = [{
            "image1": rng.uniform(0, 255, (args.batch, h, w, 3))
            .astype(np.float32),
            "image2": rng.uniform(0, 255, (args.batch, h, w, 3))
            .astype(np.float32),
            "flow": rng.uniform(-5, 5, (args.batch, h, w, 2))
            .astype(np.float32),
            "valid": np.ones((args.batch, h, w), np.float32),
        } for _ in range(max(4, args.prefetch + 2))]
        i = 0
        while True:
            yield pool[i % len(pool)]
            i += 1

    if args.mem_only:
        # compile WITHOUT executing: the memory_analysis of the
        # executable is the OOM proof (requirements vs the chip limit)
        # with no allocation and so no tunnel-wedging OOM crash
        if mesh is not None:
            from dexiraft_tpu.parallel.layout import batch_putter

            batch = batch_putter(mesh)(next(host_batches()))
        else:
            batch = jax.tree.map(jnp.asarray, next(host_batches()))
        t0 = time.perf_counter()
        compiled = step_fn.lower(state, batch).compile()
        print(f"compile-only {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        fields = mem_fields(compiled)
        for k, v in fields.items():
            print(f"{k}: {v / 2**30:.2f} GiB", file=sys.stderr)
        record = {
            "metric": f"train_step_memory@{h}x{w}",
            "platform": jax.devices()[0].platform,
            "variant": args.variant,
            "batch": args.batch,
            "iters": args.iters,
            "precision": args.precision,
            "remat": args.remat,
            "compute_sharding": args.compute_sharding,
            "mesh": dict(mesh.shape) if mesh is not None else None,
            **fields,
        }
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
            if limit:
                record["chip_bytes_limit"] = int(limit)
        except Exception:
            pass
        print(json.dumps(record), flush=True)
        return

    pf = prefetch_to_device(host_batches(), mesh, depth=args.prefetch)

    # split the one-time cost into its phases so the persistent cache's
    # effect is legible: tracing/lowering is Python (never cached), the
    # BACKEND compile is what the cache collapses to a deserialize on
    # the second identical launch. The AOT phase only exists to seed and
    # time the cache — without one, jit's own compile path could not
    # reuse the AOT executable and the backend compile would be paid
    # TWICE, so --no_compile_cache times the combined first call instead
    first = next(pf)
    lower_s = None
    compiled = None
    if cache_dir is not None:
        t0 = time.perf_counter()
        lowered = step_fn.lower(state, first)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        print(f"trace+lower {lower_s:.1f}s, backend compile "
              f"{compile_s:.1f}s (a second identical launch collapses "
              f"the compile via the persistent cache)", file=sys.stderr)

    # warmup step (hits the persistent cache the AOT compile just wrote;
    # uncached mode compiles here, once)
    t0 = time.perf_counter()
    state, metrics = step_fn(state, first)
    # explicit scalar fetch = the sync (block_until_ready unreliable
    # through the relay tunnel; jaxlint JL007)
    float(jax.device_get(metrics["loss"]))
    first_step_s = time.perf_counter() - t0
    if cache_dir is None or compiled is None:
        compile_s = first_step_s  # compile + one step, combined
    print(f"first step (compile included if uncached) {first_step_s:.1f}s",
          file=sys.stderr)

    # steady state: the chips pull already-resident batches; the only
    # host work between dispatches is the async device_put enqueue
    pf.stats.reset()  # exclude warmup/compile from the record
    # steady-state contract (analysis/guards): the warmup step above
    # compiled the ONE donated step, so this loop must be compile-flat
    # and transfer-explicit — a retrace or implicit host transfer FAILS
    # the bench instead of silently deflating steps/s. The prefetcher's
    # puts are explicit device_puts (and thread-local anyway); the one
    # loss fetch below is an explicit device_get — both pass.
    from dexiraft_tpu.analysis import guards

    with guards.strict_mode(label="train_bench"):
        t0 = time.perf_counter()
        for _ in range(args.steps):
            state, metrics = step_fn(state, next(pf))
        # ONE sync at the END: steps overlap transfers (jaxlint JL007)
        float(jax.device_get(metrics["loss"]))
        dt = (time.perf_counter() - t0) / args.steps
    print(f"steady-state {dt * 1e3:.1f} ms/step  "
          f"{1.0 / dt:.2f} steps/s  "
          f"{args.batch * args.iters / dt:.1f} pair-iters/s  "
          f"prefetch: {pf.stats.summary()}")

    # whole-train-step FLOPs from XLA's cost analysis of the compiled
    # executable, and MFU against the chip's bf16 peak (VERDICT r4
    # next-3). The AOT lower().compile() hits the persistent disk
    # cache, not the in-memory jit cache. Never fail the throughput
    # record over accounting.
    flops = peak = None
    try:
        from bench import CHIP_PEAK_BF16_FLOPS, _counted_flops
        flops = _counted_flops(step_fn, state, first)
        kind = getattr(jax.devices()[0], "device_kind", "unknown")
        if jax.devices()[0].platform == "tpu":
            peak = CHIP_PEAK_BF16_FLOPS.get(kind)
    except Exception as e:
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)

    # persistent state footprint per device — params + opt_state as the
    # COMPILED step holds them between steps (its input shardings; the
    # live arrays' own shardings when the AOT executable was skipped).
    # This is the fsdp storage win in the record schema: on an fsdp=N
    # mesh it drops toward 1/N of the replicated figure, and it is
    # exact, not sampled — shard_shape of every leaf.
    def state_bytes_per_device() -> int:
        from jax.tree_util import tree_flatten_with_path

        sh_tree = None
        if compiled is not None:
            try:
                sh_tree = compiled.input_shardings[0][0]
            except Exception:
                sh_tree = None
        flat_state = tree_flatten_with_path(state)[0]
        flat_sh = (tree_flatten_with_path(sh_tree)[0]
                   if sh_tree is not None else None)
        total = 0
        for i, (path, leaf) in enumerate(flat_state):
            if getattr(path[0], "name", None) not in ("params",
                                                      "opt_state"):
                continue
            shape = np.shape(leaf)
            sharding = (flat_sh[i][1] if flat_sh is not None
                        else getattr(leaf, "sharding", None))
            if sharding is not None:
                shape = sharding.shard_shape(tuple(shape))
            total += (int(np.prod(shape, dtype=np.int64))
                      * np.dtype(leaf.dtype).itemsize)
        return total

    report = ThroughputReport(batch=args.batch, height=h, width=w,
                              iters=args.iters)
    record = {
        "metric": f"train_steps_per_sec@{h}x{w}",
        "platform": jax.devices()[0].platform,
        "variant": args.variant,
        "batch": args.batch,
        "iters": args.iters,
        "precision": args.precision,
        "accum_steps": args.accum,
        "prefetch_depth": args.prefetch,
        "remat": args.remat,
        "compute_sharding": args.compute_sharding,
        "loss": round(float(jax.device_get(metrics["loss"])), 6),
        # backend compile when cached (AOT-timed); compile+first-step
        # combined when --no_compile_cache
        "compile_s": round(compile_s, 2),
        **({"trace_lower_s": round(lower_s, 2)} if lower_s is not None
           else {}),
        "compile_cache_dir": cache_dir,
        "prefetch_stall_ms_per_step": round(
            pf.stats.stall_per_batch_s * 1e3, 3),
        "prefetch_stalled_steps": pf.stats.stalls,
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "state_bytes_per_device": state_bytes_per_device(),
        # HBM columns (when the AOT executable exists — the cached
        # path; uncached runs get them from --mem_only instead)
        **(mem_fields(compiled) if compiled is not None else {}),
        **report.fields(dt, flops, peak),
    }
    if flops and peak is None:
        record["mfu"] = None  # no known bf16 peak for this device kind

    # peak HBM: the VERDICT training-record ask is steps/s AND memory
    # headroom at this geometry. memory_stats() is backend-dependent —
    # absent (None / missing keys) on some relay backends, so report
    # best-effort and never fail the measurement over it.
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        hbm = stats.get("peak_bytes_in_use")
        if hbm is not None:
            record["peak_hbm_gib"] = round(hbm / 2**30, 2)
            limit = stats.get("bytes_limit")
            if limit:
                record["hbm_limit_gib"] = round(limit / 2**30, 2)
    except Exception as e:
        print(f"memory_stats unavailable: {e}", file=sys.stderr)

    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
