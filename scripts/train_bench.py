"""Training-step throughput on the real chip.

Chairs-stage geometry (train_standard.sh: batch 10 crop 368x496 on 2
GPUs -> 5/GPU; here per-chip batch 6, iters 12, the mixed-precision
recipe) for the flagship v5. Prints steps/sec and pair-iters/sec
(batch * iters * steps/sec — the training-side throughput analog).

Usage: python scripts/train_bench.py [--variant v1|v5] [--batch 6]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="v5")
    ap.add_argument("--batch", type=int, default=6)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--size", type=int, nargs=2, default=(368, 496))
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--remat_lookup", action="store_true")
    ap.add_argument("--mem_only", action="store_true",
                    help="compile-only: print the executable's "
                         "memory_analysis and exit WITHOUT executing. "
                         "This is how the no-remat OOM proof is "
                         "captured — actually running an OOM-bound "
                         "step can wedge the relay tunnel")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (shakeout while the "
                         "tunnel is down; config.update beats the "
                         "axon site-hook pin)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu import config as C
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg = getattr(C, f"raft_{args.variant}")(
        mixed_precision=True, remat=args.remat,
        remat_lookup=args.remat_lookup)
    h, w = args.size
    tc = TrainConfig(name="bench", num_steps=1000, batch_size=args.batch,
                     image_size=(h, w), iters=args.iters, lr=4e-4)
    print(f"platform={jax.devices()[0].platform} variant={args.variant} "
          f"batch={args.batch} {h}x{w} iters={args.iters}", file=sys.stderr)

    t0 = time.perf_counter()
    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    step_fn = make_train_step(cfg, tc)
    print(f"init {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    rng = np.random.default_rng(0)
    batch = {
        "image1": jnp.asarray(rng.uniform(0, 255, (args.batch, h, w, 3)),
                              jnp.float32),
        "image2": jnp.asarray(rng.uniform(0, 255, (args.batch, h, w, 3)),
                              jnp.float32),
        "flow": jnp.asarray(rng.uniform(-5, 5, (args.batch, h, w, 2)),
                            jnp.float32),
        "valid": jnp.ones((args.batch, h, w), jnp.float32),
    }

    if args.mem_only:
        # compile WITHOUT executing: the memory_analysis of the
        # executable is the OOM proof (requirements vs the chip limit)
        # with no allocation and so no tunnel-wedging OOM crash
        t0 = time.perf_counter()
        compiled = step_fn.lower(state, batch).compile()
        print(f"compile-only {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        try:
            mem = compiled.memory_analysis()
            for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                         "temp_size_in_bytes", "alias_size_in_bytes",
                         "generated_code_size_in_bytes"):
                v = getattr(mem, attr, None)
                if v is not None:
                    print(f"{attr}: {v / 2**30:.2f} GiB")
            total = sum(getattr(mem, a, 0) or 0
                        for a in ("argument_size_in_bytes",
                                  "output_size_in_bytes",
                                  "temp_size_in_bytes"))
            total -= getattr(mem, "alias_size_in_bytes", 0) or 0
            print(f"total (args+out+temp-alias): {total / 2**30:.2f} GiB")
        except Exception as e:
            print(f"memory_analysis unavailable: {e}", file=sys.stderr)
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
            if limit:
                print(f"chip bytes_limit: {limit / 2**30:.2f} GiB")
        except Exception:
            pass
        return

    t0 = time.perf_counter()
    state, metrics = step_fn(state, batch)
    float(metrics["loss"])  # forced host sync (block_until_ready unreliable)
    print(f"compile+step {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        state, metrics = step_fn(state, batch)
        float(metrics["loss"])
    dt = (time.perf_counter() - t0) / reps
    print(f"steady-state {dt * 1e3:.1f} ms/step  "
          f"{1.0 / dt:.2f} steps/s  "
          f"{args.batch * args.iters / dt:.1f} pair-iters/s")

    # whole-train-step FLOPs from XLA's cost analysis of the compiled
    # executable, and MFU against the chip's bf16 peak (VERDICT r4
    # next-3). The AOT lower().compile() hits the persistent disk
    # cache (queue env / bench default), not the in-memory jit cache.
    # Never fail the throughput record over accounting.
    try:
        from bench import CHIP_PEAK_BF16_FLOPS, _counted_flops
        flops = _counted_flops(step_fn, state, batch)
        if flops:
            print(f"train-step FLOPs {flops / 1e12:.3f} TFLOP  "
                  f"({flops / dt / 1e12:.1f} TFLOP/s)")
            kind = getattr(jax.devices()[0], "device_kind", "unknown")
            peak = CHIP_PEAK_BF16_FLOPS.get(kind)
            if peak and jax.devices()[0].platform == "tpu":
                print(f"train-step MFU {flops / dt / peak:.3f} "
                      f"(peak {peak / 1e12:.0f} bf16 TFLOP/s, {kind})")
    except Exception as e:
        print(f"cost_analysis unavailable: {e}", file=sys.stderr)

    # peak HBM: the VERDICT training-record ask is steps/s AND memory
    # headroom at this geometry. memory_stats() is backend-dependent —
    # absent (None / missing keys) on some relay backends, so report
    # best-effort and never fail the measurement over it.
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        if peak is not None:
            gib = peak / 2**30
            lim = f" / {limit / 2**30:.2f} GiB limit" if limit else ""
            print(f"peak HBM {gib:.2f} GiB{lim}")
        else:
            print(f"memory_stats keys: {sorted(stats) or 'unavailable'}",
                  file=sys.stderr)
    except Exception as e:
        print(f"memory_stats unavailable: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
