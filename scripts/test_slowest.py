"""Print the slowest tests from the last recorded tier-1 run.

tests/conftest.py rewrites logs/test_durations.json after every test
(so a session killed at the 870 s tier-1 cap still leaves the completed
prefix). This prints the top offenders — the tests to mark `slow` or
cheapen when the budget guard (DEXIRAFT_TEST_CEILING_S) starts
complaining.

Usage: python scripts/test_slowest.py [-n 10]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=10, help="how many to print")
    args = ap.parse_args()

    path = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                    "logs", "test_durations.json")
    try:
        with open(path) as f:
            durations = json.load(f)
    except (OSError, ValueError) as e:
        print(f"no recorded run ({path}: {e}); run the suite first",
              file=sys.stderr)
        return 1

    ranked = sorted(durations.items(), key=lambda kv: -kv[1])
    total = sum(durations.values())
    print(f"{len(durations)} recorded tests, {total:.1f}s total "
          f"(setup+call+teardown; tier-1 budget 870s); "
          f"top {min(args.n, len(ranked))}:")
    for nodeid, dur in ranked[: args.n]:
        pct = f"{100 * dur / total:4.1f}%" if total > 0 else "   —"
        print(f"  {dur:7.2f}s  {pct}  {nodeid}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
