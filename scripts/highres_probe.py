"""Memory-scalability probe: the on-demand corr path at frame sizes the
materialized volume cannot touch.

At the default 1440x2560 the level-0 all-pairs volume alone would be
(180*320)^2 * 4 B * 2 streams ~ 26.5 GB (over 35 GB with the pyramid) —
past the chip's 15.75 GB HBM before counting activations. The on-demand
path with row chunking bounds the transient to O(chunk * W * H2 * W2)
per level (ops/local_corr.py), the same O(HW) scaling as the reference's
alt_cuda_corr CUDA kernel (SURVEY.md §2.2) — this probe demonstrates
that capability on one chip.

Usage: python scripts/highres_probe.py [--size 1440 2560] [--chunk 8]
       [--iters 8]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, nargs=2, default=(1440, 2560))
    ap.add_argument("--chunk", type=int, default=8,
                    help="query-row chunk for the on-demand path")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon site hook "
                         "pins JAX_PLATFORMS; config.update overrides)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    h, w = args.size
    assert h % 16 == 0 and w % 16 == 0

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT

    platform = jax.devices()[0].platform
    print(f"platform={platform} size={h}x{w} chunk={args.chunk} "
          f"iters={args.iters}", file=sys.stderr)

    vol_bytes = 2 * (h // 8 * w // 8) ** 2 * 4  # level 0 only; pyramid +1/3
    print(f"materialized level-0 volume would need {vol_bytes / 1e9:.1f} GB; "
          f"on-demand transient ~"
          f"{2 * args.chunk * (w // 8) * (h // 8) * (w // 8) * 4 / 1e9:.2f} GB",
          file=sys.stderr)

    cfg = raft_v5(mixed_precision=(platform == "tpu"), corr_impl="local",
                  corr_row_chunk=args.chunk)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    init = jax.jit(lambda r, a, b: model.init(r, a, b, iters=1, train=False))
    variables = jax.block_until_ready(init(rng, small, small))
    print("init done", file=sys.stderr)

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    im1 = jax.random.uniform(k1, (1, h, w, 3), jnp.float32, 0, 255)
    im2 = jax.random.uniform(k2, (1, h, w, 3), jnp.float32, 0, 255)

    @jax.jit
    def fwd(a, b):
        low, up = model.apply(variables, a, b, iters=args.iters,
                              train=False, test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    import math

    t0 = time.perf_counter()
    s = float(fwd(im1, im2))
    print(f"compile+first forward {time.perf_counter() - t0:.1f}s "
          f"(finite={math.isfinite(s)})", file=sys.stderr)
    t0 = time.perf_counter()
    s = float(fwd(im1, im2))
    dt = time.perf_counter() - t0
    print(f"steady-state {dt * 1e3:.1f} ms / forward "
          f"({args.iters} iters at {h}x{w}); finite={math.isfinite(s)}")


if __name__ == "__main__":
    main()
