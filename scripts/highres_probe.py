"""Memory-scalability probe: what killing the materialized correlation
volume unlocks (ISSUE 12).

Three strict-mode experiments, emitted as ONE pinned JSON record (the
PR 8 bench convention: every timed window runs under guards.strict_mode,
so a retrace or implicit transfer FAILS the probe instead of deflating
a number):

  eval A/B    flash-blocked vs allpairs/int8-allpairs at the 440x1024
              eval geometry — steady-state forward ms plus a peak-memory
              column read off ``compiled.memory_analysis()`` (temp +
              argument + output bytes of the ACTUAL executable, not an
              estimate).
  1080p leg   a 1088x1920 (1080p-class) geometry: the flash path's
              compile-time footprint stays O(fmaps) while the allpairs
              level-0 volume alone is ~4.3 GB/stream — past a 15.75 GB
              chip before activations, reported as
              ``allpairs_infeasible_on_chip``.
  chained     warm-start video: K frames chained through one compiled
              step with ``flow_init`` carry — the per-frame executable
              (and therefore the footprint) is identical at every
              sequence length. O(1)-memory video, demonstrated rather
              than asserted.

Off-TPU the Pallas kernels run in interpreter mode (debug-speed): the
ms columns then only prove the paths are compile-flat and
transfer-clean; the MEMORY columns are the record's point and are
platform-independent (XLA buffer assignment of the same program).

Usage:
  python scripts/highres_probe.py                    # full record
  python scripts/highres_probe.py --mode single \
         --impl local --size 1440 2560               # legacy single run
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

EVAL_GEOMETRY = (440, 1024)
HIGHRES_GEOMETRY = (1088, 1920)  # 1080p padded to /8
CHAINED_GEOMETRY = (256, 512)
CHIP_HBM_GB = 15.75  # the single-chip budget the volume blows

# ---- record schema pins (tests/test_zzzflashcorr.py) ---------------------
HIGHRES_RECORD_KEYS = frozenset({
    "metric", "platform", "model", "strict", "iters",
    "eval_geometry", "eval_ab",
    "highres_geometry", "highres",
    "chained",
})
EVAL_LEG_KEYS = frozenset({
    "corr_impl", "corr_dtype", "fused_update", "temp_mb", "peak_mb",
    "forward_ms", "executed",
})
HIGHRES_KEYS = frozenset({
    "flash_temp_mb", "flash_peak_mb", "flash_executed",
    "allpairs_level0_volume_gb", "allpairs_serve_batch_gb",
    "allpairs_infeasible_on_chip", "hbm_gb",
})
SERVE_BATCH = 4  # serve_cli's default --batch_size (the bucket granule)
CHAINED_KEYS = frozenset({
    "geometry", "seq_lens", "per_frame_ms", "per_frame_temp_mb",
    "footprint_flat",
})


def validate_record(rec: dict) -> None:
    """Schema gate — a drifted record fails the probe loudly (the
    bench.validate_record convention)."""
    if set(rec) != HIGHRES_RECORD_KEYS:
        raise ValueError(f"highres record keys drifted: "
                         f"missing {sorted(HIGHRES_RECORD_KEYS - set(rec))}, "
                         f"extra {sorted(set(rec) - HIGHRES_RECORD_KEYS)}")
    for leg in rec["eval_ab"]:
        if set(leg) != EVAL_LEG_KEYS:
            raise ValueError(f"eval_ab leg keys drifted: {sorted(leg)}")
    if set(rec["highres"]) != HIGHRES_KEYS:
        raise ValueError(f"highres keys drifted: {sorted(rec['highres'])}")
    if set(rec["chained"]) != CHAINED_KEYS:
        raise ValueError(f"chained keys drifted: {sorted(rec['chained'])}")


def _log(msg: str) -> None:
    print(f"[highres] {msg}", file=sys.stderr, flush=True)


def _mem(compiled):
    """(temp_mb, peak_mb) off the compiled executable's own buffer
    assignment. peak = temp + argument + output: the resident set the
    executable needs beyond the weights it shares with every config."""
    ma = compiled.memory_analysis()
    temp = float(ma.temp_size_in_bytes)
    peak = temp + float(ma.argument_size_in_bytes) \
        + float(ma.output_size_in_bytes)
    return round(temp / 2**20, 2), round(peak / 2**20, 2)


def _make_model(impl: str, dtype: str, fused: bool):
    from dexiraft_tpu.config import raft_v1
    from dexiraft_tpu.models.raft import RAFT

    # v1 full-size: the real 256-channel correlation load without the
    # DexiNed prelude dominating CPU wall time (the corr subsystem is
    # what this probe measures; bench.py owns the flagship v5 numbers)
    cfg = raft_v1(corr_impl=impl, corr_dtype=dtype, fused_update=fused)
    return RAFT(cfg)


def _init_variables(model):
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    init = jax.jit(lambda r, a, b: model.init(r, a, b, iters=1,
                                              train=False))
    return jax.block_until_ready(init(rng, small, small))


def _frames(h: int, w: int):
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    return (jax.random.uniform(k1, (1, h, w, 3), jnp.float32, 0, 255),
            jax.random.uniform(k2, (1, h, w, 3), jnp.float32, 0, 255))


def eval_ab_legs(iters: int, execute: bool) -> list:
    """The 440x1024 strict A/B: allpairs / int8-allpairs / flash /
    int8-flash, each with its executable's memory columns."""
    from dexiraft_tpu.analysis import guards

    h, w = EVAL_GEOMETRY
    im1, im2 = _frames(h, w)
    legs = []
    for impl, dtype, fused in (("allpairs", "fp32", False),
                               ("allpairs", "int8", False),
                               ("flash", "fp32", True),
                               ("flash", "int8", True)):
        model = _make_model(impl, dtype, fused)
        variables = _init_variables(model)

        @jax.jit
        def fwd(a, b, model=model, variables=variables):
            low, up = model.apply(variables, a, b, iters=iters,
                                  train=False, test_mode=True)
            return jnp.sum(low) + jnp.sum(up)

        compiled = fwd.lower(im1, im2).compile()
        temp_mb, peak_mb = _mem(compiled)
        forward_ms = None
        if execute:
            # execute the AOT executable itself: no second jit compile,
            # so the strict window's zero-recompile budget holds for
            # free and the memory numbers describe what actually ran
            float(jax.device_get(compiled(im1, im2)))  # warmup
            with guards.strict_mode(label=f"highres:{impl}_{dtype}"):
                t0 = time.perf_counter()
                float(jax.device_get(compiled(im1, im2)))
                forward_ms = round((time.perf_counter() - t0) * 1e3, 1)
        legs.append({"corr_impl": impl, "corr_dtype": dtype,
                     "fused_update": fused, "temp_mb": temp_mb,
                     "peak_mb": peak_mb, "forward_ms": forward_ms,
                     "executed": execute})
        _log(f"eval {impl}/{dtype}{'/fused' if fused else ''}: "
             f"temp {temp_mb} MB, peak {peak_mb} MB, "
             f"forward {forward_ms} ms")
    return legs


def highres_leg(iters: int, execute_flash: bool) -> dict:
    """1080p-class geometry: flash compiles (and on TPU runs) with an
    O(fmaps) footprint; the allpairs volume is arithmetic — level 0
    alone busts the chip, no need to compile a program XLA would spend
    minutes on."""
    h, w = HIGHRES_GEOMETRY
    n8 = (h // 8) * (w // 8)
    vol_gb = n8 * n8 * 4 / 1e9  # level-0, one sample/stream, fp32
    # what serving this geometry with allpairs would actually need:
    # the default serve batch x the full pooled pyramid (sum 4^-i over
    # 4 levels = 4/3) — the number that has to fit beside activations
    serve_gb = SERVE_BATCH * vol_gb * 4 / 3
    model = _make_model("flash", "int8", True)
    variables = _init_variables(model)
    im1, im2 = _frames(h, w)

    @jax.jit
    def fwd(a, b):
        low, up = model.apply(variables, a, b, iters=iters,
                              train=False, test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    compiled = fwd.lower(im1, im2).compile()
    temp_mb, peak_mb = _mem(compiled)
    executed = False
    if execute_flash:
        from dexiraft_tpu.analysis import guards

        float(jax.device_get(compiled(im1, im2)))  # warmup
        with guards.strict_mode(label="highres:flash_1080p"):
            float(jax.device_get(compiled(im1, im2)))
        executed = True
    out = {"flash_temp_mb": temp_mb, "flash_peak_mb": peak_mb,
           "flash_executed": executed,
           "allpairs_level0_volume_gb": round(vol_gb, 2),
           "allpairs_serve_batch_gb": round(serve_gb, 2),
           "allpairs_infeasible_on_chip": serve_gb > CHIP_HBM_GB,
           "hbm_gb": CHIP_HBM_GB}
    _log(f"1080p {h}x{w}: flash temp {temp_mb} MB vs allpairs "
         f"{vol_gb:.1f} GB level-0/sample, {serve_gb:.1f} GB at the "
         f"serve batch of {SERVE_BATCH} (chip HBM {CHIP_HBM_GB} GB) — "
         f"infeasible={out['allpairs_infeasible_on_chip']}")
    return out


def chained_leg(iters: int, seq_lens=(2, 4, 8)) -> dict:
    """Warm-start chained frames: ONE compiled step, flow_init carry.
    The executable is identical at every sequence length, so the
    per-frame footprint cannot grow with it — pinned by reading the
    same memory_analysis at each length and timing the frames."""
    from dexiraft_tpu.analysis import guards
    from dexiraft_tpu.eval.interpolate import forward_interpolate

    h, w = CHAINED_GEOMETRY
    model = _make_model("flash", "int8", True)
    variables = _init_variables(model)

    @jax.jit
    def step(a, b, flow_init):
        low, up = model.apply(variables, a, b, iters=iters, train=False,
                              flow_init=flow_init, test_mode=True)
        # the session-store warm start, on-device: splat the low-res
        # flow forward into the next frame's init (serve/sessions.py
        # carry semantics) — the whole video loop is ONE executable
        return forward_interpolate(low[0])[None], jnp.sum(up)

    zero_init = jnp.zeros((1, h // 8, w // 8, 2), jnp.float32)
    im1, _ = _frames(h, w)
    compiled = step.lower(im1, im1, zero_init).compile()
    temp_mb, _ = _mem(compiled)

    per_frame_ms, per_frame_temp = [], []
    for n in seq_lens:
        key = jax.random.PRNGKey(7)
        frames = [jax.random.uniform(jax.random.fold_in(key, i),
                                     (1, h, w, 3), jnp.float32, 0, 255)
                  for i in range(n + 1)]
        flow_init = zero_init
        jax.block_until_ready(compiled(frames[0], frames[1], flow_init))
        with guards.strict_mode(label=f"highres:chained_{n}"):
            t0 = time.perf_counter()
            for i in range(n):
                flow_init, s = compiled(frames[i], frames[i + 1],
                                        flow_init)
            float(jax.device_get(s))
            dt = (time.perf_counter() - t0) / n
        per_frame_ms.append(round(dt * 1e3, 1))
        # same executable at every length => same buffer assignment;
        # read it each time anyway so a drifted recompile cannot hide
        per_frame_temp.append(_mem(compiled)[0])
        _log(f"chained n={n}: {dt * 1e3:.1f} ms/frame, "
             f"step temp {per_frame_temp[-1]} MB")
    flat = len(set(per_frame_temp)) == 1
    return {"geometry": list(CHAINED_GEOMETRY), "seq_lens": list(seq_lens),
            "per_frame_ms": per_frame_ms,
            "per_frame_temp_mb": per_frame_temp, "footprint_flat": flat}


def run_record(args) -> dict:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    if not on_tpu:
        # interpreter-mode kernels off-chip; a big pixel block keeps the
        # interpret grid (traced per step) small at 1080p
        os.environ.setdefault("DEXIRAFT_PALLAS_INTERPRET", "1")
        os.environ.setdefault("DEXIRAFT_FLASH_PIXEL_BLOCK", "2048")
    iters = args.iters if args.iters is not None else (8 if on_tpu else 2)
    _log(f"platform={platform} iters={iters}")
    rec = {
        "metric": "flash_correlation_memory_probe",
        "platform": platform,
        "model": "raft_v1_full",
        "strict": True,
        "iters": iters,
        "eval_geometry": list(EVAL_GEOMETRY),
        "eval_ab": eval_ab_legs(iters, execute=True),
        "highres_geometry": list(HIGHRES_GEOMETRY),
        # 1080p execution is TPU-only: interpreter-mode matmuls at 32k
        # queries are minutes/iteration off-chip, and the leg's point —
        # the footprint — comes from the compile
        "highres": highres_leg(iters, execute_flash=on_tpu),
        "chained": chained_leg(iters),
    }
    validate_record(rec)
    print(json.dumps(rec), flush=True)
    return rec


# ---------------------------------------------------------------------------
# legacy single-run mode (the original probe)
# ---------------------------------------------------------------------------

def run_single(args) -> None:
    h, w = args.size
    assert h % 16 == 0 and w % 16 == 0

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT

    platform = jax.devices()[0].platform
    print(f"platform={platform} size={h}x{w} chunk={args.chunk} "
          f"iters={args.iters} impl={args.impl}", file=sys.stderr)

    vol_bytes = 2 * (h // 8 * w // 8) ** 2 * 4  # level 0 only; pyramid +1/3
    print(f"materialized level-0 volume would need {vol_bytes / 1e9:.1f} GB; "
          f"on-demand transient ~"
          f"{2 * args.chunk * (w // 8) * (h // 8) * (w // 8) * 4 / 1e9:.2f} GB",
          file=sys.stderr)

    if args.impl in ("pallas", "flash") and platform != "tpu":
        # either Pallas impl can only lower off-TPU in interpreter mode
        os.environ.setdefault("DEXIRAFT_PALLAS_INTERPRET", "1")
        os.environ.setdefault("DEXIRAFT_FLASH_PIXEL_BLOCK", "2048")
    cfg = raft_v5(mixed_precision=(platform == "tpu"), corr_impl=args.impl,
                  corr_row_chunk=args.chunk,
                  fused_update=args.impl == "flash")
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    init = jax.jit(lambda r, a, b: model.init(r, a, b, iters=1, train=False))
    variables = jax.block_until_ready(init(rng, small, small))
    print("init done", file=sys.stderr)

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    im1 = jax.random.uniform(k1, (1, h, w, 3), jnp.float32, 0, 255)
    im2 = jax.random.uniform(k2, (1, h, w, 3), jnp.float32, 0, 255)

    @jax.jit
    def fwd(a, b):
        low, up = model.apply(variables, a, b, iters=args.iters,
                              train=False, test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    import math

    t0 = time.perf_counter()
    s = float(jax.device_get(fwd(im1, im2)))
    print(f"compile+first forward {time.perf_counter() - t0:.1f}s "
          f"(finite={math.isfinite(s)})", file=sys.stderr)
    t0 = time.perf_counter()
    s = float(jax.device_get(fwd(im1, im2)))
    dt = time.perf_counter() - t0
    print(f"steady-state {dt * 1e3:.1f} ms / forward "
          f"({args.iters} iters at {h}x{w}); finite={math.isfinite(s)}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="record", choices=["record", "single"],
                    help="record = the pinned strict-mode JSON record "
                         "(eval A/B + 1080p + chained); single = the "
                         "legacy one-geometry probe")
    ap.add_argument("--size", type=int, nargs=2, default=(1440, 2560))
    ap.add_argument("--chunk", type=int, default=8,
                    help="query-row chunk for the on-demand path")
    ap.add_argument("--iters", type=int, default=None,
                    help="refinement iterations (record mode default: "
                         "8 on TPU, 2 on the CPU fallback)")
    ap.add_argument("--impl", default="local",
                    choices=["local", "pallas", "flash", "allpairs"],
                    help="corr path for --mode single")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the axon site hook "
                         "pins JAX_PLATFORMS; config.update overrides)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.mode == "record":
        run_record(args)
    else:
        if args.iters is None:
            args.iters = 8
        run_single(args)


if __name__ == "__main__":
    main()
