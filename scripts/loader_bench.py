"""Host data-pipeline throughput: can the loader keep the chip fed?

The reference trains from a 4-worker torch DataLoader
(core/datasets.py:233-234). Here `data.Loader` decodes and augments in
a thread pool ahead of the step. This benchmark measures the full host
path — PPM/flo decode -> dense augmentor (photometric, eraser, scale/
stretch/flip) -> crop -> batch stack — at the chairs-stage training
recipe (batch 6, crop 368x496, train_standard.sh:3) over a synthetic
FlyingChairs tree at the native 384x512 geometry.

The training step is host-bound only if its on-chip steps/sec exceeds
the batches/sec printed here; the margin is the headroom for scaling
batch or worker count. CPU-only — no TPU required.

Usage: python scripts/loader_bench.py [--pairs 48] [--batches 60]
       [--batch 6] [--workers 1 4 8] [--height 384] [--width 512]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys
import tempfile
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import numpy as np


def build_chairs_tree(root: str, pairs: int, h: int, w: int) -> str:
    """Synthetic FlyingChairs layout: data/NNNNN_img{1,2}.ppm +
    NNNNN_flow.flo + chairs_split.txt (all marked train)."""
    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import write_flo

    data = osp.join(root, "data")
    import os

    os.makedirs(data, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(pairs):
        for k in (1, 2):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            imageio.imwrite(osp.join(data, f"{i:05d}_img{k}.ppm"), img)
        flow = rng.normal(scale=4.0, size=(h, w, 2)).astype(np.float32)
        write_flo(osp.join(data, f"{i:05d}_flow.flo"), flow)
    with open(osp.join(root, "chairs_split.txt"), "w") as f:
        f.write("\n".join(["1"] * pairs))
    return data


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=48)
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--batch", type=int, default=6)
    ap.add_argument("--workers", type=int, nargs="+", default=[1, 4, 8])
    ap.add_argument("--modes", nargs="+", default=["thread", "process"],
                    choices=["thread", "process"])
    ap.add_argument("--height", type=int, default=384)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--crop", type=int, nargs=2, default=None,
                    help="crop size (default: chairs recipe 368x496, "
                    "clamped to the synthetic geometry)")
    args = ap.parse_args()

    from dexiraft_tpu.data.datasets import FlyingChairs
    from dexiraft_tpu.data.loader import Loader

    crop = args.crop or (min(368, args.height - 16), min(496, args.width - 16))

    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        data = build_chairs_tree(tmp, args.pairs, args.height, args.width)
        print(f"[loader_bench] built {args.pairs} synthetic pairs "
              f"({args.height}x{args.width}) in "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

        # chairs-stage augmentation recipe (datasets.py:_fetch_plain)
        aug = dict(crop_size=tuple(crop), min_scale=-0.1, max_scale=1.0,
                   do_flip=True)
        ds = FlyingChairs(aug, split="training", root=data)

        for mode in args.modes:
            for workers in args.workers:
                loader = Loader(ds, args.batch, num_workers=workers,
                                prefetch=2 * workers, worker_mode=mode)
                it = loader.batches()
                for _ in range(5):  # warm the pool + page cache
                    next(it)
                t0 = time.perf_counter()
                nbytes = 0
                for _ in range(args.batches):
                    b = next(it)
                    nbytes += sum(v.nbytes for v in b.values())
                dt = time.perf_counter() - t0
                rate = args.batches / dt
                it.close()
                print(json.dumps({
                    "metric": "loader_batches_per_sec",
                    "value": round(rate, 2),
                    "unit": "batches/s",
                    "imgs_per_sec": round(rate * args.batch * 2, 1),
                    "mb_per_sec": round(nbytes / dt / 1e6, 1),
                    "batch": args.batch,
                    "crop": list(crop),
                    "worker_mode": mode,
                    "num_workers": workers,
                    "pairs": args.pairs,
                }), flush=True)


if __name__ == "__main__":
    main()
