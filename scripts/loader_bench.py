"""Host data-pipeline throughput: can the loader keep the chip fed?

The reference trains from a 4-worker torch DataLoader
(core/datasets.py:233-234). Here `data.Loader` decodes and augments in
a thread pool ahead of the step. This benchmark measures the full host
path — PPM/flo decode -> dense augmentor (photometric, eraser, scale/
stretch/flip) -> crop -> batch stack — at the chairs-stage training
recipe (batch 6, crop 368x496, train_standard.sh:3) over a synthetic
FlyingChairs tree at the native 384x512 geometry.

The training step is host-bound only if its on-chip steps/sec exceeds
the batches/sec printed here; the margin is the headroom for scaling
batch or worker count. CPU-only — no TPU required.

--records switches to the packed-record A/B (docs/data_plane.md): a
synthetic SINTEL tree (PNG frames — the compressed decode that
dominates the real Sintel/Things/KITTI/HD1K stages; chairs' raw-binary
PPM is the one format with near-zero decode cost) is packed once via
data.records.pack_dataset, then the raw-decode Loader and the
RecordLoader run the identical recipe. One JSON record carries both
sides — steady-state samples/s AND the resume-seek latency (time from
`batches(start_epoch=, start_offset=)` to the first batch of a
mid-epoch resume) — so the packed path's win is measured, not asserted.

Usage: python scripts/loader_bench.py [--pairs 48] [--batches 60]
       [--batch 6] [--workers 1 4 8] [--height 384] [--width 512]
       python scripts/loader_bench.py --records [--shards 4] [...]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys
import tempfile
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import numpy as np


def build_chairs_tree(root: str, pairs: int, h: int, w: int) -> str:
    """Synthetic FlyingChairs layout: data/NNNNN_img{1,2}.ppm +
    NNNNN_flow.flo + chairs_split.txt (all marked train)."""
    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import write_flo

    data = osp.join(root, "data")
    import os

    os.makedirs(data, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(pairs):
        for k in (1, 2):
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            imageio.imwrite(osp.join(data, f"{i:05d}_img{k}.ppm"), img)
        flow = rng.normal(scale=4.0, size=(h, w, 2)).astype(np.float32)
        write_flo(osp.join(data, f"{i:05d}_flow.flo"), flow)
    with open(osp.join(root, "chairs_split.txt"), "w") as f:
        f.write("\n".join(["1"] * pairs))
    return data


def build_sintel_tree(root: str, pairs: int, h: int, w: int) -> str:
    """Synthetic Sintel layout: training/clean/scene_0/frame_NNNN.png
    (pairs+1 consecutive frames) + training/flow/scene_0/frame_NNNN.flo."""
    import os

    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import write_flo

    img_dir = osp.join(root, "training", "clean", "scene_0")
    flow_dir = osp.join(root, "training", "flow", "scene_0")
    os.makedirs(img_dir)
    os.makedirs(flow_dir)
    rng = np.random.default_rng(0)
    for i in range(pairs + 1):
        img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        imageio.imwrite(osp.join(img_dir, f"frame_{i:04d}.png"), img)
        if i < pairs:
            write_flo(osp.join(flow_dir, f"frame_{i:04d}.flo"),
                      rng.normal(scale=4.0, size=(h, w, 2))
                      .astype(np.float32))
    return root


# pinned schema of the --records A/B record (tests/test_zzzdata_records.py)
RECORDS_AB_KEYS = ("metric", "raw", "records", "samples_per_sec_speedup",
                   "resume_latency_speedup", "batch", "crop", "pairs",
                   "shards", "num_workers")
RECORDS_SIDE_KEYS = ("samples_per_sec", "batches_per_sec", "mb_per_sec",
                     "resume_latency_s")


def _measure_side(loader, batch: int, batches: int):
    """Steady-state throughput + mid-epoch resume-seek latency for one
    loader (raw or records); fresh iterators so pools start cold-fair."""
    it = loader.batches()
    for _ in range(3):  # warm the pool + page cache
        next(it)
    t0 = time.perf_counter()
    nbytes = 0
    for _ in range(batches):
        nbytes += sum(v.nbytes for v in next(it).values())
    dt = time.perf_counter() - t0
    it.close()

    # resume-seek: position the stream mid-epoch-1 (the exact-resume
    # path train_cli --resume takes) and time to the FIRST batch out —
    # the raw path re-decodes its slice from source files, the record
    # path seeks the shard index; best of 3 to shed scheduler noise
    offset = max(1, len(loader) // 2)
    lat = []
    for _ in range(3):
        t0 = time.perf_counter()
        it = loader.batches(start_epoch=1, start_offset=offset)
        next(it)
        lat.append(time.perf_counter() - t0)
        it.close()
    return {"samples_per_sec": round(batches * batch / dt, 2),
            "batches_per_sec": round(batches / dt, 2),
            "mb_per_sec": round(nbytes / dt / 1e6, 1),
            "resume_latency_s": round(min(lat), 4)}


def run_records_ab(args) -> None:
    """A/B: raw-decode Loader vs packed RecordLoader, one JSON record."""
    from dexiraft_tpu.data.datasets import MpiSintel
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.data.records import (
        RecordLoader,
        pack_dataset,
        verify_records,
    )

    crop = args.crop or (min(368, args.height - 16),
                         min(496, args.width - 16))
    workers = args.workers[0]
    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        build_sintel_tree(tmp, args.pairs, args.height, args.width)
        # sintel-stage augmentation recipe (datasets.py:_fetch_plain)
        aug = dict(crop_size=tuple(crop), min_scale=-0.2, max_scale=0.6,
                   do_flip=True)
        ds = MpiSintel(aug, split="training", root=tmp, dstype="clean")
        records_dir = osp.join(tmp, "records")
        manifest = pack_dataset(ds, records_dir, num_shards=args.shards,
                                stage="sintel", image_size=crop)
        problems = verify_records(records_dir)
        if problems:
            raise SystemExit(f"pack verify failed: {problems}")
        print(f"[loader_bench] packed {manifest.num_records} records "
              f"({sum(s.bytes for s in manifest.shards) / 1e6:.1f} MB, "
              f"{len(manifest.shards)} shards) in "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

        sides = {}
        for name, loader in [
            ("raw", Loader(ds, args.batch, seed=7, num_workers=workers,
                           prefetch=2 * workers)),
            ("records", RecordLoader(records_dir, args.batch, seed=7,
                                     num_workers=workers,
                                     prefetch=2 * workers)),
        ]:
            sides[name] = _measure_side(loader, args.batch, args.batches)

        rec = {
            "metric": "records_ab",
            "raw": sides["raw"],
            "records": sides["records"],
            "samples_per_sec_speedup": round(
                sides["records"]["samples_per_sec"]
                / sides["raw"]["samples_per_sec"], 2),
            "resume_latency_speedup": round(
                sides["raw"]["resume_latency_s"]
                / max(sides["records"]["resume_latency_s"], 1e-9), 2),
            "batch": args.batch,
            "crop": list(crop),
            "pairs": args.pairs,
            "shards": len(manifest.shards),
            "num_workers": workers,
        }
        assert tuple(rec) == RECORDS_AB_KEYS
        print(json.dumps(rec), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=48)
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--batch", type=int, default=6)
    ap.add_argument("--workers", type=int, nargs="+", default=[1, 4, 8])
    ap.add_argument("--modes", nargs="+", default=["thread", "process"],
                    choices=["thread", "process"])
    ap.add_argument("--height", type=int, default=384)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--crop", type=int, nargs=2, default=None,
                    help="crop size (default: chairs recipe 368x496, "
                    "clamped to the synthetic geometry)")
    ap.add_argument("--records", action="store_true",
                    help="A/B the packed-record plane against raw decode "
                         "(samples/s + resume-seek latency, one JSON "
                         "record; uses the FIRST --workers value)")
    ap.add_argument("--shards", type=int, default=4,
                    help="--records: shard-file count for the pack")
    args = ap.parse_args()

    if args.records:
        run_records_ab(args)
        return

    from dexiraft_tpu.data.datasets import FlyingChairs
    from dexiraft_tpu.data.loader import Loader

    crop = args.crop or (min(368, args.height - 16), min(496, args.width - 16))

    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.perf_counter()
        data = build_chairs_tree(tmp, args.pairs, args.height, args.width)
        print(f"[loader_bench] built {args.pairs} synthetic pairs "
              f"({args.height}x{args.width}) in "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)

        # chairs-stage augmentation recipe (datasets.py:_fetch_plain)
        aug = dict(crop_size=tuple(crop), min_scale=-0.1, max_scale=1.0,
                   do_flip=True)
        ds = FlyingChairs(aug, split="training", root=data)

        for mode in args.modes:
            for workers in args.workers:
                loader = Loader(ds, args.batch, num_workers=workers,
                                prefetch=2 * workers, worker_mode=mode)
                it = loader.batches()
                for _ in range(5):  # warm the pool + page cache
                    next(it)
                t0 = time.perf_counter()
                nbytes = 0
                for _ in range(args.batches):
                    b = next(it)
                    nbytes += sum(v.nbytes for v in b.values())
                dt = time.perf_counter() - t0
                rate = args.batches / dt
                it.close()
                print(json.dumps({
                    "metric": "loader_batches_per_sec",
                    "value": round(rate, 2),
                    "unit": "batches/s",
                    "imgs_per_sec": round(rate * args.batch * 2, 1),
                    "mb_per_sec": round(nbytes / dt / 1e6, 1),
                    "batch": args.batch,
                    "crop": list(crop),
                    "worker_mode": mode,
                    "num_workers": workers,
                    "pairs": args.pairs,
                }), flush=True)


if __name__ == "__main__":
    main()
