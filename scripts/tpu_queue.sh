#!/bin/bash
# Waits for the TPU relay tunnel to heal, then runs the queued on-chip
# measurements sequentially (one TPU process at a time — see
# .claude/skills/verify/SKILL.md). Each step gets a hard timeout so a
# re-wedged tunnel cannot hold the queue forever.
#
# Job list = VERDICT round-2 priorities, in order: the official bench
# record, micro numbers, Pallas on-chip smoke, flagship training
# throughput, the memory-story probes, and the convergence demos.
#
# Touch $OUT/pause to hold the queue between jobs (frees the chip for
# interactive work); rm it to resume. A job that exited 0 in a previous
# queue run leaves $OUT/<name>.done and is skipped (idempotent restart).
#
# Usage: bash scripts/tpu_queue.sh /tmp/tpu_queue   (output dir)

set -u
# resolve OUT against the CALLER's cwd, creating it first (readlink -f
# needs the parents to exist), so redirections survive the cd below
mkdir -p "${1:-/tmp/tpu_queue}"
OUT=$(readlink -f "${1:-/tmp/tpu_queue}")
cd "$(dirname "$0")/.."

# persistent XLA compilation cache: the tunnel sometimes heals only in
# short windows — compiles paid in one window must survive to the next
# attempt (a cold full-geometry bench is ~15-20 min of mostly compile).
# Harmless if the backend declines to serialize (soft cache miss).
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=2

probe() {
  # healthy means the REAL TPU backend answers — a CPU fallback must not
  # count, or the queued "on-chip" numbers would silently be CPU numbers
  timeout 360 python - <<'EOF' >/dev/null 2>&1
import os, threading, sys
threading.Timer(330, lambda: os._exit(3)).start()
import jax, jax.numpy as jnp
if jax.devices()[0].platform == "cpu":
    os._exit(4)
float(jax.jit(lambda x: jnp.sum(x))(jnp.ones((2, 2))))
os._exit(0)
EOF
}

wait_for_tunnel() {
  echo "$(date -u +%H:%M:%S) waiting for tunnel" >> "$OUT/queue.log"
  until probe; do
    echo "$(date -u +%H:%M:%S) tunnel still down" >> "$OUT/queue.log"
    sleep 300
  done
  echo "$(date -u +%H:%M:%S) tunnel up" >> "$OUT/queue.log"
}

wait_for_tunnel

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  # the completeness sweep below derives the job list from the run()
  # calls themselves — one source of truth, nothing to keep in sync
  JOBS_SEEN="$JOBS_SEEN $name"
  if [ -f "$OUT/$name.done" ]; then
    echo "$(date -u +%H:%M:%S) skip $name (done)" >> "$OUT/queue.log"
    return
  fi
  # the relay has died mid-queue before (2026-07-31, mid-bench): without
  # this re-probe every remaining job would hang to its full timeout in
  # sequence against a dead endpoint — hours of nothing. Re-check the
  # tunnel before EACH job and fall back to the 5-min wait loop if gone.
  # A wait_for_tunnel can last hours, so re-check pause after it; its
  # own successful probe stands — don't pay a second probe unless the
  # pause file appeared in the meantime.
  while :; do
    while [ -f "$OUT/pause" ]; do sleep 60; done
    probe && break
    echo "$(date -u +%H:%M:%S) tunnel lost before $name; re-waiting" >> "$OUT/queue.log"
    wait_for_tunnel
    [ -f "$OUT/pause" ] || break
  done
  echo "$(date -u +%H:%M:%S) start $name" >> "$OUT/queue.log"
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  # a CPU-fallback bench exits 0 but is NOT the on-chip record this job
  # exists to capture — never .done-mark it, so a queue restart retries
  if [ "$rc" -eq 0 ] && grep -q '"fallback": true' "$OUT/$name.log"; then
    rc=9
  fi
  [ "$rc" -eq 0 ] && touch "$OUT/$name.done"
  echo "$(date -u +%H:%M:%S) done $name rc=$rc" >> "$OUT/queue.log"
  sleep 30  # let the claim settle between holders
}

run_all() {
  # Round-5 list, VERDICT r4 priority order. Jobs sized to ~<=10 min
  # where the cache allows, so a short heal window lands several (r4's
  # 68-min window fit only 2.5 jobs); the 3.2 GB persistent XLA cache
  # makes most re-runs compile-free. The 1200s jobs are the ones with
  # possibly-cold compiles (bench sweep, train-step graphs, long demo).
  # 1. the official metric JSON (VERDICT next-1); warm cache -> fast.
  #    Also keeps the cache hot for the driver's own end-of-round run.
  #    BENCH_HARD_CAP_S + the ~5-min CPU-fallback child < the outer
  #    timeout, so bench's own watchdog — which gets the JSON record
  #    out and falls back cleanly — ends a stuck run, never this
  #    timeout's SIGTERM (cap 850 + fallback ~300 < 1200).
  run bench_record  1200 env BENCH_HARD_CAP_S=850 python bench.py
  # 2. flagship v5 training at chairs geometry (next-2): steps/s + HBM
  #    for the two remat options, plus the no-remat proof as a
  #    compile-only memory_analysis (running it for real would OOM and
  #    can wedge the relay tunnel for the rest of the queue)
  run train_remat_lookup 1200 python scripts/train_bench.py --variant v5 --batch 6 --remat_lookup
  run train_remat   1200 python scripts/train_bench.py --variant v5 --batch 6 --remat per_iter
  run train_noremat 600  python scripts/train_bench.py --variant v5 --batch 6 --mem_only
  # 3. Pallas kernel on real hardware: compile + parity + sweep (next-5)
  run tpu_smoke     900 python scripts/tpu_smoke.py
  # 4. memory-story probes (next-6)
  run highres       900 python scripts/highres_probe.py --iters 8
  run warmstart     900 python scripts/warmstart_bench.py --frames 8
  # 5. on-chip xplane trace for the prelude hunt (next-4: real trace,
  #    not RTT-differenced timings)
  run profile_trace 900 python scripts/profile_trace.py
  # 6. component-level forward numbers (r4 rc=124 fixed: dexined_x2
  #    config removed; warm cache)
  run micro_bench   900 python scripts/micro_bench.py
  # 7. adaptive-iteration serving frontier (PR 18): EPE-vs-latency +
  #    overload goodput at the flagship geometry. serve_bench's own
  #    watchdog (hard cap 850) ends a stuck run before this timeout.
  run serve_adaptive 1200 env SERVE_BENCH_HARD_CAP_S=850 python scripts/serve_bench.py --adaptive --variant v5 --iters 8 --size 440x1024 --frames 8 --batch 4 --requests 32 --concurrency 8
  # 8. accuracy evidence at 10x pool (next-7): on-chip long demos for
  #    v1-small AND the v5 flagship (42 steps/s on chip at this
  #    geometry -> compute is minutes; ckpt_dir so a mid-run tunnel
  #    death resumes instead of restarting) + edge
  run v1_demo_big   1200 python scripts/train_demo.py --variant small --steps 5000 --batch 4 --size 192 256 --pool 80 --heldout_every 1000 --ckpt_dir logs/v1_demo_r5_ckpt --log logs/v1_demo_r5.log
  run v5_demo_big   1200 python scripts/train_demo.py --variant v5 --steps 3000 --batch 2 --size 192 256 --pool 80 --heldout_every 500 --ckpt_dir logs/v5_demo_r5_ckpt --log logs/v5_demo_r5.log
  run dexined_demo  900 python scripts/dexined_demo.py --steps 300
}

# a mid-list tunnel death fails the remaining jobs; don't declare the
# queue complete with holes — sweep the list again (run() skips .done
# jobs) until everything landed or the retry budget is spent
for attempt in 1 2 3; do
  JOBS_SEEN=""
  run_all
  missing=""
  for j in $JOBS_SEEN; do
    [ -f "$OUT/$j.done" ] || missing="$missing $j"
  done
  if [ -z "$missing" ]; then
    echo "$(date -u +%H:%M:%S) queue complete (attempt $attempt)" >> "$OUT/queue.log"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) attempt $attempt missing:$missing" >> "$OUT/queue.log"
  sleep 120
done
echo "$(date -u +%H:%M:%S) queue gave up; missing:$missing" >> "$OUT/queue.log"
exit 1
