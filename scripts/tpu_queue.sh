#!/bin/bash
# Waits for the TPU relay tunnel to heal, then runs the queued on-chip
# measurements sequentially (one TPU process at a time — see
# .claude/skills/verify/SKILL.md). Each step gets a hard timeout so a
# re-wedged tunnel cannot hold the queue forever.
#
# Usage: bash scripts/tpu_queue.sh /tmp/tpu_queue   (output dir)

set -u
# resolve OUT against the CALLER's cwd, creating it first (readlink -f
# needs the parents to exist), so redirections survive the cd below
mkdir -p "${1:-/tmp/tpu_queue}"
OUT=$(readlink -f "${1:-/tmp/tpu_queue}")
cd "$(dirname "$0")/.."

probe() {
  # healthy means the REAL TPU backend answers — a CPU fallback must not
  # count, or the queued "on-chip" numbers would silently be CPU numbers
  timeout 360 python - <<'EOF' >/dev/null 2>&1
import os, threading, sys
threading.Timer(330, lambda: os._exit(3)).start()
import jax, jax.numpy as jnp
if jax.devices()[0].platform == "cpu":
    os._exit(4)
float(jax.jit(lambda x: jnp.sum(x))(jnp.ones((2, 2))))
os._exit(0)
EOF
}

echo "$(date -u +%H:%M:%S) waiting for tunnel" >> "$OUT/queue.log"
until probe; do
  echo "$(date -u +%H:%M:%S) tunnel still down" >> "$OUT/queue.log"
  sleep 300
done
echo "$(date -u +%H:%M:%S) tunnel up; running queue" >> "$OUT/queue.log"

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "$(date -u +%H:%M:%S) start $name" >> "$OUT/queue.log"
  timeout "$tmo" "$@" > "$OUT/$name.log" 2>&1
  echo "$(date -u +%H:%M:%S) done $name rc=$?" >> "$OUT/queue.log"
  sleep 30  # let the claim settle between holders
}

run micro_bench   1500 python scripts/micro_bench.py
run train_remat_lookup 3000 python scripts/train_bench.py --variant v5 --batch 6 --remat_lookup
run train_remat   3000 python scripts/train_bench.py --variant v5 --batch 6 --remat
run highres       2400 python scripts/highres_probe.py --iters 8
run dexined_demo  2400 python scripts/dexined_demo.py --steps 300
run warmstart     2400 python scripts/warmstart_bench.py --frames 8
echo "$(date -u +%H:%M:%S) queue complete" >> "$OUT/queue.log"
