"""Serving throughput — the eval-side analog of scripts/train_bench.py.

Three modes, one watchdogged script:

**Engine mode** (default): drives ONE mixed-geometry frame-pair stream
through the throughput-mode inference engine (dexiraft_tpu.serve) at
batch_size=1 (the reference per-image behavior) and at --batch, same
jitted eval step, and emits ONE JSON record: frame-pairs/s per config,
p50/p99 batch latency, bucket hit/compile counts (the mixed stream must
compile EXACTLY once per bucket), peak in-flight depth, fetch-blocked
time, and FLOPs/MFU from XLA's cost analysis. The speedup field is the
acceptance signal: batched throughput over the batch-1 configuration of
the same run.

**Closed-loop mode** (``--closed_loop``): a load generator against the
REAL service (serve.server.FlowService over HTTP on loopback — request
queue, SLO batching, sessions; the SERVE_r0* service record). Phases:
  1. sequential baseline — a batch_size=1 service under closed-loop
     load (each client waits for its response before sending the next),
  2. goodput-vs-concurrency — the batched service at >= 2 closed-loop
     concurrency levels, client-measured p50/p99 per level,
  3. overload — OPEN arrivals at ``--overload_factor`` x the measured
     batched goodput: admission control must shed with 503s while
     goodput holds near capacity instead of collapsing,
  4. session warm-start — a static synthetic stream posted K times
     under one ``X-Session-Id``: chained carry approximates a K*iters
     refinement, so the last warm response must sit measurably closer
     to a K*iters reference than the cold single-request response does
     (the service-side proof of the scripts/warmstart_bench.py win).
The acceptance signals: ``speedup_batched_over_sequential > 1`` and
``warm_start.warm_beats_cold``.

**Fleet mode** (``--fleet N``): spawns N ``--synthetic_init`` serve
replica PROCESSES and drives the router (serve/router.py) over them:
  1. goodput-vs-replica-count scaling curve (router re-pooled at each
     k in 1..N, session clients — affinity hit rate per level),
  2. kill-a-replica-under-load: SIGKILL one replica mid-traffic, then
     measure breaker-detection latency, client-visible recovery gap,
     failover retries, sticky-miss remaps, and the zero-drop check
     (``kill.zero_dropped``: no client saw a non-200 — router failover
     plus the client's connection-refused retry absorb the death).
The bench process itself never imports jax: replicas own the devices.
Record schema pinned by FLEET_RECORD_KEYS / tests/test_zzfleet_router.

**Adaptive mode** (``--adaptive``): the convergence-gated early-exit
engine (ServeConfig(adaptive=True) over make_eval_step(adaptive=True))
against the fixed-iteration engine with the SAME weights — the
synthetic-init contraction fixture (FlowHead_0 damped x0.01, see
docs/perf.md). Three phases: (1) quality/iters — per-pair EPE between
adaptive and fixed flows plus iters_used stats (the early-exit win must
not move the answer), (2) latency — per-pair wall time both legs,
(3) overload — OPEN arrivals at the same offered rate against BOTH
services: the adaptive scheduler must degrade iteration budgets
(iter_budget_p50 < max_iters) while goodput holds (ratio ~>= 1).
Record schema pinned by ADAPTIVE_RECORD_KEYS / tests/test_zzzadaptive.

Watchdog (the bench.py pattern, tests/test_bench_watchdog.py /
tests/test_zserve_bench.py): the measurement runs in a CHILD process;
the parent kills it when it goes silent past SERVE_BENCH_STALL_S or
overruns SERVE_BENCH_HARD_CAP_S and exits 8 — a relay-tunnel death must
never hang the driver's round-end run. SERVE_BENCH_FAKE_HANG=1 swaps in
a child that blocks forever (watchdog tests). The parent imports no jax.

Usage: python scripts/serve_bench.py [--variant v1] [--small]
           [--batch 4] [--iters 4] [--sizes 40x56,44x60,36x52]
           [--frames 16] [--bucket_multiple 16] [--inflight 2]
           [--data_parallel 0] [--cpu] [--no_compile_cache]
       python scripts/serve_bench.py --closed_loop [--size 96x128]
           [--requests 32] [--concurrency 4] [--slo_ms 150]
           [--overload_factor 4] [--warm_frames 4] [--cpu]
       python scripts/serve_bench.py --fleet 2 [--size 64x96]
           [--requests 48] [--concurrency 4] [--iters 2] [--cpu]
       python scripts/serve_bench.py --adaptive [--size 96x128]
           [--iters 32] [--min_iters 4] [--converge_tol 0.02] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import subprocess
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

STALL_S = 600.0
HARD_CAP_S = 1500.0

RECORD_KEYS = {  # pinned by tests/test_zserve_bench.py
    "metric", "platform", "variant", "iters", "sizes", "frames",
    "bucket_multiple", "configs", "speedup_batched_over_b1",
    "corr_impl_resolved",
}
CONFIG_KEYS = {
    "batch_size", "inflight", "frame_pairs_per_sec", "latency_p50_ms",
    "latency_p99_ms", "bucket_count", "compiles", "buckets",
    "peak_inflight", "fetch_blocked_ms", "pad_frames", "compile_s",
    "flops_per_pair", "tflops_per_sec", "mfu",
}

# ---- closed-loop (service) record schema, pinned by
# tests/test_zzserve_service.py ------------------------------------------
CLOSED_LOOP_RECORD_KEYS = {
    "metric", "platform", "variant", "iters", "size", "batch", "slo_ms",
    "max_queue", "sequential", "levels", "overload", "warm_start",
    "speedup_batched_over_sequential", "corr_impl_resolved",
}
LEVEL_KEYS = {
    "concurrency", "requests", "goodput_rps", "p50_ms", "p99_ms",
    "rejected", "errors", "client_retries", "dispatch_full",
    "dispatch_slo", "mean_batch_fill", "queue_peak",
}

# ---- fleet (router) record schema, pinned by
# tests/test_zzfleet_router.py --------------------------------------------
FLEET_RECORD_KEYS = {
    "metric", "platform", "variant", "iters", "size", "batch", "slo_ms",
    "max_queue", "replicas", "concurrency", "requests", "scaling",
    "kill", "goodput_scaling", "corr_impl_resolved",
}
FLEET_SCALING_KEYS = {
    "replicas", "concurrency", "requests", "goodput_rps", "p50_ms",
    "p99_ms", "errors", "client_retries", "router_retries", "failovers",
    "affinity_hit_rate",
}
FLEET_KILL_KEYS = {
    "killed", "requests", "completed", "errors", "client_retries",
    "detect_s", "recovery_s", "max_gap_s", "router_retries", "failovers",
    "sticky_misses", "affinity_hit_rate_before", "affinity_hit_rate_after",
    "zero_dropped",
}
OVERLOAD_KEYS = {
    "offered_rps", "duration_s", "completed", "rejected", "errors",
    "goodput_rps", "p99_ms",
}
WARM_KEYS = {
    "frames", "iters", "iters_ref", "warm_dist", "cold_dist",
    "warm_beats_cold",
}

# ---- adaptive-iteration record schema, pinned by
# tests/test_zzzadaptive.py -----------------------------------------------
ADAPTIVE_RECORD_KEYS = {
    "metric", "platform", "variant", "iters", "size", "frames", "batch",
    "slo_ms", "max_queue", "converge_tol", "min_iters",
    "corr_impl_resolved",
    "epe_vs_fixed_px", "mean_iters_used", "p99_iters_used",
    "iters_drop_pct", "mean_final_delta",
    "fixed_ms_per_pair", "adaptive_ms_per_pair",
    "overload_fixed", "overload_adaptive", "overload_goodput_ratio",
}
# the adaptive overload entry carries the fixed OVERLOAD_KEYS plus the
# degradation evidence: what budgets the scheduler actually granted and
# how many iterations the while_loop actually ran
ADAPTIVE_OVERLOAD_KEYS = OVERLOAD_KEYS | {
    "iter_budget_p50", "iter_budget_p99", "iters_used_mean",
}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="v5")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--batch", type=int, default=4,
                    help="the batched configuration's micro-batch size")
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--sizes", default="440x1024,436x1020,432x1016",
                    help="comma-separated HxW geometries, cycled over "
                         "the stream (mixed-geometry bucket proof)")
    ap.add_argument("--frames", type=int, default=12,
                    help="frame pairs in the stream")
    ap.add_argument("--bucket_multiple", type=int, default=64,
                    help="bucket quantization granule (multiple of 8)")
    ap.add_argument("--inflight", type=int, default=2)
    ap.add_argument("--data_parallel", type=int, default=0,
                    help="shard each batch over this many chips (0 = one)")
    ap.add_argument("--compile_cache_dir", default=None)
    ap.add_argument("--no_compile_cache", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (config.update beats the "
                         "axon site-hook pin)")
    ap.add_argument("--corr_impl", default="auto",
                    choices=["auto", "allpairs", "local", "pallas",
                             "flash"],
                    help="'auto' (default) = the production config: "
                         "flash-blocked fused step on TPU, allpairs "
                         "off-chip; the RESOLVED value is stamped into "
                         "every record as corr_impl_resolved so A/Bs "
                         "are self-describing")
    ap.add_argument("--fused_update", action="store_true",
                    help="fused Pallas lookup+update kernel (requires "
                         "--corr_impl flash or pallas)")
    # ---- closed-loop (service) mode ------------------------------------
    ap.add_argument("--closed_loop", action="store_true",
                    help="load-generate against the real FlowService over "
                         "HTTP instead of driving the engine directly")
    ap.add_argument("--size", default="96x128",
                    help="closed-loop frame geometry HxW (one bucket: the "
                         "service phases measure scheduling, not bucket "
                         "spread — engine mode covers that)")
    ap.add_argument("--requests", type=int, default=32,
                    help="closed-loop requests per concurrency level")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="highest closed-loop client count (levels are "
                         "1 and this)")
    ap.add_argument("--slo_ms", type=float, default=150.0,
                    help="service latency budget (scheduler hold window)")
    ap.add_argument("--max_queue", type=int, default=64,
                    help="service admission bound (503 past it)")
    ap.add_argument("--overload_factor", type=float, default=4.0,
                    help="open-arrival offered rate as a multiple of the "
                         "measured batched goodput")
    ap.add_argument("--overload_duration_s", type=float, default=3.0)
    ap.add_argument("--warm_frames", type=int, default=4,
                    help="frames chained through one session for the "
                         "warm-start convergence check")
    # ---- adaptive-iteration mode ---------------------------------------
    ap.add_argument("--adaptive", action="store_true",
                    help="adaptive-iteration leg: convergence-gated "
                         "early-exit engine vs the fixed-iters engine on "
                         "the damped contraction fixture — EPE delta, "
                         "iters_used, latency, overload goodput with "
                         "degraded budgets")
    ap.add_argument("--converge_tol", type=float, default=None,
                    help="override RAFTConfig.converge_tol for the "
                         "adaptive leg (default: the config's)")
    ap.add_argument("--min_iters", type=int, default=4,
                    help="adaptive scheduler budget floor (clamped to "
                         "--iters)")
    # ---- fleet (router) mode -------------------------------------------
    ap.add_argument("--fleet", type=int, default=0,
                    help="spawn this many --synthetic_init serve replica "
                         "processes and bench the router over them: "
                         "goodput-vs-replica-count scaling, kill-a-"
                         "replica recovery, session-affinity hit rate")
    ap.add_argument("--boot_timeout_s", type=float, default=600.0,
                    help="fleet replica boot bound (restore + warmup "
                         "compile)")
    return ap


def _build_eval_fn(args, iters=None, adaptive=False, damp_flow_head=None):
    """Model + jitted eval step + engine-contract eval_fn — shared by
    the engine-mode measurement and the closed-loop service phases.
    Returns (eval_fn, mesh, step, variables).

    adaptive=True builds the convergence-gated while_loop step
    (make_eval_step(adaptive=True)); the eval_fn then takes a trailing
    iter_budget (None -> the full configured iters, normalized to ONE
    np.int32 aval so every budget rides the bucket's single executable)
    and returns the 4-tuple (flow_low, flow_up, iters_used, final_delta).

    damp_flow_head scales every FlowHead_0 param leaf (the contraction
    fixture, docs/perf.md: random-init updates do not contract, damping
    the flow head's output gives the convergence plateau a trained model
    has — the adaptive leg needs weights that actually converge).
    Identical PRNGKey(0) init means two calls hand back identical
    weights, so a fixed/adaptive A/B shares one set of parameters."""
    import jax

    from dexiraft_tpu import config as C
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.profiling import enable_persistent_cache
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_eval_step

    if not args.no_compile_cache:
        cache_dir = enable_persistent_cache(args.compile_cache_dir)
        print(f"compile cache: {cache_dir}", file=sys.stderr)

    # resolve --corr_impl (default "auto" -> the platform's production
    # config) and remember the resolution for the record stamp — the
    # eval/serve CLIs print it, the records carry it (corr_impl_resolved)
    impl, fused = C.resolve_corr_impl_args(
        args, jax.devices()[0].platform, "serve_bench")
    args.corr_impl_resolved = impl
    cfg = getattr(C, f"raft_{args.variant}")(small=args.small,
                                             corr_impl=impl,
                                             fused_update=fused)
    if getattr(args, "converge_tol", None) is not None:
        import dataclasses

        cfg = dataclasses.replace(cfg, converge_tol=args.converge_tol)
    args.converge_tol_resolved = cfg.converge_tol
    state = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    if damp_flow_head:
        from jax.tree_util import tree_map_with_path

        def _damp(path, leaf):
            keys = [getattr(p, "key", getattr(p, "name", None))
                    for p in path]
            return leaf * damp_flow_head if "FlowHead_0" in keys else leaf

        variables = {"params": tree_map_with_path(_damp,
                                                  variables["params"]),
                     "batch_stats": variables["batch_stats"]}

    mesh = None
    if args.data_parallel > 0:
        from dexiraft_tpu.parallel.layout import make_serve_mesh, replicate

        mesh = make_serve_mesh(args.data_parallel)
        # params must live replicated on the mesh up front, or the
        # pinned replicated in_sharding re-transfers them every dispatch
        variables = replicate(variables, mesh)
    full = iters or args.iters
    step = make_eval_step(cfg, iters=full, mesh=mesh, adaptive=adaptive)
    if adaptive:
        import numpy as np

        # the trailing iter_budget arrives from the engine already
        # np.int32-normalized (or None = ride the full iters) — resolve
        # None to the SAME int32 aval so warmup and budgeted dispatches
        # share one executable per bucket
        if mesh is None:
            put = jax.device_put
            eval_fn = lambda a, b, fi, ib=None: step(
                variables, put(a), put(b),
                flow_init=None if fi is None else put(fi),
                iter_budget=np.int32(full if ib is None else ib))
        else:
            eval_fn = lambda a, b, fi, ib=None: step(
                variables, a, b, None, None, fi,
                np.int32(full if ib is None else ib))
    elif mesh is None:
        # explicit H2D puts: the engine hands host-stacked numpy
        # batches; spelling the transfer keeps the strict regions
        # (guards.strict_mode) clean without widening their teeth
        put = jax.device_put
        eval_fn = lambda a, b, fi: step(
            variables, put(a), put(b),
            flow_init=None if fi is None else put(fi))
    else:
        eval_fn = lambda a, b, fi: step(variables, a, b, None, None, fi)
    return eval_fn, mesh, step, variables


def _measure(args) -> None:
    import jax
    import numpy as np

    from dexiraft_tpu.analysis import guards
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig

    sizes = [tuple(int(v) for v in s.split("x")) for s in args.sizes.split(",")]
    eval_fn, mesh, step, variables = _build_eval_fn(args)
    print(f"platform={jax.devices()[0].platform} variant={args.variant} "
          f"small={args.small} iters={args.iters} sizes={args.sizes} "
          f"frames={args.frames} batch={args.batch} "
          f"multiple={args.bucket_multiple} dp={args.data_parallel}",
          file=sys.stderr)

    def stream_items():
        # pre-decoded, like the Loader hands over: host next() is free,
        # so any fetch-blocked time is genuinely device-side
        rng = np.random.default_rng(0)
        pool = []
        for k in range(args.frames):
            h, w = sizes[k % len(sizes)]
            pool.append({
                "image1": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
                "image2": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
            })
        return pool

    pool = stream_items()

    def run_config(batch_size: int) -> dict:
        engine = InferenceEngine(
            eval_fn,
            ServeConfig(batch_size=batch_size, mode="sintel",
                        bucket_multiple=args.bucket_multiple,
                        inflight=args.inflight),
            mesh=mesh)
        # warmup pass compiles every bucket (counted); the timed pass
        # must ride the in-process executable cache only. Draining
        # stream() IS the sync: every yielded Result was device_get-ed
        # by the engine's fetch side.
        t0 = time.perf_counter()  # jaxlint: disable=JL004
        for _ in engine.stream(dict(it) for it in pool):
            pass
        warm_s = time.perf_counter() - t0
        print(f"[b={batch_size}] warmup {warm_s:.1f}s "
              f"(compile {engine.compile_s:.1f}s, "
              f"{engine.registry.compiles} executables)", file=sys.stderr)
        engine.stats.reset()
        engine.registry.hits.clear()  # report the TIMED stream's hits
        # (the compiled-signature set survives: compiles stays honest)
        # steady-state contract (analysis/guards): warmup compiled every
        # bucket, so the timed stream must be compile-FLAT — a retrace
        # (or, single-chip, an implicit host transfer) here FAILS the
        # bench instead of silently deflating its number. The mesh path
        # keeps pinned in_shardings' own transfer semantics, so only the
        # recompile sentinel is armed there.
        # draining stream() fetches every Result to host (the sync)
        with guards.strict_mode(
                label=f"serve_bench[b={batch_size}]",
                transfer="disallow" if mesh is None else "allow"):
            t0 = time.perf_counter()  # jaxlint: disable=JL004
            n = sum(1 for _ in engine.stream(dict(it) for it in pool))
            dt = time.perf_counter() - t0
        print(f"[b={batch_size}] timed {dt * 1e3:.1f} ms for {n} pairs; "
              f"{engine.stats.summary()}", file=sys.stderr)

        # FLOPs of one compiled batch from XLA's own cost analysis
        # (never fail the record over accounting)
        flops_per_pair = tfps = mfu = None
        try:
            from bench import CHIP_PEAK_BF16_FLOPS, _counted_flops

            (bh, bw), _ = max(engine.registry.hits.items(),
                              key=lambda kv: kv[1])
            a = np.zeros((batch_size, bh, bw, 3), np.float32)
            lower_args = ((variables, a, a) if mesh is None
                          else (variables, a, a, None, None, None))
            flops = _counted_flops(step, *lower_args)
            if flops:
                flops_per_pair = flops / batch_size
                tfps = flops_per_pair * (n / dt) / 1e12
                kind = getattr(jax.devices()[0], "device_kind", "unknown")
                peak = (CHIP_PEAK_BF16_FLOPS.get(kind)
                        if jax.devices()[0].platform == "tpu" else None)
                if peak:
                    mfu = round(tfps * 1e12 / peak, 4)
        except Exception as e:
            print(f"cost_analysis unavailable: {e}", file=sys.stderr)

        reg = engine.registry.stats()
        return {
            "batch_size": batch_size,
            "inflight": args.inflight,
            "frame_pairs_per_sec": round(n / dt, 3),
            "latency_p50_ms": round(engine.stats.latency_ms(50), 2),
            "latency_p99_ms": round(engine.stats.latency_ms(99), 2),
            "bucket_count": reg["bucket_count"],
            "compiles": reg["compiles"],
            "buckets": reg["buckets"],
            "peak_inflight": engine.stats.peak_inflight,
            "fetch_blocked_ms": round(engine.stats.fetch_s * 1e3, 2),
            "pad_frames": engine.stats.pad_frames,
            "compile_s": round(engine.compile_s, 2),
            "flops_per_pair": flops_per_pair,
            "tflops_per_sec": round(tfps, 3) if tfps else None,
            "mfu": mfu,
        }

    # baseline: batch 1, or the smallest mesh-divisible batch when
    # data-parallel (a batch of 1 cannot shard over N chips)
    base_bs = max(1, args.data_parallel)
    configs = [run_config(base_bs)]
    if args.batch > base_bs:
        configs.append(run_config(args.batch))
    b1 = configs[0]["frame_pairs_per_sec"]
    record = {
        "metric": "serve_frame_pairs_per_sec",
        "platform": jax.devices()[0].platform,
        "variant": args.variant + ("-small" if args.small else ""),
        "iters": args.iters,
        "sizes": args.sizes,
        "frames": args.frames,
        "bucket_multiple": args.bucket_multiple,
        "corr_impl_resolved": args.corr_impl_resolved,
        "configs": configs,
        # None when only the baseline ran (e.g. --batch <= the
        # data-parallel baseline) — never a self-ratio of 1.0
        "speedup_batched_over_b1": (
            round(configs[-1]["frame_pairs_per_sec"] / b1, 3)
            if len(configs) > 1 and b1 else None),
    }
    assert set(record) == RECORD_KEYS, sorted(set(record) ^ RECORD_KEYS)
    assert all(set(c) == CONFIG_KEYS for c in configs)
    print(json.dumps(record), flush=True)


# ---- closed-loop (service) mode -----------------------------------------


def _http_get_json(host: str, port: int, path: str) -> dict:
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def _pctl_ms(samples, p: float) -> float:
    import numpy as np

    if not samples:
        return 0.0
    return round(float(np.percentile(samples, p)) * 1e3, 2)


_CLIENT_TRIES = 4          # attempts per request (1 + up to 3 retries)
_CLIENT_BACKOFF_S = 0.05   # doubling, jittered


def _client_thread(host: str, port: int, body: bytes, n: int,
                   latencies: list, rejects: list, session=None,
                   retries: list = None, completions: list = None) -> None:
    """One closed-loop client: POST, wait for the response, repeat.
    Keep-alive (HTTP/1.1) — one connection per client, like a real
    streaming caller. Appends per-request latency (s) or the reject
    status code; list.append is atomic, no lock needed.

    Connection-shaped failures (refused/reset — a replica restarting
    under the client) RETRY with doubling jittered backoff instead of
    counting as errors: a restart window is a liveness blip, not a
    service failure, and conflating the two made every rolling restart
    read as client errors. Each retry appends to `retries` (reported
    separately from `rejects`); only exhausting every attempt appends
    the sentinel -1 to `rejects`. `completions` (when given) collects
    (t_monotonic, status) per finished request — the fleet kill leg's
    gap/recovery analysis reads it."""
    import http.client

    headers = {"Content-Type": "application/x-npz"}
    if session:
        headers["X-Session-Id"] = session
    rng = __import__("random").Random(hash((port, session)) & 0xFFFF)
    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        for _ in range(n):
            t0 = time.monotonic()
            status = -1
            for attempt in range(_CLIENT_TRIES):
                try:
                    conn.request("POST", "/v1/flow", body=body,
                                 headers=headers)
                    resp = conn.getresponse()
                    resp.read()
                    status = resp.status
                    break
                except (ConnectionRefusedError, ConnectionResetError,
                        BrokenPipeError, http.client.BadStatusLine,
                        http.client.RemoteDisconnected):
                    conn.close()
                    conn = http.client.HTTPConnection(host, port,
                                                      timeout=60)
                    if attempt == _CLIENT_TRIES - 1:
                        break
                    if retries is not None:
                        retries.append(attempt)
                    time.sleep(_CLIENT_BACKOFF_S * (2 ** attempt)
                               * (1 + rng.random()))
            now = time.monotonic()
            if completions is not None:
                completions.append((now, status))
            if status == 200:
                latencies.append(now - t0)
            else:
                rejects.append(status)
    finally:
        conn.close()


def _run_level(service, body: bytes, concurrency: int, requests: int) -> dict:
    """Closed-loop load at one concurrency level; the /stats?reset=1
    scrape hands the measurement window off exactly like a monitoring
    agent would (and pins that the reset path works under load)."""
    import threading

    host, port = service.address
    latencies: list = []
    rejects: list = []
    retries: list = []
    per = [requests // concurrency] * concurrency
    for i in range(requests % concurrency):
        per[i] += 1
    threads = [threading.Thread(target=_client_thread,
                                args=(host, port, body, n, latencies,
                                      rejects, None, retries))
               for n in per if n]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    sched = _http_get_json(host, port, "/stats?reset=1")["scheduler"]
    # "rejected" is ONLY admission shedding (503): folding 4xx/5xx or
    # connection failures in would let an erroring service masquerade
    # as one that is load-shedding gracefully
    shed = sum(1 for s in rejects if s == 503)
    out = {
        "concurrency": concurrency,
        "requests": requests,
        "goodput_rps": round(len(latencies) / wall, 3) if wall else 0.0,
        "p50_ms": _pctl_ms(latencies, 50),
        "p99_ms": _pctl_ms(latencies, 99),
        "rejected": shed,
        "errors": len(rejects) - shed,
        "client_retries": len(retries),
        "dispatch_full": sched["dispatch_full"],
        "dispatch_slo": sched["dispatch_slo"],
        "mean_batch_fill": sched["mean_batch_fill"],
        "queue_peak": sched["queue_peak"],
    }
    print(f"[closed c={concurrency}] {out['goodput_rps']} req/s, "
          f"p50 {out['p50_ms']} / p99 {out['p99_ms']} ms, "
          f"fill {out['mean_batch_fill']}, "
          f"full/slo {out['dispatch_full']}/{out['dispatch_slo']}",
          file=sys.stderr)
    return out


def _overload_sender(host: str, port: int, body: bytes, interval: float,
                     offset: float, t_end: float,
                     latencies: list, rejects: list) -> None:
    """One open-loop sender: fires on an absolute schedule (t0 + offset
    + k*interval) regardless of completions — if a request runs long the
    next one is already late and goes out immediately, preserving the
    offered rate. Keep-alive connection, reopened on error."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=60)
    t0 = time.monotonic()
    k = 0
    try:
        while True:
            nxt = t0 + offset + k * interval
            pause = nxt - time.monotonic()
            if pause > 0:
                time.sleep(pause)
            if time.monotonic() >= t_end:
                return
            k += 1
            t_req = time.monotonic()
            try:
                conn.request("POST", "/v1/flow", body=body,
                             headers={"Content-Type": "application/x-npz"})
                resp = conn.getresponse()
                resp.read()
                if resp.status == 200:
                    latencies.append(time.monotonic() - t_req)
                else:
                    rejects.append(resp.status)
            except Exception:
                rejects.append(-1)
                conn.close()
                conn = http.client.HTTPConnection(host, port, timeout=60)
    finally:
        conn.close()


def _run_overload(service, body: bytes, offered_rps: float,
                  duration_s: float, stats_out: dict = None) -> dict:
    """OPEN arrivals at a fixed offered rate (no back-pressure from
    completions): admission control must shed the excess with 503s and
    keep goodput near capacity — the queue-collapse counterexample.
    A FIXED pool of senders paces the rate (a thread per arrival would
    exhaust threads/fds at the offered rates real hardware produces)."""
    import threading

    host, port = service.address
    latencies: list = []
    rejects: list = []
    senders = max(4, min(64, int(offered_rps * 0.5)))
    interval = senders / max(offered_rps, 1e-6)
    t_end = time.monotonic() + duration_s
    threads = [threading.Thread(
        target=_overload_sender,
        args=(host, port, body, interval, i * interval / senders, t_end,
              latencies, rejects))
        for i in range(senders)]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.monotonic() - t0
    shed = sum(1 for s in rejects if s == 503)
    out = {
        "offered_rps": round(offered_rps, 3),
        "duration_s": round(duration_s, 3),
        "completed": len(latencies),
        "rejected": shed,
        "errors": len(rejects) - shed,
        "goodput_rps": round(len(latencies) / wall, 3) if wall else 0.0,
        "p99_ms": _pctl_ms(latencies, 99),
    }
    payload = _http_get_json(host, port, "/stats?reset=1")
    if stats_out is not None:
        # the adaptive leg reads the scheduler's granted-budget stats
        # out of the same scrape-and-reset the window handoff uses
        stats_out.update(payload)
    print(f"[overload] offered {out['offered_rps']} req/s for "
          f"{duration_s:g}s: {out['completed']} served, "
          f"{out['rejected']} shed / {out['errors']} errored, "
          f"goodput {out['goodput_rps']} req/s",
          file=sys.stderr)
    return out


def _measure_closed_loop(args) -> None:
    import threading

    import jax
    import numpy as np

    from dexiraft_tpu.data.padder import InputPadder
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig, bucket_shape
    from dexiraft_tpu.serve.server import (FlowService, decode_response,
                                           encode_request)

    h, w = (int(v) for v in args.size.split("x"))
    rng = np.random.default_rng(0)
    im1 = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
    im2 = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
    body = encode_request(im1, im2)

    eval_fn, mesh, step, variables = _build_eval_fn(args)
    print(f"platform={jax.devices()[0].platform} variant={args.variant} "
          f"small={args.small} iters={args.iters} size={args.size} "
          f"batch={args.batch} slo_ms={args.slo_ms:g} "
          f"concurrency={args.concurrency}", file=sys.stderr)

    def make_service(batch_size: int, warm: bool) -> FlowService:
        engine = InferenceEngine(
            eval_fn,
            ServeConfig(batch_size=batch_size, mode="sintel",
                        bucket_multiple=args.bucket_multiple,
                        inflight=args.inflight, warm_start=warm),
            mesh=mesh)
        svc = FlowService(engine, port=0, slo_ms=args.slo_ms,
                          max_queue=args.max_queue,
                          session_ttl_s=60.0 if warm else 0.0,
                          request_timeout_s=60.0)
        svc.start()
        # warmup: compile the one bucket signature outside any timed
        # window, then hand off a clean measurement window
        _client_thread(*svc.address, body, 1, [], [])
        svc.reset_stats()
        return svc

    # -- phase 1: sequential baseline (batch_size=1 service) -------------
    seq_svc = make_service(1, warm=False)
    sequential = _run_level(seq_svc, body, args.concurrency, args.requests)
    seq_svc.drain_and_stop()

    # -- phases 2-4 share the batched, session-enabled service ----------
    svc = make_service(args.batch, warm=True)
    levels = [_run_level(svc, body, c, args.requests)
              for c in sorted({1, args.concurrency})]
    batched_rps = levels[-1]["goodput_rps"]

    overload = _run_overload(svc, body,
                             args.overload_factor * max(batched_rps, 0.5),
                             args.overload_duration_s)

    # -- phase 4: session warm-start convergence --------------------------
    # K chained warm requests ~ K*iters refinement (each frame seeds the
    # next through the session carry), so the K-th warm response must be
    # closer to a K*iters reference than the cold 1*iters response is
    import http.client

    host, port = svc.address
    conn = http.client.HTTPConnection(host, port, timeout=60)
    flow_cold = flow_warm = None
    try:
        for k in range(args.warm_frames):
            conn.request("POST", "/v1/flow", body=body,
                         headers={"X-Session-Id": "warm-bench"})
            resp = conn.getresponse()
            data = resp.read()
            assert resp.status == 200, (resp.status, data)
            if k == 0:
                flow_cold = decode_response(data)  # first frame IS cold
            flow_warm = decode_response(data)
    finally:
        conn.close()

    ref_eval_fn, _, _, _ = _build_eval_fn(
        args, iters=args.iters * args.warm_frames)
    bucket = bucket_shape(h, w, multiple=args.bucket_multiple)
    padder = InputPadder(im1.shape, mode="sintel", target=bucket)
    _, up_ref = ref_eval_fn(padder.pad(im1)[0][None],
                            padder.pad(im2)[0][None], None)
    flow_ref = padder.unpad(jax.device_get(up_ref)[0])
    warm_dist = float(np.mean(np.abs(flow_warm - flow_ref)))
    cold_dist = float(np.mean(np.abs(flow_cold - flow_ref)))
    warm_start = {
        "frames": args.warm_frames,
        "iters": args.iters,
        "iters_ref": args.iters * args.warm_frames,
        "warm_dist": round(warm_dist, 4),
        "cold_dist": round(cold_dist, 4),
        "warm_beats_cold": warm_dist < cold_dist,
    }
    print(f"[warm] dist-to-{warm_start['iters_ref']}-iter-ref: "
          f"cold {cold_dist:.4f} vs warm {warm_dist:.4f} "
          f"({'WIN' if warm_dist < cold_dist else 'NO WIN'})",
          file=sys.stderr)

    svc.drain_and_stop()

    record = {
        "metric": "serve_closed_loop",
        "platform": jax.devices()[0].platform,
        "variant": args.variant + ("-small" if args.small else ""),
        "iters": args.iters,
        "size": args.size,
        "batch": args.batch,
        "slo_ms": args.slo_ms,
        "max_queue": args.max_queue,
        "corr_impl_resolved": args.corr_impl_resolved,
        "sequential": sequential,
        "levels": levels,
        "overload": overload,
        "warm_start": warm_start,
        "speedup_batched_over_sequential": (
            round(batched_rps / sequential["goodput_rps"], 3)
            if sequential["goodput_rps"] else None),
    }
    assert set(record) == CLOSED_LOOP_RECORD_KEYS, \
        sorted(set(record) ^ CLOSED_LOOP_RECORD_KEYS)
    assert set(sequential) == LEVEL_KEYS
    assert all(set(lv) == LEVEL_KEYS for lv in levels)
    assert set(overload) == OVERLOAD_KEYS
    assert set(warm_start) == WARM_KEYS
    print(json.dumps(record), flush=True)


# ---- adaptive-iteration mode --------------------------------------------


def _measure_adaptive(args) -> None:
    """Adaptive-iteration leg (docstring "Adaptive mode"): the
    convergence-gated engine vs the fixed-iteration engine, SAME damped
    weights. Emits ONE JSON record (ADAPTIVE_RECORD_KEYS)."""
    import threading  # noqa: F401  (client threads under the hood)

    import jax
    import numpy as np

    from dexiraft_tpu.data.padder import InputPadder
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig, bucket_shape
    from dexiraft_tpu.serve.server import FlowService, encode_request

    h, w = (int(v) for v in args.size.split("x"))
    rng = np.random.default_rng(0)
    body = encode_request(
        rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
        rng.uniform(0, 255, (h, w, 3)).astype(np.float32))
    min_iters = max(1, min(args.min_iters, args.iters))

    # identical PRNGKey(0) init + identical damping -> the two steps
    # share one set of weights; only the refinement driver differs
    fixed_fn, mesh, _, _ = _build_eval_fn(args, damp_flow_head=0.01)
    adapt_fn, _, _, _ = _build_eval_fn(args, adaptive=True,
                                       damp_flow_head=0.01)
    tol = args.converge_tol_resolved
    print(f"platform={jax.devices()[0].platform} variant={args.variant} "
          f"small={args.small} iters={args.iters} size={args.size} "
          f"converge_tol={tol:g} min_iters={min_iters}", file=sys.stderr)

    # -- phase 1+2: per-pair quality / iters_used / latency ---------------
    bucket = bucket_shape(h, w, multiple=args.bucket_multiple)
    padder = InputPadder((h, w, 3), mode="sintel", target=bucket)
    pairs = [(rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
              rng.uniform(0, 255, (h, w, 3)).astype(np.float32))
             for _ in range(args.frames)]

    def prep(im):
        return jax.device_put(padder.pad(im)[0][None])

    # warmup both signatures outside the timed loop (one compile each)
    a0, b0 = prep(pairs[0][0]), prep(pairs[0][1])
    jax.block_until_ready(fixed_fn(a0, b0, None))
    jax.block_until_ready(adapt_fn(a0, b0, None))

    epes, used, deltas = [], [], []
    t_fixed = t_adapt = 0.0
    for im1, im2 in pairs:
        a, b = prep(im1), prep(im2)
        t0 = time.perf_counter()  # jaxlint: disable=JL004
        _, up_f = jax.block_until_ready(fixed_fn(a, b, None))
        t_fixed += time.perf_counter() - t0  # jaxlint: disable=JL004
        t0 = time.perf_counter()  # jaxlint: disable=JL004
        _, up_a, iu, fd = jax.block_until_ready(adapt_fn(a, b, None))
        t_adapt += time.perf_counter() - t0  # jaxlint: disable=JL004
        ff, fa = jax.device_get((up_f, up_a))
        epes.append(float(np.sqrt(((fa - ff) ** 2).sum(-1)).mean()))
        used.append(int(jax.device_get(iu)[0]))
        deltas.append(float(jax.device_get(fd)[0]))
    mean_used = float(np.mean(used))
    print(f"[adaptive] epe_vs_fixed {np.mean(epes):.4f} px, iters_used "
          f"mean {mean_used:.1f}/{args.iters} "
          f"(p99 {np.percentile(used, 99):.1f}), final_delta mean "
          f"{np.mean(deltas):.2e}; per-pair fixed "
          f"{t_fixed / len(pairs) * 1e3:.1f} ms vs adaptive "
          f"{t_adapt / len(pairs) * 1e3:.1f} ms", file=sys.stderr)

    # -- phase 3: overload, fixed service vs adaptive service -------------
    def make_service(eval_fn, adaptive: bool) -> FlowService:
        engine = InferenceEngine(
            eval_fn,
            ServeConfig(batch_size=args.batch, mode="sintel",
                        bucket_multiple=args.bucket_multiple,
                        inflight=args.inflight, adaptive=adaptive),
            mesh=mesh)
        svc = FlowService(engine, port=0, slo_ms=args.slo_ms,
                          max_queue=args.max_queue,
                          request_timeout_s=60.0,
                          max_iters=args.iters, min_iters=min_iters)
        svc.start()
        _client_thread(*svc.address, body, 1, [], [])
        svc.reset_stats()
        return svc

    svc_fixed = make_service(fixed_fn, adaptive=False)
    # capacity probe on the FIXED service sets one shared offered rate:
    # both overload runs face the same open-arrival pressure
    level = _run_level(svc_fixed, body, args.concurrency, args.requests)
    offered = args.overload_factor * max(level["goodput_rps"], 0.5)
    overload_fixed = _run_overload(svc_fixed, body, offered,
                                   args.overload_duration_s)
    svc_fixed.drain_and_stop()

    svc_adapt = make_service(adapt_fn, adaptive=True)
    stats: dict = {}
    ov = _run_overload(svc_adapt, body, offered, args.overload_duration_s,
                       stats_out=stats)
    svc_adapt.drain_and_stop()
    sched = stats.get("scheduler", {})
    overload_adaptive = dict(
        ov,
        iter_budget_p50=sched.get("iter_budget_p50"),
        iter_budget_p99=sched.get("iter_budget_p99"),
        iters_used_mean=stats.get("engine", {}).get("iters_used_mean"),
    )
    print(f"[adaptive overload] budgets p50 "
          f"{overload_adaptive['iter_budget_p50']} / p99 "
          f"{overload_adaptive['iter_budget_p99']} (full {args.iters}), "
          f"goodput {ov['goodput_rps']} vs fixed "
          f"{overload_fixed['goodput_rps']} req/s", file=sys.stderr)

    record = {
        "metric": "serve_adaptive",
        "platform": jax.devices()[0].platform,
        "variant": args.variant + ("-small" if args.small else ""),
        "iters": args.iters,
        "size": args.size,
        "frames": args.frames,
        "batch": args.batch,
        "slo_ms": args.slo_ms,
        "max_queue": args.max_queue,
        "converge_tol": tol,
        "min_iters": min_iters,
        "corr_impl_resolved": args.corr_impl_resolved,
        "epe_vs_fixed_px": round(float(np.mean(epes)), 4),
        "mean_iters_used": round(mean_used, 2),
        "p99_iters_used": round(float(np.percentile(used, 99)), 2),
        # the early-exit win: % of the fixed iteration count NOT spent
        "iters_drop_pct": round(100.0 * (1.0 - mean_used / args.iters), 1),
        "mean_final_delta": round(float(np.mean(deltas)), 6),
        "fixed_ms_per_pair": round(t_fixed / len(pairs) * 1e3, 2),
        "adaptive_ms_per_pair": round(t_adapt / len(pairs) * 1e3, 2),
        "overload_fixed": overload_fixed,
        "overload_adaptive": overload_adaptive,
        "overload_goodput_ratio": (
            round(ov["goodput_rps"] / overload_fixed["goodput_rps"], 3)
            if overload_fixed["goodput_rps"] else None),
    }
    assert set(record) == ADAPTIVE_RECORD_KEYS, \
        sorted(set(record) ^ ADAPTIVE_RECORD_KEYS)
    assert set(overload_fixed) == OVERLOAD_KEYS
    assert set(overload_adaptive) == ADAPTIVE_OVERLOAD_KEYS, \
        sorted(set(overload_adaptive) ^ ADAPTIVE_OVERLOAD_KEYS)
    print(json.dumps(record), flush=True)


# ---- fleet (router) mode ------------------------------------------------


def _free_ports(n: int) -> list:
    import socket

    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _fleet_serve_args(args) -> list:
    """Replica argv: --synthetic_init serve processes (random weights —
    the fleet legs measure routing/failover, not EPE), warmed up on the
    bench geometry so /healthz only answers once the compile is paid."""
    sa = ["--synthetic_init", "--variant", args.variant,
          "--iters", str(args.iters), "--batch_size", str(args.batch),
          "--slo_ms", str(args.slo_ms),
          "--max_queue", str(args.max_queue),
          "--session_ttl_s", "60",
          "--bucket_multiple", str(args.bucket_multiple),
          "--corr_impl", args.corr_impl,
          "--warmup", args.size, "--request_timeout_s", "60"]
    if args.fused_update:
        # without this a fleet A/B of the fused config silently spawns
        # UNFUSED replicas (explicit --corr_impl resolves fused=False)
        sa.append("--fused_update")
    if args.small:
        sa.append("--small")
    if args.cpu:
        sa.append("--cpu")
    return sa


def _fleet_router(urls, **overrides):
    from dexiraft_tpu.serve.router import Router, RouterConfig

    kw = dict(probe_interval_s=0.2, cooldown_s=1.0, fail_threshold=2,
              deadline_s=60.0)
    kw.update(overrides)
    return Router(urls, port=0, config=RouterConfig(**kw)).start()


def _measure_fleet(args) -> None:
    """Router-over-N-replicas legs: (1) goodput-vs-replica-count
    scaling curve, (2) kill-one-replica-under-load — recovery
    wall-time, zero-drop check, affinity hit rate before/after. The
    bench process itself NEVER imports jax: replicas own the devices
    (N processes cannot share one TPU chip), and the router/clients are
    pure control plane."""
    import threading
    from urllib.parse import urlparse

    from dexiraft_tpu.config import resolve_corr_impl
    from dexiraft_tpu.router_cli import spawn_replica, wait_ready
    from dexiraft_tpu.serve.server import encode_request

    import numpy as np

    h, w = (int(v) for v in args.size.split("x"))
    rng = np.random.default_rng(0)
    body = encode_request(
        rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
        rng.uniform(0, 255, (h, w, 3)).astype(np.float32))

    n = args.fleet
    if n < 2:
        raise SystemExit("--fleet needs >= 2 replicas (the kill leg "
                         "must have a survivor)")
    ports = _free_ports(n)
    serve_args = _fleet_serve_args(args)
    procs = {f"r{i}": spawn_replica(p, serve_args)
             for i, p in enumerate(ports)}
    urls = {f"r{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)}
    platform = "cpu" if args.cpu else os.environ.get("JAX_PLATFORMS",
                                                     "default")

    def run_clients(url, concurrency, per, prefix, completions=None):
        u = urlparse(url)
        latencies, rejects, retries = [], [], []
        threads = [threading.Thread(
            target=_client_thread,
            args=(u.hostname, u.port, body, per, latencies, rejects,
                  f"{prefix}-{i}", retries, completions))
            for i in range(concurrency)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        return threads, latencies, rejects, retries, t0

    try:
        for i, p in enumerate(ports):
            if not wait_ready("127.0.0.1", p, args.boot_timeout_s):
                raise RuntimeError(f"replica r{i} (port {p}) not healthy "
                                   f"within {args.boot_timeout_s:g}s")
            print(f"[fleet] replica r{i} healthy on port {p}",
                  file=sys.stderr, flush=True)

        per = max(1, args.requests // args.concurrency)

        # -- leg 1: goodput-vs-replica-count scaling curve ----------------
        scaling = []
        for k in range(1, n + 1):
            router = _fleet_router({r: urls[r] for r in list(urls)[:k]})
            threads, lat, rej, ret, t0 = run_clients(
                router.url, args.concurrency, per, f"scale{k}")
            for t in threads:
                t.join()
            wall = time.monotonic() - t0
            rec = router.stats.record()
            aff = router.pool.affinity_record()
            router.stop()
            entry = {
                "replicas": k,
                "concurrency": args.concurrency,
                "requests": per * args.concurrency,
                "goodput_rps": round(len(lat) / wall, 3) if wall else 0.0,
                "p50_ms": _pctl_ms(lat, 50),
                "p99_ms": _pctl_ms(lat, 99),
                "errors": len(rej),
                "client_retries": len(ret),
                "router_retries": rec["retries"],
                "failovers": rec["failovers"],
                "affinity_hit_rate": aff["hit_rate"],
            }
            scaling.append(entry)
            print(f"[fleet k={k}] {entry['goodput_rps']} req/s, p50 "
                  f"{entry['p50_ms']} / p99 {entry['p99_ms']} ms, "
                  f"affinity {entry['affinity_hit_rate']}",
                  file=sys.stderr)

        # -- leg 2: kill one replica under load ---------------------------
        router = _fleet_router(urls)
        completions: list = []
        kill_per = max(3, per)
        total = kill_per * args.concurrency
        threads, lat, rej, ret, t0 = run_clients(
            router.url, args.concurrency, kill_per, "kill", completions)
        # let the fleet warm (sessions homed, ~1/3 of traffic served) …
        while len(completions) < max(args.concurrency, total // 3):
            if time.monotonic() - t0 > 300:
                raise RuntimeError("kill leg warm phase stalled")
            time.sleep(0.02)
        aff_before = router.pool.affinity_record()
        # kill the replica that OWNS the first kill-stream's session —
        # a session-less victim would make the sticky-miss/remap
        # numbers vacuous
        victim = router.pool.ring.lookup("kill-0")
        procs[victim].kill()          # SIGKILL: abrupt death, no drain
        procs[victim].wait()
        t_kill = time.monotonic()
        print(f"[fleet] killed {victim} after {len(completions)}/{total} "
              f"requests", file=sys.stderr)
        while (router.pool.replicas[victim].state != "open"
               and time.monotonic() - t_kill < 60):
            time.sleep(0.02)
        detect_s = time.monotonic() - t_kill
        for t in threads:
            t.join()
        aff_end = router.pool.affinity_record()
        rec = router.stats.record()
        router.stop()

        succ = sorted(t for t, s in completions if s == 200)
        post = [t for t in succ if t >= t_kill]
        gaps = [b - a for a, b in zip(succ, succ[1:])]
        hits_d = aff_end["hits"] - aff_before["hits"]
        miss_d = aff_end["sticky_misses"] - aff_before["sticky_misses"]
        kill = {
            "killed": victim,
            "requests": total,
            "completed": len(succ),
            "errors": len(rej),
            "client_retries": len(ret),
            # breaker-open latency (the router stopped ASSIGNING to the
            # corpse this fast; individual requests failed over earlier
            # via the passive path)
            "detect_s": round(detect_s, 3),
            # first successful completion after the kill — the client-
            # visible service gap
            "recovery_s": (round(post[0] - t_kill, 3) if post else None),
            "max_gap_s": (round(max(gaps), 3) if gaps else None),
            "router_retries": rec["retries"],
            "failovers": rec["failovers"],
            "sticky_misses": aff_end["sticky_misses"],
            "affinity_hit_rate_before": aff_before["hit_rate"],
            "affinity_hit_rate_after": (
                round(hits_d / (hits_d + miss_d), 4)
                if hits_d + miss_d else None),
            "zero_dropped": len(rej) == 0,
        }
        print(f"[fleet kill] detect {kill['detect_s']}s, recovery "
              f"{kill['recovery_s']}s, {kill['errors']} errors / "
              f"{kill['client_retries']} client retries / "
              f"{kill['failovers']} failovers, affinity "
              f"{kill['affinity_hit_rate_before']} -> "
              f"{kill['affinity_hit_rate_after']}", file=sys.stderr)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    record = {
        "metric": "serve_fleet",
        "platform": platform,
        "variant": args.variant + ("-small" if args.small else ""),
        "iters": args.iters,
        "size": args.size,
        "batch": args.batch,
        "slo_ms": args.slo_ms,
        "max_queue": args.max_queue,
        "replicas": n,
        "concurrency": args.concurrency,
        "requests": args.requests,
        # the bench process never imports jax (replicas own the devices)
        # so it resolves for the platform the replicas run on: --cpu
        # forces cpu everywhere, otherwise the fleet is a TPU deployment
        "corr_impl_resolved": resolve_corr_impl(
            args.corr_impl, "cpu" if args.cpu else "tpu")[0],
        "scaling": scaling,
        "kill": kill,
        "goodput_scaling": (
            round(scaling[-1]["goodput_rps"] / scaling[0]["goodput_rps"],
                  3) if scaling[0]["goodput_rps"] else None),
    }
    assert set(record) == FLEET_RECORD_KEYS, \
        sorted(set(record) ^ FLEET_RECORD_KEYS)
    assert all(set(s) == FLEET_SCALING_KEYS for s in scaling)
    assert set(kill) == FLEET_KILL_KEYS, sorted(set(kill) ^ FLEET_KILL_KEYS)
    print(json.dumps(record), flush=True)


def main() -> int:
    """Parent: spawn the measurement child under the stall watchdog.
    No jax import on this side — a wedged backend can only hang the
    child, and the child gets killed."""
    import signal
    import threading

    stall_s = float(os.environ.get("SERVE_BENCH_STALL_S", STALL_S))
    hard_cap_s = float(os.environ.get("SERVE_BENCH_HARD_CAP_S", HARD_CAP_S))
    env = dict(os.environ, SERVE_BENCH_CHILD="1")
    child = subprocess.Popen([sys.executable, osp.abspath(__file__)]
                             + sys.argv[1:], env=env,
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE)

    def _on_term(signum, frame):
        # the queue's outer `timeout` signals only the parent; forward
        # the kill so the measurement child is never orphaned holding a
        # device claim
        child.terminate()
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()
        sys.exit(128 + signum)

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_term)

    last = [time.monotonic()]
    # shared watchdog-relay hygiene (bench.py): the XLA host-feature
    # warning goes to a side log once, never into the relayed stderr —
    # the queue's recorded tail must end with the JSON metric line
    from bench import make_stderr_filter

    warn_filt = make_stderr_filter(tag="serve_bench")

    def pump(src, dst, is_stderr=False):
        for line in iter(src.readline, b""):
            last[0] = time.monotonic()
            if is_stderr:
                line = warn_filt(line)
                if line is None:
                    continue
            dst.buffer.write(line)
            dst.flush()

    threads = [
        threading.Thread(target=pump, args=(child.stdout, sys.stdout),
                         daemon=True),
        threading.Thread(target=pump, args=(child.stderr, sys.stderr, True),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    while True:
        rc = child.poll()
        if rc is not None:
            break
        time.sleep(min(2.0, stall_s / 4))
        now = time.monotonic()
        if now - last[0] > stall_s or now - t0 > hard_cap_s:
            why = (f"silent {now - last[0]:.0f}s (stalled)"
                   if now - last[0] > stall_s
                   else f"overran {hard_cap_s:.0f}s")
            print(f"[serve_bench] child stalled ({why}); killing",
                  file=sys.stderr)
            child.terminate()
            try:
                child.wait(timeout=15)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
            rc = 8
            break
    for t in threads:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    if os.environ.get("SERVE_BENCH_CHILD"):
        if os.environ.get("SERVE_BENCH_FAKE_HANG"):
            print("fake child hanging", file=sys.stderr, flush=True)
            while True:
                time.sleep(3600)
        _args = build_parser().parse_args()
        if _args.fleet:
            # fleet mode never imports jax in this process (replicas
            # own the devices); --cpu is forwarded to them instead
            _measure_fleet(_args)
            sys.exit(0)
        if _args.cpu:
            import jax

            jax.config.update("jax_platforms", "cpu")
        (_measure_adaptive if _args.adaptive else
         _measure_closed_loop if _args.closed_loop else _measure)(_args)
        sys.exit(0)
    sys.exit(main())
