"""Serving throughput — the eval-side analog of scripts/train_bench.py.

Drives ONE mixed-geometry frame-pair stream through the throughput-mode
inference engine (dexiraft_tpu.serve) at batch_size=1 (the reference
per-image behavior) and at --batch, same jitted eval step, and emits ONE
JSON record: frame-pairs/s per config, p50/p99 batch latency, bucket
hit/compile counts (the mixed stream must compile EXACTLY once per
bucket), peak in-flight depth, fetch-blocked time, and FLOPs/MFU from
XLA's cost analysis. The speedup field is the acceptance signal:
batched throughput over the batch-1 configuration of the same run.

Watchdog (the bench.py pattern, tests/test_bench_watchdog.py /
tests/test_zserve_bench.py): the measurement runs in a CHILD process;
the parent kills it when it goes silent past SERVE_BENCH_STALL_S or
overruns SERVE_BENCH_HARD_CAP_S and exits 8 — a relay-tunnel death must
never hang the driver's round-end run. SERVE_BENCH_FAKE_HANG=1 swaps in
a child that blocks forever (watchdog tests). The parent imports no jax.

Usage: python scripts/serve_bench.py [--variant v1] [--small]
           [--batch 4] [--iters 4] [--sizes 40x56,44x60,36x52]
           [--frames 16] [--bucket_multiple 16] [--inflight 2]
           [--data_parallel 0] [--cpu] [--no_compile_cache]
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import subprocess
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

STALL_S = 600.0
HARD_CAP_S = 1500.0

RECORD_KEYS = {  # pinned by tests/test_zserve_bench.py
    "metric", "platform", "variant", "iters", "sizes", "frames",
    "bucket_multiple", "configs", "speedup_batched_over_b1",
}
CONFIG_KEYS = {
    "batch_size", "inflight", "frame_pairs_per_sec", "latency_p50_ms",
    "latency_p99_ms", "bucket_count", "compiles", "buckets",
    "peak_inflight", "fetch_blocked_ms", "pad_frames", "compile_s",
    "flops_per_pair", "tflops_per_sec", "mfu",
}


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="v5")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--batch", type=int, default=4,
                    help="the batched configuration's micro-batch size")
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--sizes", default="440x1024,436x1020,432x1016",
                    help="comma-separated HxW geometries, cycled over "
                         "the stream (mixed-geometry bucket proof)")
    ap.add_argument("--frames", type=int, default=12,
                    help="frame pairs in the stream")
    ap.add_argument("--bucket_multiple", type=int, default=64,
                    help="bucket quantization granule (multiple of 8)")
    ap.add_argument("--inflight", type=int, default=2)
    ap.add_argument("--data_parallel", type=int, default=0,
                    help="shard each batch over this many chips (0 = one)")
    ap.add_argument("--compile_cache_dir", default=None)
    ap.add_argument("--no_compile_cache", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (config.update beats the "
                         "axon site-hook pin)")
    return ap


def _measure() -> None:
    args = build_parser().parse_args()
    import jax
    import numpy as np

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu import config as C
    from dexiraft_tpu.analysis import guards
    from dexiraft_tpu.config import TrainConfig
    from dexiraft_tpu.profiling import enable_persistent_cache
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_eval_step

    if not args.no_compile_cache:
        cache_dir = enable_persistent_cache(args.compile_cache_dir)
        print(f"compile cache: {cache_dir}", file=sys.stderr)

    sizes = [tuple(int(v) for v in s.split("x")) for s in args.sizes.split(",")]
    cfg = getattr(C, f"raft_{args.variant}")(small=args.small)
    state = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    variables = {"params": state.params, "batch_stats": state.batch_stats}

    mesh = None
    if args.data_parallel > 0:
        from dexiraft_tpu.parallel.mesh import make_serve_mesh, replicate

        mesh = make_serve_mesh(args.data_parallel)
        # params must live replicated on the mesh up front, or the
        # pinned replicated in_sharding re-transfers them every dispatch
        variables = replicate(variables, mesh)
    step = make_eval_step(cfg, iters=args.iters, mesh=mesh)
    if mesh is None:
        # explicit H2D puts: the engine hands host-stacked numpy
        # batches; spelling the transfer keeps the strict region below
        # (guards.strict_mode) clean without widening its teeth
        put = jax.device_put
        eval_fn = lambda a, b, fi: step(
            variables, put(a), put(b),
            flow_init=None if fi is None else put(fi))
    else:
        eval_fn = lambda a, b, fi: step(variables, a, b, None, None, fi)
    print(f"platform={jax.devices()[0].platform} variant={args.variant} "
          f"small={args.small} iters={args.iters} sizes={args.sizes} "
          f"frames={args.frames} batch={args.batch} "
          f"multiple={args.bucket_multiple} dp={args.data_parallel}",
          file=sys.stderr)

    def stream_items():
        # pre-decoded, like the Loader hands over: host next() is free,
        # so any fetch-blocked time is genuinely device-side
        rng = np.random.default_rng(0)
        pool = []
        for k in range(args.frames):
            h, w = sizes[k % len(sizes)]
            pool.append({
                "image1": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
                "image2": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
            })
        return pool

    pool = stream_items()

    def run_config(batch_size: int) -> dict:
        engine = InferenceEngine(
            eval_fn,
            ServeConfig(batch_size=batch_size, mode="sintel",
                        bucket_multiple=args.bucket_multiple,
                        inflight=args.inflight),
            mesh=mesh)
        # warmup pass compiles every bucket (counted); the timed pass
        # must ride the in-process executable cache only. Draining
        # stream() IS the sync: every yielded Result was device_get-ed
        # by the engine's fetch side.
        t0 = time.perf_counter()  # jaxlint: disable=JL004
        for _ in engine.stream(dict(it) for it in pool):
            pass
        warm_s = time.perf_counter() - t0
        print(f"[b={batch_size}] warmup {warm_s:.1f}s "
              f"(compile {engine.compile_s:.1f}s, "
              f"{engine.registry.compiles} executables)", file=sys.stderr)
        engine.stats.reset()
        engine.registry.hits.clear()  # report the TIMED stream's hits
        # (the compiled-signature set survives: compiles stays honest)
        # steady-state contract (analysis/guards): warmup compiled every
        # bucket, so the timed stream must be compile-FLAT — a retrace
        # (or, single-chip, an implicit host transfer) here FAILS the
        # bench instead of silently deflating its number. The mesh path
        # keeps pinned in_shardings' own transfer semantics, so only the
        # recompile sentinel is armed there.
        # draining stream() fetches every Result to host (the sync)
        with guards.strict_mode(
                label=f"serve_bench[b={batch_size}]",
                transfer="disallow" if mesh is None else "allow"):
            t0 = time.perf_counter()  # jaxlint: disable=JL004
            n = sum(1 for _ in engine.stream(dict(it) for it in pool))
            dt = time.perf_counter() - t0
        print(f"[b={batch_size}] timed {dt * 1e3:.1f} ms for {n} pairs; "
              f"{engine.stats.summary()}", file=sys.stderr)

        # FLOPs of one compiled batch from XLA's own cost analysis
        # (never fail the record over accounting)
        flops_per_pair = tfps = mfu = None
        try:
            from bench import CHIP_PEAK_BF16_FLOPS, _counted_flops

            (bh, bw), _ = max(engine.registry.hits.items(),
                              key=lambda kv: kv[1])
            a = np.zeros((batch_size, bh, bw, 3), np.float32)
            lower_args = ((variables, a, a) if mesh is None
                          else (variables, a, a, None, None, None))
            flops = _counted_flops(step, *lower_args)
            if flops:
                flops_per_pair = flops / batch_size
                tfps = flops_per_pair * (n / dt) / 1e12
                kind = getattr(jax.devices()[0], "device_kind", "unknown")
                peak = (CHIP_PEAK_BF16_FLOPS.get(kind)
                        if jax.devices()[0].platform == "tpu" else None)
                if peak:
                    mfu = round(tfps * 1e12 / peak, 4)
        except Exception as e:
            print(f"cost_analysis unavailable: {e}", file=sys.stderr)

        reg = engine.registry.stats()
        return {
            "batch_size": batch_size,
            "inflight": args.inflight,
            "frame_pairs_per_sec": round(n / dt, 3),
            "latency_p50_ms": round(engine.stats.latency_ms(50), 2),
            "latency_p99_ms": round(engine.stats.latency_ms(99), 2),
            "bucket_count": reg["bucket_count"],
            "compiles": reg["compiles"],
            "buckets": reg["buckets"],
            "peak_inflight": engine.stats.peak_inflight,
            "fetch_blocked_ms": round(engine.stats.fetch_s * 1e3, 2),
            "pad_frames": engine.stats.pad_frames,
            "compile_s": round(engine.compile_s, 2),
            "flops_per_pair": flops_per_pair,
            "tflops_per_sec": round(tfps, 3) if tfps else None,
            "mfu": mfu,
        }

    # baseline: batch 1, or the smallest mesh-divisible batch when
    # data-parallel (a batch of 1 cannot shard over N chips)
    base_bs = max(1, args.data_parallel)
    configs = [run_config(base_bs)]
    if args.batch > base_bs:
        configs.append(run_config(args.batch))
    b1 = configs[0]["frame_pairs_per_sec"]
    record = {
        "metric": "serve_frame_pairs_per_sec",
        "platform": jax.devices()[0].platform,
        "variant": args.variant + ("-small" if args.small else ""),
        "iters": args.iters,
        "sizes": args.sizes,
        "frames": args.frames,
        "bucket_multiple": args.bucket_multiple,
        "configs": configs,
        # None when only the baseline ran (e.g. --batch <= the
        # data-parallel baseline) — never a self-ratio of 1.0
        "speedup_batched_over_b1": (
            round(configs[-1]["frame_pairs_per_sec"] / b1, 3)
            if len(configs) > 1 and b1 else None),
    }
    assert set(record) == RECORD_KEYS, sorted(set(record) ^ RECORD_KEYS)
    assert all(set(c) == CONFIG_KEYS for c in configs)
    print(json.dumps(record), flush=True)


def main() -> int:
    """Parent: spawn the measurement child under the stall watchdog.
    No jax import on this side — a wedged backend can only hang the
    child, and the child gets killed."""
    import signal
    import threading

    stall_s = float(os.environ.get("SERVE_BENCH_STALL_S", STALL_S))
    hard_cap_s = float(os.environ.get("SERVE_BENCH_HARD_CAP_S", HARD_CAP_S))
    env = dict(os.environ, SERVE_BENCH_CHILD="1")
    child = subprocess.Popen([sys.executable, osp.abspath(__file__)]
                             + sys.argv[1:], env=env,
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE)

    def _on_term(signum, frame):
        # the queue's outer `timeout` signals only the parent; forward
        # the kill so the measurement child is never orphaned holding a
        # device claim
        child.terminate()
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()
        sys.exit(128 + signum)

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_term)

    last = [time.monotonic()]

    def pump(src, dst):
        for line in iter(src.readline, b""):
            last[0] = time.monotonic()
            dst.buffer.write(line)
            dst.flush()

    threads = [
        threading.Thread(target=pump, args=(child.stdout, sys.stdout),
                         daemon=True),
        threading.Thread(target=pump, args=(child.stderr, sys.stderr),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    while True:
        rc = child.poll()
        if rc is not None:
            break
        time.sleep(min(2.0, stall_s / 4))
        now = time.monotonic()
        if now - last[0] > stall_s or now - t0 > hard_cap_s:
            why = (f"silent {now - last[0]:.0f}s (stalled)"
                   if now - last[0] > stall_s
                   else f"overran {hard_cap_s:.0f}s")
            print(f"[serve_bench] child stalled ({why}); killing",
                  file=sys.stderr)
            child.terminate()
            try:
                child.wait(timeout=15)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
            rc = 8
            break
    for t in threads:
        t.join(timeout=5)
    return rc


if __name__ == "__main__":
    if os.environ.get("SERVE_BENCH_CHILD"):
        if os.environ.get("SERVE_BENCH_FAKE_HANG"):
            print("fake child hanging", file=sys.stderr, flush=True)
            while True:
                time.sleep(3600)
        _measure()
        sys.exit(0)
    sys.exit(main())
