"""DexiNed standalone-workload training demo with exact ground truth.

The reference trains DexiNed on BIPED (core/DexiNed/main.py); no edge
datasets are mounted here, so this demo trains on procedurally generated
scenes with EXACT boundary labels: each image is a textured background
with random filled shapes (rectangles / ellipses), and the label marks
the 1-pixel shape boundaries (binary erosion difference) — correct by
construction. The per-scale weighted BDCN loss dropping and the fused
output's F-measure rising demonstrate the whole standalone edge workload
(model, 7-scale loss, Adam) learning on-chip.

Writes a transcript to logs/dexined_demo_<platform>.log.

Usage: python scripts/dexined_demo.py [--steps 200] [--batch 4] [--cpu]
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time
from functools import partial

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from scipy import ndimage


def make_scene(rng, size):
    """(image [0,255] HxWx3, edges {0,1} HxW) with exact boundaries."""
    h = w = size
    img = np.stack([ndimage.zoom(rng.uniform(40, 215, (8, 8)),
                                 size / 8, order=3)[:h, :w]
                    for _ in range(3)], axis=-1)
    edges = np.zeros((h, w), bool)
    yy, xx = np.mgrid[:h, :w]
    for _ in range(rng.integers(3, 7)):
        kind = rng.integers(2)
        cy, cx = rng.integers(8, h - 8), rng.integers(8, w - 8)
        ry, rx = rng.integers(6, h // 3), rng.integers(6, w // 3)
        if kind == 0:
            m = (np.abs(yy - cy) < ry) & (np.abs(xx - cx) < rx)
        else:
            m = ((yy - cy) / ry) ** 2 + ((xx - cx) / rx) ** 2 < 1.0
        color = rng.uniform(0, 255, 3)
        img[m] = 0.75 * color + 0.25 * img[m]
        # a later shape overpaints earlier boundaries inside it — clear
        # them so labels only mark edges the image actually shows
        # (border_value=1 keeps frame-clipped interiors in the clearing
        # mask, matching the boundary erosion below)
        edges &= ~ndimage.binary_erosion(m, border_value=1)
        # border_value=1: shapes clipped by the frame get no boundary
        # label along the border (there is no contrast there)
        boundary = m & ~ndimage.binary_erosion(m, border_value=1)
        edges |= boundary
    return img, edges.astype(np.float32)


def make_batch(rng, batch, size):
    ims, eds = zip(*[make_scene(rng, size) for _ in range(batch)])
    return (jnp.asarray(np.stack(ims), jnp.float32),
            jnp.asarray(np.stack(eds)[..., None], jnp.float32))


def f_measure(prob: np.ndarray, gt: np.ndarray, thresh: float = 0.5,
              tol: int = 1) -> float:
    """Loose boundary F1: predictions within ``tol`` px of a GT edge count
    as hits (a cheap stand-in for the full ODS machinery in
    dexiraft_tpu.dexined.metrics, which this demo does not need)."""
    pred = prob > thresh
    gt_b = gt > 0.5
    gt_dil = ndimage.binary_dilation(gt_b, iterations=tol)
    pred_dil = ndimage.binary_dilation(pred, iterations=tol)
    tp_p = (pred & gt_dil).sum()
    tp_r = (gt_b & pred_dil).sum()
    prec = tp_p / max(pred.sum(), 1)
    rec = tp_r / max(gt_b.sum(), 1)
    return 2 * prec * rec / max(prec + rec, 1e-9)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--size", type=int, default=192)
    ap.add_argument("--pool", type=int, default=12)
    ap.add_argument("--lr", type=float, default=5e-5)
    ap.add_argument("--log", default=None)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import optax

    from dexiraft_tpu.dexined.losses import weighted_multiscale_loss
    from dexiraft_tpu.models.dexined import DexiNed

    platform = jax.devices()[0].platform
    log_path = args.log or osp.join(
        osp.dirname(osp.dirname(osp.abspath(__file__))),
        "logs", f"dexined_demo_{platform}.log")
    import os

    os.makedirs(osp.dirname(log_path), exist_ok=True)
    log_f = open(log_path, "w")

    def log(msg):
        print(msg)
        print(msg, file=log_f, flush=True)

    log(f"# dexined_demo: platform={platform}, batch={args.batch}, "
        f"{args.size}x{args.size}, steps={args.steps}, synthetic shapes "
        f"(exact boundary GT), weighted BDCN multiscale loss")

    model = DexiNed()
    rng = jax.random.PRNGKey(1234)
    t0 = time.perf_counter()
    dummy = jnp.zeros((1, args.size, args.size, 3), jnp.float32)
    variables = jax.jit(lambda r, x: model.init(r, x, train=True))(rng, dummy)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    log(f"# {n_params} parameters; init {time.perf_counter() - t0:.1f}s")

    tx = optax.adam(args.lr)
    opt_state = tx.init(params)

    # donate the threaded state (jaxlint JL006): the demo's step would
    # otherwise hold pre- and post-update params/moments simultaneously
    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            preds, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            return (weighted_multiscale_loss(preds, labels),
                    mut.get("batch_stats", batch_stats))

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    nrng = np.random.default_rng(1234)
    pool = [make_batch(nrng, args.batch, args.size) for _ in range(args.pool)]
    val_im, val_gt = make_batch(np.random.default_rng(99), 2, args.size)

    @jax.jit
    def fused_prob(params, batch_stats, images):
        preds = model.apply({"params": params, "batch_stats": batch_stats},
                            images, train=False)
        return jax.nn.sigmoid(preds[-1][..., 0])

    def val_f1(params, batch_stats):
        probs = jax.device_get(fused_prob(params, batch_stats, val_im))
        gt = np.asarray(val_gt[..., 0])
        return float(np.mean([f_measure(probs[i], gt[i])
                              for i in range(probs.shape[0])]))

    log(f"# untrained val F1 {val_f1(params, batch_stats):.3f}")

    t0 = time.perf_counter()
    images, labels = pool[0]
    params, batch_stats, opt_state, loss = step(
        params, batch_stats, opt_state, images, labels)
    log(f"# compile+first step {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    eval_s = 0.0
    last_f1 = None
    for i in range(1, args.steps):
        images, labels = pool[i % args.pool]
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
        if i % 25 == 0 or i == args.steps - 1:
            # the in-loop loss cycles over recycled pool batches, so
            # lines are not comparable; the fixed val F1 every 50 steps
            # is the monotone signal. Eval time is excluded from the
            # steps/s denominator so the rate stays a training
            # throughput.
            # drain the async train stream first (loss fetch = sync
            # point) so pending steps accrue to train time, not eval
            loss_v = float(jax.device_get(loss))
            f1 = ""
            train_elapsed = time.perf_counter() - t0 - eval_s
            if i % 50 == 0 or i == args.steps - 1:
                te = time.perf_counter()
                last_f1 = val_f1(params, batch_stats)
                eval_s += time.perf_counter() - te
                f1 = f"val_f1 {last_f1:.3f}  "
            log(f"[{i:5d}] loss {loss_v:9.1f}  {f1}"
                f"{i / train_elapsed:5.2f} steps/s")

    if last_f1 is None:
        last_f1 = val_f1(params, batch_stats)
    log(f"# trained val F1 {last_f1:.3f} "
        f"(boundary tolerance 1px, fused scale)")
    log_f.close()


if __name__ == "__main__":
    main()
