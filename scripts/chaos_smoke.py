"""Chaos smoke: a short CPU run proving the recovery paths recover
(~4 min on a laptop-class CPU, dominated by the XLA compiles).

Injects the fault families the resilience layer claims to survive —
corrupt samples, decode-worker death, SIGTERM mid-run, a truncated
checkpoint, a hard kill DURING an async checkpoint flush, a dead
virtual host on a 2-process mesh, and a serve replica SIGKILLed behind
the fleet router — against the REAL loader, the REAL train CLI, the
real multiprocess runtime, and real serve processes, and exits nonzero
if any path fails to recover. Intended for CI and for a quick sanity check
after touching the train/data/resilience path:

    python scripts/chaos_smoke.py 2>&1 | tee logs/chaos_smoke.log

Phases:
  1 corrupt-sample   Loader + always-failing samples: batches keep
                     flowing, skips counted, shapes stable
  2 worker-death     process-pool worker os._exit()s: pool rebuilt,
                     batches bit-identical to a clean run
  3 sigterm-resume   train_cli with a real SIGTERM after step N:
                     emergency checkpoint + stream position, --resume,
                     final params BIT-EXACT vs an uninterrupted run
  4 truncated-ckpt   newest checkpoint file truncated: verified restore
                     falls back to the previous step
  5 kill-mid-flush   train_cli killed while an async checkpoint flush
                     is in flight (--chaos kill_mid_flush@N, a real
                     os._exit mid-serialize): the uncommitted step is
                     invisible, restore_verified lands on the prior
                     committed step, --resume completes the run
  6 multihost-kill   2-process virtual mesh, one host os._exit()s
                     mid-run: the survivor exits NONZERO (watchdog /
                     collective error) instead of hanging, and a
                     --resume pair agrees on one step and finishes
                     BIT-EXACT vs an uninterrupted reference pair
  7 router-failover  2 serve replicas behind the fleet router
                     (serve/router.py), one SIGKILLed under closed-loop
                     session load: ZERO accepted requests dropped (the
                     router's failover retry + the clients' connection
                     retry absorb the death), the breaker opens inside
                     the recovery bound, and the dead replica's
                     sessions remap (sticky misses, then warm again)
  8 shrink-and-continue  the SAME kill as phase 6 but under --elastic
                     (resilience.membership): the survivor re-forms a
                     solo membership epoch, restores the agreed step,
                     and FINISHES the run with exit 0 — its
                     reconfiguration recovery_s is pinned into the
                     record next to phase 6's exit-98 abort wall (the
                     cost elastic replaces), and the child's lock-order
                     runtime must report zero violations across the
                     reconfiguration

The last stdout line is a JSON record with per-phase recovery
wall-times (`[chaos] record {...}` — RECORD_KEYS pins the schema), so
recovery-latency regressions are visible run-over-run in the logs. The
record also carries the lock-order runtime's verdict (analysis/locks):
the kill-mid-flush, router-failover, and shrink-and-continue phases
assert — and pin into their record entries — ZERO lock-order
violations and ZERO deadlock cycles while their thread fabric was
under fire, so the concurrency gate holds under the exact chaos it
exists for, not just in unit tests. The shrink phase additionally
records its elastic `recovery_s` next to the multihost-kill phase's
exit-98 `abort_s` — the restart cost it replaces — and asserts it is
cheaper.
The smoke also runs `lint_gate.py --json` up front (the machine-
readable contract, no stdout scraping) and pins the static gate's
verdict alongside — one record answers both halves of the concurrency
story: the tree lints clean AND the runtime observed no violations.
"""

from __future__ import annotations

import json
import os
import os.path as osp
import subprocess
import sys
import tempfile
import time
import traceback

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

# JSON-tail schema: per-phase {ok, wall_s} plus totals; the locks block
# is the lock-order runtime's verdict (analysis/locks.py) — the
# kill-mid-flush, router-failover, and shrink-and-continue phases
# additionally pin a per-phase snapshot proving ZERO order violations /
# deadlock cycles were observed while their thread fabric was under
# fire (the shrink phase's snapshot comes from the SURVIVOR CHILD —
# the process that ran the lease thread + flush executor + watchdog
# through a real reconfiguration)
RECORD_KEYS = ("phases", "failures", "total_s", "locks", "lint_gate",
               "collective_trace")
# every phase entry carries at least these keys ...
PHASE_KEYS = ("ok", "wall_s")
# ... and the concurrency-gate phases (kill-mid-flush,
# router-failover, shrink-and-continue) additionally merge this key —
# their per-phase lock-order snapshot; multihost-kill merges abort_s
# and shrink-and-continue merges {recovery_s, exit98_abort_s}, the
# before/after pair of the elastic-membership story
PHASE_LOCKS_KEY = "locks"


def _locks_verdict(phase: str) -> dict:
    """Assert the lock-order runtime saw no violations, and return the
    snapshot for the phase's record entry. In-process the smoke drives
    the REAL router/checkpoint thread fabric (handler threads, health
    loop, drain threads, the flush barrier), so a nonzero count here is
    a concurrency regression even when the phase's recovery contract
    still held."""
    from dexiraft_tpu.analysis import locks

    rec = locks.stats_record()
    assert rec["order_violations"] == 0, \
        f"{phase}: lock-order violations under fire: {rec['violations']}"
    assert rec["cycles"] == 0, \
        f"{phase}: deadlock cycles detected under fire: {rec['violations']}"
    return {"locks": {"order_violations": rec["order_violations"],
                      "cycles": rec["cycles"],
                      "contended": sum(v["contended"]
                                       for v in rec["by_lock"].values())}}


def _build_chairs_tree(tmp: str, n: int = 8) -> None:
    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import write_flo

    root = os.path.join(tmp, "FlyingChairs_release", "data")
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n):
        imageio.imwrite(f"{root}/{i:05d}_img1.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        imageio.imwrite(f"{root}/{i:05d}_img2.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        write_flo(f"{root}/{i:05d}_flow.flo",
                  rng.normal(size=(96, 128, 2)).astype(np.float32))
    with open(os.path.join(tmp, "FlyingChairs_release",
                           "chairs_split.txt"), "w") as f:
        f.write("\n".join(["1"] * n))


def _train_args(tmp: str, name: str, steps: int, extra=()):
    return ["--name", name, "--stage", "chairs", "--variant", "v1", "--small",
            "--num_steps", str(steps), "--batch_size", "2",
            "--image_size", "64", "64", "--iters", "2", "--lr", "1e-4",
            "--num_workers", "1", "--val_freq", "1000",
            "--output", f"{tmp}/ckpts", "--log_dir", f"{tmp}/runs", *extra]


def phase_corrupt_sample() -> None:
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.resilience import chaos

    ds = chaos.SyntheticFlowDataset(n=8, size=(16, 16))
    bad = chaos.CorruptSampleDataset(ds, [0, 5])
    loader = Loader(bad, 2, num_workers=2, prefetch=2, max_retries=1,
                    retry_backoff_s=0.001)
    it = loader.batches()
    got = [next(it) for _ in range(8)]  # two epochs: both bad indices hit
    it.close()
    assert all(b["image1"].shape == (2, 16, 16, 3) for b in got), \
        "batch shape drifted under skips"
    assert loader.stats.skipped_samples >= 2, loader.stats.summary()
    print(f"    {loader.stats.summary()}")


def phase_worker_death() -> None:
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.resilience import chaos

    ds = chaos.SyntheticFlowDataset(n=8, size=(16, 16))
    with tempfile.TemporaryDirectory() as sentinels:
        killer = chaos.WorkerDeathDataset(ds, [1], sentinels)
        loader = Loader(killer, 2, num_workers=1, prefetch=2,
                        worker_mode="process", mp_start_method="spawn",
                        max_retries=3, retry_backoff_s=0.01)
        it = loader.batches()
        got = [next(it) for _ in range(4)]
        it.close()
    assert loader.stats.worker_restarts >= 1, loader.stats.summary()
    clean = Loader(ds, 2, num_workers=1, prefetch=2)
    ic = clean.batches()
    ref = [next(ic) for _ in range(4)]
    ic.close()
    for a, b in zip(got, ref):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    print(f"    {loader.stats.summary()}; batches bit-identical to clean run")


def phase_sigterm_resume(tmp: str) -> None:
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train_cli import main as train_main

    train_main(_train_args(tmp, "ref", 4))
    train_main(_train_args(tmp, "cut", 4, ["--chaos", "sigterm@2"]))
    saved = ckpt.latest_step(f"{tmp}/ckpts/cut")
    assert saved == 2, f"expected emergency save at step 2, got {saved}"
    assert os.path.exists(f"{tmp}/ckpts/cut/stream/2.json"), \
        "stream-position sidecar missing"
    train_main(_train_args(tmp, "cut", 4, ["--resume"]))
    assert ckpt.latest_step(f"{tmp}/ckpts/cut") == 4

    template = create_state(jax.random.PRNGKey(0), raft_v1(small=True),
                            TrainConfig())
    ref = ckpt.restore_checkpoint(f"{tmp}/ckpts/ref", template, step=4)
    cut = ckpt.restore_checkpoint(f"{tmp}/ckpts/cut", template, step=4)
    for a, b in zip(jax.tree.leaves(ref.params), jax.tree.leaves(cut.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    print("    SIGTERM@2 -> emergency save -> resume: params BIT-EXACT "
          "vs uninterrupted run")


def phase_truncated_checkpoint(tmp: str) -> None:
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.resilience import chaos, restore_verified
    from dexiraft_tpu.train.state import create_state

    ckpt_dir = f"{tmp}/ckpts/ref"  # steps 2 (val_freq path unused) … 4
    template = create_state(jax.random.PRNGKey(0), raft_v1(small=True),
                            TrainConfig())
    # damage the NEWEST step; verified restore must land on the previous
    from dexiraft_tpu.train import checkpoint as ckpt

    steps = ckpt.all_steps(ckpt_dir)
    assert len(steps) >= 1, steps
    if len(steps) == 1:
        # make a second step to fall back to
        ckpt.save_checkpoint(ckpt_dir, template, step=steps[-1] + 1)
        steps = ckpt.all_steps(ckpt_dir)
    damaged = chaos.truncate_checkpoint(ckpt_dir, steps[-1])
    assert damaged, "nothing truncated"
    state, got = restore_verified(ckpt_dir, template)
    assert got == steps[-2], (got, steps)
    print(f"    step {steps[-1]} truncated -> restored step {got} instead")


def _train_subprocess(tmp: str, cli_args, expect_rc: int,
                      timeout: float = 600.0) -> str:
    """Run train_cli in a SUBPROCESS (the injected fault is a real
    os._exit — in-process it would take the smoke down) and assert the
    exit code. Returns combined output."""
    repo = osp.dirname(osp.dirname(osp.abspath(__file__)))
    env = {**os.environ, "DEXIRAFT_DATA_DIR": tmp,
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    proc = subprocess.run(
        [sys.executable, "-m", "dexiraft_tpu", "train", *cli_args],
        cwd=tmp, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=timeout)
    out = proc.stdout.decode(errors="replace")
    assert proc.returncode == expect_rc, \
        f"expected rc {expect_rc}, got {proc.returncode}:\n{out[-3000:]}"
    return out


def phase_kill_mid_flush(tmp: str) -> dict:
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.resilience import restore_verified, \
        uncommitted_flushes
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    args = _train_args(tmp, "flushkill", 6,
                       ["--val_freq", "2", "--validation"])
    # saves at 2/4/6; the chaos spec arms at step 3, so step 4's async
    # flush is the one killed in flight (rc 7 = the injector's exit)
    out = _train_subprocess(
        tmp, args + ["--chaos", "kill_mid_flush@3"], expect_rc=7)
    assert "killing process mid-flush of step 4" in out, out[-2000:]
    ckpt_dir = f"{tmp}/ckpts/flushkill"
    debris = uncommitted_flushes(ckpt_dir)
    assert debris, "kill was not mid-serialize: no uncommitted tmp dir"
    template = create_state(jax.random.PRNGKey(0), raft_v1(small=True),
                            TrainConfig())
    # clean_debris: this is the WRITER recovering its own directory
    state, got = restore_verified(ckpt_dir, template, clean_debris=True)
    assert got == 2, f"expected fallback to committed step 2, got {got}"
    assert uncommitted_flushes(ckpt_dir) == [], "debris not cleaned"
    # and the run completes from the prior committed step
    out = _train_subprocess(tmp, args + ["--resume"], expect_rc=0)
    assert ckpt.latest_step(ckpt_dir) == 6
    assert "flush" in out and "train blocked" in out  # async stats logged
    print(f"    killed mid-flush of step 4 (debris: {len(debris)} tmp "
          f"dir(s)) -> restore_verified landed on step {got}; --resume "
          f"completed to step 6")
    # the in-process half (restore_verified + the wait_pending barriers
    # above) ran the flush-lock fabric: pin zero order violations
    return _locks_verdict("kill-mid-flush")


# phase 6 publishes its exit-98 abort wall here; phase 8 records its
# elastic recovery next to it — the two numbers are the before/after of
# the elastic-membership story and belong in the same record
_EXIT98_BASELINE: dict = {}

# phase 8's survivor child publishes its collective flight-recorder
# snapshot here (analysis/collective_trace); the record's top-level
# collective_trace block folds it in next to the parent's own counters
_SURVIVOR_TRACE: dict = {}


def phase_multihost_kill(tmp: str) -> dict:
    repo = osp.dirname(osp.dirname(osp.abspath(__file__)))
    child = osp.join(repo, "tests", "multiproc_resilience_child.py")
    # the SAME pair orchestration the tier-1 multihost tests use (kill
    # + reap on timeout, placeholder logs), so smoke and suite cannot
    # drift
    from tests._mp_common import spawn_child_pair

    def spawn_pair(tag, ckpt_dir, extra):
        outs = [f"{tmp}/{tag}{pid}.json" for pid in range(2)]
        rcs, logs, _ = spawn_child_pair(
            child, outs, ckpt_dir,
            extra=["--num_steps", "8", "--save_every", "2", *extra],
            timeout=240.0)
        return rcs, logs

    rcs, logs = spawn_pair("ref", f"{tmp}/mh_ref",
                           ["--stall_timeout", "60"])
    assert rcs == [0, 0], f"reference pair failed:\n{logs[0][-2000:]}"
    t_kill = time.perf_counter()
    rcs, logs = spawn_pair("cut", f"{tmp}/mh_cut",
                           ["--die_step", "5", "--die_host", "1",
                            "--stall_timeout", "20"])
    abort_s = time.perf_counter() - t_kill
    assert rcs[1] == 3, logs[1][-1500:]
    survivor_rc = rcs[0]
    # the survivor must abort ITSELF (watchdog 98 / hard-exit 97) —
    # a -9 means spawn_child_pair's timeout killed a hung survivor,
    # which is exactly the outcome this phase exists to disprove
    assert survivor_rc not in (0, None, -9), \
        f"survivor rc {survivor_rc} — expected a coordinated nonzero " \
        f"exit:\n{logs[0][-1500:]}"
    assert "<killed: timed out>" not in logs[0], \
        "survivor hung past the spawn timeout — the watchdog did not " \
        "bound the dead-peer collective"
    assert abort_s < 150, \
        f"survivor took {abort_s:.0f}s to abort — the watchdog did " \
        f"not bound the hang"
    rcs, logs = spawn_pair("res", f"{tmp}/mh_cut",
                           ["--resume", "--stall_timeout", "60"])
    assert rcs == [0, 0], f"resume pair failed:\n{logs[0][-2000:]}"
    ref = [json.load(open(f"{tmp}/ref{i}.json")) for i in range(2)]
    res = [json.load(open(f"{tmp}/res{i}.json")) for i in range(2)]
    resumed = [r["events"][0]["resumed"] for r in res]
    assert resumed[0] == resumed[1], resumed
    assert res[0]["final_w"] == ref[0]["final_w"] == res[1]["final_w"], \
        "resumed params diverged from the uninterrupted reference"
    print(f"    host 1 killed at step 5 -> survivor aborted nonzero "
          f"(rc {survivor_rc}) in {abort_s:.0f}s; resume pair agreed on "
          f"step {resumed[0]} and finished BIT-EXACT vs the "
          f"uninterrupted pair")
    _EXIT98_BASELINE["abort_s"] = round(abort_s, 1)
    return {"abort_s": round(abort_s, 1)}


def phase_shrink_and_continue(tmp: str) -> dict:
    """Phase 6's kill under --elastic: the survivor must CONTINUE (rc 0,
    all 8 steps) through a membership reconfiguration instead of
    aborting for an orchestrator restart. recovery_s (verdict-to-new-
    world, from the survivor's membership event) lands in the record
    next to phase 6's abort wall — and must beat it: elastic recovery
    is only worth its complexity while it is cheaper than the exit-98
    path it replaces, BEFORE even counting the restart's re-init and
    re-compile that the baseline number does not include."""
    repo = osp.dirname(osp.dirname(osp.abspath(__file__)))
    child = osp.join(repo, "tests", "multiproc_resilience_child.py")
    from tests._mp_common import spawn_child_pair

    outs = [f"{tmp}/el{pid}.json" for pid in range(2)]
    rcs, logs, wall = spawn_child_pair(
        child, outs, f"{tmp}/mh_elastic",
        extra=["--elastic", "--die_step", "3", "--die_host", "1",
               "--num_steps", "8", "--stall_timeout", "25"],
        timeout=240.0)
    assert rcs == [0, 3], \
        f"elastic pair rcs {rcs}:\n{logs[0][-2000:]}\n{logs[1][-800:]}"
    surv = json.load(open(outs[0]))
    shrinks = [e for e in surv["membership_events"]
               if e["kind"] == "shrink"]
    assert len(shrinks) == 1, surv["membership_events"]
    assert shrinks[0]["members"] == [0]
    recovery_s = shrinks[0]["recovery_s"]
    assert 0 < recovery_s < 60, f"recovery_s {recovery_s}"
    assert surv["final_epoch"] == {"epoch": 1, "size": 1, "index": 0}
    assert "8" in surv["losses"], "survivor never finished the run"
    # the child's lock-order runtime ran the lease thread + flush
    # executor + watchdog fabric through the reconfiguration
    assert surv["locks"]["order_violations"] == 0, surv["locks"]
    assert surv["locks"]["cycles"] == 0, surv["locks"]
    # the child's collective flight recorder stamped every consensus
    # round, membership epoch, and orbax barrier across the
    # reconfiguration — lockstep must have verified clean (the in-band
    # check compares every peer stamp while the world is > 1 host)
    ct = surv.get("collective_trace") or {}
    assert ct.get("divergences") == 0, \
        f"survivor observed collective divergences: {ct}"
    assert ct.get("entries", 0) > 0, \
        f"flight recorder stamped nothing across the scenario: {ct}"
    _SURVIVOR_TRACE.update(ct)
    baseline = _EXIT98_BASELINE.get("abort_s")
    if baseline is not None:
        assert recovery_s < baseline, \
            f"elastic recovery ({recovery_s:.1f}s) is not cheaper than " \
            f"the exit-98 abort it replaces ({baseline:.1f}s)"
    print(f"    host 1 killed at step 3 under --elastic -> survivor "
          f"reconfigured to a solo epoch in {recovery_s:.2f}s and "
          f"finished all 8 steps (rc 0); exit-98 baseline abort: "
          f"{baseline}s; child locks clean")
    return {"recovery_s": round(recovery_s, 2),
            "exit98_abort_s": baseline,
            "locks": dict(surv["locks"]),
            "collective_trace": {
                "entries": ct.get("entries"),
                "verified_rounds": ct.get("verified_rounds"),
                "divergences": ct.get("divergences")}}


def phase_router_failover(tmp: str) -> dict:
    """Kill 1 of 2 replicas behind the fleet router under closed-loop
    session load. Recovery contract: zero accepted requests dropped
    (router failover + client connection-retry absorb the death), the
    victim's breaker opens inside the bound, sessions remap."""
    import threading
    from urllib.parse import urlparse

    repo = osp.dirname(osp.dirname(osp.abspath(__file__)))
    sys.path.insert(0, osp.join(repo, "scripts"))
    try:
        from serve_bench import _client_thread, _free_ports
    finally:
        sys.path.pop(0)
    from dexiraft_tpu.router_cli import spawn_replica, wait_ready
    from dexiraft_tpu.serve.router import Router, RouterConfig
    from dexiraft_tpu.serve.server import encode_request

    ports = _free_ports(2)
    serve_args = ["--synthetic_init", "--variant", "v1", "--small",
                  "--iters", "2", "--batch_size", "2", "--slo_ms", "100",
                  "--bucket_multiple", "8", "--session_ttl_s", "60",
                  "--max_queue", "64", "--warmup", "48x64", "--cpu"]
    env = {**os.environ,
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                            "")}
    procs = {f"r{i}": spawn_replica(p, serve_args, env=env)
             for i, p in enumerate(ports)}
    router = None
    try:
        for i, p in enumerate(ports):
            assert wait_ready("127.0.0.1", p, 240.0), \
                f"replica r{i} (port {p}) never became healthy"
        router = Router(
            {f"r{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)},
            port=0, config=RouterConfig(probe_interval_s=0.2,
                                        cooldown_s=1.0,
                                        fail_threshold=2)).start()
        rng = np.random.default_rng(0)
        body = encode_request(
            rng.uniform(0, 255, (48, 64, 3)).astype(np.float32),
            rng.uniform(0, 255, (48, 64, 3)).astype(np.float32))
        u = urlparse(router.url)
        n_clients, per = 4, 10
        latencies, rejects, retries, completions = [], [], [], []
        threads = [threading.Thread(
            target=_client_thread,
            args=(u.hostname, u.port, body, per, latencies, rejects,
                  f"cam-{i}", retries, completions))
            for i in range(n_clients)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        # warm: every stream homed and ~1/3 of the traffic served
        while len(completions) < (n_clients * per) // 3:
            assert time.perf_counter() - t0 < 120, "load never warmed"
            time.sleep(0.02)
        aff_before = router.pool.affinity_record()
        # kill the replica that OWNS a live session — killing an idle
        # one proves nothing about affinity remap
        victim = router.pool.ring.lookup("cam-0")
        procs[victim].kill()        # SIGKILL mid-load: no drain, no flush
        procs[victim].wait()
        t_kill = time.perf_counter()
        while (router.pool.replicas[victim].state != "open"
               and time.perf_counter() - t_kill < 30):
            time.sleep(0.02)
        detect_s = time.perf_counter() - t_kill
        for t in threads:
            t.join(timeout=120.0)
        aff_after = router.pool.affinity_record()
        rec = router.stats.record()

        assert router.pool.replicas[victim].state == "open", \
            f"breaker never opened on the killed replica ({detect_s:.1f}s)"
        assert detect_s < 10.0, \
            f"breaker took {detect_s:.1f}s to open — recovery unbounded"
        assert rejects == [], \
            f"{len(rejects)} client-visible failures {rejects} — " \
            f"in-flight requests were dropped"
        assert len(latencies) == n_clients * per, \
            f"only {len(latencies)}/{n_clients * per} requests completed"
        assert aff_after["sticky_misses"] > aff_before["sticky_misses"], \
            "victim's sessions never remapped (sticky_misses flat)"
        print(f"    killed {victim} under load: breaker open in "
              f"{detect_s:.2f}s, {len(latencies)}/{n_clients * per} "
              f"requests OK (0 dropped, {len(retries)} client retries, "
              f"{rec['failovers']} router failovers), affinity "
              f"{aff_before['hit_rate']} -> {aff_after['hit_rate']} "
              f"({aff_after['sticky_misses']} sticky misses)")
        # the router ran IN-PROCESS with its full thread fabric
        # (handler threads x4 clients, health loop, passive breaker
        # marking) while a replica died under it: pin zero lock-order
        # violations across the failover
        return _locks_verdict("router-failover")
    finally:
        if router is not None:
            router.stop()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def _lint_gate_verdict(failures: list) -> dict:
    """Run the static gate through its --json contract (no stdout
    scraping): the smoke's recovery phases prove the RUNTIME lock
    discipline holds under fire; this pins that the STATIC half
    (threadlint JL020+ with the rest of jaxlint) is clean on the same
    tree, in the same record."""
    gate = osp.join(osp.dirname(osp.abspath(__file__)), "lint_gate.py")
    proc = subprocess.run([sys.executable, gate, "--json"],
                          capture_output=True, text=True, timeout=120)
    try:
        blob = json.loads(proc.stdout)
    except ValueError:
        print(f"[chaos] lint gate emitted unparseable --json output "
              f"(rc {proc.returncode}):\n{proc.stdout[-1000:]}",
              flush=True)
        failures.append("lint-gate")
        return {"ok": False, "findings": None}
    verdict = {"ok": blob["ok"], "findings": len(blob["findings"]),
               "per_rule": {r: c["findings"]
                            for r, c in blob["per_rule"].items()
                            if c["findings"]},
               # per-family breakdown (jaxlint/shardlint/threadlint/
               # distlint): the record shows at a glance WHICH gate
               # family a regression landed in
               "per_family": {fam: {"rules": c["rules"],
                                    "findings": c["findings"]}
                              for fam, c in
                              blob.get("per_family", {}).items()}}
    if not blob["ok"]:
        print(f"[chaos] lint gate FAIL: {verdict}", flush=True)
        failures.append("lint-gate")
    else:
        print(f"[chaos] lint gate clean ({blob['files']} files)",
              flush=True)
    return verdict


def main() -> int:
    t_start = time.perf_counter()
    failures = []
    record: dict = {}
    gate_verdict = _lint_gate_verdict(failures)
    with tempfile.TemporaryDirectory() as tmp:
        _build_chairs_tree(tmp)
        os.environ["DEXIRAFT_DATA_DIR"] = tmp
        cwd = os.getcwd()
        os.chdir(tmp)
        phases = [
            ("corrupt-sample", phase_corrupt_sample),
            ("worker-death", phase_worker_death),
            ("sigterm-resume", lambda: phase_sigterm_resume(tmp)),
            ("truncated-ckpt", lambda: phase_truncated_checkpoint(tmp)),
            ("kill-mid-flush", lambda: phase_kill_mid_flush(tmp)),
            ("multihost-kill", lambda: phase_multihost_kill(tmp)),
            ("router-failover", lambda: phase_router_failover(tmp)),
            ("shrink-and-continue",
             lambda: phase_shrink_and_continue(tmp)),
        ]
        try:
            for name, fn in phases:
                t0 = time.perf_counter()
                print(f"[chaos] {name} ...", flush=True)
                extra: dict = {}
                try:
                    extra = fn() or {}
                    ok = True
                    print(f"[chaos] {name} PASS "
                          f"({time.perf_counter() - t0:.1f}s)", flush=True)
                except Exception:
                    traceback.print_exc()
                    ok = False
                    print(f"[chaos] {name} FAIL", flush=True)
                    failures.append(name)
                # per-phase recovery wall-time: the run-over-run signal
                # for recovery-latency regressions (+ the locks verdict
                # the concurrency-gate phases pin)
                record[name] = {"ok": ok,
                                "wall_s": round(time.perf_counter() - t0,
                                                1), **extra}
        finally:
            os.chdir(cwd)
    total = time.perf_counter() - t_start
    if failures:
        print(f"[chaos] FAILED: {failures} ({total:.1f}s)")
    else:
        print(f"[chaos] all {len(phases)} recovery paths recovered "
              f"({total:.1f}s)")
    from dexiraft_tpu.analysis import collective_trace, locks

    lrec = locks.stats_record()
    trec = collective_trace.recorder()
    print("[chaos] record " + json.dumps(
        {"phases": record, "failures": failures,
         "total_s": round(total, 1),
         # the whole smoke's lock-order verdict: every in-process
         # phase's thread fabric, one line, greppable run-over-run
         "locks": {"order_violations": lrec["order_violations"],
                   "cycles": lrec["cycles"],
                   "held_too_long": lrec["held_too_long"]},
         # ... and its collective-lockstep verdict: the parent's own
         # flight recorder plus the shrink survivor's (the process
         # that ran real consensus rounds through a reconfiguration);
         # divergences folds both — the pinned contract is 0
         "collective_trace": {
             "divergences": (trec.divergences
                             + int(_SURVIVOR_TRACE.get("divergences")
                                   or 0)),
             "local_entries": trec.recorded,
             "survivor_entries": _SURVIVOR_TRACE.get("entries"),
             "survivor_verified_rounds":
                 _SURVIVOR_TRACE.get("verified_rounds")},
         "lint_gate": gate_verdict},
        sort_keys=True), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
