"""Chaos smoke: a short single-process CPU run proving the recovery paths
(~2 min on a laptop-class CPU, dominated by the one XLA compile).

Injects the four fault families the resilience layer claims to survive —
corrupt samples, decode-worker death, SIGTERM mid-run, and a truncated
checkpoint — against the REAL loader and the REAL train CLI on a tiny
synthetic chairs tree, and exits nonzero if any path fails to recover.
Intended for CI and for a quick sanity check after touching the
train/data path:

    python scripts/chaos_smoke.py 2>&1 | tee logs/chaos_smoke.log

Phases:
  1 corrupt-sample   Loader + always-failing samples: batches keep
                     flowing, skips counted, shapes stable
  2 worker-death     process-pool worker os._exit()s: pool rebuilt,
                     batches bit-identical to a clean run
  3 sigterm-resume   train_cli with a real SIGTERM after step N:
                     emergency checkpoint + stream position, --resume,
                     final params BIT-EXACT vs an uninterrupted run
  4 truncated-ckpt   newest checkpoint file truncated: verified restore
                     falls back to the previous step
"""

from __future__ import annotations

import os
import os.path as osp
import sys
import tempfile
import time
import traceback

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _build_chairs_tree(tmp: str, n: int = 8) -> None:
    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import write_flo

    root = os.path.join(tmp, "FlyingChairs_release", "data")
    os.makedirs(root, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n):
        imageio.imwrite(f"{root}/{i:05d}_img1.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        imageio.imwrite(f"{root}/{i:05d}_img2.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        write_flo(f"{root}/{i:05d}_flow.flo",
                  rng.normal(size=(96, 128, 2)).astype(np.float32))
    with open(os.path.join(tmp, "FlyingChairs_release",
                           "chairs_split.txt"), "w") as f:
        f.write("\n".join(["1"] * n))


def _train_args(tmp: str, name: str, steps: int, extra=()):
    return ["--name", name, "--stage", "chairs", "--variant", "v1", "--small",
            "--num_steps", str(steps), "--batch_size", "2",
            "--image_size", "64", "64", "--iters", "2", "--lr", "1e-4",
            "--num_workers", "1", "--val_freq", "1000",
            "--output", f"{tmp}/ckpts", "--log_dir", f"{tmp}/runs", *extra]


def phase_corrupt_sample() -> None:
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.resilience import chaos

    ds = chaos.SyntheticFlowDataset(n=8, size=(16, 16))
    bad = chaos.CorruptSampleDataset(ds, [0, 5])
    loader = Loader(bad, 2, num_workers=2, prefetch=2, max_retries=1,
                    retry_backoff_s=0.001)
    it = loader.batches()
    got = [next(it) for _ in range(8)]  # two epochs: both bad indices hit
    it.close()
    assert all(b["image1"].shape == (2, 16, 16, 3) for b in got), \
        "batch shape drifted under skips"
    assert loader.stats.skipped_samples >= 2, loader.stats.summary()
    print(f"    {loader.stats.summary()}")


def phase_worker_death() -> None:
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.resilience import chaos

    ds = chaos.SyntheticFlowDataset(n=8, size=(16, 16))
    with tempfile.TemporaryDirectory() as sentinels:
        killer = chaos.WorkerDeathDataset(ds, [1], sentinels)
        loader = Loader(killer, 2, num_workers=1, prefetch=2,
                        worker_mode="process", mp_start_method="spawn",
                        max_retries=3, retry_backoff_s=0.01)
        it = loader.batches()
        got = [next(it) for _ in range(4)]
        it.close()
    assert loader.stats.worker_restarts >= 1, loader.stats.summary()
    clean = Loader(ds, 2, num_workers=1, prefetch=2)
    ic = clean.batches()
    ref = [next(ic) for _ in range(4)]
    ic.close()
    for a, b in zip(got, ref):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    print(f"    {loader.stats.summary()}; batches bit-identical to clean run")


def phase_sigterm_resume(tmp: str) -> None:
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train_cli import main as train_main

    train_main(_train_args(tmp, "ref", 4))
    train_main(_train_args(tmp, "cut", 4, ["--chaos", "sigterm@2"]))
    saved = ckpt.latest_step(f"{tmp}/ckpts/cut")
    assert saved == 2, f"expected emergency save at step 2, got {saved}"
    assert os.path.exists(f"{tmp}/ckpts/cut/stream/2.json"), \
        "stream-position sidecar missing"
    train_main(_train_args(tmp, "cut", 4, ["--resume"]))
    assert ckpt.latest_step(f"{tmp}/ckpts/cut") == 4

    template = create_state(jax.random.PRNGKey(0), raft_v1(small=True),
                            TrainConfig())
    ref = ckpt.restore_checkpoint(f"{tmp}/ckpts/ref", template, step=4)
    cut = ckpt.restore_checkpoint(f"{tmp}/ckpts/cut", template, step=4)
    for a, b in zip(jax.tree.leaves(ref.params), jax.tree.leaves(cut.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    print("    SIGTERM@2 -> emergency save -> resume: params BIT-EXACT "
          "vs uninterrupted run")


def phase_truncated_checkpoint(tmp: str) -> None:
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.resilience import chaos, restore_verified
    from dexiraft_tpu.train.state import create_state

    ckpt_dir = f"{tmp}/ckpts/ref"  # steps 2 (val_freq path unused) … 4
    template = create_state(jax.random.PRNGKey(0), raft_v1(small=True),
                            TrainConfig())
    # damage the NEWEST step; verified restore must land on the previous
    from dexiraft_tpu.train import checkpoint as ckpt

    steps = ckpt.all_steps(ckpt_dir)
    assert len(steps) >= 1, steps
    if len(steps) == 1:
        # make a second step to fall back to
        ckpt.save_checkpoint(ckpt_dir, template, step=steps[-1] + 1)
        steps = ckpt.all_steps(ckpt_dir)
    damaged = chaos.truncate_checkpoint(ckpt_dir, steps[-1])
    assert damaged, "nothing truncated"
    state, got = restore_verified(ckpt_dir, template)
    assert got == steps[-2], (got, steps)
    print(f"    step {steps[-1]} truncated -> restored step {got} instead")


def main() -> int:
    t_start = time.perf_counter()
    failures = []
    with tempfile.TemporaryDirectory() as tmp:
        _build_chairs_tree(tmp)
        os.environ["DEXIRAFT_DATA_DIR"] = tmp
        cwd = os.getcwd()
        os.chdir(tmp)
        phases = [
            ("corrupt-sample", phase_corrupt_sample),
            ("worker-death", phase_worker_death),
            ("sigterm-resume", lambda: phase_sigterm_resume(tmp)),
            ("truncated-ckpt", lambda: phase_truncated_checkpoint(tmp)),
        ]
        try:
            for name, fn in phases:
                t0 = time.perf_counter()
                print(f"[chaos] {name} ...", flush=True)
                try:
                    fn()
                    print(f"[chaos] {name} PASS "
                          f"({time.perf_counter() - t0:.1f}s)", flush=True)
                except Exception:
                    traceback.print_exc()
                    print(f"[chaos] {name} FAIL", flush=True)
                    failures.append(name)
        finally:
            os.chdir(cwd)
    total = time.perf_counter() - t_start
    if failures:
        print(f"[chaos] FAILED: {failures} ({total:.1f}s)")
        return 1
    print(f"[chaos] all {len(phases)} recovery paths recovered "
          f"({total:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
