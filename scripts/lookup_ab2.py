"""Second-round lookup experiments: where do the 2.9 ms/iter go?

Variants (all 2 streams batched, N = 14080, 4 levels, 32 chained iters):
  current     interp_window as shipped (y-contraction first)
  xfirst      contract x first (K = lane-major 128) then y
  fused       single three-operand einsum (XLA picks the path)
  build_only  just construct the one-hot A matrices each iteration
  mm_only     pre-built A matrices outside the loop, only the matmuls
"""

from __future__ import annotations

import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.corr import (
    _axis_interp_matrix,
    build_corr_pyramid,
    corr_lookup,
)
from dexiraft_tpu.ops.grid import coords_grid

H8, W8, C = 55, 128, 256
ITERS = 32
R = 4
WIN = 2 * R + 1


def _pyr():
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (2, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (2, H8, W8, C))
    return f1, f2


def _time(name, run, *args):
    float(run(*args))
    t0 = time.perf_counter()
    for _ in range(3):
        float(run(*args))
    dt = (time.perf_counter() - t0) / 3
    print(f"{name:>10s}: {dt * 1e3:8.1f} ms total, {dt / ITERS * 1e3:6.2f} ms/iter")


def bench_lookup(name, level_fn):
    f1, f2 = _pyr()

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, R)
        coords = coords_grid(2, H8, W8)

        def body(co, _):
            flat = co.reshape(-1, 2)
            out = []
            for i, corr in enumerate(pyr.levels):
                out.append(level_fn(corr[..., 0], flat / (2.0 ** i)))
            s = jnp.concatenate(out, axis=-1).reshape(2, H8, W8, -1)
            return co + 0.01 * s.mean(axis=-1, keepdims=True), None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    _time(name, run, f1, f2)


def lvl_current(vol, centers):
    ay = _axis_interp_matrix(centers[:, 1], R, vol.shape[1])
    ax = _axis_interp_matrix(centers[:, 0], R, vol.shape[2])
    rows = jnp.einsum("nby,nyx->nbx", ay, vol,
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nax,nbx->nab", ax, rows,
                      preferred_element_type=jnp.float32).reshape(
        vol.shape[0], WIN * WIN)


def lvl_xfirst(vol, centers):
    ay = _axis_interp_matrix(centers[:, 1], R, vol.shape[1])
    ax = _axis_interp_matrix(centers[:, 0], R, vol.shape[2])
    cols = jnp.einsum("nax,nyx->nay", ax, vol,
                      preferred_element_type=jnp.float32)
    return jnp.einsum("nby,nay->nab", ay, cols,
                      preferred_element_type=jnp.float32).reshape(
        vol.shape[0], WIN * WIN)


def lvl_fused(vol, centers):
    ay = _axis_interp_matrix(centers[:, 1], R, vol.shape[1])
    ax = _axis_interp_matrix(centers[:, 0], R, vol.shape[2])
    return jnp.einsum("nby,nyx,nax->nab", ay, vol, ax,
                      preferred_element_type=jnp.float32).reshape(
        vol.shape[0], WIN * WIN)


def bench_build_only():
    f1, f2 = _pyr()

    @jax.jit
    def run(f1, f2):
        coords = coords_grid(2, H8, W8)
        sizes = [(H8, W8), (27, 64), (13, 32), (6, 16)]

        def body(co, _):
            flat = co.reshape(-1, 2)
            acc = 0.0
            for i, (hl, wl) in enumerate(sizes):
                c = flat / (2.0 ** i)
                ay = _axis_interp_matrix(c[:, 1], R, hl)
                ax = _axis_interp_matrix(c[:, 0], R, wl)
                acc = acc + ay.sum() + ax.sum()
            return co + 1e-9 * acc, None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    _time("build_only", run, f1, f2)


def bench_mm_only():
    f1, f2 = _pyr()

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, R)
        coords = coords_grid(2, H8, W8)
        flat = coords.reshape(-1, 2)
        mats = []
        for i, corr in enumerate(pyr.levels):
            c = flat / (2.0 ** i)
            mats.append((_axis_interp_matrix(c[:, 1], R, corr.shape[1]),
                         _axis_interp_matrix(c[:, 0], R, corr.shape[2])))

        def body(carry, _):
            acc = carry
            outs = []
            for (ay, ax), corr in zip(mats, pyr.levels):
                vol = corr[..., 0] + acc  # keep iteration-dependent
                rows = jnp.einsum("nby,nyx->nbx", ay, vol,
                                  preferred_element_type=jnp.float32)
                w = jnp.einsum("nax,nbx->nab", ax, rows,
                               preferred_element_type=jnp.float32)
                outs.append(w.sum())
            return acc + 1e-9 * sum(outs), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), None, length=ITERS)
        return acc

    _time("mm_only", run, f1, f2)


def bench_blockdiag():
    """All 4 levels' y-einsums fused into ONE batched matmul against a
    block-diagonal concatenated volume (built once, loop-invariant);
    probes whether per-matmul-instance overhead dominates."""
    f1, f2 = _pyr()
    sizes = [(55, 128), (27, 64), (13, 32), (6, 16)]
    yoff = [0, 55, 82, 95]
    xoff = [0, 128, 192, 224]
    ktot, xtot = 101, 240

    @jax.jit
    def run(f1, f2):
        pyr = build_corr_pyramid(f1, f2, 4, R)
        n = 2 * H8 * W8
        vol_cat = jnp.zeros((n, ktot, xtot), jnp.float32)
        for lvl, corr in enumerate(pyr.levels):
            hl, wl = sizes[lvl]
            vol_cat = jax.lax.dynamic_update_slice(
                vol_cat, corr[..., 0], (0, yoff[lvl], xoff[lvl]))
        coords = coords_grid(2, H8, W8)

        def hats(flat):
            ays, axs = [], []
            for lvl in range(4):
                c = flat / (2.0 ** lvl)
                hl, wl = sizes[lvl]
                ays.append(_axis_interp_matrix(c[:, 1], R, hl))
                axs.append(_axis_interp_matrix(c[:, 0], R, wl))
            # place each level's hat into its global K/X range
            ay = jnp.zeros((flat.shape[0], 4, WIN, ktot), jnp.float32)
            ax = jnp.zeros((flat.shape[0], 4, WIN, xtot), jnp.float32)
            for lvl in range(4):
                hl, wl = sizes[lvl]
                ay = ay.at[:, lvl, :, yoff[lvl]:yoff[lvl] + hl].set(ays[lvl])
                ax = ax.at[:, lvl, :, xoff[lvl]:xoff[lvl] + wl].set(axs[lvl])
            return ay.reshape(-1, 4 * WIN, ktot), ax

        def body(co, _):
            flat = co.reshape(-1, 2)
            ay, ax = hats(flat)
            rows = jnp.einsum("nby,nyx->nbx", ay, vol_cat,
                              preferred_element_type=jnp.float32)
            rows = rows.reshape(-1, 4, WIN, xtot)
            w = jnp.einsum("nlax,nlbx->nlab", ax, rows,
                           preferred_element_type=jnp.float32)
            s = w.reshape(2, H8, W8, -1)
            return co + 0.01 * s.mean(axis=-1, keepdims=True), None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    _time("blockdiag", run, f1, f2)


def main():
    print(f"platform={jax.devices()[0].platform}", file=sys.stderr)
    t = jax.jit(lambda x: jnp.sum(x))
    float(t(jnp.ones((8, 8))))
    t0 = time.perf_counter()
    for _ in range(3):
        float(t(jnp.ones((8, 8))))
    print(f"       rtt: {(time.perf_counter() - t0) / 3 * 1e3:8.1f} ms")

    bench_lookup("current", lvl_current)
    bench_lookup("xfirst", lvl_xfirst)
    bench_lookup("fused", lvl_fused)
    bench_build_only()
    bench_mm_only()
    bench_blockdiag()


if __name__ == "__main__":
    main()
