"""Third-round experiment: bf16 inputs for the on-demand (local) corr path.

The local path recomputes the all-pairs block f1·f2ᵀ every iteration —
MXU FLOPs, not HBM reads, so input precision is the lever: fp32 matmuls
on TPU run as multi-pass bf16 decompositions, while native bf16 inputs
with fp32 accumulation (preferred_element_type) are one pass.

Variants (dual-stream batch B=2, 55x128x256, 4 levels, 32 chained iters):
  fp32      inputs cast to fp32 (shipped default — reference parity,
            core/raft.py:139-142 keeps correlation fp32)
  bf16      f1/f2 in bf16, fp32 accumulate; hats fp32
  bf16_all  f1/f2 AND hat matrices bf16, fp32 accumulate

Also prints the max |delta| of one lookup vs fp32 to bound the accuracy
cost of each variant.
"""

from __future__ import annotations

import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.corr import _axis_interp_matrix, avg_pool_2x2
from dexiraft_tpu.ops.grid import coords_grid

B, H8, W8, C = 2, 55, 128, 256
ITERS = 32
R = 4
WIN = 2 * R + 1


def _fmaps():
    key = jax.random.PRNGKey(0)
    f1 = jax.random.normal(key, (B, H8, W8, C), jnp.float32)
    f2 = jax.random.normal(jax.random.fold_in(key, 1), (B, H8, W8, C))
    return f1, f2


def local_level(f1, f2, centers, in_dtype, hat_dtype):
    """One level of the on-demand lookup at the given precisions."""
    b, h, w, c = f1.shape
    n = b * h * w
    q = f1.reshape(b, h * w, c).astype(in_dtype)
    t = f2.reshape(b, -1, c).astype(in_dtype)
    vol = jnp.einsum("bnd,bmd->bnm", q, t,
                     preferred_element_type=jnp.float32)
    vol = (vol / jnp.sqrt(jnp.float32(c))).reshape(n, f2.shape[1], f2.shape[2])
    ay = _axis_interp_matrix(centers[:, 1], R, f2.shape[1]).astype(hat_dtype)
    ax = _axis_interp_matrix(centers[:, 0], R, f2.shape[2]).astype(hat_dtype)
    win = jnp.einsum("nby,nyx,nax->nab", ay, vol.astype(hat_dtype), ax,
                     preferred_element_type=jnp.float32)
    return win.reshape(n, WIN * WIN)


def make_run(in_dtype, hat_dtype):
    @jax.jit
    def run(f1, f2):
        pyr2 = [f2]
        for _ in range(3):
            pyr2.append(avg_pool_2x2(pyr2[-1]))
        coords = coords_grid(B, H8, W8)

        def body(co, _):
            flat = co.reshape(-1, 2)
            out = [local_level(f1, lvl, flat / (2.0 ** i), in_dtype, hat_dtype)
                   for i, lvl in enumerate(pyr2)]
            s = jnp.concatenate(out, axis=-1).reshape(B, H8, W8, -1)
            return co + 0.01 * s.mean(axis=-1, keepdims=True), None

        co, _ = jax.lax.scan(body, coords, None, length=ITERS)
        return jnp.sum(co)

    return run


def main():
    print(f"platform={jax.devices()[0].platform}", file=sys.stderr)
    f1, f2 = _fmaps()

    t = jax.jit(lambda x: jnp.sum(x))
    float(t(jnp.ones((8, 8))))
    t0 = time.perf_counter()
    for _ in range(3):
        float(t(jnp.ones((8, 8))))
    rtt = (time.perf_counter() - t0) / 3
    print(f"       rtt: {rtt * 1e3:8.1f} ms")

    # accuracy bound: one lookup at identity coords, each variant vs fp32
    flat = coords_grid(B, H8, W8).reshape(-1, 2)
    ref = local_level(f1, f2, flat, jnp.float32, jnp.float32)
    for name, dts in [("bf16", (jnp.bfloat16, jnp.float32)),
                      ("bf16_all", (jnp.bfloat16, jnp.bfloat16))]:
        d = jnp.max(jnp.abs(local_level(f1, f2, flat, *dts) - ref))
        r = jnp.max(jnp.abs(ref))
        print(f"{name:>10s}: max|delta| {float(d):.4f} on max|corr| {float(r):.2f}")

    for name, dts in [("fp32", (jnp.float32, jnp.float32)),
                      ("bf16", (jnp.bfloat16, jnp.float32)),
                      ("bf16_all", (jnp.bfloat16, jnp.bfloat16))]:
        run = make_run(*dts)
        float(run(f1, f2))
        t0 = time.perf_counter()
        for _ in range(3):
            float(run(f1, f2))
        raw = (time.perf_counter() - t0) / 3
        # floor guard (same rule as bench.py): the RTT floor is measured
        # once and the tunnel latency drifts — never print a negative or
        # near-zero corrected time, fall back to the raw number
        dt = raw - rtt if raw > rtt else raw
        print(f"{name:>10s}: {dt * 1e3:8.1f} ms total "
              f"(raw {raw * 1e3:.1f}), {dt / ITERS * 1e3:6.2f} ms/iter")


if __name__ == "__main__":
    main()
