"""Probe raw batched-matmul cost on the chip for lookup-shaped operands.

Each case: scan of 32 chained einsums (carry-dependent) -> per-call cost.
"""

from __future__ import annotations

import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

ITERS = 32


def probe(name, batch, m, k, n, dtype=jnp.float32):
    a = jax.random.normal(jax.random.PRNGKey(0), (batch, m, k), dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (batch, k, n), dtype)

    @jax.jit
    def run(a, b):
        def body(carry, _):
            out = jnp.einsum("bmk,bkn->bmn", a + carry, b,
                             preferred_element_type=jnp.float32)
            return jnp.float32(1e-6) * jnp.mean(out), None

        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=ITERS)
        return c

    float(run(a, b))
    t0 = time.perf_counter()
    for _ in range(3):
        float(run(a, b))
    dt = (time.perf_counter() - t0) / 3 / ITERS
    per = dt / batch
    print(f"{name:>28s}: {dt * 1e3:7.2f} ms/call  {per * 1e9:7.1f} ns/elem")


def main():
    print(f"platform={jax.devices()[0].platform}", file=sys.stderr)
    t = jax.jit(lambda x: jnp.sum(x))
    float(t(jnp.ones((8, 8))))
    t0 = time.perf_counter()
    for _ in range(3):
        float(t(jnp.ones((8, 8))))
    rtt = (time.perf_counter() - t0) / 3
    print(f"rtt {rtt * 1e3:.1f} ms (already amortized /32 below: "
          f"{rtt / ITERS * 1e3:.2f} ms/call)")

    probe("L0 y-einsum b14080 9x55x128", 14080, 9, 55, 128)
    probe("L0 x-einsum b14080 9x128x9 ", 14080, 9, 128, 9)
    probe("L1 y-einsum b14080 9x27x64 ", 14080, 9, 27, 64)
    probe("wide-M     b3520 36x55x128 ", 3520, 36, 55, 128)
    probe("wide-M    b1760 72x55x128  ", 1760, 72, 55, 128)
    probe("bf16 L0    b14080 9x55x128 ", 14080, 9, 55, 128, jnp.bfloat16)
    probe("tall-K    b14080 55x9x128  ", 14080, 55, 9, 128)


if __name__ == "__main__":
    main()
