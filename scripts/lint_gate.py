"""CI lint gate: run jaxlint over dexiraft_tpu/ + scripts/, exit nonzero
on any unallowlisted finding.

This is the commit-time tripwire for the JAX/TPU footgun class the
benches can only catch after the fact (silent recompiles, implicit
host syncs, PRNG key reuse, missing donation — see
docs/static_analysis.md). Runs pre-pytest in the verify path; pure
stdlib, no jax import (jaxlint.py is loaded by file path so even
package __init__ side effects stay out), so it finishes in ~a second
and works offline.

Usage:
  python scripts/lint_gate.py                 # gate: exit 1 on findings
  python scripts/lint_gate.py --emit-allow    # print ready-to-paste
                                              # baseline.json entries for
                                              # current findings
  python scripts/lint_gate.py --list-rules
  python scripts/lint_gate.py path/to/file.py # lint specific files

Determinism config: dexiraft_tpu/analysis/baseline.json —
  "exclude": glob list of files the gate skips (archived probe scripts),
  "allow":   reviewed findings (rule + path + stripped source line +
             reason). A stale allow entry (matching nothing) fails the
             gate too: excuses die with the code they excused.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os.path as osp
import sys

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
LINTER = osp.join(REPO, "dexiraft_tpu", "analysis", "jaxlint.py")
BASELINE = osp.join(REPO, "dexiraft_tpu", "analysis", "baseline.json")


def _load_jaxlint():
    spec = importlib.util.spec_from_file_location("_jaxlint", LINTER)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations through sys.modules
    sys.modules["_jaxlint"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("lint_gate")
    ap.add_argument("files", nargs="*",
                    help="specific repo-relative files (default: the "
                         "whole dexiraft_tpu/ + scripts/ tree)")
    ap.add_argument("--baseline", default=BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="raw findings: no excludes, no allowlist")
    ap.add_argument("--emit-allow", action="store_true",
                    help="print baseline.json 'allow' entries for every "
                         "current finding (review before pasting!)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    jl = _load_jaxlint()
    if args.list_rules:
        for rule, name in sorted(jl.RULES.items()):
            print(f"{rule}  {name}")
        return 0

    baseline = None
    if not args.no_baseline:
        baseline = jl.Baseline.load(args.baseline)

    if args.files:
        findings = []
        for rel in args.files:
            rel = rel.replace(osp.sep, "/")
            if baseline is not None and baseline.excludes(rel):
                continue
            findings.extend(jl.lint_file(osp.join(REPO, rel), rel))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        if baseline is not None:
            kept, allowed, _ = baseline.split(findings)
            stale = []  # partial run can't judge staleness
        else:
            kept, allowed, stale = findings, [], []
        stats = {"files": len(args.files), "excluded": 0}
    else:
        kept, allowed, stale, stats = jl.lint_tree(REPO, baseline=baseline)

    if args.emit_allow:
        print(json.dumps([f.baseline_entry() for f in kept], indent=2))
        return 0 if not kept else 1

    for f in kept:
        print(f)
    for e in stale:
        print(f"stale baseline entry (matches nothing — remove it): "
              f"{json.dumps(e)}")
    ok = not kept and not stale
    print(f"lint gate: {stats['files']} files, {len(kept)} finding(s), "
          f"{len(allowed)} allowlisted, {stats['excluded']} excluded"
          f"{'' if ok else ' — FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
