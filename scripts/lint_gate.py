"""CI lint gate: run jaxlint over dexiraft_tpu/ + scripts/ + the
repo-root entry points (__graft_entry__.py, bench.py), exit nonzero on
any unallowlisted finding.

This is the commit-time tripwire for the JAX/TPU footgun class the
benches can only catch after the fact (silent recompiles, implicit
host syncs, PRNG key reuse, missing donation — see
docs/static_analysis.md). Runs pre-pytest in the verify path; pure
stdlib, no jax import (jaxlint.py is loaded by file path so even
package __init__ side effects stay out), so it finishes in ~a second
and works offline.

Usage:
  python scripts/lint_gate.py                 # gate: exit 1 on findings
  python scripts/lint_gate.py --emit-allow    # print ready-to-paste
                                              # baseline.json entries for
                                              # current findings
  python scripts/lint_gate.py --stats         # per-rule finding/allowlist
                                              # counts (rule-set growth
                                              # stays observable)
  python scripts/lint_gate.py --json          # machine-readable verdict:
                                              # findings + per-rule counts
                                              # as one JSON object (CI and
                                              # chaos_smoke consume this
                                              # instead of scraping
                                              # stdout); exit code
                                              # semantics unchanged
  python scripts/lint_gate.py --list-rules
  python scripts/lint_gate.py --rules JL03x   # run a rule subset (comma
                                              # list; trailing x is a
                                              # decade wildcard) — allow
                                              # entries outside the
                                              # subset are out of scope,
                                              # not stale
  python scripts/lint_gate.py path/to/file.py # lint specific files

Determinism config: dexiraft_tpu/analysis/baseline.json —
  "exclude": glob list of files the gate skips (archived probe scripts),
  "allow":   reviewed findings (rule + path + stripped source line +
             reason). A stale allow entry (matching nothing) fails the
             gate too: excuses die with the code they excused.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os.path as osp
import sys

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
LINTER = osp.join(REPO, "dexiraft_tpu", "analysis", "jaxlint.py")
BASELINE = osp.join(REPO, "dexiraft_tpu", "analysis", "baseline.json")


def _load_jaxlint():
    spec = importlib.util.spec_from_file_location("_jaxlint", LINTER)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations through sys.modules
    sys.modules["_jaxlint"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("lint_gate")
    ap.add_argument("files", nargs="*",
                    help="specific repo-relative files (default: the "
                         "whole dexiraft_tpu/ + scripts/ tree)")
    ap.add_argument("--baseline", default=BASELINE)
    ap.add_argument("--no-baseline", action="store_true",
                    help="raw findings: no excludes, no allowlist")
    ap.add_argument("--emit-allow", action="store_true",
                    help="print baseline.json 'allow' entries for every "
                         "current finding (review before pasting!)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (e.g. "
                         "JL030,JL022); a trailing 'x' wildcards the "
                         "decade (JL03x = every distlint rule). "
                         "Baseline allow entries for unselected rules "
                         "are out of scope, not stale")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule finding/allowlist counts after "
                         "the gate verdict")
    ap.add_argument("--json", action="store_true",
                    help="emit the whole verdict (findings, stale "
                         "entries, per-rule counts) as one JSON object "
                         "on stdout; exit code semantics unchanged")
    args = ap.parse_args(argv)

    jl = _load_jaxlint()
    if args.list_rules:
        for rule, name in sorted(jl.RULES.items()):
            print(f"{rule}  {name}")
        return 0

    rules = _expand_rules(args.rules, jl.RULES) if args.rules else None

    baseline = None
    if not args.no_baseline:
        baseline = jl.Baseline.load(args.baseline)
        if rules is not None:
            # a rule-subset run judges staleness only WITHIN the subset:
            # entries for unselected rules can't match (their rules never
            # ran) and must not read as stale
            baseline.allow = [e for e in baseline.allow
                              if e.get("rule") in rules]

    if args.files:
        findings = []
        n_excluded = 0
        for rel in args.files:
            rel = rel.replace(osp.sep, "/")
            if baseline is not None and baseline.excludes(rel):
                n_excluded += 1
                continue
            findings.extend(jl.lint_file(osp.join(REPO, rel), rel, rules))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        if baseline is not None:
            kept, allowed, _ = baseline.split(findings)
            stale = []  # partial run can't judge staleness
        else:
            kept, allowed, stale = findings, [], []
        stats = {"files": len(args.files) - n_excluded,
                 "excluded": n_excluded}
    else:
        kept, allowed, stale, stats = jl.lint_tree(REPO, baseline=baseline,
                                                   rules=rules)

    if args.emit_allow:
        print(json.dumps([f.baseline_entry() for f in kept], indent=2))
        return 0 if not kept else 1

    if args.json:
        return _emit_json(jl, baseline, kept, allowed, stale, stats)

    for f in kept:
        print(f)
    for e in stale:
        print(f"stale baseline entry (matches nothing — remove it): "
              f"{json.dumps(e)}")
    stale_ex = stats.get("stale_excludes", [])
    for pat in stale_ex:
        print(f"stale baseline exclude (matches no file — remove it): "
              f"{pat!r}")
    missing = stats.get("missing_scope", [])
    for sub in missing:
        print(f"missing scope entry (file vanished — the gate's reach "
              f"must not silently shrink): {sub!r}")
    ok = not kept and not stale and not stale_ex and not missing
    print(f"lint gate: {stats['files']} files, {len(kept)} finding(s), "
          f"{len(allowed)} allowlisted, {stats['excluded']} excluded"
          f"{'' if ok else ' — FAIL'}")
    if args.stats:
        _print_stats(jl, baseline, kept, allowed)
    return 0 if ok else 1


def _expand_rules(spec: str, all_rules) -> set:
    """--rules value -> concrete rule-id set. Tokens are exact ids or a
    decade wildcard (trailing 'x': JL03x -> JL030..JL039); a token
    matching no known rule is a usage error, not an empty run."""
    sel = set()
    for tok in spec.split(","):
        tok = tok.strip().upper()
        if not tok:
            continue
        if tok.endswith("X"):
            hits = {r for r in all_rules if r.startswith(tok[:-1])}
        else:
            hits = {tok} if tok in all_rules else set()
        if not hits:
            raise SystemExit(
                f"lint_gate: --rules token {tok!r} matches no known "
                f"rule (see --list-rules)")
        sel |= hits
    return sel


#: rule-id decade -> rule-family module (JL0dN: d selects the family)
FAMILIES = {0: "jaxlint", 1: "shardlint", 2: "threadlint", 3: "distlint"}


def _family(rule: str) -> str:
    return FAMILIES.get(int(rule[2:]) // 10, "unknown")


def _emit_json(jl, baseline, kept, allowed, stale, stats) -> int:
    """The --json verdict: everything the text mode prints, as one
    parseable object. ``ok`` mirrors the exit code (0 iff ok) so a
    consumer never has to reconcile two verdicts."""
    from collections import Counter

    n_kept = Counter(f.rule for f in kept)
    n_allowed = Counter(f.rule for f in allowed)
    n_entries = Counter(e.get("rule") for e in
                        (baseline.allow if baseline else []))
    stale_ex = stats.get("stale_excludes", [])
    missing = stats.get("missing_scope", [])
    ok = not kept and not stale and not stale_ex and not missing
    blob = {
        "ok": ok,
        "files": stats["files"],
        "excluded": stats["excluded"],
        "findings": [
            {"rule": f.rule, "name": jl.RULES[f.rule], "path": f.path,
             "line": f.line, "col": f.col, "message": f.message,
             "snippet": f.snippet}
            for f in kept],
        "allowlisted": len(allowed),
        "stale_allow": list(stale),
        "stale_excludes": list(stale_ex),
        "missing_scope": list(missing),
        "per_rule": {
            rule: {"findings": n_kept[rule], "allowlisted": n_allowed[rule],
                   "baseline_entries": n_entries[rule]}
            for rule in sorted(jl.RULES)
            if n_kept[rule] or n_allowed[rule] or n_entries[rule]},
        "per_family": {
            fam: {
                "rules": sum(1 for r in jl.RULES if _family(r) == fam),
                "findings": sum(n_kept[r] for r in jl.RULES
                                if _family(r) == fam),
                "allowlisted": sum(n_allowed[r] for r in jl.RULES
                                   if _family(r) == fam),
                "baseline_entries": sum(n_entries[r] for r in jl.RULES
                                        if _family(r) == fam),
            }
            for fam in sorted(set(_family(r) for r in jl.RULES))},
    }
    print(json.dumps(blob, indent=2, sort_keys=True))
    return 0 if ok else 1


def _print_stats(jl, baseline, kept, allowed) -> None:
    """Per-rule observability: live findings, allowlisted findings, and
    baseline allow entries, one row per rule that has any."""
    from collections import Counter

    n_kept = Counter(f.rule for f in kept)
    n_allowed = Counter(f.rule for f in allowed)
    n_entries = Counter(e.get("rule") for e in
                        (baseline.allow if baseline else []))
    print("rule   name                 findings  allowlisted  "
          "baseline-entries")
    for rule in sorted(jl.RULES):
        row = (n_kept[rule], n_allowed[rule], n_entries[rule])
        if not any(row):
            continue
        print(f"{rule}  {jl.RULES[rule]:<20} {row[0]:>8}  {row[1]:>11}  "
              f"{row[2]:>16}")
    print(f"total  {len(jl.RULES)} rules active        "
          f"{sum(n_kept.values()):>8}  {sum(n_allowed.values()):>11}  "
          f"{sum(n_entries.values()):>16}")


if __name__ == "__main__":
    sys.exit(main())
