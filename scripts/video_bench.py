"""Streaming-video perf probe: what the split encoder + device carry buy.

Four strict-mode experiments, emitted as ONE pinned JSON record (the
PR 8 bench convention: every timed window runs under
guards.strict_mode, so a retrace or implicit transfer FAILS the probe
instead of deflating a number):

  pairwise    the chained-pairs baseline: the monolithic eval step per
              frame (encoders run on BOTH frames of every pair), flow
              carried through the on-device splat.
  streamed    the split path (models/raft.py mode="encode"/"step"):
              each frame encoded ONCE, the previous frame's features
              reused — per-frame p50/p99 and the encoder-reuse speedup.
              Flow outputs must match the pairwise leg to <= 1e-4
              (identical chaining, so the A/B isolates encoder reuse).
  footprint   the streamed executables are length-independent: one
              compiled encode + refine + splat drive n in {2, 8, 32}
              frames with the SAME memory_analysis at every leg
              (extends the PR 12 highres_probe chained leg to the
              split path).
  carry       session-carry transfer bytes, MEASURED off the inference
              engine's ServeStats counters: the PR 6 host round-trip
              (flow_low D2H per response + flow_init H2D per warm
              request) vs the device-resident handoff's zero.

Off-TPU the Pallas kernels would run interpreter-mode, so
``resolve_corr_impl("auto")`` picks allpairs here — the record stamps
``corr_impl_resolved`` so A/Bs are self-describing across boxes.

Usage:
  python scripts/video_bench.py --cpu                  # full record
  python scripts/video_bench.py --variant v5 --iters 8 # heavier model
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import numpy as np

# ---- record schema pins (tests/test_zzvideo.py) --------------------------
VIDEO_RECORD_KEYS = frozenset({
    "metric", "platform", "variant", "small", "iters", "geometry",
    "strict", "corr_impl_resolved", "corr_dtype", "fused_update",
    "pairwise", "streamed", "speedup_streamed_over_pairwise",
    "parity_max_abs_diff", "parity_ok", "footprint", "carry",
})
LEG_KEYS = frozenset({
    "frames", "per_frame_ms_p50", "per_frame_ms_p99", "per_frame_ms_mean",
})
FOOTPRINT_KEYS = frozenset({
    "seq_lens", "encode_temp_mb", "refine_temp_mb", "per_frame_ms",
    "footprint_flat",
})
CARRY_KEYS = frozenset({
    "frames", "flow_init_bytes", "host_h2d_bytes_per_frame",
    "host_d2h_bytes_per_frame", "device_h2d_bytes_per_frame",
    "device_d2h_bytes_per_frame",
})


def validate_record(rec: dict) -> None:
    """Schema gate — a drifted record fails the probe loudly (the
    bench.validate_record convention)."""
    if set(rec) != VIDEO_RECORD_KEYS:
        raise ValueError(
            f"video record keys drifted: "
            f"missing {sorted(VIDEO_RECORD_KEYS - set(rec))}, "
            f"extra {sorted(set(rec) - VIDEO_RECORD_KEYS)}")
    for leg in ("pairwise", "streamed"):
        if set(rec[leg]) != LEG_KEYS:
            raise ValueError(f"{leg} leg keys drifted: {sorted(rec[leg])}")
    if set(rec["footprint"]) != FOOTPRINT_KEYS:
        raise ValueError(f"footprint keys drifted: "
                         f"{sorted(rec['footprint'])}")
    if set(rec["carry"]) != CARRY_KEYS:
        raise ValueError(f"carry keys drifted: {sorted(rec['carry'])}")


def _log(msg: str) -> None:
    print(f"[video_bench] {msg}", file=sys.stderr, flush=True)


def _pctl(samples, p):
    return round(float(np.percentile(samples, p)) * 1e3, 2)


def _leg_record(per_frame_s) -> dict:
    return {
        "frames": len(per_frame_s),
        "per_frame_ms_p50": _pctl(per_frame_s, 50),
        "per_frame_ms_p99": _pctl(per_frame_s, 99),
        "per_frame_ms_mean": round(float(np.mean(per_frame_s)) * 1e3, 2),
    }


def _temp_mb(compiled) -> float:
    ma = compiled.memory_analysis()
    return round(float(ma.temp_size_in_bytes) / 2**20, 2)


def _frames(n, h, w, seed=1):
    import jax

    key = jax.random.PRNGKey(seed)
    return [jax.device_get(jax.random.uniform(
        jax.random.fold_in(key, i), (1, h, w, 3), dtype="float32",
        minval=0, maxval=255)) for i in range(n)]


def _build(args):
    """(cfg, variables, resolved) — synthetic init (the probe measures
    the serving stack, not EPE), one resident device copy."""
    import jax

    from dexiraft_tpu.config import VARIANTS, TrainConfig, \
        resolve_corr_impl_args
    from dexiraft_tpu.train.state import create_state

    impl, fused = resolve_corr_impl_args(
        args, jax.devices()[0].platform, "video_bench")
    cfg = VARIANTS[args.variant](small=args.small, corr_impl=impl,
                                 corr_dtype=args.corr_dtype,
                                 fused_update=fused)
    state = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    variables = jax.device_put({"params": state.params,
                                "batch_stats": state.batch_stats})
    return cfg, variables, impl, fused


def run_record(args) -> dict:
    import jax

    from dexiraft_tpu.analysis import guards
    from dexiraft_tpu.eval.interpolate import forward_interpolate
    from dexiraft_tpu.train.step import (make_encode_step, make_eval_step,
                                         make_refine_step)

    h, w = (int(v) for v in args.size.split("x"))
    assert h % 8 == 0 and w % 8 == 0, "geometry must be /8 (bucket shape)"
    cfg, variables, impl, fused = _build(args)
    platform = jax.devices()[0].platform
    _log(f"platform={platform} variant={args.variant}"
         f"{'-small' if args.small else ''} iters={args.iters} "
         f"size={h}x{w} corr_impl={impl} frames={args.frames}")

    frames = _frames(args.frames + 1, h, w)
    frames_dev = [jax.device_put(f) for f in frames]
    zero_fi = jax.device_put(np.zeros((1, h // 8, w // 8, 2), np.float32))

    splat = jax.jit(lambda low: forward_interpolate(low[0])[None])

    # ---- pairwise baseline: monolithic step per chained pair ------------
    pair_step = make_eval_step(cfg, iters=args.iters)
    pair_c = pair_step.lower(variables, frames_dev[0], frames_dev[1],
                             None, None, zero_fi).compile()
    splat_c = None

    def run_pairwise():
        nonlocal splat_c
        times, flows = [], []
        fi = zero_fi
        for i in range(args.frames):
            t0 = time.perf_counter()
            low, up = pair_c(variables, frames_dev[i], frames_dev[i + 1],
                             None, None, fi)
            fi = splat_c(low)
            flows.append(jax.device_get(up))   # the response payload
            times.append(time.perf_counter() - t0)
        return times, flows

    # warmup (compiles splat too), then the strict timed window
    low0, _ = pair_c(variables, frames_dev[0], frames_dev[1], None, None,
                     zero_fi)
    splat_c = splat.lower(low0).compile()
    run_pairwise()
    with guards.strict_mode(label="video_bench:pairwise"):
        pair_times, pair_flows = run_pairwise()
    pairwise = _leg_record(pair_times)
    _log(f"pairwise: {pairwise['per_frame_ms_mean']} ms/frame mean "
         f"(p50 {pairwise['per_frame_ms_p50']})")

    # ---- streamed: encode once per frame, features reused ---------------
    encode_step = make_encode_step(cfg)
    refine_step = make_refine_step(cfg, iters=args.iters)
    enc_c = encode_step.lower(variables, frames_dev[0]).compile()
    feats0 = enc_c(variables, frames_dev[0])
    ref_c = refine_step.lower(variables, feats0, feats0, zero_fi).compile()

    def run_streamed():
        times, flows = [], []
        fi = zero_fi
        feats_prev = enc_c(variables, frames_dev[0])
        for i in range(args.frames):
            t0 = time.perf_counter()
            feats = enc_c(variables, frames_dev[i + 1])
            low, up = ref_c(variables, feats_prev, feats, fi)
            fi = splat_c(low)
            feats_prev = feats
            flows.append(jax.device_get(up))
            times.append(time.perf_counter() - t0)
        return times, flows

    run_streamed()
    with guards.strict_mode(label="video_bench:streamed"):
        stream_times, stream_flows = run_streamed()
    streamed = _leg_record(stream_times)
    _log(f"streamed: {streamed['per_frame_ms_mean']} ms/frame mean "
         f"(p50 {streamed['per_frame_ms_p50']})")

    # ---- parity: identical chaining => identical outputs ----------------
    parity = max(float(np.max(np.abs(a - b)))
                 for a, b in zip(pair_flows, stream_flows))
    _log(f"parity max |streamed - pairwise| = {parity:.2e}")

    # ---- footprint: one executable, any stream length -------------------
    per_frame_ms, enc_temp, ref_temp = [], [], []
    for n in args.seq_lens:
        seq = [jax.device_put(f) for f in _frames(n + 1, h, w, seed=7)]
        fi = zero_fi
        feats_prev = enc_c(variables, seq[0])
        with guards.strict_mode(label=f"video_bench:footprint_{n}"):
            t0 = time.perf_counter()
            for i in range(n):
                feats = enc_c(variables, seq[i + 1])
                low, up = ref_c(variables, feats_prev, feats, fi)
                fi = splat_c(low)
                feats_prev = feats
            jax.block_until_ready(up)
            per_frame_ms.append(round((time.perf_counter() - t0) / n * 1e3,
                                      1))
        # same executables at every length => same buffer assignment;
        # read them each time anyway so a drifted recompile cannot hide
        enc_temp.append(_temp_mb(enc_c))
        ref_temp.append(_temp_mb(ref_c))
        _log(f"footprint n={n}: {per_frame_ms[-1]} ms/frame, encode temp "
             f"{enc_temp[-1]} MB, refine temp {ref_temp[-1]} MB")
    footprint = {
        "seq_lens": list(args.seq_lens),
        "encode_temp_mb": enc_temp,
        "refine_temp_mb": ref_temp,
        "per_frame_ms": per_frame_ms,
        "footprint_flat": (len(set(enc_temp)) == 1
                           and len(set(ref_temp)) == 1),
    }

    # ---- carry bytes: host round-trip vs device handoff, MEASURED ------
    carry = measure_carry(args, cfg, variables, h, w)

    rec = {
        "metric": "video_stream_per_frame",
        "platform": platform,
        "variant": args.variant,
        "small": args.small,
        "iters": args.iters,
        "geometry": [h, w],
        "strict": True,
        "corr_impl_resolved": impl,
        "corr_dtype": args.corr_dtype,
        "fused_update": fused,
        "pairwise": pairwise,
        "streamed": streamed,
        "speedup_streamed_over_pairwise": round(
            pairwise["per_frame_ms_mean"] / streamed["per_frame_ms_mean"],
            3),
        "parity_max_abs_diff": parity,
        "parity_ok": parity <= 1e-4,
        "footprint": footprint,
        "carry": carry,
    }
    validate_record(rec)
    print(json.dumps(rec), flush=True)
    return rec


def measure_carry(args, cfg, variables, h: int, w: int) -> dict:
    """Session-carry transfer bytes off the engine's own counters: K
    chained warm frames through the PR 6 host path (flow_low fetched
    per response, flow_init re-uploaded per request) and through the
    device-resident handoff (both stay on chip). The timed loops run
    strict with transfer='allow' — the host leg's round-trip is the
    MEASURED phenomenon, not an accident."""
    import jax

    from dexiraft_tpu.analysis import guards
    from dexiraft_tpu.eval.interpolate import forward_interpolate
    from dexiraft_tpu.serve import InferenceEngine, ServeConfig
    from dexiraft_tpu.train.step import make_eval_step

    k_frames = 4
    step = make_eval_step(cfg, iters=args.iters)

    def eval_fn(a, b, fi):
        put = jax.device_put
        return step(variables, put(a), put(b),
                    flow_init=None if fi is None else put(fi))

    rng = np.random.default_rng(3)
    items = [{"image1": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
              "image2": rng.uniform(0, 255, (h, w, 3)).astype(np.float32)}
             for _ in range(k_frames)]

    def drive(device_carry: bool):
        engine = InferenceEngine(eval_fn, ServeConfig(
            batch_size=1, warm_start=True, device_carry=device_carry))
        if device_carry:
            carry_fn = jax.jit(lambda low: forward_interpolate(low))
        else:
            carry_fn = (lambda low:
                        jax.device_get(forward_interpolate(
                            jax.device_put(low))))
        # warmup: compile the bucket + splat signatures outside the
        # measured window, then reset the byte counters
        (res,) = engine.run_batch([dict(items[0])])
        carry_fn(res.flow_low)
        engine.watch.mark_warm()  # the splat compile is expected, not drift
        engine.reset_stats()
        engine.stats.carry_h2d_bytes = engine.stats.carry_d2h_bytes = 0
        fi = None
        with guards.strict_mode(label=f"video_bench:carry_"
                                      f"{'dev' if device_carry else 'host'}",
                                transfer="allow"):
            for it in items:
                item = dict(it)
                if fi is not None:
                    item["flow_init"] = fi
                (res,) = engine.run_batch([item])
                fi = carry_fn(res.flow_low)
        return (engine.stats.carry_h2d_bytes // k_frames,
                engine.stats.carry_d2h_bytes // k_frames)

    host_h2d, host_d2h = drive(device_carry=False)
    dev_h2d, dev_d2h = drive(device_carry=True)
    fi_bytes = (h // 8) * (w // 8) * 2 * 4
    _log(f"carry bytes/frame: host {host_h2d} up / {host_d2h} down vs "
         f"device {dev_h2d} / {dev_d2h} (flow_init is {fi_bytes} B)")
    return {
        "frames": k_frames,
        "flow_init_bytes": fi_bytes,
        "host_h2d_bytes_per_frame": int(host_h2d),
        "host_d2h_bytes_per_frame": int(host_d2h),
        "device_h2d_bytes_per_frame": int(dev_h2d),
        "device_d2h_bytes_per_frame": int(dev_d2h),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variant", default="v1")
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--iters", type=int, default=4,
                    help="refinement iterations per frame")
    ap.add_argument("--size", default="96x128",
                    help="frame geometry HxW (must be /8)")
    ap.add_argument("--frames", type=int, default=8,
                    help="frames in the timed pairwise/streamed legs")
    ap.add_argument("--seq_lens", type=int, nargs="+", default=(2, 8, 32),
                    help="stream lengths for the flat-footprint leg")
    ap.add_argument("--corr_impl", default="auto",
                    choices=["auto", "allpairs", "local", "pallas",
                             "flash"])
    ap.add_argument("--corr_dtype", default="fp32",
                    choices=["fp32", "bf16", "int8"])
    ap.add_argument("--fused_update", action="store_true")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (config.update beats "
                         "the axon site-hook pin)")
    args = ap.parse_args()
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    run_record(args)


if __name__ == "__main__":
    main()
