"""CI shard-audit gate: compile the train/eval/serve steps on a forced
8-virtual-device host mesh, resolve every input/output leaf's sharding,
and diff against the checked-in golden — exit nonzero on any drift.

The static companion to scripts/lint_gate.py: lint proves specs are
DRAWN from the canonical layout (parallel/layout.py, jaxlint JL010+);
this proves what the compiled executables actually DO with them, and
that nothing big resolves fully replicated (the ~200 MB correlation
volume being the canary). Runs on CPU — GSPMD partitioning is
platform-independent, so the resolved specs here are the pod's specs.
Wired into the tier-1 verify command right after lint_gate.py
(ROADMAP.md).

Usage:
  python scripts/shard_audit.py                  # gate: diff vs golden
  python scripts/shard_audit.py --write-golden   # regenerate (review the
                                                 # diff in the PR!)
  python scripts/shard_audit.py --steps serve    # partial (faster) audit
  python scripts/shard_audit.py --json           # dump the full report

Exit codes: 0 clean, 1 drift or a flagged replicated group.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The host platform must be forced BEFORE jax's backend initializes —
# the environment's site hook pins JAX_PLATFORMS to the TPU tunnel, so
# the env var alone is not enough (same dance as __graft_entry__).
_N_DEVICES = 8
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEVICES}")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("shard_audit")
    ap.add_argument("--steps", default="train,eval,serve",
                    help="comma-separated subset of train,eval,serve "
                         "(partial runs diff only their sections)")
    ap.add_argument("--golden", default=None,
                    help="golden path (default: "
                         "dexiraft_tpu/analysis/layout_golden.json)")
    ap.add_argument("--write-golden", action="store_true",
                    help="regenerate the golden from this run (always "
                         "audits ALL steps)")
    ap.add_argument("--threshold-mb", type=float, default=None,
                    help="replicated-array size tripwire (default 64)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report JSON")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu.analysis import shardaudit

    golden_path = args.golden or shardaudit.GOLDEN_PATH
    threshold = (args.threshold_mb if args.threshold_mb is not None
                 else shardaudit.DEFAULT_THRESHOLD_MB)
    steps = [s for s in args.steps.split(",") if s]
    unknown = set(steps) - set(shardaudit.STEP_AUDITS)
    if unknown:
        ap.error(f"unknown steps {sorted(unknown)}; "
                 f"choose from {sorted(shardaudit.STEP_AUDITS)}")
    if args.write_golden:
        steps = sorted(shardaudit.STEP_AUDITS)

    report = shardaudit.run_audit(steps, threshold_mb=threshold)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))

    flagged = shardaudit.flagged_groups(report)
    for line in flagged:
        print(f"shard audit: FLAGGED {line}")

    if args.write_golden:
        if flagged:
            print("shard audit: refusing to write a golden with flagged "
                  "replicated groups — fix the layout first")
            return 1
        shardaudit.write_golden(report, golden_path)
        print(f"shard audit: wrote {golden_path} "
              f"(hash {shardaudit.golden_hash(golden_path)[:12]})")
        return 0

    try:
        golden = shardaudit.load_golden(golden_path)
    except FileNotFoundError:
        print(f"shard audit: no golden at {golden_path} — bootstrap with "
              f"--write-golden")
        return 1
    drift = shardaudit.diff_golden(report, golden)
    for line in drift:
        print(f"shard audit: DRIFT {line}")
    ok = not drift and not flagged
    print(f"shard audit: {len(steps)} step(s) "
          f"({','.join(steps)}), {len(drift)} drift line(s), "
          f"{len(flagged)} flagged group(s), golden "
          f"{shardaudit.golden_hash(golden_path)[:12]}"
          f"{'' if ok else ' — FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
