"""CI shard-audit gate: compile the train/eval/serve steps on a forced
8-virtual-device host mesh, resolve every input/output leaf's sharding,
and diff against the checked-in golden — exit nonzero on any drift.

The static companion to scripts/lint_gate.py: lint proves specs are
DRAWN from the canonical layout (parallel/layout.py, jaxlint JL010+);
this proves what the compiled executables actually DO with them, and
that nothing big resolves fully replicated (the ~200 MB correlation
volume being the canary). Runs on CPU — GSPMD partitioning is
platform-independent, so the resolved specs here are the pod's specs.
Wired into the tier-1 verify command right after lint_gate.py
(ROADMAP.md).

Usage:
  python scripts/shard_audit.py                  # gate: diff vs ALL
                                                 # goldens (incl. the
                                                 # fsdp and halo legs)
  python scripts/shard_audit.py --write-golden   # regenerate all three
                                                 # (review the diff in
                                                 # the PR!)
  python scripts/shard_audit.py --steps serve    # partial (faster) audit
  python scripts/shard_audit.py --steps train_fsdp  # fsdp leg only
  python scripts/shard_audit.py --steps train_halo  # halo leg only
  python scripts/shard_audit.py --json           # dump the full report

Exit codes: 0 clean, 1 drift or a flagged replicated group.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The host platform must be forced BEFORE jax's backend initializes —
# the environment's site hook pins JAX_PLATFORMS to the TPU tunnel, so
# the env var alone is not enough (same dance as __graft_entry__).
_N_DEVICES = 8
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={_N_DEVICES}")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("shard_audit")
    ap.add_argument("--steps",
                    default="train,eval,serve,serve_encode,serve_refine,"
                            "train_fsdp,train_halo",
                    help="comma-separated subset of train,eval,serve,"
                         "serve_encode,serve_refine,train_fsdp,"
                         "train_halo (partial runs diff only their "
                         "sections; train_fsdp diffs the fsdp golden, "
                         "train_halo the halo one — the "
                         "compute_sharding='halo' step; serve_encode/"
                         "serve_refine are the split-model streaming "
                         "signatures)")
    ap.add_argument("--golden", default=None,
                    help="golden path (default: "
                         "dexiraft_tpu/analysis/layout_golden.json)")
    ap.add_argument("--fsdp-golden", default=None,
                    help="fsdp golden path (default: dexiraft_tpu/"
                         "analysis/layout_golden_fsdp.json)")
    ap.add_argument("--halo-golden", default=None,
                    help="halo golden path (default: dexiraft_tpu/"
                         "analysis/layout_golden_halo.json)")
    ap.add_argument("--write-golden", action="store_true",
                    help="regenerate ALL goldens from this run (always "
                         "audits ALL steps)")
    ap.add_argument("--threshold-mb", type=float, default=None,
                    help="replicated-array size tripwire (default 64)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report JSON")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu.analysis import shardaudit

    golden_path = args.golden or shardaudit.GOLDEN_PATH
    fsdp_golden_path = args.fsdp_golden or shardaudit.FSDP_GOLDEN_PATH
    halo_golden_path = args.halo_golden or shardaudit.HALO_GOLDEN_PATH
    threshold = (args.threshold_mb if args.threshold_mb is not None
                 else shardaudit.DEFAULT_THRESHOLD_MB)
    steps = [s for s in args.steps.split(",") if s]
    known = (set(shardaudit.STEP_AUDITS) | set(shardaudit.FSDP_STEP_AUDITS)
             | set(shardaudit.HALO_STEP_AUDITS))
    unknown = set(steps) - known
    if unknown:
        ap.error(f"unknown steps {sorted(unknown)}; "
                 f"choose from {sorted(known)}")
    if args.write_golden:
        steps = sorted(known)
    main_steps = [s for s in steps if s in shardaudit.STEP_AUDITS]
    fsdp_steps = [s for s in steps if s in shardaudit.FSDP_STEP_AUDITS]
    halo_steps = [s for s in steps if s in shardaudit.HALO_STEP_AUDITS]

    # (report, golden path, label) per golden file in play — the fsdp
    # leg diffs its own golden so the data x seq one never drifts when
    # only the fsdp layout changes (and vice versa)
    legs = []
    if main_steps or args.write_golden:
        legs.append((shardaudit.run_audit(main_steps,
                                          threshold_mb=threshold),
                     golden_path, "main"))
    if fsdp_steps:
        legs.append((shardaudit.run_audit_fsdp(fsdp_steps,
                                               threshold_mb=threshold),
                     fsdp_golden_path, "fsdp"))
    if halo_steps:
        legs.append((shardaudit.run_audit_halo(halo_steps,
                                               threshold_mb=threshold),
                     halo_golden_path, "halo"))

    if args.json:
        print(json.dumps({label: rep for rep, _, label in legs},
                         indent=1, sort_keys=True))

    flagged = []
    for rep, _, label in legs:
        for line in shardaudit.flagged_groups(rep):
            flagged.append(f"[{label}] {line}")
    for line in flagged:
        print(f"shard audit: FLAGGED {line}")

    if args.write_golden:
        if flagged:
            print("shard audit: refusing to write a golden with flagged "
                  "replicated groups — fix the layout first")
            return 1
        for rep, path, label in legs:
            shardaudit.write_golden(rep, path)
            print(f"shard audit: wrote {path} "
                  f"(hash {shardaudit.golden_hash(path)[:12]})")
        return 0

    drift = []
    hashes = []
    for rep, path, label in legs:
        try:
            golden = shardaudit.load_golden(path)
        except FileNotFoundError:
            print(f"shard audit: no golden at {path} — bootstrap with "
                  f"--write-golden")
            return 1
        drift += [f"[{label}] {d}"
                  for d in shardaudit.diff_golden(rep, golden)]
        hashes.append(shardaudit.golden_hash(path)[:12])
    for line in drift:
        print(f"shard audit: DRIFT {line}")
    ok = not drift and not flagged
    print(f"shard audit: {len(steps)} step(s) "
          f"({','.join(steps)}), {len(drift)} drift line(s), "
          f"{len(flagged)} flagged group(s), golden {'+'.join(hashes)}"
          f"{'' if ok else ' — FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
