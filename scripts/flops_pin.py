"""Pin the whole-forward FLOP count at the bench geometry, chip-free.

XLA's cost analysis counts the arithmetic of the optimized HLO — a
property of the program, not the silicon — so the 440x1024x32-iters
forward FLOPs can be pinned by a compile-only pass on the CPU backend
while the relay tunnel is down. The on-chip bench (bench.py MFU fields)
measures the same quantity on the TPU executable; this record is the
cross-check / tunnel-down fallback for the MFU denominator math in
docs/perf.md.

Compile only — never executes the forward (a 440x1024 CPU run costs
~100 s/forward; the count needs none of it).

Usage: python scripts/flops_pin.py [--iters 32] [--size 440 1024]
"""

from __future__ import annotations

import argparse
import json
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--size", type=int, nargs=2, default=(440, 1024))
    ap.add_argument("--corr_impl", default="allpairs")
    ap.add_argument("--mixed", action="store_true",
                    help="bf16 policy like the on-chip bench (flop "
                         "count is precision-independent; default fp32 "
                         "avoids CPU bf16 conv corner cases)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT

    h, w = args.size
    cfg = raft_v5(mixed_precision=args.mixed, corr_impl=args.corr_impl)
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = jax.jit(
        lambda r, a, b: model.init(r, a, b, iters=1, train=False))(
            rng, small, small)

    @jax.jit
    def forward(a, b):
        low, up = model.apply(variables, a, b, iters=args.iters,
                              train=False, test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    spec = jax.ShapeDtypeStruct((1, h, w, 3), jnp.float32)
    t0 = time.perf_counter()
    cost = forward.lower(spec, spec).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    flops = float(cost.get("flops", 0.0))
    print(f"# compile {time.perf_counter() - t0:.0f}s", file=sys.stderr)
    print(json.dumps({
        "metric": f"v5_forward_flops@{h}x{w}x{args.iters}it",
        "flops": flops,
        "tflops": round(flops / 1e12, 3),
        "corr_impl": args.corr_impl,
        "backend": "cpu-compile cost_analysis (program property)",
        "bytes_accessed": cost.get("bytes accessed"),
    }), flush=True)


if __name__ == "__main__":
    main()
