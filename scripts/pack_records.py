"""Offline packer CLI: fetch_dataset stage -> packed-record directory.

Decodes every distinct raw sample of a training stage ONCE and writes
the sharded record files + manifest that `train_cli --records_dir` and
`data.records.RecordLoader` consume (format spec: docs/data_plane.md).
Curriculum replication factors stay symbolic in the manifest, so the
sintel mixture's 2.6 M logical epoch packs only its distinct decodes.

--verify re-reads every record of every shard against the manifest
(CRC, counts, member ranges, dtypes) and exits nonzero on any mismatch
— run it after packing to a new filesystem before pointing a pod at it.
--verify_only skips packing and just audits an existing directory.

Usage:
  python scripts/pack_records.py --stage chairs --out /data/records/chairs \
      [--image_size 368 496] [--shards 16] [--train_ds C+T+K+S+H] [--verify]
  python scripts/pack_records.py --verify_only --out /data/records/chairs

Dataset roots come from DEXIRAFT_DATA_DIR exactly like training; no jax
import anywhere on this path, so it runs on any CPU box near the data.
"""

from __future__ import annotations

import argparse
import os.path as osp
import sys
import time

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

def main(argv=None) -> int:
    ap = argparse.ArgumentParser("pack_records")
    ap.add_argument("--stage",
                    choices=["chairs", "things", "sintel", "kitti"],
                    help="fetch_dataset stage to pack (omit with "
                         "--verify_only)")
    ap.add_argument("--out", required=True,
                    help="output records directory (shards + manifest.json)")
    ap.add_argument("--image_size", type=int, nargs=2, default=None,
                    help="crop recipe to bake into the pack's augmentor "
                         "params (default: the stage's training default "
                         "from config.STANDARD_STAGES)")
    ap.add_argument("--train_ds", default=None,
                    help="sintel-stage mixture selector (default: "
                         "datasets.DEFAULT_TRAIN_DS — the one train_cli "
                         "trains with; NOTE: train_cli --records_dir "
                         "REFUSES sintel packs made with any other "
                         "selector)")
    ap.add_argument("--shards", type=int, default=8,
                    help="shard-file count (clamped to the record count)")
    ap.add_argument("--verify", action="store_true",
                    help="after packing, re-read every shard against the "
                         "manifest; nonzero exit on any mismatch")
    ap.add_argument("--verify_only", action="store_true",
                    help="skip packing; audit an existing --out directory")
    args = ap.parse_args(argv)

    from dexiraft_tpu.data.records import pack_dataset, verify_records

    if not args.verify_only:
        if args.stage is None:
            ap.error("--stage is required unless --verify_only")
        # both jax-free imports; the defaults come from the SAME source
        # train_cli trains with, so a default pack always passes its
        # provenance gate
        from dexiraft_tpu.config import STANDARD_STAGES
        from dexiraft_tpu.data.datasets import DEFAULT_TRAIN_DS, fetch_dataset

        train_ds = args.train_ds or DEFAULT_TRAIN_DS
        if args.stage == "sintel" and train_ds != DEFAULT_TRAIN_DS:
            # say it BEFORE the hours of decoding, not after the pack
            # is refused at train time
            print(f"[pack] WARNING: train_ds={train_ds!r} differs from "
                  f"the default {DEFAULT_TRAIN_DS!r} — train_cli "
                  f"--records_dir will refuse this sintel pack "
                  f"(provenance gate); it remains usable for offline "
                  f"tooling only", file=sys.stderr)
        image_size = tuple(args.image_size or next(
            tc.image_size for tc in STANDARD_STAGES
            if tc.stage == args.stage))
        dataset = fetch_dataset(args.stage, image_size, train_ds=train_ds)
        t0 = time.perf_counter()
        last = [0.0]

        def progress(done: int, total: int) -> None:
            now = time.perf_counter()
            if now - last[0] > 10 or done == total:
                last[0] = now
                print(f"[pack] {done}/{total} records "
                      f"({done / (now - t0):.1f} rec/s)", flush=True)

        manifest = pack_dataset(
            dataset, args.out, num_shards=args.shards, stage=args.stage,
            image_size=image_size, train_ds=train_ds,
            progress=progress)
        dt = time.perf_counter() - t0
        nbytes = sum(s.bytes for s in manifest.shards)
        print(f"[pack] {manifest.num_records} records "
              f"({manifest.num_samples} logical samples) -> "
              f"{len(manifest.shards)} shard(s), {nbytes / 1e6:.1f} MB "
              f"in {dt:.1f}s; fingerprint {manifest.fingerprint[:12]} "
              f"-> {args.out}")

    if args.verify or args.verify_only:
        problems = verify_records(args.out)
        if problems:
            for p in problems:
                print(f"[verify] FAIL: {p}", file=sys.stderr)
            print(f"[verify] {len(problems)} problem(s) in {args.out}",
                  file=sys.stderr)
            return 1
        print(f"[verify] OK: every shard matches the manifest in "
              f"{args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
