"""Capture an on-chip profiler trace of the flagship v5 forward.

docs/perf.md's conclusion after the r4 component profile: the remaining
prelude gap (vs the ~4 ms MXU floor) sits in small-channel ops each too
small to resolve through the relay tunnel's ~80 ms RTT floor — the next
step is an on-device trace, not more RTT-differenced timings. This job
captures that trace (xplane protos via `dexiraft_tpu.profiling.trace`,
SURVEY.md §5) at the bench geometry so any later session — or an
operator with TensorBoard's profile plugin / Perfetto — can read
per-fusion device times without needing chip access of their own.

Writes to logs/profile_trace/<platform>/ and prints the artifact list.

Usage: python scripts/profile_trace.py [--iters 32] [--reps 3] [--cpu]
"""

from __future__ import annotations

import argparse
import glob
import os
import os.path as osp
import sys

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

import jax
import jax.numpy as jnp

HEIGHT, WIDTH = 440, 1024


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (shakeout; the axon "
                         "site hook pins JAX_PLATFORMS)")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT
    from dexiraft_tpu.profiling import trace

    platform = jax.devices()[0].platform
    print(f"platform={platform} geometry={HEIGHT}x{WIDTH} "
          f"iters={args.iters}", file=sys.stderr)

    cfg = raft_v5(mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = jax.jit(
        lambda r, a, b: model.init(r, a, b, iters=1, train=False))(
            jax.random.PRNGKey(0), small, small)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    im1 = jax.random.uniform(k1, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)
    im2 = jax.random.uniform(k2, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)

    @jax.jit
    def fwd(a, b):
        low, up = model.apply(variables, a, b, iters=args.iters,
                              train=False, test_mode=True)
        return jnp.sum(low) + jnp.sum(up)

    float(fwd(im1, im2))  # compile + warm OUTSIDE the trace window

    out_dir = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                       "logs", "profile_trace", platform)
    os.makedirs(out_dir, exist_ok=True)
    with trace(out_dir):
        for _ in range(args.reps):
            # the float() sync is the only fetch that provably postdates
            # the computation through the relay (see bench.py)
            float(fwd(im1, im2))

    arts = sorted(glob.glob(osp.join(out_dir, "**", "*"), recursive=True))
    files = [a for a in arts if osp.isfile(a)]
    total = sum(osp.getsize(f) for f in files)
    print(f"trace captured: {len(files)} files, {total / 1e6:.1f} MB "
          f"under {out_dir}")
    for f in files[:12]:
        print(f"  {osp.relpath(f, out_dir)}  {osp.getsize(f)}")
    if not files:
        raise SystemExit("no trace artifacts written")


if __name__ == "__main__":
    main()
