"""Parity test for convex upsampling vs. the reference implementation
(core/raft.py:87-98), re-expressed in torch."""

import numpy as np
import pytest

from dexiraft_tpu.ops import upsample_flow_convex

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402


def torch_upsample_flow(flow, mask):
    """Reference core/raft.py:87-98. flow (N,2,H,W), mask (N,576,H,W)."""
    N, _, H, W = flow.shape
    mask = mask.view(N, 1, 9, 8, 8, H, W)
    mask = torch.softmax(mask, dim=2)
    up_flow = F.unfold(8 * flow, [3, 3], padding=1)
    up_flow = up_flow.view(N, 2, 9, 1, 1, H, W)
    up_flow = torch.sum(mask * up_flow, dim=2)
    up_flow = up_flow.permute(0, 1, 4, 2, 5, 3)
    return up_flow.reshape(N, 2, 8 * H, 8 * W)


def test_convex_upsample_matches_reference():
    rng = np.random.RandomState(0)
    N, H, W = 2, 5, 7
    flow = rng.randn(N, H, W, 2).astype(np.float32)
    mask = rng.randn(N, H, W, 576).astype(np.float32)

    ours = np.asarray(upsample_flow_convex(flow, mask))

    # NHWC mask channels are (9, 8, 8) row-major = torch's view(N,1,9,8,8,H,W)
    t_flow = torch.from_numpy(flow.transpose(0, 3, 1, 2))
    t_mask = torch.from_numpy(mask.transpose(0, 3, 1, 2))
    ref = torch_upsample_flow(t_flow, t_mask).numpy().transpose(0, 2, 3, 1)

    assert ours.shape == (N, 8 * H, 8 * W, 2)
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_convex_upsample_uniform_mask_is_identityish():
    # with a uniform mask every output subpixel is the mean of the 3x3
    # neighborhood of 8*flow; for constant flow that equals 8*flow exactly
    # except at borders (zero padding) — check the interior.
    flow = np.ones((1, 4, 4, 2), np.float32) * 2.0
    mask = np.zeros((1, 4, 4, 576), np.float32)
    up = np.asarray(upsample_flow_convex(flow, mask))
    np.testing.assert_allclose(up[0, 8:24, 8:24], 16.0, rtol=1e-6)
