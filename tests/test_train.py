"""Training layer: schedule parity vs torch, step convergence, DP sharding,
checkpoint round-trip and curriculum partial restore."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dexiraft_tpu.config import RAFTConfig, TrainConfig, raft_v1
from dexiraft_tpu.parallel import make_mesh, shard_batch
from dexiraft_tpu.train import create_state, make_train_step, onecycle_lr
from dexiraft_tpu.train.state import param_count

SMALL = raft_v1(small=True)
TC = TrainConfig(num_steps=200, batch_size=2, iters=2, image_size=(64, 64), lr=1e-4)


def synthetic_batch(rng, batch=2, size=(64, 64)):
    """Pair of frames related by a constant 2px shift, so flow is learnable."""
    h, w = size
    base = rng.uniform(0, 255, (batch, h + 8, w + 8, 3)).astype(np.float32)
    img1 = base[:, 4 : 4 + h, 4 : 4 + w]
    img2 = base[:, 4 : 4 + h, 2 : 2 + w]  # shift x by +2
    flow = np.zeros((batch, h, w, 2), np.float32)
    flow[..., 0] = 2.0
    valid = np.ones((batch, h, w), np.float32)
    return {
        "image1": jnp.asarray(img1),
        "image2": jnp.asarray(img2),
        "flow": jnp.asarray(flow),
        "valid": jnp.asarray(valid),
    }


class TestOneCycle:
    def test_matches_torch_onecycle_linear(self):
        torch = pytest.importorskip("torch")
        total, max_lr = 1000, 4e-4
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.AdamW([p], lr=max_lr)
        sched = torch.optim.lr_scheduler.OneCycleLR(
            opt, max_lr, total_steps=total, pct_start=0.05,
            cycle_momentum=False, anneal_strategy="linear",
        )
        ours = onecycle_lr(max_lr, total)
        torch_lrs = []
        for _ in range(total):
            torch_lrs.append(opt.param_groups[0]["lr"])
            opt.step()
            sched.step()
        got = np.array([float(ours(s)) for s in range(total)])
        np.testing.assert_allclose(got, np.array(torch_lrs), rtol=1e-5, atol=1e-10)

    def test_clamps_past_total(self):
        s = onecycle_lr(1e-3, 100)
        assert float(s(150)) == pytest.approx(float(s(99)))


class TestTrainStep:
    def test_loss_decreases(self):
        state = create_state(jax.random.key(0), SMALL, TC)
        step = make_train_step(SMALL, TC)
        batch = synthetic_batch(np.random.default_rng(0))
        losses = []
        for _ in range(8):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        assert int(state.step) == 8

    def test_metrics_keys_and_lr(self):
        state = create_state(jax.random.key(0), SMALL, TC)
        step = make_train_step(SMALL, TC)
        _, metrics = step(state, synthetic_batch(np.random.default_rng(1)))
        for k in ("epe", "1px", "3px", "5px", "loss", "lr"):
            assert k in metrics
        assert float(metrics["lr"]) == pytest.approx(float(onecycle_lr(TC.lr, TC.num_steps + 100)(0)))

    def test_param_count_nonzero(self):
        state = create_state(jax.random.key(0), SMALL, TC)
        assert param_count(state.params) > 900_000  # small RAFT ~1M params


class TestShardedStep:
    def test_dp_mesh_matches_single_device(self):
        mesh = make_mesh()
        assert mesh.devices.size == 8, "conftest must provide 8 virtual devices"
        tc = TrainConfig(num_steps=200, batch_size=8, iters=2, image_size=(64, 64), lr=1e-4)
        batch = synthetic_batch(np.random.default_rng(2), batch=8)

        state_a = create_state(jax.random.key(0), SMALL, tc)
        step_single = make_train_step(SMALL, tc)
        state_a, m_single = step_single(state_a, batch)

        state_b = create_state(jax.random.key(0), SMALL, tc)
        step_dp = make_train_step(SMALL, tc, mesh=mesh)
        state_b, m_dp = step_dp(state_b, shard_batch(batch, mesh))

        assert np.isfinite(float(m_dp["loss"]))
        np.testing.assert_allclose(
            float(m_dp["loss"]), float(m_single["loss"]), rtol=1e-4
        )
        # parameters after one step agree (grad allreduce == full-batch grad)
        la = jax.tree.leaves(state_a.params)
        lb = jax.tree.leaves(state_b.params)
        for a, b in zip(la, lb):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-5)


class TestCheckpoint:
    def test_roundtrip_and_partial_restore(self, tmp_path):
        from dexiraft_tpu.train.checkpoint import (
            restore_checkpoint,
            restore_params_into,
            save_checkpoint,
        )

        state = create_state(jax.random.key(0), SMALL, TC)
        step = make_train_step(SMALL, TC)
        state, _ = step(state, synthetic_batch(np.random.default_rng(3)))
        save_checkpoint(str(tmp_path / "ck"), state)

        template = create_state(jax.random.key(1), SMALL, TC)
        restored = restore_checkpoint(str(tmp_path / "ck"), template)
        assert int(restored.step) == 1
        for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # strict=False semantics: graft into a DIFFERENT architecture
        big = RAFTConfig(variant="raft", small=False)
        fresh = create_state(jax.random.key(2), big, TC)
        merged, skipped = restore_params_into(fresh.params, restored.params)
        assert len(skipped) > 0  # architectures differ
        assert jax.tree_util.tree_structure(merged) == jax.tree_util.tree_structure(fresh.params)


class TestStateFiniteSignal:
    """The checkpoint gate's poison detector (train.step.all_finite):
    value_and_grad computes the loss from PRE-update params, so a step
    whose UPDATE introduces non-finite values passes a loss-only guard
    while the checkpoint would save the poisoned post-update state.
    state_finite is computed on the new state inside the step."""

    def test_healthy_step_reports_finite(self):
        state = create_state(jax.random.key(0), SMALL, TC)
        step = make_train_step(SMALL, TC)
        _, metrics = step(state, synthetic_batch(np.random.default_rng(0)))
        assert "state_finite" in metrics
        assert bool(metrics["state_finite"])

    def test_poisoned_update_flags_despite_finite_loss(self):
        """Inf in the optimizer's moments: the loss (pre-update params)
        stays finite, but the update poisons params — exactly the blind
        spot a loss-only guard has."""
        state = create_state(jax.random.key(0), SMALL, TC)
        step = make_train_step(SMALL, TC)
        state, _ = step(state, synthetic_batch(np.random.default_rng(0)))

        poisoned_opt = jax.tree.map(
            lambda x: (jnp.full_like(x, jnp.inf)
                       if jnp.issubdtype(x.dtype, jnp.inexact) else x),
            state.opt_state)
        state = state.replace(opt_state=poisoned_opt)
        new_state, metrics = step(state,
                                  synthetic_batch(np.random.default_rng(1)))
        assert np.isfinite(float(metrics["loss"]))  # pre-update loss: fine
        assert not bool(metrics["state_finite"])    # post-update: poisoned
        # and the poison is real, not a false alarm
        leaves = jax.tree.leaves(new_state.params)
        assert not all(np.isfinite(np.asarray(l)).all() for l in leaves)

    def test_all_finite_ignores_integer_leaves(self):
        from dexiraft_tpu.train.step import all_finite

        tree = {"count": jnp.int32(3), "x": jnp.ones((2, 2))}
        assert bool(all_finite(tree))
        tree["x"] = tree["x"].at[0, 0].set(jnp.nan)
        assert not bool(all_finite(tree))


class TestEdgeSumFusion:
    def test_step_runs_and_differs_from_plain(self):
        """alt/train_1.py:173-176 capability: per-iter predictions of the
        image pair and the edge-image pair are summed before the loss."""
        import dataclasses

        from dexiraft_tpu.train.state import create_state
        from dexiraft_tpu.train.step import make_train_step

        tc = dataclasses.replace(TC, edge_sum_fusion=True)
        rng = np.random.default_rng(0)
        batch = synthetic_batch(rng)
        batch["edges1"] = batch["image1"] * 0.5
        batch["edges2"] = batch["image2"] * 0.5

        state = create_state(jax.random.key(0), SMALL, tc)
        step = make_train_step(SMALL, tc)
        state2, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))

        plain_step = make_train_step(SMALL, TC)
        plain_state = create_state(jax.random.key(0), SMALL, TC)
        _, m_plain = plain_step(plain_state, {k: v for k, v in batch.items()
                                              if not k.startswith("edges")})
        # summed fusion must actually change the loss
        assert abs(float(m["loss"]) - float(m_plain["loss"])) > 1e-6

    def test_missing_edges_raises(self):
        import dataclasses

        import pytest

        from dexiraft_tpu.train.state import create_state
        from dexiraft_tpu.train.step import make_train_step

        tc = dataclasses.replace(TC, edge_sum_fusion=True)
        state = create_state(jax.random.key(0), SMALL, tc)
        step = make_train_step(SMALL, tc)
        with pytest.raises(ValueError, match="edge_sum_fusion"):
            step(state, synthetic_batch(np.random.default_rng(1)))
