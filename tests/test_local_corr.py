"""Local (on-demand) correlation vs the materialized all-pairs path.

At level 0 the two formulations compute the same quantity, so they must
agree to float tolerance for arbitrary fractional coords. Higher levels
legitimately differ (pooled correlation vs pooled fmap2 — the same
approximation the reference's AlternateCorrBlock makes, core/corr.py:63-91).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.ops.corr import build_corr_pyramid
from dexiraft_tpu.ops.local_corr import build_local_corr, local_corr_level


def _fmaps(key, b=2, h=12, w=16, c=32):
    k1, k2 = jax.random.split(key)
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    return f1, f2


def _coords(key, b, h, w, lo=-2.0, hi=2.0):
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    base = jnp.stack([xs, ys], axis=-1)[None].repeat(b, 0)
    return base + jax.random.uniform(key, (b, h, w, 2), jnp.float32, lo, hi)


class TestLevel0Parity:
    @pytest.mark.parametrize("radius", [3, 4])
    def test_matches_allpairs(self, radius):
        f1, f2 = _fmaps(jax.random.PRNGKey(0))
        b, h, w, _ = f1.shape
        coords = _coords(jax.random.PRNGKey(1), b, h, w)

        allpairs = build_corr_pyramid(f1, f2, num_levels=1, radius=radius)
        local = build_local_corr(f1, f2, num_levels=1, radius=radius)
        np.testing.assert_allclose(
            np.asarray(allpairs(coords)), np.asarray(local(coords)),
            rtol=1e-4, atol=1e-4)

    def test_far_out_of_frame_is_zero(self):
        f1, f2 = _fmaps(jax.random.PRNGKey(2))
        b, h, w, _ = f1.shape
        coords = jnp.full((b, h, w, 2), 1000.0)
        out = local_corr_level(f1, f2, coords, radius=4)
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_row_chunking_equivalent(self):
        f1, f2 = _fmaps(jax.random.PRNGKey(3), h=13)  # odd H: chunk padding
        b, h, w, _ = f1.shape
        coords = _coords(jax.random.PRNGKey(4), b, h, w)
        full = local_corr_level(f1, f2, coords, radius=4)
        chunked = local_corr_level(f1, f2, coords, radius=4, row_chunk=4)
        np.testing.assert_allclose(np.asarray(full), np.asarray(chunked),
                                   rtol=1e-5, atol=1e-5)


class TestPyramid:
    def test_multilevel_shapes(self):
        f1, f2 = _fmaps(jax.random.PRNGKey(5), h=16, w=16)
        b, h, w, _ = f1.shape
        coords = _coords(jax.random.PRNGKey(6), b, h, w)
        local = build_local_corr(f1, f2, num_levels=4, radius=4)
        out = local(coords)
        assert out.shape == (b, h, w, 4 * 81)
        assert out.dtype == jnp.float32

    def test_integer_coords_match_direct_dot(self):
        """At integer coords with zero offset the (r, r) window center is
        exactly <f1[p], f2[p]> / sqrt(C)."""
        f1, f2 = _fmaps(jax.random.PRNGKey(7))
        b, h, w, c = f1.shape
        ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                              jnp.arange(w, dtype=jnp.float32), indexing="ij")
        coords = jnp.stack([xs, ys], axis=-1)[None].repeat(b, 0)
        r = 4
        out = local_corr_level(f1, f2, coords, radius=r)
        center = out.reshape(b, h, w, 2 * r + 1, 2 * r + 1)[:, :, :, r, r]
        expect = jnp.einsum("bhwc,bhwc->bhw", f1, f2) / jnp.sqrt(jnp.float32(c))
        np.testing.assert_allclose(np.asarray(center), np.asarray(expect),
                                   rtol=1e-4, atol=1e-4)


class TestGradients:
    def test_grads_flow_to_fmaps_not_coords(self):
        f1, f2 = _fmaps(jax.random.PRNGKey(8), b=1, h=6, w=6, c=8)
        coords = _coords(jax.random.PRNGKey(9), 1, 6, 6)

        def loss(f1_, f2_, coords_):
            return jnp.sum(local_corr_level(f1_, f2_, coords_, radius=2) ** 2)

        g1, g2, gc = jax.grad(loss, argnums=(0, 1, 2))(f1, f2, coords)
        assert float(jnp.abs(g1).max()) > 0
        assert float(jnp.abs(g2).max()) > 0
        np.testing.assert_allclose(np.asarray(gc), 0.0)  # CUDA-kernel semantics


class TestRAFTIntegration:
    def test_raft_local_forward(self):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        cfg = raft_v1(small=True, corr_impl="local")
        model = RAFT(cfg)
        img = jnp.zeros((1, 64, 64, 3), jnp.float32)
        variables = model.init(jax.random.PRNGKey(0), img, img, iters=1, train=False)
        rng = jax.random.PRNGKey(1)
        im1 = jax.random.uniform(rng, (1, 64, 64, 3), jnp.float32, 0, 255)
        preds = model.apply(variables, im1, im1, iters=2, train=False)
        assert preds.shape == (2, 1, 64, 64, 2)
        assert np.isfinite(np.asarray(preds)).all()

    def test_raft_pallas_forward_matches_local(self, monkeypatch):
        # the corr_impl="pallas" seam through the WHOLE model (init with
        # local — the param tree is corr-independent — then apply with
        # the kernel in interpret mode, off-chip)
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        img = jnp.zeros((1, 32, 32, 3), jnp.float32)
        rng = jax.random.PRNGKey(1)
        im1 = jax.random.uniform(rng, (1, 32, 32, 3), jnp.float32, 0, 255)
        im2 = jax.random.uniform(jax.random.PRNGKey(2),
                                 (1, 32, 32, 3), jnp.float32, 0, 255)

        cfg_l = raft_v1(small=True, corr_impl="local")
        variables = RAFT(cfg_l).init(jax.random.PRNGKey(0), img, img,
                                     iters=1, train=False)
        ref = RAFT(cfg_l).apply(variables, im1, im2, iters=2, train=False)

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        cfg_p = raft_v1(small=True, corr_impl="pallas")
        out = RAFT(cfg_p).apply(variables, im1, im2, iters=2, train=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)
