"""Streaming video tier (PR 14): DeviceSessionStore byte-budget LRU
accounting, VideoEngine chunk/carry semantics (numpy stubs — no model),
the /v1/flow/stream endpoint, the engine's device-carry flow_init
assembly, and the split-model parity pin (encode_frame +
step_from_features == monolithic __call__ on the same params).

Named test_zz* to sort after the long-standing tail tests (tier-1 870 s
budget convention, see test_zpipeline_async.py); the jax-model parity
tests sit at the end of the file and use the small config at tiny
geometry.
"""

import json
import os.path as osp
import sys
import urllib.request

import numpy as np
import pytest

from dexiraft_tpu.serve import (DeviceSessionStore, FlowService,
                                InferenceEngine, ServeConfig, VideoEngine)
from dexiraft_tpu.serve.server import (decode_stream_response,
                                       encode_stream_request)
from dexiraft_tpu.serve.sessions import carry_nbytes


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _feats(kb: int) -> dict:
    """A feature-dict stand-in of `kb` KiB (float32)."""
    return {"fmap": np.zeros((kb * 256,), np.float32)}


_FI = np.zeros((4, 4, 2), np.float32)  # 128 B flow seed


# ---- DeviceSessionStore: byte-budget LRU accounting ----------------------


class TestDeviceSessionStore:
    def test_byte_budget_evicts_oldest_and_counters_move(self):
        clock = FakeClock()
        st = DeviceSessionStore(budget_bytes=2 * 1024 + 512, ttl_s=60,
                                clock=clock)
        st.put("a", (32, 32), _feats(1), _FI)
        clock.advance(1)
        st.put("b", (32, 32), _feats(1), _FI)
        assert len(st) == 2
        used = st.bytes_in_use
        assert used == 2 * carry_nbytes(_feats(1), _FI)
        clock.advance(1)
        # admitting c busts the budget -> the OLDEST stream (a) goes
        st.put("c", (32, 32), _feats(1), _FI)
        assert st.get("a", (32, 32)) is None       # evicted
        assert st.get("b", (32, 32)) is not None   # LRU survivor
        assert st.get("c", (32, 32)) is not None
        rec = st.stats_record()
        assert rec["budget_evicted"] == 1
        assert rec["active"] == 2
        assert st.bytes_in_use == used  # back under budget

    def test_touch_order_protects_hot_streams(self):
        clock = FakeClock()
        st = DeviceSessionStore(budget_bytes=2 * 1024 + 512, ttl_s=60,
                                clock=clock)
        st.put("a", (32, 32), _feats(1), _FI)
        clock.advance(1)
        st.put("b", (32, 32), _feats(1), _FI)
        clock.advance(1)
        st.get("a", (32, 32))   # a is now most-recent
        st.put("c", (32, 32), _feats(1), _FI)
        assert st.get("b", (32, 32)) is None   # b was LRU, not a
        assert st.get("a", (32, 32)) is not None

    def test_single_over_budget_stream_kept_and_counted(self):
        st = DeviceSessionStore(budget_bytes=1024, ttl_s=60,
                                clock=FakeClock())
        st.put("big", (64, 64), _feats(4), _FI)   # 4 KiB > 1 KiB budget
        assert st.get("big", (64, 64)) is not None
        assert st.stats_record()["over_budget"] == 1
        assert st.stats_record()["budget_evicted"] == 0

    def test_bucket_change_resets_exactly_one_stream(self):
        st = DeviceSessionStore(budget_bytes=1 << 20, ttl_s=60,
                                clock=FakeClock())
        st.put("a", (32, 32), _feats(1), _FI)
        st.put("b", (32, 32), _feats(1), _FI)
        # a's camera changed geometry into a new bucket: cold restart
        # for a ONLY, counted once
        assert st.get("a", (64, 64)) is None
        rec = st.stats_record()
        assert rec["bucket_resets"] == 1
        assert rec["active"] == 1
        assert st.get("b", (32, 32)) is not None  # untouched

    def test_ttl_expiry_and_update_accounting(self):
        clock = FakeClock()
        st = DeviceSessionStore(budget_bytes=1 << 20, ttl_s=10,
                                clock=clock)
        st.put("a", (32, 32), _feats(1), _FI)
        clock.advance(11)
        assert st.get("a", (32, 32)) is None
        assert st.stats_record()["expired"] == 1
        # replacing a carry re-accounts bytes instead of double-counting
        st.put("b", (32, 32), _feats(1), _FI)
        st.put("b", (32, 32), _feats(2), _FI)
        assert st.bytes_in_use == carry_nbytes(_feats(2), _FI)
        assert len(st) == 1

    def test_counter_reset_keeps_state(self):
        st = DeviceSessionStore(budget_bytes=1 << 20, ttl_s=60,
                                clock=FakeClock())
        st.put("a", (32, 32), _feats(1), _FI)
        st.get("a", (32, 32))
        st.reset_counters()
        rec = st.stats_record()
        assert rec["hits"] == 0 and rec["active"] == 1
        assert rec["bytes_in_use_mb"] > 0
        assert set(rec) == {
            "active", "ttl_s", "max_sessions", "budget_mb",
            "bytes_in_use_mb", "peak_mb", "hits", "misses", "expired",
            "lru_evicted", "budget_evicted", "bucket_resets",
            "over_budget"}


# ---- VideoEngine: chunk/carry semantics over numpy stubs ----------------


def _stub_encode(frame):
    return {"fmap": np.asarray(frame)[..., :1].copy()}


def _stub_refine(f1, f2, fi):
    """flow_low = flow_init + 1 (chaining visible); flow_up broadcasts
    its mean so the test can read the chain depth off the response."""
    b, h, w = f1["fmap"].shape[:3]
    low = np.asarray(fi) + 1.0
    up = np.full((b, h, w, 2), float(np.mean(low)), np.float32)
    return low, up


def _video(**kw):
    kw.setdefault("sessions", DeviceSessionStore(budget_bytes=1 << 20,
                                                 ttl_s=60,
                                                 clock=FakeClock()))
    return VideoEngine(_stub_encode, _stub_refine, bucket_multiple=16,
                       **kw)


def _chunk(t=3, h=40, w=56, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 255, (t, h, w, 3)).astype(np.float32)


class TestVideoEngine:
    def test_cold_chunk_yields_t_minus_1_flows(self):
        v = _video()
        res = v.process_chunk("cam", _chunk(3))
        assert not res.warm
        assert res.frames_in == 3 and len(res.flows) == 2
        assert res.flows[0].shape == (40, 56, 2)
        # consecutive pairs chain: seed 0 -> low 1 -> low 2
        assert float(res.flows[0].mean()) == pytest.approx(1.0)
        assert float(res.flows[1].mean()) == pytest.approx(2.0)

    def test_warm_chunk_pairs_carry_with_first_frame(self):
        v = _video()
        v.process_chunk("cam", _chunk(3))
        res = v.process_chunk("cam", _chunk(3, seed=1))
        # warm: (carry, f0) + 2 in-chunk pairs, chain continues 3, 4, 5
        assert res.warm and len(res.flows) == 3
        assert [float(f.mean()) for f in res.flows] == [
            pytest.approx(3.0), pytest.approx(4.0), pytest.approx(5.0)]

    def test_cold_single_frame_primes_carry_only(self):
        v = _video()
        res = v.process_chunk("cam", _chunk(1))
        assert res.frames_in == 1 and len(res.flows) == 0
        res = v.process_chunk("cam", _chunk(1, seed=1))
        assert res.warm and len(res.flows) == 1

    def test_no_session_id_is_standalone(self):
        v = _video()
        v.process_chunk(None, _chunk(3))
        assert len(v.sessions) == 0
        res = v.process_chunk(None, _chunk(3))
        assert not res.warm    # nothing carried

    def test_blank_session_id_is_standalone(self):
        # "" as a real key would share one carry across every client
        # that sends a blank X-Session-Id header (pair endpoint parity)
        v = _video()
        v.process_chunk("", _chunk(3))
        assert len(v.sessions) == 0
        res = v.process_chunk("", _chunk(3))
        assert not res.warm

    def test_chunk_cap_rejects_oversize(self):
        v = _video(max_chunk_frames=4)
        with pytest.raises(ValueError, match="caps chunks at 4"):
            v.process_chunk("cam", _chunk(5))
        assert v.process_chunk("cam", _chunk(4)).frames_in == 4
        with pytest.raises(ValueError):
            _video(max_chunk_frames=0)

    def test_inflight_zero_at_rest_and_after_traffic(self):
        v = _video()
        assert v.inflight() == 0
        v.process_chunk("cam", _chunk(3))
        assert v.inflight() == 0

    def test_admission_sheds_past_max_pending_chunks(self):
        from dexiraft_tpu.serve.video import StreamOverloaded

        v = _video(max_pending_chunks=2)
        with v._inflight_lock:
            v._inflight = 2   # two chunks already queued on the lock
        try:
            with pytest.raises(StreamOverloaded, match="retry"):
                v.process_chunk("cam", _chunk(2))
        finally:
            with v._inflight_lock:
                v._inflight = 0
        assert v.process_chunk("cam", _chunk(2)).frames_in == 2
        with pytest.raises(ValueError):
            _video(max_pending_chunks=0)

    def test_stats_scrape_never_blocks_behind_a_live_chunk(self):
        # _lock is held for a whole chunk's frame loop; stats_record
        # takes only the stats lock, so a /stats scrape (router
        # aggregation, monitoring) returns immediately
        import threading

        v = _video()
        v.process_chunk("cam", _chunk(3))
        out = {}
        with v._lock:   # a chunk is "mid-flight"
            t = threading.Thread(
                target=lambda: out.update(rec=v.stats_record()))
            t.start()
            t.join(timeout=5)
            assert not t.is_alive(), "stats_record blocked on _lock"
        assert out["rec"]["chunks"] == 1

    def test_bucket_change_restarts_cold(self):
        v = _video()
        v.process_chunk("cam", _chunk(3))
        res = v.process_chunk("cam", _chunk(3, h=72, w=88))
        assert not res.warm and len(res.flows) == 2
        assert v.sessions.stats_record()["bucket_resets"] == 1

    def test_validation_rejects_malformed(self):
        v = _video()
        with pytest.raises(ValueError):
            v.process_chunk("cam", np.zeros((40, 56, 3), np.float32))
        with pytest.raises(ValueError):
            v.process_chunk("cam", np.zeros((0, 40, 56, 3), np.float32))
        with pytest.raises(ValueError):
            v.process_chunk("cam", np.zeros((2, 40, 56, 4), np.float32))

    def test_stats_record_and_reset(self):
        v = _video()
        v.process_chunk("cam", _chunk(3))
        v.process_chunk("cam", _chunk(3))
        rec = v.stats_record()
        assert rec["chunks"] == 2 and rec["frames_in"] == 6
        assert rec["flows_out"] == 5
        assert rec["warm_chunks"] == 1 and rec["cold_chunks"] == 1
        assert rec["compiled_buckets"] == ["48x64"]
        assert rec["sessions"]["active"] == 1
        v.reset_stats()
        rec = v.stats_record()
        assert rec["chunks"] == 0
        assert rec["compiled_buckets"] == ["48x64"]   # state survives
        assert rec["sessions"]["active"] == 1


# ---- the /v1/flow/stream endpoint over the stub video engine ------------


def _stub_eval(im1, im2, flow_init=None):
    b, h, w = im1.shape[:3]
    return (np.zeros((b, h // 8, w // 8, 2), np.float32),
            np.zeros((b, h, w, 2), np.float32))


class TestStreamEndpoint:
    @pytest.fixture()
    def service(self):
        svc = FlowService(
            InferenceEngine(_stub_eval, ServeConfig(batch_size=1)),
            port=0, video=_video()).start()
        yield svc
        svc.drain_and_stop(timeout=10)

    def _post(self, svc, frames, sid=None):
        headers = {"X-Session-Id": sid} if sid else {}
        req = urllib.request.Request(svc.url + "/v1/flow/stream",
                                     data=encode_stream_request(frames),
                                     headers=headers)
        resp = urllib.request.urlopen(req, timeout=30)
        return resp, decode_stream_response(resp.read())

    def test_chunked_stream_carries_across_requests(self, service):
        resp, flows = self._post(service, _chunk(3), "vid")
        assert resp.headers["X-Warm-Start"] == "0"
        assert resp.headers["X-Flows-Out"] == "2"
        assert flows.shape == (2, 40, 56, 2)
        resp, flows = self._post(service, _chunk(3, seed=1), "vid")
        assert resp.headers["X-Warm-Start"] == "1"
        assert flows.shape == (3, 40, 56, 2)
        assert resp.headers["X-Bucket"] == "48x64"

    def test_stream_stats_on_endpoint(self, service):
        self._post(service, _chunk(2), "vid")
        stats = json.loads(urllib.request.urlopen(
            service.url + "/stats", timeout=30).read())
        assert stats["video"]["chunks"] == 1
        assert stats["video"]["sessions"]["active"] == 1

    def test_healthz_inflight_counts_streaming_chunks(self, service):
        # streaming bypasses the scheduler; the router's zero-drop
        # drain polls healthz inflight, so live chunks must count there
        assert service.health_record()["inflight"] == 0
        with service.video._inflight_lock:
            service.video._inflight += 1
        try:
            assert service.health_record()["inflight"] == 1
        finally:
            with service.video._inflight_lock:
                service.video._inflight -= 1

    def test_overloaded_stream_is_503_with_retry_after(self, service):
        with service.video._inflight_lock:
            service.video._inflight = service.video.max_pending_chunks
        try:
            req = urllib.request.Request(
                service.url + "/v1/flow/stream",
                data=encode_stream_request(_chunk(2)))
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=30)
            assert e.value.code == 503
            assert e.value.headers["Retry-After"] == "1"
        finally:
            with service.video._inflight_lock:
                service.video._inflight = 0

    def test_oversize_chunk_is_400(self, service):
        service.video.max_chunk_frames = 2
        req = urllib.request.Request(
            service.url + "/v1/flow/stream",
            data=encode_stream_request(_chunk(3)))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 400
        assert b"caps chunks" in e.value.read()

    def test_malformed_chunk_is_400(self, service):
        req = urllib.request.Request(
            service.url + "/v1/flow/stream",
            data=encode_stream_request(np.zeros((40, 56, 3))))
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 400

    def test_streaming_disabled_is_404_with_hint(self):
        svc = FlowService(
            InferenceEngine(_stub_eval, ServeConfig(batch_size=1)),
            port=0).start()
        try:
            req = urllib.request.Request(
                svc.url + "/v1/flow/stream",
                data=encode_stream_request(_chunk(2)))
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=30)
            assert e.value.code == 404
            assert b"stream_sessions_mb" in e.value.read()
        finally:
            svc.drain_and_stop(timeout=10)


# ---- engine device-carry flow_init assembly ------------------------------


class TestEngineDeviceCarry:
    def test_host_path_counts_carry_bytes(self):
        eng = InferenceEngine(_stub_eval,
                              ServeConfig(batch_size=2, warm_start=True))
        fi = np.ones((5, 7, 2), np.float32)
        eng.run_batch([
            {"image1": np.zeros((40, 56, 3), np.float32),
             "image2": np.zeros((40, 56, 3), np.float32),
             "flow_init": fi},
            {"image1": np.zeros((40, 56, 3), np.float32),
             "image2": np.zeros((40, 56, 3), np.float32)}])
        assert eng.stats.carry_h2d_bytes == fi.nbytes  # warm row only
        assert eng.stats.carry_d2h_bytes == 0          # stub: no fetch

    def test_device_carry_strict_compile_flat_on_multi_row_batches(self):
        """The per-row carry slice (low[row]) is one executable per
        STATIC row index: a one-item warmup batch only ever slices row
        0, so rows 1.. must be pre-compiled inside the fresh-dispatch
        sanctioned window or the first real multi-warm batch trips the
        --strict check."""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def eval_fn(im1, im2, fi):
            return fi, jnp.zeros(im1.shape[:3] + (2,), jnp.float32)

        eng = InferenceEngine(
            eval_fn, ServeConfig(batch_size=3, warm_start=True,
                                 device_carry=True, strict=True))
        item = lambda: {"image1": np.zeros((40, 56, 3), np.float32),
                        "image2": np.zeros((40, 56, 3), np.float32)}
        eng.run_batch([item()])                      # warmup-like, fresh
        eng.run_batch([item(), item(), item()])      # slices rows 1, 2
        eng.run_batch([item(), item(), item()])      # strict: stays flat

    def test_device_path_assembles_on_device_with_zero_bytes(self):
        import jax

        eng = InferenceEngine(
            _stub_eval, ServeConfig(batch_size=2, warm_start=True,
                                    device_carry=True))
        row = jax.device_put(np.full((5, 7, 2), 2.0, np.float32))
        fi = eng._assemble_fi((40, 56), [row, None])
        assert fi.shape == (2, 5, 7, 2)
        got = jax.device_get(fi)
        np.testing.assert_array_equal(got[0], 2.0)
        np.testing.assert_array_equal(got[1], 0.0)
        assert eng.stats.carry_h2d_bytes == 0
        # device flow_init into a host-carry engine is refused loudly
        host_eng = InferenceEngine(_stub_eval,
                                   ServeConfig(batch_size=2,
                                               warm_start=True))
        with pytest.raises(ValueError, match="device_carry"):
            host_eng._assemble_fi((40, 56), [row, None])


# ---- video_bench record schema ------------------------------------------


def test_video_bench_record_schema_pins():
    sys.path.insert(0, osp.join(osp.dirname(osp.dirname(
        osp.abspath(__file__))), "scripts"))
    try:
        from video_bench import (CARRY_KEYS, FOOTPRINT_KEYS, LEG_KEYS,
                                 VIDEO_RECORD_KEYS, validate_record)
    finally:
        sys.path.pop(0)
    leg = {k: 0 for k in LEG_KEYS}
    rec = {k: None for k in VIDEO_RECORD_KEYS}
    rec.update(pairwise=dict(leg), streamed=dict(leg),
               footprint={k: [] for k in FOOTPRINT_KEYS},
               carry={k: 0 for k in CARRY_KEYS})
    validate_record(rec)   # complete record passes
    bad = dict(rec)
    del bad["corr_impl_resolved"]
    with pytest.raises(ValueError):
        validate_record(bad)
    rec["streamed"] = {**leg, "extra": 1}
    with pytest.raises(ValueError):
        validate_record(rec)


# ---- split-model parity pin (jax; small model, tiny frames) -------------


@pytest.mark.parametrize("variant", ["v1", "v5"])
def test_split_encoder_parity_with_monolithic(variant):
    """encode_frame + step_from_features == monolithic __call__ on the
    SAME params (the streaming tier's correctness contract): cold and
    warm-start forwards agree to <= 1e-4, and the split path never
    forks the param tree (the same init serves both)."""
    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.config import VARIANTS
    from dexiraft_tpu.models.raft import RAFT

    cfg = VARIANTS[variant](small=True)
    model = RAFT(cfg)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    im1 = jax.random.uniform(k1, (1, 48, 64, 3), jnp.float32, 0, 255)
    im2 = jax.random.uniform(k2, (1, 48, 64, 3), jnp.float32, 0, 255)
    variables = model.init(jax.random.PRNGKey(0), im1, im2, iters=1,
                           train=False)

    low_m, up_m = model.apply(variables, im1, im2, iters=2,
                              test_mode=True)
    f1 = model.apply(variables, im1, mode="encode")
    f2 = model.apply(variables, im2, mode="encode")
    low_s, up_s = model.apply(variables, None, iters=2, test_mode=True,
                              mode="step", features1=f1, features2=f2)
    assert float(jnp.max(jnp.abs(low_m - low_s))) <= 1e-4
    assert float(jnp.max(jnp.abs(up_m - up_s))) <= 1e-4

    # warm start rides the same contract (flow_init enters in "step")
    fi = jax.random.uniform(jax.random.PRNGKey(3), (1, 6, 8, 2),
                            jnp.float32, -1, 1)
    _, up_mw = model.apply(variables, im1, im2, iters=2, test_mode=True,
                           flow_init=fi)
    _, up_sw = model.apply(variables, None, iters=2, test_mode=True,
                           flow_init=fi, mode="step", features1=f1,
                           features2=f2)
    assert float(jnp.max(jnp.abs(up_mw - up_sw))) <= 1e-4

    # a forgotten frame fails loudly, not as a NoneType deep crash
    # (images became Optional for the split modes)
    with pytest.raises(ValueError, match="mode='pair' needs"):
        model.apply(variables, im1, iters=1, test_mode=True)


def test_streaming_feature_reuse_matches_chained_pairs():
    """The cross-frame reuse claim itself: driving frames f0, f1, f2 as
    (encode-once, refine) streaming steps equals the chained monolithic
    pairs (f0,f1), (f1,f2) — frame 1 is encoded ONCE in the streamed
    path yet serves as frame 2 of the first pair and frame 1 of the
    second."""
    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.config import raft_v1
    from dexiraft_tpu.models.raft import RAFT

    cfg = raft_v1(small=True)
    model = RAFT(cfg)
    key = jax.random.PRNGKey(5)
    frames = [jax.random.uniform(jax.random.fold_in(key, i),
                                 (1, 48, 64, 3), jnp.float32, 0, 255)
              for i in range(3)]
    variables = model.init(jax.random.PRNGKey(0), frames[0], frames[1],
                           iters=1, train=False)

    # chained monolithic pairs with flow carry
    low, up_a1 = model.apply(variables, frames[0], frames[1], iters=2,
                             test_mode=True)
    _, up_a2 = model.apply(variables, frames[1], frames[2], iters=2,
                           test_mode=True, flow_init=low)

    # streamed: each frame encoded once
    feats = [model.apply(variables, f, mode="encode") for f in frames]
    low_s, up_b1 = model.apply(variables, None, iters=2, test_mode=True,
                               mode="step", features1=feats[0],
                               features2=feats[1])
    _, up_b2 = model.apply(variables, None, iters=2, test_mode=True,
                           mode="step", features1=feats[1],
                           features2=feats[2], flow_init=low_s)
    assert float(jnp.max(jnp.abs(up_a1 - up_b1))) <= 1e-4
    assert float(jnp.max(jnp.abs(up_a2 - up_b2))) <= 1e-4
