"""One process of the true multi-process distributed test.

Spawned (not imported) by tests/test_multiprocess.py, twice: each child
owns 2 virtual CPU devices, joins the other over jax.distributed through
the package's own initialize(), decodes ONLY its host slice of every
global batch through data.Loader, and runs the real sharded train step
over the resulting 4-device global mesh. Results (losses, param norm,
consumed sample indices) are written as JSON for the parent to check
against a single-process run of the same schedule.
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    from dexiraft_tpu.parallel.distributed import initialize

    # the code path under test: explicit-args mode of the package's init
    initialize(coordinator_address=f"127.0.0.1:{args.port}",
               num_processes=args.num_processes,
               process_id=args.process_id)
    assert jax.process_count() == args.num_processes
    n_devices = len(jax.devices())
    assert n_devices == 2 * args.num_processes, jax.devices()

    from tests._mp_common import GLOBAL_BATCH, N_STEPS, SEED, \
        SyntheticFlowDataset, make_configs
    from dexiraft_tpu.data.loader import Loader
    from dexiraft_tpu.parallel.mesh import make_mesh, replicate, shard_batch
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    loader = Loader(SyntheticFlowDataset(), GLOBAL_BATCH, shuffle=True,
                    seed=SEED, num_workers=2,
                    process_index=jax.process_index(),
                    process_count=jax.process_count())
    stream = loader.batches()
    local_batches, consumed = [], []
    for _ in range(N_STEPS):
        batch = next(stream)
        consumed.append(batch.pop("index").tolist())
        local_batches.append(batch)

    cfg, tc = make_configs()
    mesh = make_mesh()
    state = replicate(create_state(jax.random.PRNGKey(0), cfg, tc), mesh)
    step_fn = make_train_step(cfg, tc, mesh)

    losses = []
    for batch in local_batches:
        state, metrics = step_fn(state, shard_batch(batch, mesh))
        # metrics are replicated global arrays — float() is legal on
        # every process and synchronizes the step
        losses.append(float(metrics["loss"]))

    norm = jax.jit(
        lambda p: jnp.sqrt(sum(jnp.sum(x ** 2)
                               for x in jax.tree.leaves(p))))(state.params)
    result = {
        "process_id": args.process_id,
        "n_devices": n_devices,
        "losses": losses,
        "param_norm": float(norm),
        "consumed": consumed,
    }
    with open(args.out, "w") as f:
        json.dump(result, f)
    print("child done", json.dumps(result)[:200])


if __name__ == "__main__":
    main()
