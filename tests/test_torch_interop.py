"""Numerical parity: reference torch DexiNed vs our flax DexiNed under
converted weights — validates every conversion rule (conv transpose
orientation, BN stats, block name map) end to end.

Skipped when the reference checkout or torch is unavailable.
"""

import os
import sys

import numpy as np
import pytest

_REF = "/root/reference/core/DexiNed"

torch = pytest.importorskip("torch")
pytestmark = pytest.mark.skipif(not os.path.isdir(_REF),
                                reason="reference checkout not mounted")


def _reference_model():
    sys.path.insert(0, _REF)
    try:
        from model import DexiNed as TorchDexiNed
    finally:
        sys.path.remove(_REF)
    torch.manual_seed(0)
    m = TorchDexiNed()
    m.eval()
    # randomize BN stats so the parity test actually exercises them
    with torch.no_grad():
        for name, buf in m.named_buffers():
            if name.endswith("running_mean"):
                buf.normal_(0, 0.05)
            elif name.endswith("running_var"):
                buf.uniform_(0.5, 1.5)
    return m


@pytest.fixture(scope="module")
def parity_pair():
    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.interop.torch_convert import (
        convert_dexined_state_dict,
        verify_against,
    )
    from dexiraft_tpu.models.dexined import DexiNed

    tm = _reference_model()
    variables = convert_dexined_state_dict(tm.state_dict())

    jm = DexiNed()
    template = jax.eval_shape(
        lambda: jm.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 64, 64, 3)), train=False))
    verify_against(template, variables)
    return tm, jm, variables


def test_full_model_parity(parity_pair):
    import jax.numpy as jnp

    from dexiraft_tpu.models.dexined import DexiNed

    tm, jm, variables = parity_pair
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (1, 96, 128, 3)).astype(np.float32)

    with torch.no_grad():
        t_out = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    j_out = jm.apply(variables, jnp.asarray(x), train=False)

    assert len(t_out) == len(j_out) == 7
    for i, (t, j) in enumerate(zip(t_out, j_out)):
        t_np = t.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(
            np.asarray(j), t_np, rtol=2e-3, atol=2e-3,
            err_msg=f"output {i} diverges")


class TestRAFTParity:
    """End-to-end RAFT forward parity with the reference torch model under
    converted weights — validates the encoders, correlation pyramid,
    bilinear lookup, ConvGRU update, and convex upsampling numerics in one
    shot (SURVEY.md §7 hard parts 2 and 4)."""

    @pytest.fixture(scope="class")
    def raft_pair(self):
        import argparse

        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.interop.torch_convert import (
            convert_raft_state_dict,
            verify_against,
        )
        from dexiraft_tpu.models.raft import RAFT

        ref_core = "/root/reference/core"
        sys.path.insert(0, ref_core)
        try:
            from raft_1 import RAFT as TorchRAFT
        finally:
            sys.path.remove(ref_core)

        torch.manual_seed(0)
        args = argparse.Namespace(small=False, dropout=0.0,
                                  mixed_precision=False, alternate_corr=False)
        tm = TorchRAFT(args)
        tm.eval()
        with torch.no_grad():  # exercise BN stats, not just init values
            for name, buf in tm.named_buffers():
                if name.endswith("running_mean"):
                    buf.normal_(0, 0.05)
                elif name.endswith("running_var"):
                    buf.uniform_(0.5, 1.5)

        variables = convert_raft_state_dict(tm.state_dict())
        jm = RAFT(raft_v1())
        template = jax.eval_shape(
            lambda: jm.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 64, 64, 3)),
                            jnp.zeros((1, 64, 64, 3)), iters=1, train=False))
        verify_against(template, variables)
        return tm, jm, variables

    def test_forward_parity(self, raft_pair):
        import jax.numpy as jnp

        tm, jm, variables = raft_pair
        rng = np.random.default_rng(1)
        # frames large enough that the level-3 volume is >= 2x2 — at 1x1
        # the REFERENCE's grid_sample normalization divides by zero
        # (core/utils/utils.py:64-65) and emits NaN
        im1 = rng.uniform(0, 255, (1, 128, 160, 3)).astype(np.float32)
        im2 = rng.uniform(0, 255, (1, 128, 160, 3)).astype(np.float32)

        with torch.no_grad():
            t1 = torch.from_numpy(im1.transpose(0, 3, 1, 2))
            t2 = torch.from_numpy(im2.transpose(0, 3, 1, 2))
            t_low, t_up = tm(t1, t2, iters=4, test_mode=True)

        j_low, j_up = jm.apply(variables, jnp.asarray(im1), jnp.asarray(im2),
                               iters=4, train=False, test_mode=True)

        np.testing.assert_allclose(
            np.asarray(j_low), t_low.numpy().transpose(0, 2, 3, 1),
            rtol=5e-3, atol=5e-3)
        np.testing.assert_allclose(
            np.asarray(j_up), t_up.numpy().transpose(0, 2, 3, 1),
            rtol=5e-3, atol=5e-3)


def test_stacked_edge_maps_shape(parity_pair):
    import jax.numpy as jnp

    from dexiraft_tpu.models.dexined import stack_edge_maps

    _, jm, variables = parity_pair
    x = jnp.zeros((2, 64, 64, 3))
    maps = stack_edge_maps(jm.apply(variables, x, train=False))
    assert maps.shape == (2, 64, 64, 7)
