"""Numerical parity: reference torch models vs our flax models under
converted weights — validates every conversion rule (conv transpose
orientation, BN stats routing, block name maps) and the forward numerics
(encoders, correlation pyramid + bilinear lookup, ConvGRU update, convex
upsampling; SURVEY.md §7 hard parts 2 and 4) end to end.

Skipped when the reference checkout or torch is unavailable.
"""

import os
import sys

import numpy as np
import pytest

_REF = "/root/reference/core/DexiNed"
_REF_CORE = "/root/reference/core"

torch = pytest.importorskip("torch")
pytestmark = pytest.mark.skipif(not os.path.isdir(_REF),
                                reason="reference checkout not mounted")


def _import_from(path, module):
    sys.path.insert(0, path)
    try:
        return __import__(module)
    finally:
        sys.path.remove(path)


def _randomize_bn_stats(model):
    """Fresh-init BN buffers are all (0, 1); randomize so a converter that
    routes stats to the wrong same-shaped module fails the test."""
    with torch.no_grad():
        for name, buf in model.named_buffers():
            if name.endswith("running_mean"):
                buf.normal_(0, 0.05)
            elif name.endswith("running_var"):
                buf.uniform_(0.5, 1.5)


def _reference_model():
    TorchDexiNed = _import_from(_REF, "model").DexiNed
    torch.manual_seed(0)
    m = TorchDexiNed()
    m.eval()
    _randomize_bn_stats(m)
    return m


@pytest.fixture(scope="module")
def parity_pair():
    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.interop.torch_convert import (
        convert_dexined_state_dict,
        verify_against,
    )
    from dexiraft_tpu.models.dexined import DexiNed

    tm = _reference_model()
    variables = convert_dexined_state_dict(tm.state_dict())

    jm = DexiNed()
    template = jax.eval_shape(
        lambda: jm.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 64, 64, 3)), train=False))
    verify_against(template, variables)
    return tm, jm, variables


def test_full_model_parity(parity_pair):
    import jax.numpy as jnp

    tm, jm, variables = parity_pair
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (1, 96, 128, 3)).astype(np.float32)

    with torch.no_grad():
        t_out = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)))
    j_out = jm.apply(variables, jnp.asarray(x), train=False)

    assert len(t_out) == len(j_out) == 7
    for i, (t, j) in enumerate(zip(t_out, j_out)):
        t_np = t.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(
            np.asarray(j), t_np, rtol=2e-3, atol=2e-3,
            err_msg=f"output {i} diverges")


def test_stacked_edge_maps_shape(parity_pair):
    import jax.numpy as jnp

    from dexiraft_tpu.models.dexined import stack_edge_maps

    _, jm, variables = parity_pair
    x = jnp.zeros((2, 64, 64, 3))
    maps = stack_edge_maps(jm.apply(variables, x, train=False))
    assert maps.shape == (2, 64, 64, 7)


def _raft_parity_case(torch_model, cfg, *, small=False, seed=1, tol=5e-3):
    """Shared harness: convert weights, verify the tree, compare the
    test-mode forward (both low- and full-resolution flow) at 128x160 —
    frames large enough that the level-3 volume is >= 2x2; at 1x1 the
    REFERENCE's grid_sample normalization divides by zero
    (core/utils/utils.py:64-65) and emits NaN."""
    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.interop.torch_convert import (
        convert_raft_state_dict,
        verify_against,
    )
    from dexiraft_tpu.models.raft import RAFT

    torch_model.eval()
    _randomize_bn_stats(torch_model)

    variables = convert_raft_state_dict(torch_model.state_dict(), small=small)
    jm = RAFT(cfg)
    template = jax.eval_shape(
        lambda: jm.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 128, 160, 3)),
                        jnp.zeros((1, 128, 160, 3)), iters=1, train=False))
    verify_against(template, variables)

    rng = np.random.default_rng(seed)
    im1 = rng.uniform(0, 255, (1, 128, 160, 3)).astype(np.float32)
    im2 = rng.uniform(0, 255, (1, 128, 160, 3)).astype(np.float32)

    with torch.no_grad():
        t_low, t_up = torch_model(
            torch.from_numpy(im1.transpose(0, 3, 1, 2)),
            torch.from_numpy(im2.transpose(0, 3, 1, 2)),
            iters=4, test_mode=True)
    j_low, j_up = jm.apply(variables, jnp.asarray(im1), jnp.asarray(im2),
                           iters=4, train=False, test_mode=True)

    np.testing.assert_allclose(
        np.asarray(j_low), t_low.numpy().transpose(0, 2, 3, 1),
        rtol=tol, atol=tol)
    np.testing.assert_allclose(
        np.asarray(j_up), t_up.numpy().transpose(0, 2, 3, 1),
        rtol=tol, atol=tol)


def _v1_args(small):
    import argparse

    return argparse.Namespace(small=small, dropout=0.0,
                              mixed_precision=False, alternate_corr=False)


class TestRAFTParity:
    def test_full_model(self):
        from dexiraft_tpu.config import raft_v1

        TorchRAFT = _import_from(_REF_CORE, "raft_1").RAFT
        torch.manual_seed(0)
        _raft_parity_case(TorchRAFT(_v1_args(False)), raft_v1(), seed=1)

    def test_small_model(self):
        from dexiraft_tpu.config import raft_v1

        TorchRAFT = _import_from(_REF_CORE, "raft_1").RAFT
        torch.manual_seed(1)
        _raft_parity_case(TorchRAFT(_v1_args(True)), raft_v1(small=True),
                          small=True, seed=2)

    def test_v5_dual_stream(self, monkeypatch):
        """Flagship v5: embedded frozen DexiNed, dual streams, shared
        update block, coupled delta-f + delta-ef update (core/raft.py:183)."""
        from dexiraft_tpu.config import raft_v5

        # the reference RAFT.__init__ loads a DexiNed checkpoint from disk
        # (core/raft.py:30-33) that ships outside the repo — feed it a
        # randomly initialized DexiNed state dict instead
        TorchDexiNed = _import_from(_REF, "model").DexiNed
        torch.manual_seed(3)
        dexi_sd = TorchDexiNed().state_dict()
        monkeypatch.setattr(torch, "load", lambda *a, **k: dexi_sd)

        TorchRAFTv5 = _import_from(_REF_CORE, "raft").RAFT
        tm = TorchRAFTv5(_v1_args(False))
        _raft_parity_case(tm, raft_v5(), seed=4, tol=1e-2)


class TestExportRoundTrip:
    """export_*_state_dict must exactly invert the import converter: a
    torch state_dict converted to flax and exported back is bitwise
    identical (and torch can load_state_dict the result strict=True)."""

    def _assert_round_trip(self, sd, exported):
        assert set(exported) == set(sd)
        for k in sd:
            a = sd[k].detach().cpu().numpy()
            b = exported[k]
            assert a.shape == tuple(np.shape(b)), k
            np.testing.assert_array_equal(a, np.asarray(b), err_msg=k)

    def test_dexined(self):
        from dexiraft_tpu.interop.torch_convert import (
            convert_dexined_state_dict,
            export_dexined_state_dict,
        )

        tm = _reference_model()
        sd = tm.state_dict()
        variables = convert_dexined_state_dict(sd)
        exported = export_dexined_state_dict(variables, sd)
        self._assert_round_trip(sd, exported)
        tm.load_state_dict(
            {k: torch.from_numpy(np.asarray(v)) for k, v in exported.items()},
            strict=True)

    def test_raft_v1_full_and_small(self):
        from dexiraft_tpu.interop.torch_convert import (
            convert_raft_state_dict,
            export_raft_state_dict,
        )

        TorchRAFT = _import_from(_REF_CORE, "raft_1").RAFT
        for small, seed in ((False, 10), (True, 11)):
            torch.manual_seed(seed)
            tm = TorchRAFT(_v1_args(small))
            tm.eval()
            _randomize_bn_stats(tm)
            sd = tm.state_dict()
            variables = convert_raft_state_dict(sd, small=small)
            exported = export_raft_state_dict(variables, sd, small=small)
            self._assert_round_trip(sd, exported)

    def test_raft_v5_with_embedded_dexined(self, monkeypatch):
        from dexiraft_tpu.interop.torch_convert import (
            convert_raft_state_dict,
            export_raft_state_dict,
        )

        TorchDexiNed = _import_from(_REF, "model").DexiNed
        torch.manual_seed(12)
        dexi_sd = TorchDexiNed().state_dict()
        monkeypatch.setattr(torch, "load", lambda *a, **k: dexi_sd)
        TorchRAFTv5 = _import_from(_REF_CORE, "raft").RAFT
        tm = TorchRAFTv5(_v1_args(False))
        tm.eval()
        _randomize_bn_stats(tm)
        sd = tm.state_dict()
        variables = convert_raft_state_dict(sd)
        exported = export_raft_state_dict(variables, sd)
        self._assert_round_trip(sd, exported)
