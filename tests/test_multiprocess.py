"""True multi-process data parallelism: two spawned processes, four
devices, one global batch — grads (hence losses and updated params) must
match a single-process run of the identical schedule.

This is the multi-host story the single-process 8-device dryrun cannot
cover: jax.distributed.initialize through parallel.distributed, per-host
disjoint slices from data.Loader, global-array assembly in
parallel.mesh.shard_batch/replicate, and the sharded train step's psum
all riding the real cross-process runtime (reference gap: DataParallel,
train.py:139, is single-process only).
"""

from __future__ import annotations

import json
import os
import os.path as osp
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

from tests._mp_common import (
    GLOBAL_BATCH,
    N_STEPS,
    SEED,
    SyntheticFlowDataset,
    make_configs,
)

if jax.default_backend() == "cpu":
    # On the CPU backend these tests spawn fresh interpreters that
    # re-emulate the distributed runtime over loopback — minutes of
    # wall clock re-checking what the single-process 8-virtual-device
    # suites already pin, and the rendezvous is the suite's one
    # recurring flake source. Skip EXPLICITLY (visible in the report,
    # unlike a silent deselect) and leave real multi-host coverage to
    # accelerator runs, where the cross-process runtime is real.
    pytest.skip("multi-process rendezvous tests need a non-CPU backend "
                "(loopback emulation is slow and flaky; single-process "
                "8-device suites cover the math)",
                allow_module_level=True)

_CHILD = osp.join(osp.dirname(osp.abspath(__file__)), "multiproc_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_children(script: str, outs, timeout: float = 900.0) -> None:
    """Spawn one process per out-path with a shared rendezvous port,
    wait for all, and assert success. XLA_FLAGS is stripped so the
    children control their own virtual device count."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = []
    for pid, out in enumerate(outs):
        procs.append(subprocess.Popen(
            [sys.executable, script, "--port", str(port),
             "--process_id", str(pid), "--out", str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    logs = []
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=timeout)
            logs.append(stdout.decode(errors="replace"))
    finally:
        # a child deadlocked in the distributed rendezvous (e.g. its peer
        # died pre-initialize) must not outlive the test run
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"child failed:\n{log[-3000:]}"


@pytest.fixture(scope="module")
def child_results(tmp_path_factory):
    out_dir = tmp_path_factory.mktemp("mp")
    outs = [out_dir / f"child{pid}.json" for pid in range(2)]
    _run_children(_CHILD, outs)
    return [json.loads(out.read_text()) for out in outs]


def test_children_join_one_runtime(child_results):
    for r in child_results:
        assert r["n_devices"] == 4


def test_host_slices_disjoint_and_complete(child_results):
    # the loader must hand each host the right quarter of every global
    # batch: rebuild the expected epoch order with the Loader's own
    # shuffle rule and compare batch by batch
    order = np.arange(len(SyntheticFlowDataset()))
    np.random.default_rng((SEED, 0)).shuffle(order)
    half = GLOBAL_BATCH // 2
    for step in range(N_STEPS):
        got0 = child_results[0]["consumed"][step]
        got1 = child_results[1]["consumed"][step]
        expect = order[step * GLOBAL_BATCH:(step + 1) * GLOBAL_BATCH]
        assert got0 == expect[:half].tolist()
        assert got1 == expect[half:].tolist()
        assert not set(got0) & set(got1)


def test_losses_replicated_across_processes(child_results):
    assert child_results[0]["losses"] == pytest.approx(
        child_results[1]["losses"], rel=1e-6)
    assert child_results[0]["param_norm"] == pytest.approx(
        child_results[1]["param_norm"], rel=1e-6)


def test_ring_lookup_across_process_boundary(tmp_path):
    """Cross-process CONTEXT parallelism: a (data=1, seq=4) ring over
    2 processes x 2 devices — the ppermute hops between devices 1 and 2
    cross the process boundary (the DCN/multi-host analog the
    single-process ring tests cannot cover). The reassembled sharded
    output must equal the unsharded lookup bit-for-bit in fp32 tolerance.
    """
    from tests._mp_common import (
        CP_B,
        CP_H,
        CP_LEVELS,
        CP_RADIUS,
        CP_W,
        cp_full_inputs,
    )

    cp_child = osp.join(osp.dirname(osp.abspath(__file__)),
                        "multiproc_cp_child.py")
    outs = [tmp_path / f"cp{pid}.npz" for pid in range(2)]
    _run_children(cp_child, outs)

    # reassemble the sharded rows
    got = np.zeros((CP_B, CP_H, CP_W, CP_LEVELS * (2 * CP_RADIUS + 1) ** 2),
                   np.float32)
    seen = 0
    for out in outs:
        with np.load(out) as z:
            for r0, rows in z.items():
                got[:, int(r0):int(r0) + rows.shape[1]] = rows
                seen += rows.shape[1]
    assert seen == CP_H

    # unsharded reference on this process
    import jax.numpy as jnp

    from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup

    f1, f2, coords = cp_full_inputs()
    pyr = build_corr_pyramid(jnp.asarray(f1), jnp.asarray(f2),
                             num_levels=CP_LEVELS, radius=CP_RADIUS)
    want = np.asarray(corr_lookup(pyr, jnp.asarray(coords)))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_grads_match_single_process(child_results):
    # identical init, identical global batches, no mesh: if the sharded
    # two-process losses and updated-param norm agree with this run, the
    # psum'd gradients agreed too
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg, tc = make_configs()
    dataset = SyntheticFlowDataset()
    order = np.arange(len(dataset))
    np.random.default_rng((SEED, 0)).shuffle(order)

    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    step_fn = make_train_step(cfg, tc, mesh=None)
    losses = []
    for step in range(N_STEPS):
        ids = order[step * GLOBAL_BATCH:(step + 1) * GLOBAL_BATCH]
        samples = [dataset.sample(int(i), None) for i in ids]
        batch = {k: np.stack([s[k] for s in samples])
                 for k in samples[0] if k != "index"}
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))

    import jax.numpy as jnp

    norm = float(jax.jit(
        lambda p: jnp.sqrt(sum(jnp.sum(x ** 2)
                               for x in jax.tree.leaves(p))))(state.params))
    for r in child_results:
        assert r["losses"] == pytest.approx(losses, rel=2e-4, abs=1e-5)
        assert r["param_norm"] == pytest.approx(norm, rel=1e-5)
