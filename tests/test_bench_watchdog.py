"""The bench harness must never hang the driver's round-end run.

A relay-tunnel death mid-measurement leaves device fetches blocked
forever (observed live: bench silent >15 min after init when the tunnel
process died under it). bench.py therefore runs the measurement in a
child process under a stall watchdog. These tests exercise the watchdog
with a fake child that blocks forever (BENCH_FAKE_HANG), at a short
test-only stall threshold (BENCH_STALL_S).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_record_schema_pinned():
    """The ONE JSON line the driver greps is schema-pinned: required keys
    (including this PR's corr_dtype/fused_update config naming), optional
    conditional keys, and tag-prefixed per-config diagnostics — anything
    else fails validate_record, so the record cannot drift silently."""
    bench = _load_bench()
    assert {"corr_dtype", "fused_update", "corr_impl",
            "dexined_upconv"} <= bench.BENCH_RECORD_KEYS
    rec = {k: None for k in bench.BENCH_RECORD_KEYS}
    rec.update(allpairs_raw_ms=1.0, fused_pallas_int8_iters_per_sec=2.0,
               local_transpose_rtt_ms=3.0, mfu=0.5)
    bench.validate_record(rec)  # required + diag + optional: passes

    with pytest.raises(ValueError, match="missing"):
        bench.validate_record({k: None for k in
                               bench.BENCH_RECORD_KEYS - {"corr_dtype"}})
    bad = {k: None for k in bench.BENCH_RECORD_KEYS}
    bad["surprise_key"] = 1
    with pytest.raises(ValueError, match="unpinned"):
        bench.validate_record(bad)


def test_cpu_anchor_parse_keeps_freshest_per_geometry(tmp_path, monkeypatch):
    """The anchor script APPENDS on re-runs; the bench record carries one
    ratio per measured geometry, each the freshest for that geometry
    (ADVICE r3 + VERDICT r4 next-8). Malformed lines, key-missing lines,
    and legacy geometry-less records are skipped without losing good
    ones."""
    bench = _load_bench()

    log = tmp_path / "logs" / "torch_cpu_anchor.log"
    log.parent.mkdir()
    log.write_text(
        "# methodology note\n"
        '{"flax_over_torch": 1.18, "host": "loaded"}\n'  # legacy: no metric
        '{"broken json\n'
        '{"no_ratio_key": true}\n'
        '{"metric": "cpu_anchor_v5_forward@224x512x6it",'
        ' "flax_over_torch": 1.9, "host": "loaded"}\n'
        '{"metric": "cpu_anchor_v5_forward@224x512x6it",'
        ' "flax_over_torch": 2.06, "host": "idle"}\n'
        '{"metric": "cpu_anchor_v5_forward@440x1024x32it",'
        ' "flax_over_torch": 1.27}\n'
        '{"metric": "cpu_anchor_v5_trainstep@96x128x12it",'
        ' "flax_over_torch_train": 0.23}\n')
    # _cpu_anchor_fields resolves the log relative to its module's
    # __file__ — point that at tmp_path rather than patching the
    # process-global os.path.dirname
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    fields = bench._cpu_anchor_fields()
    assert fields["cpu_anchor_flax_over_torch"] == {
        "224x512x6it": 2.06, "440x1024x32it": 1.27}
    assert fields["cpu_anchor_flax_over_torch_train"] == {
        "96x128x12it": 0.23}


def test_watchdog_kills_stalled_child():
    # the stall threshold must outlast interpreter startup, which can
    # take >10 s on a loaded host — the fake child prints one line as
    # soon as it is up, then blocks
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_HANG="1",
               BENCH_STALL_S="40")
    r = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, timeout=180)
    # want_cpu path: one stall cycle, no TPU->CPU retry, exit code 8
    assert r.returncode == 8, r.stderr.decode()
    assert b"stalled" in r.stderr
    assert b"fake child hanging" in r.stderr


def test_sigterm_forwards_to_measurement_child():
    # the queue's outer `timeout` signals only the parent; the parent
    # must kill the measurement grandchild before dying or it would be
    # orphaned still holding the TPU claim
    import glob
    import time

    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_HANG="1",
               BENCH_STALL_S="600")
    p = subprocess.Popen([sys.executable, BENCH], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 120
        saw_child = False
        while time.time() < deadline:
            line = p.stderr.readline()
            if b"fake child hanging" in line:
                saw_child = True
                break
        assert saw_child, "fake child never started"
        p.terminate()
        assert p.wait(timeout=30) == 143  # 128 + SIGTERM
        time.sleep(1.0)
        # no orphaned bench.py process may remain
        orphans = []
        for cmd in glob.glob("/proc/[0-9]*/cmdline"):
            try:
                with open(cmd, "rb") as f:
                    argv = f.read().split(b"\0")
            except OSError:
                continue
            if any(a == BENCH.encode() for a in argv):
                orphans.append(cmd)
        assert not orphans, orphans
    finally:
        if p.poll() is None:
            p.kill()


def test_hard_cap_kills_overrunning_child():
    # even a child that is not silent long enough to trip the stall
    # check must die at the hard cap
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_HANG="1",
               BENCH_STALL_S="600", BENCH_HARD_CAP_S="25")
    r = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, timeout=180)
    assert r.returncode == 8, r.stderr.decode()
    assert b"overran" in r.stderr
