"""The bench harness must never hang the driver's round-end run.

A relay-tunnel death mid-measurement leaves device fetches blocked
forever (observed live: bench silent >15 min after init when the tunnel
process died under it). bench.py therefore runs the measurement in a
child process under a stall watchdog. These tests exercise the watchdog
with a fake child that blocks forever (BENCH_FAKE_HANG), at a short
test-only stall threshold (BENCH_STALL_S).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_watchdog_kills_stalled_child():
    # the stall threshold must outlast interpreter startup, which can
    # take >10 s on a loaded host — the fake child prints one line as
    # soon as it is up, then blocks
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_HANG="1",
               BENCH_STALL_S="40")
    r = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, timeout=180)
    # want_cpu path: one stall cycle, no TPU->CPU retry, exit code 8
    assert r.returncode == 8, r.stderr.decode()
    assert b"stalled" in r.stderr
    assert b"fake child hanging" in r.stderr


def test_sigterm_forwards_to_measurement_child():
    # the queue's outer `timeout` signals only the parent; the parent
    # must kill the measurement grandchild before dying or it would be
    # orphaned still holding the TPU claim
    import glob
    import time

    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_HANG="1",
               BENCH_STALL_S="600")
    p = subprocess.Popen([sys.executable, BENCH], env=env,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.time() + 120
        saw_child = False
        while time.time() < deadline:
            line = p.stderr.readline()
            if b"fake child hanging" in line:
                saw_child = True
                break
        assert saw_child, "fake child never started"
        p.terminate()
        assert p.wait(timeout=30) == 143  # 128 + SIGTERM
        time.sleep(1.0)
        # no orphaned bench.py process may remain
        orphans = []
        for cmd in glob.glob("/proc/[0-9]*/cmdline"):
            try:
                with open(cmd, "rb") as f:
                    argv = f.read().split(b"\0")
            except OSError:
                continue
            if any(a == BENCH.encode() for a in argv):
                orphans.append(cmd)
        assert not orphans, orphans
    finally:
        if p.poll() is None:
            p.kill()


def test_hard_cap_kills_overrunning_child():
    # even a child that is not silent long enough to trip the stall
    # check must die at the hard cap
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_FAKE_HANG="1",
               BENCH_STALL_S="600", BENCH_HARD_CAP_S="25")
    r = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, timeout=180)
    assert r.returncode == 8, r.stderr.decode()
    assert b"overran" in r.stderr
