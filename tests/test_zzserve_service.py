"""Persistent flow service (dexiraft_tpu/serve/{scheduler,sessions,
server}.py): SLO-aware partial-batch dispatch timing (fake clock,
deterministic), session affinity carrying flow_init with TTL eviction,
the HTTP surface (/v1/flow round trip, /healthz, /stats schema pin,
400/503 discipline), and graceful SIGTERM drain via a real in-process
signal (the PR 4 preemption-harness pattern).

Everything runs on the numpy stub eval_fn — no jax, no model, no
sockets beyond loopback — so the whole file stays far under the tier-1
per-test budget. Named test_zz* to sort after the long-standing tail
tests (870 s budget convention, see test_zpipeline_async.py).
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dexiraft_tpu.serve import (FlowService, InferenceEngine, QueueFull,
                                Scheduler, SchedulerClosed, ServeConfig,
                                SessionStore)
from dexiraft_tpu.serve.server import (decode_response, encode_request,
                                       encode_response)


def _stub_eval(im1, im2, flow_init=None):
    """Constant (2, -1) flow; warm rows add their upsampled flow_init
    (observable carry); flow_low = flow_init + 0.5 so chaining is
    visible too (test_zserve_engine's stub, carry-accumulating)."""
    b, h, w = im1.shape[:3]
    up = np.broadcast_to(np.float32([2.0, -1.0]), (b, h, w, 2)).copy()
    low = np.full((b, h // 8, w // 8, 2), 0.5, np.float32)
    if flow_init is not None:
        fi = np.asarray(flow_init)
        up = up + np.repeat(np.repeat(fi, 8, 1), 8, 2)
        low = low + fi
    return low, up


def _item(h=40, w=56, seed=0):
    rng = np.random.default_rng(seed)
    return {"image1": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
            "image2": rng.uniform(0, 255, (h, w, 3)).astype(np.float32)}


def _engine(batch_size=2, eval_fn=_stub_eval, **kw):
    return InferenceEngine(eval_fn,
                           ServeConfig(batch_size=batch_size, **kw))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---- scheduler: SLO policy, deterministic via fake clock ----------------


class TestSchedulerPolicy:
    def test_full_batch_dispatches_immediately(self):
        clock = FakeClock()
        s = Scheduler(_engine(2), slo_ms=1000.0, clock=clock)
        r1 = s.submit_async(_item())
        assert not s.poll_once()            # 1 < batch_size and budget left
        r2 = s.submit_async(_item())
        assert s.poll_once()                # bucket filled -> go NOW
        assert r1.event.is_set() and r2.event.is_set()
        assert s.stats.dispatch_full == 1 and s.stats.dispatch_slo == 0
        assert r1.result.flow_up.shape == (40, 56, 2)

    def test_partial_batch_waits_exactly_the_slo_hold(self):
        # pre-measurement estimate is slo/2, so the head request's
        # deadline is t_submit + slo/2 — not before, not after
        clock = FakeClock()
        s = Scheduler(_engine(4), slo_ms=100.0, clock=clock)
        s.submit_async(_item())
        assert not s.poll_once()
        clock.advance(0.049)                # 1 ms before the deadline
        assert not s.poll_once()
        clock.advance(0.002)                # past it
        assert s.poll_once()
        assert s.stats.dispatch_slo == 1
        assert s.stats.record()["mean_batch_fill"] == 1.0

    def test_hold_tracks_measured_service_time(self):
        # a measured 30 ms service estimate stretches the hold window to
        # slo - 30 ms: the scheduler waits as long as the budget allows
        clock = FakeClock()

        def timed_eval(im1, im2, flow_init=None):
            clock.advance(0.030)
            return _stub_eval(im1, im2, flow_init)

        s = Scheduler(_engine(4, eval_fn=timed_eval), slo_ms=100.0,
                      clock=clock)
        s.submit_async(_item())
        clock.advance(0.051)
        assert s.poll_once()                # warms the estimate (~30 ms)
        # the first batch's REAL compile span is subtracted from the
        # fake-clock measurement, so est <= 30 ms and hold >= 70 ms —
        # assert with margins on both sides of that bound
        s.submit_async(_item())
        clock.advance(0.060)                # inside the stretched hold
        assert not s.poll_once()
        clock.advance(0.100)                # far past any plausible hold
        assert s.poll_once()
        assert s.stats.dispatch_slo == 2

    def test_queue_bound_rejects_at_admission(self):
        s = Scheduler(_engine(4), slo_ms=1000.0, max_queue=2,
                      clock=FakeClock())
        s.submit_async(_item())
        s.submit_async(_item())
        with pytest.raises(QueueFull):
            s.submit_async(_item())
        assert s.stats.rejected == 1
        assert s.stats.submitted == 2

    def test_engine_error_reraised_to_every_caller(self):
        def broken(im1, im2, flow_init=None):
            raise RuntimeError("chip fell over")

        clock = FakeClock()
        s = Scheduler(_engine(2, eval_fn=broken), slo_ms=100.0, clock=clock)
        r1 = s.submit_async(_item())
        r2 = s.submit_async(_item())
        assert s.poll_once()
        assert isinstance(r1.error, RuntimeError)
        assert isinstance(r2.error, RuntimeError)
        assert s.stats.failed == 2

    def test_stats_record_schema(self):
        s = Scheduler(_engine(2), slo_ms=100.0, clock=FakeClock())
        rec = s.stats_record()
        assert set(rec) == {
            "submitted", "completed", "failed", "rejected",
            "dispatch_full", "dispatch_slo", "dispatch_drain",
            "queue_peak", "mean_batch_fill", "wait_p50_ms", "wait_p99_ms",
            "latency_p50_ms", "latency_p99_ms", "queue_depth", "inflight",
            "slo_ms", "max_queue", "service_est_ms", "draining",
        }

    def test_inflight_counts_queued_and_mid_dispatch(self):
        """The /healthz readiness payload's `inflight` must cover a
        batch that LEFT the queue but hasn't answered yet — that is
        exactly the window a router's zero-drop drain waits out."""
        clock = FakeClock()
        s = Scheduler(_engine(2), slo_ms=1000.0, clock=clock)
        seen = []
        s.post_dispatch = lambda bucket, results: seen.append(s.inflight())
        s.submit_async(_item())
        s.submit_async(_item())
        assert s.inflight() == 2 and s.queue_depth() == 2
        assert s.poll_once()
        # inside the dispatch (post_dispatch hook) the queue was empty
        # but both requests still counted as in flight
        assert seen == [2]
        assert s.inflight() == 0 and s.queue_depth() == 0


class TestSchedulerLifecycle:
    def test_drain_flushes_partials_then_refuses(self):
        # real dispatcher thread: a partial the SLO would hold for 100 s
        # leaves immediately once drain begins, and later submits are
        # refused with SchedulerClosed
        s = Scheduler(_engine(4), slo_ms=100_000.0).start()
        r1 = s.submit_async(_item())
        r2 = s.submit_async(_item())
        assert s.drain(timeout=10.0)
        assert r1.event.wait(5.0) and r2.event.wait(5.0)
        assert r1.result is not None and r2.result is not None
        assert s.stats.dispatch_drain >= 1
        with pytest.raises(SchedulerClosed):
            s.submit_async(_item())
        s.close()

    def test_slo_partial_dispatch_through_real_thread(self):
        # end-to-end: one lonely request at batch_size 4 is served
        # within ~the SLO by the dispatcher thread itself
        s = Scheduler(_engine(4), slo_ms=30.0).start()
        res = s.submit(_item(), timeout=10.0)
        assert res.flow_up.shape == (40, 56, 2)
        assert s.stats.dispatch_slo == 1
        s.close()


# ---- sessions: affinity + TTL -------------------------------------------


class TestSessionStore:
    def test_carry_roundtrip_and_ttl_eviction(self):
        clock = FakeClock()
        st = SessionStore(ttl_s=10.0, clock=clock)
        carry = np.ones((5, 7, 2), np.float32)
        st.put("cam-1", (40, 56), carry)
        np.testing.assert_array_equal(st.get("cam-1", (40, 56)), carry)
        clock.advance(10.1)                 # past the TTL
        assert st.get("cam-1", (40, 56)) is None
        rec = st.stats_record()
        assert rec["active"] == 0 and rec["expired"] == 1
        assert rec["hits"] == 1

    def test_bucket_change_restarts_cold(self):
        # a stream that moves buckets must NOT get a misaligned seed
        st = SessionStore(ttl_s=10.0, clock=FakeClock())
        st.put("cam-1", (40, 56), np.zeros((5, 7, 2), np.float32))
        assert st.get("cam-1", (64, 80)) is None
        assert st.stats_record()["bucket_resets"] == 1
        assert st.stats_record()["active"] == 0

    def test_lru_bound(self):
        st = SessionStore(ttl_s=100.0, max_sessions=2, clock=FakeClock())
        z = np.zeros((5, 7, 2), np.float32)
        st.put("a", (40, 56), z)
        st.put("b", (40, 56), z)
        st.put("c", (40, 56), z)            # evicts the LRU ("a")
        assert st.get("a", (40, 56)) is None
        assert st.get("c", (40, 56)) is not None
        assert st.stats_record()["lru_evicted"] == 1

    def test_stats_schema(self):
        st = SessionStore(ttl_s=1.0, clock=FakeClock())
        assert set(st.stats_record()) == {
            "active", "ttl_s", "max_sessions", "hits", "misses",
            "expired", "lru_evicted", "bucket_resets",
        }


# ---- HTTP service -------------------------------------------------------


def _post(url, body, session=None, timeout=10.0):
    headers = {"Content-Type": "application/x-npz"}
    if session:
        headers["X-Session-Id"] = session
    req = urllib.request.Request(url + "/v1/flow", data=body,
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, decode_response(r.read()), dict(r.headers)


def _get_json(url, path):
    with urllib.request.urlopen(url + path, timeout=10.0) as r:
        return r.status, json.load(r)


@pytest.fixture()
def service():
    svc = FlowService(
        InferenceEngine(_stub_eval,
                        ServeConfig(batch_size=2, warm_start=True)),
        port=0, slo_ms=50.0, max_queue=8, session_ttl_s=30.0).start()
    yield svc
    if not svc.stopped.is_set():
        svc.drain_and_stop(timeout=10.0)


class TestHTTPService:
    def test_flow_roundtrip_and_session_carry(self, service):
        body = encode_request(**{"image1": _item()["image1"],
                                 "image2": _item()["image2"]})
        status, flow, hdr = _post(service.url, body, session="cam-1")
        assert status == 200
        assert hdr["X-Warm-Start"] == "0"           # first frame = cold
        assert hdr["X-Bucket"] == "40x56"
        np.testing.assert_allclose(flow, np.broadcast_to(
            np.float32([2.0, -1.0]), flow.shape))
        # frame 2 of the same stream rides the carry (stub: +0.5 px)
        status, flow2, hdr2 = _post(service.url, body, session="cam-1")
        assert hdr2["X-Warm-Start"] == "1"
        np.testing.assert_allclose(flow2, np.broadcast_to(
            np.float32([2.5, -0.5]), flow2.shape))
        # a session-less request stays cold
        _, flow3, hdr3 = _post(service.url, body)
        assert hdr3["X-Warm-Start"] == "0"
        np.testing.assert_allclose(flow3, flow)

    def test_malformed_requests_rejected_400(self, service):
        for bad in (b"junk",                         # not an npz
                    encode_response(np.zeros((4, 4, 2)))):  # missing keys
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(service.url, bad)
            assert ei.value.code == 400
            assert "error" in json.load(ei.value)
        # valid npz, invalid geometry (rank-2 image)
        buf = encode_request(np.zeros((8, 8), np.float32),
                             np.zeros((8, 8), np.float32))
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(service.url, buf)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(service.url, "/nope")
        assert ei.value.code == 404

    def test_healthz_and_stats_schema_pin(self, service):
        body = encode_request(**_item())
        _post(service.url, body)
        status, health = _get_json(service.url, "/healthz")
        assert status == 200
        # liveness/readiness split: the readiness payload must let a
        # router distinguish "dying" from "busy" and poll a drain down
        assert set(health) == {"status", "draining", "inflight",
                               "sessions", "uptime_s", "queue_depth"}
        assert health["status"] == "ok"
        assert health["draining"] is False
        assert health["inflight"] == 0 and health["sessions"] == 0
        status, live = _get_json(service.url, "/livez")
        assert status == 200 and live == {"status": "alive"}

        status, stats = _get_json(service.url, "/stats?reset=1")
        assert set(stats) == {"service", "engine", "scheduler", "sessions",
                              "video", "locks"}
        # lock-order runtime verdicts (analysis/locks): a healthy
        # replica serves with zero violations — strict mode is armed
        # suite-wide, so a nonzero here would have raised upstream
        assert stats["locks"]["order_violations"] == 0
        assert stats["locks"]["cycles"] == 0
        assert set(stats["service"]) == {
            "uptime_s", "draining", "slo_ms", "sessions_enabled",
            "adaptive"}
        assert stats["service"]["adaptive"] is False
        # engine blob: ServeStats + registry, incl. the bucket SHAPES
        # and compiled signature names (which geometries are hot vs
        # compiling — the BucketRegistry.stats() satellite)
        eng = stats["engine"]
        for key in ("batch_size", "frames", "batches", "latency_p50_ms",
                    "latency_p99_ms", "buckets", "bucket_count",
                    "compiles", "compiled"):
            assert key in eng, key
        assert eng["buckets"] == {"40x56": 1}
        assert eng["compiled"] == ["40x56+warm"]
        assert stats["scheduler"]["submitted"] == 1
        assert stats["sessions"]["active"] == 0

        # ?reset=1 handed the window off: counters zero, compiled state
        # (the executables) survives — the reset_stats() satellite
        _, stats2 = _get_json(service.url, "/stats")
        assert stats2["scheduler"]["submitted"] == 0
        assert stats2["engine"]["frames"] == 0
        assert stats2["engine"]["buckets"] == {}
        assert stats2["engine"]["compiled"] == ["40x56+warm"]

    def test_overload_sheds_with_503(self):
        gate = threading.Event()

        def gated(im1, im2, flow_init=None):
            gate.wait(10.0)
            return _stub_eval(im1, im2, flow_init)

        svc = FlowService(
            InferenceEngine(gated, ServeConfig(batch_size=1)),
            port=0, slo_ms=50.0, max_queue=2, session_ttl_s=0.0).start()
        try:
            body = encode_request(**_item())
            results = []

            def post_bg():
                try:
                    results.append(_post(svc.url, body)[0])
                except urllib.error.HTTPError as e:
                    results.append(e.code)

            threads = [threading.Thread(target=post_bg)]
            threads[0].start()
            time.sleep(0.3)       # dispatcher picked it up, blocked in eval
            for _ in range(2):    # fill max_queue
                threads.append(threading.Thread(target=post_bg))
                threads[-1].start()
            time.sleep(0.3)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(svc.url, body)          # 4th concurrent -> shed
            assert ei.value.code == 503
            assert "Retry-After" in dict(ei.value.headers)
            gate.set()
            for t in threads:
                t.join(timeout=10.0)
            assert results == [200, 200, 200]
        finally:
            gate.set()
            svc.drain_and_stop(timeout=10.0)

    def test_sigterm_drains_inflight_then_exits(self):
        """The acceptance path: a REAL SIGTERM through the installed
        handler (os.kill on ourselves — the PR 4 harness pattern) while
        requests are in flight: both admitted requests complete with
        200, new work is refused 503, /healthz flips to draining, and
        the service reports stopped only after responses flushed."""
        gate = threading.Event()

        def gated(im1, im2, flow_init=None):
            gate.wait(10.0)
            return _stub_eval(im1, im2, flow_init)

        svc = FlowService(
            InferenceEngine(gated, ServeConfig(batch_size=1)),
            port=0, slo_ms=50.0, max_queue=8, session_ttl_s=0.0).start()
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        try:
            assert svc.install_signal_handlers()
            body = encode_request(**_item())
            results = []

            def post_bg():
                try:
                    results.append(_post(svc.url, body)[0])
                except urllib.error.HTTPError as e:
                    results.append(e.code)

            threads = [threading.Thread(target=post_bg) for _ in range(2)]
            for t in threads:
                t.start()
            time.sleep(0.3)  # one dispatched (blocked in eval), one queued

            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 5.0
            while (not svc.draining) and time.monotonic() < deadline:
                time.sleep(0.01)
            assert svc.draining

            # draining: the LB signal flips and new admissions are shed
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(svc.url, "/healthz")
            assert ei.value.code == 503
            # … but liveness holds: draining is not dead (the router
            # restarts on /livez, routes on /healthz)
            status, live = _get_json(svc.url, "/livez")
            assert status == 200 and live["status"] == "alive"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(svc.url, body)
            assert ei.value.code == 503

            gate.set()                       # let the in-flight work finish
            assert svc.stopped.wait(10.0)
            for t in threads:
                t.join(timeout=10.0)
            assert results == [200, 200]     # nothing admitted was dropped
        finally:
            gate.set()
            signal.signal(signal.SIGTERM, prev_term)
            signal.signal(signal.SIGINT, prev_int)
            if not svc.stopped.is_set():
                svc.drain_and_stop(timeout=10.0)


# ---- engine satellites --------------------------------------------------


class TestEngineSatellites:
    def test_reset_stats_keeps_compiled_state(self):
        eng = _engine(batch_size=2)
        list(eng.stream([_item(), _item(seed=1)]))
        assert eng.stats.frames == 2 and eng.registry.compiles == 1
        eng.reset_stats()
        assert eng.stats.frames == 0 and eng.stats.batches == 0
        assert eng.registry.hits == {}
        # the executables survive: the next dispatch is NOT a compile
        assert eng.registry.compiles == 1
        list(eng.stream([_item(seed=2)]))
        assert eng.registry.compiles == 1    # still the same signature

    def test_registry_stats_carry_shapes(self):
        eng = _engine(batch_size=1, warm_start=True)
        list(eng.stream([_item(), _item(h=64, w=80)]))
        rec = eng.registry.stats()
        assert rec["buckets"] == {"40x56": 1, "64x80": 1}
        assert rec["compiled"] == ["40x56+warm", "64x80+warm"]

    def test_serve_stats_latency_window_bounded(self):
        from dexiraft_tpu.profiling import ServeStats

        st = ServeStats(maxlen=8)
        for i in range(50):
            st.batch_latency_s.append(i * 1e-3)
        assert len(st.batch_latency_s) == 8   # bounded, newest kept
        assert min(st.batch_latency_s) == 42 * 1e-3


# ---- closed-loop bench record schema (the SERVE_r0* service record) -----


def test_closed_loop_record_schema_pinned():
    import os.path as osp
    import sys

    scripts = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                       "scripts")
    sys.path.insert(0, scripts)
    try:
        from serve_bench import (CLOSED_LOOP_RECORD_KEYS, LEVEL_KEYS,
                                 OVERLOAD_KEYS, WARM_KEYS)
    finally:
        sys.path.pop(0)
    assert {"metric", "sequential", "levels", "overload", "warm_start",
            "speedup_batched_over_sequential"} <= CLOSED_LOOP_RECORD_KEYS
    assert {"concurrency", "goodput_rps", "p50_ms", "p99_ms",
            "rejected"} <= LEVEL_KEYS
    assert {"offered_rps", "goodput_rps", "rejected"} <= OVERLOAD_KEYS
    assert {"warm_dist", "cold_dist", "warm_beats_cold"} <= WARM_KEYS
