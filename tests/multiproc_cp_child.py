"""One process of the cross-process CONTEXT-PARALLEL test.

Spawned (never imported) twice by tests/test_multiprocess.py:
2 processes x 2 virtual CPU devices = a (data=1, seq=4) global mesh
whose ring ppermute hops CROSS THE PROCESS BOUNDARY — the DCN/multi-host
analog of the single-process ring tests in test_context_parallel.py.
Each child builds the globally row-sharded inputs from its
process-local rows, runs ring_corr_lookup under jit, and dumps its
addressable output rows for the parent to reassemble and pin against
the unsharded lookup. Geometry and inputs live in tests/_mp_common.py
(side-effect free) so the parent never has to import this module.
"""

from __future__ import annotations

import argparse
import os
import os.path as osp
import sys

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    from tests._mp_common import CP_H, CP_LEVELS, CP_RADIUS, cp_full_inputs

    from dexiraft_tpu.parallel.distributed import initialize

    initialize(coordinator_address=f"127.0.0.1:{args.port}",
               num_processes=args.num_processes,
               process_id=args.process_id)
    n_seq = len(jax.devices())
    assert n_seq == 4, jax.devices()

    from jax.sharding import NamedSharding, PartitionSpec as P

    from dexiraft_tpu.parallel.context import ring_corr_lookup
    from dexiraft_tpu.parallel.mesh import make_mesh_2d

    mesh = make_mesh_2d(1, n_seq)
    f1, f2, coords = cp_full_inputs()

    def rows_global(arr):
        # each process contributes only the rows its devices own —
        # nothing outside the local slice is ever materialized globally
        sh = NamedSharding(mesh, P(None, "seq"))
        lo = jax.process_index() * (CP_H // args.num_processes)
        hi = lo + CP_H // args.num_processes
        return jax.make_array_from_process_local_data(
            sh, arr[:, lo:hi], arr.shape)

    out = jax.jit(lambda a, b, c: ring_corr_lookup(
        a, b, c, mesh, num_levels=CP_LEVELS, radius=CP_RADIUS))(
            rows_global(f1), rows_global(f2), rows_global(coords))
    jax.block_until_ready(out)

    rows = {}
    for shard in out.addressable_shards:
        r0 = shard.index[1].start or 0
        rows[str(r0)] = np.asarray(shard.data)
    np.savez(args.out, **rows)
    print(f"child {args.process_id} wrote {sorted(rows)} shapes "
          f"{[v.shape for v in rows.values()]}")


if __name__ == "__main__":
    main()
