"""End-to-end eval-stack parity vs the reference's own evaluate.py.

The strongest accuracy claim this environment physically allows (no real
datasets or trained checkpoints are mounted): build a synthetic
Sintel-layout dataset on disk, load the SAME v5 weights into the actual
reference torch stack and into our flax stack via the converter, then run
the reference's `evaluate.validate_sintel` (evaluate.py:102-133 — its
real loop, its InputPadder, its EPE/px accumulation) against our
`eval.validate.validate_sintel` and pin every reported metric equal to
tolerance. This closes the full chain: image decode -> pad -> forward ->
unpad -> metric accumulation.

The reference loop calls .cuda(); there is no CUDA here, so
torch.Tensor.cuda is patched to a no-op — the code path is otherwise
untouched. Skipped when the reference checkout or torch is unavailable.
"""

import os
import sys

import numpy as np
import pytest

_REF = "/root/reference"
_REF_CORE = "/root/reference/core"

torch = pytest.importorskip("torch")
pytestmark = pytest.mark.skipif(not os.path.isdir(_REF_CORE),
                                reason="reference checkout not mounted")

# image geometry: neither dim divisible by 8 so the padder actually pads
# (the sintel-mode split pad + unpad is part of the stack under test).
# Also large enough that the coarsest corr-pyramid level keeps >=2 rows
# and cols: the reference's bilinear_sampler normalizes grid coords by
# (dim-1) (core/utils/utils.py:63-66), which divides by zero and floods
# the update block with nan when a level collapses to 1 pixel — at
# 100x136 padded (13x17 at 1/8, level-3 height 1) the REFERENCE returns
# nan EPE. Our one-hot interpolation matmul has no such normalization
# and is finite at any size; parity is only testable where both are
# defined, and real Sintel/KITTI geometries always are.
H, W = 132, 164  # padded 136x168 -> 1/8 grid 17x21 -> level 3 is 2x2
ITERS = 8  # both stacks; fewer than the reference's 32 for CPU runtime


def _import_ref_evaluate():
    """Import the reference's evaluate.py with its sibling modules.

    evaluate.py does sys.path.append('core') relative to the reference
    checkout's cwd, so the core dir must be injected here. Pre-existing
    unrelated modules named 'datasets'/'utils' (e.g. huggingface
    datasets) would shadow the reference's — evict them first and let
    the reference's own imports win while its paths are at the front.
    """
    import types

    # the reference's datasets.py imports torchvision for its augmentor;
    # torchvision is not installed here and the eval path (aug_params
    # None) never constructs an augmentor — stub just enough to import
    try:
        import torchvision  # noqa: F401
    except ModuleNotFoundError:
        tv = types.ModuleType("torchvision")
        tr = types.ModuleType("torchvision.transforms")

        class _NeverUsedColorJitter:  # pragma: no cover
            def __init__(self, *a, **k):
                raise AssertionError("augmentor used on the eval path")

        tr.ColorJitter = _NeverUsedColorJitter
        tv.transforms = tr
        sys.modules["torchvision"] = tv
        sys.modules["torchvision.transforms"] = tr

    evicted = {}
    for name in ("datasets", "utils", "evaluate"):
        mod = sys.modules.get(name)
        if mod is not None and not getattr(
                mod, "__file__", "").startswith(_REF):
            evicted[name] = sys.modules.pop(name)
    for p in (_REF, _REF_CORE):
        sys.path.insert(0, p)
    try:
        import evaluate as ref_evaluate
        return ref_evaluate
    finally:
        for p in (_REF, _REF_CORE):
            sys.path.remove(p)
        # the reference modules stay importable via sys.modules (they
        # hold references to each other); only restore what was evicted
        # and does not collide
        for name, mod in evicted.items():
            if name not in sys.modules:
                sys.modules[name] = mod


def _write_sintel_tree(root, rng):
    """Synthetic MpiSintel training layout: 2 scenes x 3 frames (2 pairs
    each) for both render passes, with smooth random .flo ground truth."""
    from PIL import Image

    from dexiraft_tpu.data.flow_io import write_flo

    for scene in ("alley_9", "market_9"):
        for dstype in ("clean", "final"):
            img_dir = os.path.join(root, "training", dstype, scene)
            os.makedirs(img_dir, exist_ok=True)
            import zlib

            # NOT hash(): that is salted per process (PYTHONHASHSEED),
            # which would make any failure unreproducible
            srng = np.random.default_rng(
                zlib.crc32(f"{scene}/{dstype}".encode()))
            for i in range(1, 4):
                img = srng.integers(0, 256, (H, W, 3), dtype=np.uint8)
                Image.fromarray(img).save(
                    os.path.join(img_dir, f"frame_{i:04d}.png"))
        flow_dir = os.path.join(root, "training", "flow", scene)
        os.makedirs(flow_dir, exist_ok=True)
        for i in range(1, 3):
            # low-frequency flow upsampled from a coarse grid keeps the
            # GT smooth (realistic EPE distribution, no threshold pileup)
            coarse = rng.uniform(-4, 4, (5, 7, 2)).astype(np.float32)
            flow = np.kron(coarse, np.ones((27, 24, 1),
                                           np.float32))[:H, :W]
            assert flow.shape == (H, W, 2)
            write_flo(os.path.join(flow_dir, f"frame_{i:04d}.flo"), flow)


@pytest.fixture(scope="module")
def v5_pair():
    """One random-init reference v5 + converted flax variables, shared
    across the sintel and kitti tests (the torch build + conversion is
    the expensive part)."""
    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.interop.reference import build_reference_v5
    from dexiraft_tpu.interop.torch_convert import convert_raft_state_dict

    tm = build_reference_v5()
    return tm, raft_v5(), convert_raft_state_dict(tm.state_dict())


@pytest.mark.slow
def test_validate_sintel_matches_reference(tmp_path, monkeypatch, capsys,
                                           v5_pair):
    import re

    import jax.numpy as jnp

    from dexiraft_tpu.data.datasets import MpiSintel
    from dexiraft_tpu.eval.validate import validate_sintel
    from dexiraft_tpu.train.step import make_eval_step

    root = str(tmp_path / "Sintel")
    _write_sintel_tree(root, np.random.default_rng(42))

    tm, cfg, variables = v5_pair

    # ---- reference stack, verbatim loop, CPU-patched ----
    ref_evaluate = _import_ref_evaluate()
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self)
    # point the reference dataset at the synthetic tree by rewriting the
    # __init__ default for `root` — rebinding the module-global class
    # name (e.g. with functools.partial) breaks its call-time
    # super(MpiSintel, self) lookup, so the class object must stay put
    ref_sintel_init = ref_evaluate.datasets.MpiSintel.__init__
    defaults = list(ref_sintel_init.__defaults__)
    defaults[-2] = root  # (aug_params, split, root, dstype)
    monkeypatch.setattr(ref_sintel_init, "__defaults__", tuple(defaults))
    capsys.readouterr()  # drop anything pending
    with torch.no_grad():
        ref = ref_evaluate.validate_sintel(tm, iters=ITERS)
    # the px accuracies are only PRINTED by the reference
    # (evaluate.py:128-131) — recover them from its stdout, captured
    # before our own validator prints its look-alike lines
    ref_out = capsys.readouterr().out
    for dstype in ("clean", "final"):
        m = re.search(
            rf"Validation \({dstype}\) EPE: ([\d.]+), 1px: ([\d.]+), "
            rf"3px: ([\d.]+), 5px: ([\d.]+)", ref_out)
        assert m, f"reference output not parseable:\n{ref_out}"
        for k, g in zip(("_px1", "_px3", "_px5"), m.groups()[1:]):
            ref[dstype + k] = float(g)

    # ---- our stack ----
    step = make_eval_step(cfg, iters=ITERS)

    def eval_fn(i1, i2):
        lo, up = step(variables, jnp.asarray(i1), jnp.asarray(i2))
        return np.asarray(lo), np.asarray(up)

    ours = validate_sintel(eval_fn, datasets={
        d: MpiSintel(None, split="training", root=root, dstype=d)
        for d in ("clean", "final")})

    for dstype in ("clean", "final"):
        # forward parity for v5 is ~1e-2 absolute on flow (accumulated
        # through 8 GRU iterations); means over ~54k pixels agree much
        # tighter, px fractions can flip only on threshold-adjacent epes
        np.testing.assert_allclose(ours[dstype], ref[dstype],
                                   rtol=5e-3, atol=5e-3,
                                   err_msg=f"{dstype} EPE")
        assert ref[f"{dstype}_px1"] == pytest.approx(
            ours[f"{dstype}_px1"], abs=5e-3)
        assert ref[f"{dstype}_px3"] == pytest.approx(
            ours[f"{dstype}_px3"], abs=5e-3)
        assert ref[f"{dstype}_px5"] == pytest.approx(
            ours[f"{dstype}_px5"], abs=5e-3)


def _write_kitti_tree(root, rng):
    """Synthetic KITTI-2015 training layout: *_10/_11.png pairs plus
    sparse 16-bit flow_occ PNGs with a random ~70% valid mask."""
    from PIL import Image

    from dexiraft_tpu.data.flow_io import write_flow_kitti

    # not divisible by 8 (kitti-mode pad engages); padded 128x200 keeps
    # every corr level >=2 pixels (see the geometry note at the top)
    kh, kw = 124, 196
    base = os.path.join(root, "data_scene_flow", "training")
    img_dir = os.path.join(base, "image_2")
    flow_dir = os.path.join(base, "flow_occ")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(flow_dir, exist_ok=True)
    for i in range(3):
        for suffix in ("10", "11"):
            img = rng.integers(0, 256, (kh, kw, 3), dtype=np.uint8)
            Image.fromarray(img).save(
                os.path.join(img_dir, f"{i:06d}_{suffix}.png"))
        coarse = rng.uniform(-4, 4, (5, 7, 2)).astype(np.float32)
        flow = np.kron(coarse, np.ones((26, 28, 1), np.float32))[:kh, :kw]
        # quantize to the PNG encoding's 1/64 grid so the GT both stacks
        # read back is exactly what parity is computed against
        flow = np.round(flow * 64.0) / 64.0
        valid = (rng.random((kh, kw)) < 0.7).astype(np.float32)
        write_flow_kitti(os.path.join(flow_dir, f"{i:06d}_10.png"),
                         flow, valid)


@pytest.mark.slow
def test_validate_kitti_matches_reference(tmp_path, monkeypatch, v5_pair):
    import jax.numpy as jnp

    from dexiraft_tpu.data.datasets import KITTI
    from dexiraft_tpu.eval.validate import validate_kitti
    from dexiraft_tpu.train.step import make_eval_step

    root = str(tmp_path / "Kitti_2015")
    _write_kitti_tree(root, np.random.default_rng(5))

    tm, cfg, variables = v5_pair

    ref_evaluate = _import_ref_evaluate()
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self)
    ref_kitti_init = ref_evaluate.datasets.KITTI.__init__
    defaults = list(ref_kitti_init.__defaults__)
    defaults[-1] = root  # (aug_params, split, root)
    monkeypatch.setattr(ref_kitti_init, "__defaults__", tuple(defaults))
    with torch.no_grad():
        ref = ref_evaluate.validate_kitti(tm, iters=ITERS)

    step = make_eval_step(cfg, iters=ITERS)

    def eval_fn(i1, i2):
        lo, up = step(variables, jnp.asarray(i1), jnp.asarray(i2))
        return np.asarray(lo), np.asarray(up)

    ours = validate_kitti(
        eval_fn, dataset=KITTI(None, split="training", root=root))

    np.testing.assert_allclose(ours["kitti-epe"], ref["kitti-epe"],
                               rtol=5e-3, atol=5e-3, err_msg="KITTI EPE")
    # F1 is a percentage of outlier pixels — threshold-crossing flips
    # move it in quanta of 100/n_valid; allow a handful of pixels
    assert ref["kitti-f1"] == pytest.approx(ours["kitti-f1"], abs=0.5)


@pytest.mark.slow
def test_validate_chairs_matches_reference(tmp_path, monkeypatch, v5_pair):
    """Fourth validator: FlyingChairs val EPE (evaluate.py:79-98 — the
    one remaining runnable reference eval path; no padder, raw-size
    forward). Same synthetic tree, same converted weights, pinned
    equal. chairs_split.txt is read cwd-relative by the reference
    (core/datasets.py:131), so the test chdirs into the fixture."""
    import imageio.v2 as imageio

    import jax.numpy as jnp

    from dexiraft_tpu.data.datasets import FlyingChairs
    from dexiraft_tpu.data.flow_io import write_flo
    from dexiraft_tpu.eval.validate import validate_chairs
    from dexiraft_tpu.train.step import make_eval_step

    ch, cw = 128, 160  # /8 exact (the reference path never pads) and
    # large enough that no corr level degenerates (16x20 at 1/8)
    data = tmp_path / "FlyingChairs_release" / "data"
    data.mkdir(parents=True)
    rng = np.random.default_rng(13)
    n = 4
    for i in range(n):
        for k in (1, 2):
            imageio.imwrite(
                data / f"{i:05d}_img{k}.ppm",
                rng.integers(0, 256, (ch, cw, 3), dtype=np.uint8))
        coarse = rng.uniform(-4, 4, (5, 7, 2)).astype(np.float32)
        write_flo(data / f"{i:05d}_flow.flo",
                  np.kron(coarse, np.ones((26, 24, 1),
                                          np.float32))[:ch, :cw])
    # 3 of 4 pairs land in the validation split (label 2)
    (tmp_path / "chairs_split.txt").write_text("2\n2\n1\n2\n")

    tm, cfg, variables = v5_pair

    ref_evaluate = _import_ref_evaluate()
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self)
    ref_chairs_init = ref_evaluate.datasets.FlyingChairs.__init__
    defaults = list(ref_chairs_init.__defaults__)
    defaults[-1] = str(data)  # (aug_params, split, root)
    monkeypatch.setattr(ref_chairs_init, "__defaults__", tuple(defaults))
    monkeypatch.chdir(tmp_path)  # chairs_split.txt lookup
    with torch.no_grad():
        ref = ref_evaluate.validate_chairs(tm, iters=ITERS)

    step = make_eval_step(cfg, iters=ITERS)

    def eval_fn(i1, i2):
        lo, up = step(variables, jnp.asarray(i1), jnp.asarray(i2))
        return np.asarray(lo), np.asarray(up)

    ours = validate_chairs(eval_fn, dataset=FlyingChairs(
        None, split="validation", root=str(data)))
    np.testing.assert_allclose(ours["chairs"], ref["chairs"],
                               rtol=5e-3, atol=5e-3, err_msg="Chairs EPE")


@pytest.mark.slow
def test_sintel_submission_reference_crashes_ours_writes(tmp_path,
                                                        monkeypatch,
                                                        v5_pair):
    """The reference's create_sintel_submission is unrunnable as
    written: it builds the TRAINING split, whose samples are 4-tuples
    (image1, image2, flow, valid), but unpacks three values
    (evaluate.py:26,33) — ValueError on the first sample. Pin that
    crash, then write the submission tree from the same synthetic data
    with our writer (same warm-start protocol, on-device splat)."""
    import jax.numpy as jnp

    from dexiraft_tpu.data.datasets import MpiSintel
    from dexiraft_tpu.eval.submission import create_sintel_submission
    from dexiraft_tpu.train.step import make_eval_step

    root = str(tmp_path / "Sintel")
    _write_sintel_tree(root, np.random.default_rng(21))

    tm, cfg, variables = v5_pair

    ref_evaluate = _import_ref_evaluate()
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self)
    ref_sintel_init = ref_evaluate.datasets.MpiSintel.__init__
    defaults = list(ref_sintel_init.__defaults__)
    defaults[-2] = root
    monkeypatch.setattr(ref_sintel_init, "__defaults__", tuple(defaults))
    with torch.no_grad(), pytest.raises(ValueError):
        ref_evaluate.create_sintel_submission(
            tm, iters=2, output_path=str(tmp_path / "ref_sub"))

    step = make_eval_step(cfg, iters=2)

    def eval_fn(i1, i2, flow_init=None):
        lo, up = step(variables, jnp.asarray(i1), jnp.asarray(i2),
                      flow_init=None if flow_init is None
                      else jnp.asarray(flow_init))
        return np.asarray(lo), np.asarray(up)

    out = tmp_path / "sub"
    create_sintel_submission(
        eval_fn, output_path=str(out), warm_start=True,
        datasets={"clean": MpiSintel(None, split="training", root=root,
                                     dstype="clean", qualitative=True)})
    written = sorted(p.relative_to(out).as_posix()
                     for p in out.rglob("*.flo"))
    assert written == ["clean/alley_9/frame0001.flo",
                       "clean/alley_9/frame0002.flo",
                       "clean/market_9/frame0001.flo",
                       "clean/market_9/frame0002.flo"]


def _write_hd1k_tree(root, rng):
    """Synthetic HD1K layout, one sequence of 3 frames with sparse GT."""
    from PIL import Image

    from dexiraft_tpu.data.flow_io import write_flow_kitti

    kh, kw = 124, 196  # same corr-level-safe geometry as the KITTI tree
    img_dir = os.path.join(root, "hd1k_input", "image_2")
    flow_dir = os.path.join(root, "hd1k_flow_gt", "flow_occ")
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(flow_dir, exist_ok=True)
    for i in range(3):
        img = rng.integers(0, 256, (kh, kw, 3), dtype=np.uint8)
        Image.fromarray(img).save(
            os.path.join(img_dir, f"000000_{i:04d}.png"))
        coarse = rng.uniform(-4, 4, (5, 7, 2)).astype(np.float32)
        flow = np.kron(coarse, np.ones((26, 28, 1), np.float32))[:kh, :kw]
        flow = np.round(flow * 64.0) / 64.0
        valid = (rng.random((kh, kw)) < 0.7).astype(np.float32)
        write_flow_kitti(os.path.join(flow_dir, f"000000_{i:04d}.png"),
                         flow, valid)


@pytest.mark.slow
def test_kitti_submission_reference_crashes_ours_writes(tmp_path,
                                                       monkeypatch,
                                                       v5_pair):
    """create_kitti_submission shares the 3-of-4 unpack crash (it also
    writes .flo files where the KITTI devkit expects 16-bit PNGs —
    evaluate.py:58-77). Pin the crash; our writer emits the PNGs on the
    proper testing split and they decode back finite."""
    import jax.numpy as jnp
    from PIL import Image

    from dexiraft_tpu.data.datasets import KITTI
    from dexiraft_tpu.data.flow_io import read_flow_kitti
    from dexiraft_tpu.eval.submission import create_kitti_submission
    from dexiraft_tpu.train.step import make_eval_step

    root = str(tmp_path / "Kitti_2015")
    rng = np.random.default_rng(9)
    _write_kitti_tree(root, rng)  # training split, for the reference
    test_img = os.path.join(root, "data_scene_flow", "testing", "image_2")
    os.makedirs(test_img)
    for i in range(2):
        for suffix in ("10", "11"):
            Image.fromarray(rng.integers(0, 256, (124, 196, 3),
                                         dtype=np.uint8)).save(
                os.path.join(test_img, f"{i:06d}_{suffix}.png"))

    tm, cfg, variables = v5_pair
    ref_evaluate = _import_ref_evaluate()
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self)
    ref_kitti_init = ref_evaluate.datasets.KITTI.__init__
    defaults = list(ref_kitti_init.__defaults__)
    defaults[-1] = root
    monkeypatch.setattr(ref_kitti_init, "__defaults__", tuple(defaults))
    with torch.no_grad(), pytest.raises(ValueError):
        ref_evaluate.create_kitti_submission(
            tm, iters=2, output_path=str(tmp_path / "ref_sub"))

    step = make_eval_step(cfg, iters=2)

    def eval_fn(i1, i2):
        lo, up = step(variables, jnp.asarray(i1), jnp.asarray(i2))
        return np.asarray(lo), np.asarray(up)

    out = tmp_path / "sub"
    create_kitti_submission(
        eval_fn, output_path=str(out),
        dataset=KITTI(None, split="testing", root=root))
    pngs = sorted(p.name for p in out.glob("*.png"))
    assert pngs == ["000000_10.png", "000001_10.png"]
    flow, valid = read_flow_kitti(out / "000000_10.png")
    assert flow.shape == (124, 196, 2) and np.isfinite(flow).all()
    assert (valid == 1).all()


@pytest.mark.slow
def test_validate_hd1k_reference_crashes_ours_scores(tmp_path, monkeypatch,
                                                     v5_pair):
    """The reference's validate_HD1K is unrunnable as written: it
    unpacks the valid mask into `_` and then reads `valid_gt`
    (evaluate.py:182,197) — NameError on the first sample. Pinning the
    crash documents that our validate_hd1k (which uses the mask) is a
    bug fix, not a divergence; there is no reference number to match."""
    import jax.numpy as jnp

    from dexiraft_tpu.data.datasets import HD1K
    from dexiraft_tpu.eval.validate import validate_hd1k
    from dexiraft_tpu.train.step import make_eval_step

    root = str(tmp_path / "HD1k")
    _write_hd1k_tree(root, np.random.default_rng(11))

    tm, cfg, variables = v5_pair

    ref_evaluate = _import_ref_evaluate()
    monkeypatch.setattr(torch.Tensor, "cuda",
                        lambda self, *a, **k: self)
    ref_hd1k_init = ref_evaluate.datasets.HD1K.__init__
    defaults = list(ref_hd1k_init.__defaults__)
    defaults[-1] = root  # (aug_params, root)
    monkeypatch.setattr(ref_hd1k_init, "__defaults__", tuple(defaults))
    with torch.no_grad(), pytest.raises(NameError):
        ref_evaluate.validate_HD1K(tm, iters=2)

    step = make_eval_step(cfg, iters=2)

    def eval_fn(i1, i2):
        lo, up = step(variables, jnp.asarray(i1), jnp.asarray(i2))
        return np.asarray(lo), np.asarray(up)

    ours = validate_hd1k(eval_fn, dataset=HD1K(None, root=root))
    assert np.isfinite(ours["hd1k-epe"])
    assert 0.0 <= ours["hd1k-f1"] <= 100.0
