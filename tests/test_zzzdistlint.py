"""distlint (JL030+) + collective flight recorder coverage.

One positive + one negative fixture per collective-divergence rule
(incl. inline suppression, the matching-branches exemption, and
JL032's distributed-path scoping), the CollectiveTrace ring/digest/
counter semantics on a fake clock, the pure lockstep verifier naming
the first divergent op on scripted traces, the snapshot/record schema
pins, and the lint_gate --rules filter + per_family --json contract.

Named zzz to sort LAST (tier-1 budget convention); everything here is
pure-stdlib AST fixtures + in-process recorder plumbing — target well
under 5 s. The 2-process seeded-divergence leg (a REAL pair diagnosing
a real skew) lives in tests/test_zzmultihost_resilience.py.
"""

from __future__ import annotations

import json
import os.path as osp
import subprocess
import sys
import textwrap

from dexiraft_tpu.analysis import collective_trace as ct
from dexiraft_tpu.analysis import jaxlint, locks, threadlint

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
GATE = osp.join(REPO, "scripts", "lint_gate.py")

#: JL032 is path-scoped to the distributed tier; the other rules run
#: everywhere, so fixtures default to a neutral path
DIST_PATH = "dexiraft_tpu/resilience/fixture.py"


def rules_of(src: str, path: str = "dexiraft_tpu/serve/fixture.py"):
    return {f.rule for f in jaxlint.lint_source(textwrap.dedent(src), path)}


# --------------------------------------------------------------------------
# static rules: one positive + one negative fixture per rule
# --------------------------------------------------------------------------


class TestRuleFixtures:
    def test_jl030_divergent_collective_branch(self):
        pos = """
            import jax

            def broadcast(coord, flag):
                if jax.process_index() == 0:
                    return coord.any_flag(flag)
                return flag
        """
        assert "JL030" in rules_of(pos)
        # matching-branches exemption: both arms run the SAME collective
        # sequence (different args, same protocol) — lockstep holds
        neg = """
            import jax

            def broadcast(coord, flag):
                if jax.process_index() == 0:
                    return coord.any_flag(flag)
                else:
                    return coord.any_flag(False)
        """
        assert "JL030" not in rules_of(neg)

    def test_jl030_needs_identity_test_and_collective(self):
        # branch on replicated state (a count) is lockstep: clean
        neg = """
            def maybe(coord, n, flag):
                if n > 1:
                    return coord.any_flag(flag)
                return flag
        """
        assert "JL030" not in rules_of(neg)
        # identity branch with only local work (KV posts, prints): clean
        neg2 = """
            def leader_log(self, msg):
                if self.index == 0:
                    print(msg)
        """
        assert "JL030" not in rules_of(neg2)

    def test_jl031_mid_protocol_bail(self):
        pos = """
            def protocol(coord, ok):
                seen = coord.any_flag(False)
                if not ok:
                    return None
                return seen, coord.min_int(3)
        """
        assert "JL031" in rules_of(pos)
        # bail governed by a collective verdict: every host bails
        # together — the sanctioned shape
        neg = """
            def protocol(coord, ok):
                seen = coord.any_flag(False)
                if coord.any_flag(not ok):
                    return None
                return seen, coord.min_int(3)
        """
        assert "JL031" not in rules_of(neg)
        # ... including via a verdict NAME assigned from a collective
        neg2 = """
            def protocol(coord, ok):
                stop = coord.any_flag(not ok)
                if stop:
                    return None
                return coord.min_int(3)
        """
        assert "JL031" not in rules_of(neg2)

    def test_jl031_loop_continue_and_exemptions(self):
        pos = """
            def train(coord, steps):
                for step in steps:
                    if step.skip_locally:
                        continue
                    coord.any_flag(step.bad)
        """
        assert "JL031" in rules_of(pos)
        # break stays inside the function, before the next round — and a
        # raise inside an except handler is failing loudly AFTER a
        # broken round, not a divergence
        neg = """
            def train(coord, steps):
                for step in steps:
                    if step.done:
                        break
                    try:
                        coord.any_flag(step.bad)
                    except RuntimeError as e:
                        raise ValueError(str(e))
        """
        assert "JL031" not in rules_of(neg)
        # a single-collective function is not a protocol: bail freely
        neg2 = """
            def once(coord, ok):
                if not ok:
                    return None
                return coord.any_flag(True)
        """
        assert "JL031" not in rules_of(neg2)

    def test_jl032_unbounded_distributed_wait(self):
        pos = """
            def drain(fut):
                return fut.result()
        """
        assert "JL032" in rules_of(pos, DIST_PATH)
        # timeout=None is the spelled-out unbounded form: still flagged
        pos2 = """
            def drain(ev):
                ev.wait(timeout=None)
        """
        assert "JL032" in rules_of(pos2, DIST_PATH)
        # keyword or positional timeout bounds the wait: clean
        neg = """
            def drain(fut, ev, t):
                fut.result(timeout=5.0)
                ev.wait(2.0)
                t.join(timeout=1.0)
        """
        assert "JL032" not in rules_of(neg, DIST_PATH)

    def test_jl032_is_path_scoped(self):
        # the same unbounded wait OUTSIDE the distributed tier keeps its
        # idiom (single-process queue plumbing has no dead peers)
        src = """
            def drain(fut):
                return fut.result()
        """
        assert "JL032" not in rules_of(src)  # serve/ fixture path
        assert "JL032" in rules_of(
            src, "dexiraft_tpu/parallel/distributed.py")

    def test_jl033_swallowed_collective_error(self):
        pos = """
            def vote(coord, flag):
                try:
                    return coord.any_flag(flag)
                except Exception:
                    return False
        """
        assert "JL033" in rules_of(pos)
        # re-raising (bare or wrapped) keeps the divergence loud: clean
        neg = """
            def vote(coord, flag):
                try:
                    return coord.any_flag(flag)
                except Exception as e:
                    raise RuntimeError("vote failed") from e
        """
        assert "JL033" not in rules_of(neg)
        # a try with no collective inside carries no round counter
        neg2 = """
            def local(io):
                try:
                    return io.read()
                except Exception:
                    return None
        """
        assert "JL033" not in rules_of(neg2)

    def test_jl034_unreleased_armed_region(self):
        pos = """
            def step(wd, fn):
                wd.arm(1)
                out = fn()
                wd.disarm()
                return out
        """
        assert "JL034" in rules_of(pos)
        # the sanctioned idiom: arm OUTSIDE the try, release in finally
        neg = """
            def step(wd, fn):
                wd.arm(1)
                try:
                    return fn()
                finally:
                    wd.stop()
        """
        assert "JL034" not in rules_of(neg)

    def test_jl034_receiver_must_match(self):
        # releasing a DIFFERENT receiver does not discharge the arm
        pos = """
            def step(wd, other, fn):
                wd.arm(1)
                try:
                    return fn()
                finally:
                    other.stop()
        """
        assert "JL034" in rules_of(pos)
        # dotted receivers match on their full spelling
        neg = """
            def step(self, fn):
                self.wd.arm(1)
                try:
                    return fn()
                finally:
                    self.wd.disarm()
        """
        assert "JL034" not in rules_of(neg)

    def test_jl034_sanctioned_window(self):
        pos = """
            def reshape(watch, fn):
                watch.sanctioned()
                return fn()
        """
        assert "JL034" in rules_of(pos)
        neg = """
            def reshape(watch, fn):
                with watch.sanctioned():
                    return fn()
        """
        assert "JL034" not in rules_of(neg)
        # assigned to a name later entered by `with` (the conditional-
        # window idiom) also counts as scoped
        neg2 = """
            from contextlib import nullcontext

            def reshape(watch, fn, fresh):
                win = watch.sanctioned() if fresh else nullcontext()
                with win:
                    return fn()
        """
        assert "JL034" not in rules_of(neg2)

    def test_inline_suppression(self):
        src = """
            def protocol(coord, ok):
                seen = coord.any_flag(False)
                if not ok:
                    return None  # jaxlint: disable=JL031 test-owned bail
                return seen, coord.min_int(3)
        """
        assert "JL031" not in rules_of(src)


# --------------------------------------------------------------------------
# the gate trips on every injected-footgun fixture (one invocation),
# and --rules/--json per_family report it machine-readably
# --------------------------------------------------------------------------


_FOOTGUNS = {
    "JL030": """
        import jax

        def broadcast(coord, flag):
            if jax.process_index() == 0:
                return coord.any_flag(flag)
            return flag
    """,
    "JL031": """
        def protocol(coord, ok):
            seen = coord.any_flag(False)
            if not ok:
                return None
            return seen, coord.min_int(3)
    """,
    "JL032": """
        def drain(fut):
            return fut.result()
    """,
    "JL033": """
        def vote(coord, flag):
            try:
                return coord.any_flag(flag)
            except Exception:
                return False
    """,
    "JL034": """
        def step(wd, fn):
            wd.arm(1)
            out = fn()
            wd.disarm()
            return out
    """,
}


def _write_fixtures(tmp_path):
    """Fixture files, repo-relative. JL032's lives under a
    dexiraft_tpu/resilience/ subtree so its path marker matches."""
    rels = []
    for rule, src in _FOOTGUNS.items():
        if rule == "JL032":
            d = tmp_path / "dexiraft_tpu" / "resilience"
            d.mkdir(parents=True, exist_ok=True)
            p = d / "fixture_jl032.py"
        else:
            p = tmp_path / f"fixture_{rule.lower()}.py"
        p.write_text(textwrap.dedent(src))
        rels.append(osp.relpath(str(p), REPO))
    return rels


def test_gate_trips_on_each_rule_fixture(tmp_path):
    """Acceptance pin: lint_gate exits nonzero on every JL03x footgun
    (all five fixtures in ONE gate run to stay inside the test budget),
    and --json reports the same verdict machine-readably."""
    rels = _write_fixtures(tmp_path)
    r = subprocess.run([sys.executable, GATE, "--json", *rels], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    blob = json.loads(r.stdout)
    assert blob["ok"] is False
    fired = {f["rule"] for f in blob["findings"]}
    assert set(_FOOTGUNS) <= fired, (set(_FOOTGUNS) - fired, blob)
    for rule in _FOOTGUNS:
        assert blob["per_rule"][rule]["findings"] >= 1
    # the per-family breakdown attributes every hit to distlint
    assert blob["per_family"]["distlint"]["findings"] >= 5
    assert blob["per_family"]["distlint"]["rules"] == 5
    assert set(blob["per_family"]) == {"jaxlint", "shardlint",
                                       "threadlint", "distlint"}


def test_gate_rules_filter_selects_families(tmp_path):
    """--rules JL03x runs ONLY distlint: a file carrying both a JL021
    (threadlint) and a JL031 (distlint) footgun fires just the
    latter."""
    both = tmp_path / "fixture_both.py"
    both.write_text(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def a(self):
                with self._lock:
                    self.n += 1

            def b(self):
                self.n += 1

        def protocol(coord, ok):
            seen = coord.any_flag(False)
            if not ok:
                return None
            return seen, coord.min_int(3)
    """))
    rel = osp.relpath(str(both), REPO)
    r = subprocess.run(
        [sys.executable, GATE, "--rules", "JL03x", "--json", rel],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    blob = json.loads(r.stdout)
    fired = {f["rule"] for f in blob["findings"]}
    assert "JL031" in fired and "JL021" not in fired, fired
    # an unknown token is a usage error, not a silent empty run
    r2 = subprocess.run(
        [sys.executable, GATE, "--rules", "JL099", rel],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r2.returncode != 0
    assert "matches no known rule" in (r2.stdout + r2.stderr)


def test_gate_rules_subset_tree_run_is_clean():
    """`--rules JL03x` over the real tree: zero findings, AND the
    baseline's jaxlint allow entries must read as out-of-scope, not
    stale (the subset filter owns staleness semantics)."""
    r = subprocess.run(
        [sys.executable, GATE, "--rules", "JL03x", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-1000:]
    blob = json.loads(r.stdout)
    assert blob["ok"] is True
    assert blob["findings"] == []
    assert blob["stale_allow"] == []
    assert blob["per_family"]["distlint"] == {
        "rules": 5, "findings": 0, "allowlisted": 0,
        "baseline_entries": 0}


def test_stale_distlint_baseline_entry_fails_gate(tmp_path):
    """Stale-entry detection covers distlint: an allow entry for a
    JL03x finding that no longer exists must fail the gate with the
    entry named (excuses die with the code they excused)."""
    base = json.load(open(osp.join(REPO, "dexiraft_tpu", "analysis",
                                   "baseline.json")))
    base["allow"].append({
        "rule": "JL031", "path": "dexiraft_tpu/resilience/coord.py",
        "snippet": "return None  # long-gone bail", "reason": "test"})
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(base))
    r = subprocess.run(
        [sys.executable, GATE, "--json", "--baseline", str(p)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout[-2000:]
    blob = json.loads(r.stdout)
    assert blob["ok"] is False
    assert any(e.get("rule") == "JL031" for e in blob["stale_allow"]), \
        blob["stale_allow"]
    assert blob["findings"] == []  # ONLY the stale entry failed it


# --------------------------------------------------------------------------
# CollectiveTrace: ring / digest / counter semantics on a fake clock
# --------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestCollectiveTrace:
    def test_ring_bounds_memory_counters_keep_totals(self):
        tr = ct.CollectiveTrace(host=1, capacity=4, clock=FakeClock())
        for i in range(7):
            tr.record("ns", "op", round_id=i)
        assert tr.recorded == 7
        kept = tr.tail(10)
        assert len(kept) == 4  # ring evicted the oldest three
        assert [e[1] for e in kept] == [3, 4, 5, 6]

    def test_auto_round_counters_are_per_namespace(self):
        tr = ct.CollectiveTrace(clock=FakeClock())
        a0 = tr.record("a", "x")
        b0 = tr.record("b", "y")
        a1 = tr.record("a", "x")
        assert (a0[1], b0[1], a1[1]) == (0, 0, 1)

    def test_args_digest_stable_and_discriminating(self):
        d1 = ct.args_digest("ns", 3, "any_flag")
        assert d1 == ct.args_digest("ns", 3, "any_flag")
        assert len(d1) == 8 and int(d1, 16) >= 0
        assert d1 != ct.args_digest("ns", 3, "min_int")
        assert d1 != ct.args_digest("ns", 4, "any_flag")

    def test_default_digest_derived_from_identity(self):
        tr = ct.CollectiveTrace(clock=FakeClock())
        ns, rid, op, dig = tr.record("ns", "op", round_id=5)
        assert dig == ct.args_digest("ns", "op", 5)

    def test_snapshot_schema_pin(self):
        tr = ct.CollectiveTrace(host=2, clock=FakeClock())
        for i in range(12):
            tr.record("ns", "op", round_id=i)
        tr.note_verified(3)
        snap = tr.snapshot()
        assert set(snap) == {"host", "entries", "verified_rounds",
                             "divergences", "last"}
        assert snap["host"] == 2
        assert snap["entries"] == 12
        assert snap["verified_rounds"] == 3
        assert snap["divergences"] == 0
        assert len(snap["last"]) == 8  # bounded result-JSON footprint
        assert all(len(e) == 4 for e in snap["last"])
        json.dumps(snap)  # result-JSON-safe by construction

    def test_encode_decode_round_trip(self):
        tr = ct.CollectiveTrace(clock=FakeClock())
        tr.record("dexiraft/coord", "any_flag", round_id=0)
        tr.record("dexiraft/barrier", "orbax_sync", round_id=1)
        rows = ct.decode_trace(tr.encode_tail())
        assert rows == [tuple(e[:4]) for e in tr.tail()]
        assert ct.decode_trace("") == []

    def test_render_and_dump_name_the_rounds(self, tmp_path):
        clock = FakeClock()
        clock.t = 1.5
        tr = ct.CollectiveTrace(host=1, clock=clock)
        tr.record("dexiraft/coord", "min_int", round_id=7)
        text = tr.render_tail()
        assert "dexiraft/coord/7: min_int" in text
        assert "host 1" in text and "t=1.500" in text
        path = tr.dump(str(tmp_path / "trace.log"))
        assert "min_int" in open(path).read()

    def test_module_recorder_install_and_lazy(self):
        saved = ct._RECORDER
        try:
            tr = ct.install(host=3, clock=FakeClock())
            assert ct.recorder() is tr
            ct.record("ns", "op")
            assert tr.recorded == 1 and tr.host == 3
            ct._RECORDER = None
            assert ct.recorder().host == 0  # lazy default: always on
        finally:
            ct._RECORDER = saved

    def test_trace_ring_lock_is_registered_leaf(self):
        assert "resilience.trace.ring" in locks.LOCK_ORDER
        # and the threadlint static mirror carries it too (the
        # LOCK_ORDER mirror pin keeps them equal; this pins presence)
        assert "resilience.trace.ring" in threadlint.LOCK_ORDER
        assert locks.LOCK_ORDER[-1] == "resilience.trace.ring"


# --------------------------------------------------------------------------
# the lockstep verifier (pure, scripted traces)
# --------------------------------------------------------------------------


def _trace(*ops, ns="c"):
    return [(ns, i, op, ct.args_digest(ns, i, op))
            for i, op in enumerate(ops)]


class TestVerifyLockstep:
    def test_identical_traces_are_clean(self):
        t = _trace("any_flag", "min_int", "any_flag")
        v = ct.verify_lockstep({0: t, 1: list(t), 2: list(t)})
        assert v["ok"] is True
        assert v["first_divergence"] is None
        assert v["hosts"] == 3 and v["compared"] == 6

    def test_seeded_divergence_names_first_divergent_op(self):
        ref = _trace("any_flag", "min_int", "any_flag", "min_int")
        skew = _trace("any_flag", "min_int", "min_int", "any_flag")
        v = ct.verify_lockstep({0: ref, 1: skew})
        assert v["ok"] is False
        d = v["first_divergence"]
        assert d["host"] == 1 and d["index"] == 2 and d["round"] == 2
        assert d["expected"].startswith("c/2:any_flag[")
        assert d["seen"].startswith("c/2:min_int[")

    def test_length_skew_is_not_a_divergence(self):
        ref = _trace("any_flag", "min_int", "any_flag")
        short = ref[:1]  # ring capacity / publish cadence skew
        v = ct.verify_lockstep({0: ref, 1: short})
        assert v["ok"] is True and v["compared"] == 1

    def test_earliest_divergence_wins_across_peers(self):
        ref = _trace("a_op", "b_op", "c_op")

        def mutate(rows, i, op):
            rows = list(rows)
            ns, rid, _, _ = rows[i]
            rows[i] = (ns, rid, op, ct.args_digest(ns, rid, op))
            return rows

        traces = {0: ref,
                  1: mutate(ref, 2, "late_op"),
                  2: mutate(ref, 1, "early_op")}
        d = ct.verify_lockstep(traces)["first_divergence"]
        assert (d["host"], d["index"]) == (2, 1)

    def test_trailing_fields_ignored_and_empty_ok(self):
        ref = [r + (1.25,) for r in _trace("any_flag")]  # timestamps
        assert ct.verify_lockstep({0: ref, 1: _trace("any_flag")})["ok"]
        assert ct.verify_lockstep({})["ok"] is True

    def test_divergence_exception_names_the_split(self):
        e = ct.CollectiveDivergence("dexiraft/coord", 3, 1,
                                    expected="any_flag[aa]",
                                    seen="min_int[bb]")
        msg = str(e)
        assert "round 3" in msg and "host 1" in msg
        assert "any_flag[aa]" in msg and "min_int[bb]" in msg
        assert isinstance(e, RuntimeError)
        assert (e.namespace, e.round_id, e.host) == ("dexiraft/coord",
                                                     3, 1)


# --------------------------------------------------------------------------
# schema pins shared with the chaos smoke
# --------------------------------------------------------------------------


def test_chaos_record_pins_collective_trace_block():
    sys.path.insert(0, osp.join(REPO, "scripts"))
    try:
        import chaos_smoke
    finally:
        sys.path.pop(0)
    assert "collective_trace" in chaos_smoke.RECORD_KEYS
    assert set(chaos_smoke.RECORD_KEYS) >= {
        "phases", "failures", "total_s", "locks", "lint_gate",
        "collective_trace"}


def test_coordinator_timeout_references_trace_dump():
    from dexiraft_tpu.resilience.coord import CoordinatorTimeout

    e = CoordinatorTimeout("ns", 4, 1, 6.0, trace_path="/tmp/t.log")
    assert "local collective trace: /tmp/t.log" in str(e)
    assert e.trace_path == "/tmp/t.log"
    # without a dump the message stays clean
    assert "collective trace" not in str(
        CoordinatorTimeout("ns", 4, 1, 6.0))
