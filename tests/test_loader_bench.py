"""Smoke for scripts/loader_bench.py — the host-throughput measurement
must keep working as the data pipeline evolves (it is the evidence that
the chip, not the host, is the training bottleneck)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "loader_bench.py")


def test_loader_bench_smoke():
    r = subprocess.run(
        [sys.executable, SCRIPT, "--pairs", "6", "--batches", "3",
         "--batch", "2", "--workers", "2", "--height", "96", "--width",
         "128", "--crop", "64", "96", "--modes", "thread"],
        capture_output=True, timeout=300)
    assert r.returncode == 0, r.stderr.decode()
    lines = [l for l in r.stdout.decode().splitlines() if l.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["metric"] == "loader_batches_per_sec"
    assert rec["value"] > 0
    assert rec["crop"] == [64, 96]
