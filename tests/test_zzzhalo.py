"""Halo compute-sharding tests: widths, exchange parity, step parity.

Named to sort LAST (tier-1 870 s budget convention, after test_zzzfsdp).
The cheap pins — the halo-width table, the support matrix's refusals,
the padder's seq alignment, the per-block gather schedule, and the
bit-level single-conv exchange parity — run in tier-1; the full
fence-vs-halo train/eval parity compiles two complete train steps and
is marked ``slow`` (the repo's declared category for multi-minute
full-model parity), shared through one module-scoped fixture.

What is pinned here and why:

  * ``halo_rows()`` — the per-module exchange widths, derived from the
    declarative conv chains next to the modules. A kernel-size change
    that forgets its exchange width fails THIS table, not a pod run.
  * ``halo_conv`` vs the unsharded conv, bit level — the non-circular
    ppermute zero-fill must be byte-identical to global symmetric zero
    padding, for stride-1 AND the stride-2 stem shape.
  * fence-vs-halo loss/param/eval parity — the halo step's whole claim
    is that the explicit shard_map program computes the SAME math as
    the replicated-compute fence step while rows shard over 'seq' and
    params stay fsdp-sharded through compute.
  * ``check_halo_support`` — every refusal in the v1 support matrix is
    a one-line actionable error, not a wrong answer downstream.
"""

from __future__ import annotations

import numpy as np
import pytest


# --------------------------------------------------------------------------
# halo arithmetic pins (pure — no compiles)
# --------------------------------------------------------------------------


class TestChainHalo:
    def test_single_conv_margins(self):
        from dexiraft_tpu.parallel.halo import chain_halo

        # (k, s, p): lo = p rows above, hi = max(0, k - s - p) below
        assert chain_halo(((3, 1, 1),)) == (1, 1)
        assert chain_halo(((7, 2, 3),)) == (3, 2)  # the encoder stem
        assert chain_halo(((1, 1, 0),)) == (0, 0)  # 1x1 never exchanges

    def test_chain_composition(self):
        from dexiraft_tpu.parallel.halo import chain_halo

        # two 3x3s stack linearly...
        assert chain_halo(((3, 1, 1), (3, 1, 1))) == (2, 2)
        # ...but a downstream margin m costs s*m rows through a
        # stride-s conv: stem (7,2,3) then 3x3 -> lo=3+2*1, hi=2+2*1
        assert chain_halo(((7, 2, 3), (3, 1, 1))) == (5, 4)


class TestHaloRowsTable:
    def test_pinned_widths(self):
        """THE table. Derived live from the conv chains declared next to
        the modules; these pins are what makes a silent kernel-size /
        stride / padding change a test failure instead of a wrong pod
        answer. Update BOTH the module's chain and this pin when a
        receptive field legitimately changes."""
        from dexiraft_tpu.parallel.halo import halo_rows

        assert halo_rows() == {
            "encoder_basic": 53,   # 7/2 stem + 3 residual stages
            "encoder_small": 25,   # bottleneck stages, fewer 3x3s
            "motion_encoder": 5,
            "gru_sep": 4,          # two passes of the 1x5/5x1 pair
            "gru_conv": 2,
            "flow_head": 2,
            "mask_head": 1,
            "upsample_convex": 1,  # 3x3 mask taps one coarse row over
            "upflow8": 1,          # bilinear hat support
        }

    def test_exchange_perms_are_non_circular(self):
        from dexiraft_tpu.parallel.layout import seq_halo_perms

        fwd, bwd = seq_halo_perms(4)
        # no (n-1, 0) / (0, n-1) wrap: the mesh-edge halos arrive
        # ZERO-filled, which is exactly the global conv's zero padding
        assert fwd == [(0, 1), (1, 2), (2, 3)]
        assert bwd == [(1, 0), (2, 1), (3, 2)]


# --------------------------------------------------------------------------
# bit-level exchange parity: one conv, sharded vs unsharded
# --------------------------------------------------------------------------


class TestHaloConvBitParity:
    def _run(self, kh: int, stride: int):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from dexiraft_tpu.parallel.halo import halo_conv, shard_map
        from dexiraft_tpu.parallel.layout import LAYOUT, make_mesh_2d

        mesh = make_mesh_2d(2, 4)  # rows split 4 ways
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
        x = jax.random.normal(k1, (2, 16, 8, 3), jnp.float32)
        kernel = jax.random.normal(k2, (kh, kh, 3, 4), jnp.float32)
        bias = jax.random.normal(k3, (4,), jnp.float32)
        p = kh // 2

        ref = jax.lax.conv_general_dilated(
            x, kernel, (stride, stride), ((p, p), (p, p)),
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + bias

        bsc = LAYOUT.batch_spatial_compute()
        fn = shard_map(
            lambda xl, kl, bl: halo_conv(xl, kl, bl, stride=stride,
                                         n_seq=4),
            mesh=mesh, in_specs=(bsc, P(), P()), out_specs=bsc)
        with mesh:
            got = fn(x, kernel, bias)
        return np.asarray(got), np.asarray(ref)

    def test_stride1_3x3(self):
        got, ref = self._run(kh=3, stride=1)
        # BIT parity: same convolution on the same rows — the exchange
        # moved bytes, it did not change the math
        assert np.array_equal(got, ref)

    def test_stride2_7x7_stem(self):
        # the encoder stem's (7, 2, 3): asymmetric lo=3 / hi=2 margins
        # and output rows that must land on the device owning them
        got, ref = self._run(kh=7, stride=2)
        assert np.array_equal(got, ref)


# --------------------------------------------------------------------------
# support matrix: every unsupported configuration refuses loudly
# --------------------------------------------------------------------------


def _ok_setup():
    from dexiraft_tpu.config import TrainConfig, raft_v1

    cfg = raft_v1(small=True)
    tc = TrainConfig(name="halo-test", stage="chairs", num_steps=20,
                     batch_size=4, image_size=(48, 64), iters=2)
    return cfg, tc


class TestSupportMatrix:
    @pytest.fixture()
    def mesh(self):
        from dexiraft_tpu.parallel.layout import make_mesh_fsdp

        return make_mesh_fsdp(2, 2, 2)

    def test_supported_config_passes(self, mesh):
        from dexiraft_tpu.parallel.halo import check_halo_support

        cfg, tc = _ok_setup()
        check_halo_support(cfg, tc, mesh)  # no raise

    def test_needs_seq_axis(self):
        from dexiraft_tpu.parallel.halo import check_halo_support
        from dexiraft_tpu.parallel.layout import make_mesh_fsdp

        cfg, tc = _ok_setup()
        with pytest.raises(ValueError, match="'seq' axis"):
            check_halo_support(cfg, tc, None)
        with pytest.raises(ValueError, match="'seq' axis"):
            check_halo_support(cfg, tc, make_mesh_fsdp(2, 2))

    def test_v1_variant_only(self, mesh):
        from dexiraft_tpu.config import raft_v5
        from dexiraft_tpu.parallel.halo import check_halo_support

        _, tc = _ok_setup()
        with pytest.raises(ValueError, match="variant='raft'"):
            check_halo_support(raft_v5(), tc, mesh)

    def test_fp32_allpairs_only(self, mesh):
        import dataclasses

        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.parallel.halo import check_halo_support

        cfg, tc = _ok_setup()
        with pytest.raises(ValueError, match="allpairs"):
            check_halo_support(raft_v1(small=True, corr_impl="local"),
                               tc, mesh)
        with pytest.raises(ValueError, match="fp32"):
            check_halo_support(raft_v1(small=True, mixed_precision=True),
                               tc, mesh)
        with pytest.raises(ValueError, match="fp32"):
            check_halo_support(
                cfg, dataclasses.replace(tc, precision="bf16"), mesh)

    def test_train_mode_restrictions(self, mesh):
        import dataclasses

        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.parallel.halo import check_halo_support

        cfg, tc = _ok_setup()
        with pytest.raises(ValueError, match="dropout"):
            check_halo_support(raft_v1(small=True, dropout=0.5), tc, mesh)
        with pytest.raises(ValueError, match="accum_steps=1"):
            check_halo_support(
                cfg, dataclasses.replace(tc, accum_steps=2), mesh)
        with pytest.raises(ValueError, match="freeze_bn"):
            # the FULL model trains BatchNorm; halo runs BN frozen only
            check_halo_support(raft_v1(), tc, mesh)
        check_halo_support(raft_v1(),
                           dataclasses.replace(tc, freeze_bn=True), mesh)

    def test_geometry_restrictions(self, mesh):
        import dataclasses

        from dexiraft_tpu.parallel.halo import check_halo_support

        cfg, tc = _ok_setup()
        with pytest.raises(ValueError, match="not divisible"):
            check_halo_support(
                cfg, dataclasses.replace(tc, batch_size=3), mesh)
        with pytest.raises(ValueError, match="divisible by 8"):
            check_halo_support(
                cfg, dataclasses.replace(tc, image_size=(40, 64)), mesh)
        with pytest.raises(ValueError, match=">= 3"):
            # 32 rows over 2 seq shards = 2 rows/device at 1/8 res
            check_halo_support(
                cfg, dataclasses.replace(tc, image_size=(32, 64)), mesh)


class TestPadderSeqAlignment:
    def test_height_aligns_to_stride_times_seq(self):
        from dexiraft_tpu.data.padder import InputPadder

        # 44 rows, seq=2: height must hit a multiple of 8*2=16 while
        # width keeps plain stride-8
        p = InputPadder((1, 44, 60, 3), seq=2)
        assert p.padded_shape == (48, 64)
        # already aligned: no height pad
        assert InputPadder((1, 48, 64, 3), seq=2).padded_shape == (48, 64)

    def test_seq_one_is_reference_behavior(self):
        from dexiraft_tpu.data.padder import InputPadder

        assert InputPadder((1, 44, 60, 3)).padded_shape == \
            InputPadder((1, 44, 60, 3), seq=1).padded_shape == (48, 64)

    def test_bad_seq_refused(self):
        from dexiraft_tpu.data.padder import InputPadder

        with pytest.raises(ValueError, match="seq"):
            InputPadder((1, 48, 64, 3), seq=0)

    def test_unaligned_bucket_refused(self):
        from dexiraft_tpu.data.padder import InputPadder

        # 40 is stride-8 aligned but not 16-aligned: a seq=2 bucket
        # cannot split it into whole-stride row slabs
        with pytest.raises(ValueError, match="stride\\*seq"):
            InputPadder((1, 40, 64, 3), target=(40, 64), seq=2)


# --------------------------------------------------------------------------
# fence vs halo: full-step parity (slow — two train-step compiles)
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def halo_run():
    """Three fence steps and three halo steps of the SAME schedule,
    computed once and shared.

    Mesh asymmetry is deliberate and load-bearing: the fence arm runs on
    a (data 2, fsdp 2) mesh WITHOUT a seq axis because GSPMD's spatial
    partitioning of convolutions miscompiles on this CPU backend (wrong
    loss — the same class of bug as the feature-dim conv miscompile that
    motivated the fence design, tests/test_zzzfsdp.py). The halo arm on
    (data 2, fsdp 2, seq 2) replaces exactly that GSPMD path with
    explicit collectives, so comparing it against the KNOWN-GOOD no-seq
    fence pins both parity and the motivation in one test.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.parallel.layout import (
        gather_state,
        make_mesh_fsdp,
        shard_state,
    )
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg, tc = _ok_setup()
    tc = dataclasses.replace(tc, batch_size=8)
    h, w = tc.image_size

    def batches(n):
        out = []
        for i in range(n):
            k1, k2, k3 = jax.random.split(jax.random.PRNGKey(100 + i), 3)
            out.append(dict(
                image1=jax.random.uniform(k1, (8, h, w, 3), jnp.float32,
                                          0, 255),
                image2=jax.random.uniform(k2, (8, h, w, 3), jnp.float32,
                                          0, 255),
                flow=jax.random.normal(k3, (8, h, w, 2)) * 2.0,
                valid=jnp.ones((8, h, w), jnp.float32)))
        return out

    mesh_f = make_mesh_fsdp(2, 2)      # fence: fsdp storage, no seq
    mesh_h = make_mesh_fsdp(2, 2, 2)   # halo: + seq compute sharding
    fence = make_train_step(cfg, tc, mesh=mesh_f)
    halo = make_train_step(cfg, tc, mesh=mesh_h, compute_sharding="halo")
    s_f = shard_state(create_state(jax.random.PRNGKey(0), cfg, tc), mesh_f)
    s_h = shard_state(create_state(jax.random.PRNGKey(0), cfg, tc), mesh_h)

    fence_metrics, halo_metrics = [], []
    for b in batches(3):
        s_f, m_f = fence(s_f, b)
        s_h, m_h = halo(s_h, b)
        fence_metrics.append(
            {k: float(jax.device_get(v)) for k, v in m_f.items()})
        halo_metrics.append(
            {k: float(jax.device_get(v)) for k, v in m_h.items()})

    # host-side gathered copies: the two states live on DIFFERENT meshes
    # (4 vs 8 devices), so any comparison must cross through numpy
    params_f = jax.tree.map(np.asarray, gather_state(s_f.params, mesh_f))
    params_h = jax.tree.map(np.asarray, gather_state(s_h.params, mesh_h))
    return dict(cfg=cfg, tc=tc, batches=batches, mesh_h=mesh_h,
                state_h=s_h, fence_metrics=fence_metrics,
                halo_metrics=halo_metrics, params_f=params_f,
                params_h=params_h)


@pytest.mark.slow
class TestFenceHaloParity:
    # fp32 accumulation-order tolerance, same as the fsdp parity pins
    # (tests/test_zzzfsdp.py): the two programs sum losses and grads in
    # different orders (psum trees vs replicated reductions), so bit
    # equality is not expected — agreement to atol=1e-4 / rtol=1e-3
    # over three optimizer steps is.
    ATOL, RTOL = 1e-4, 1e-3

    def test_loss_parity_over_steps(self, halo_run):
        for mf, mh in zip(halo_run["fence_metrics"],
                          halo_run["halo_metrics"]):
            assert mh["loss"] == pytest.approx(
                mf["loss"], rel=self.RTOL, abs=self.ATOL)
            assert mh["epe"] == pytest.approx(
                mf["epe"], rel=self.RTOL, abs=self.ATOL)

    def test_state_stays_finite(self, halo_run):
        assert all(m["state_finite"] for m in halo_run["halo_metrics"])

    def test_params_track_after_three_steps(self, halo_run):
        import jax

        worst = max(
            float(np.max(np.abs(a - b))) for a, b in zip(
                jax.tree.leaves(halo_run["params_f"]),
                jax.tree.leaves(halo_run["params_h"])))
        assert worst < 5e-4, (
            f"fence/halo params diverged: max|Δ|={worst:.3e}")

    def test_halo_state_stored_sharded(self, halo_run):
        """Params must STAY fsdp-sharded through the halo step — a
        silently replicated train state would defeat the per-block
        gather design."""
        import jax

        from dexiraft_tpu.parallel.layout import LAYOUT

        mesh_h = halo_run["mesh_h"]
        n_fsdp = LAYOUT.fsdp_size(mesh_h)
        sharded = 0
        for leaf in jax.tree.leaves(halo_run["state_h"].params):
            shard = leaf.sharding.shard_shape(leaf.shape)
            if int(np.prod(shard)) * n_fsdp == int(np.prod(leaf.shape)):
                sharded += 1
        assert sharded > 0, "no param leaf is fsdp-sharded after the step"


@pytest.mark.slow
class TestHaloEval:
    def test_eval_matches_unsharded_apply(self, halo_run):
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.models.raft import RAFT
        from dexiraft_tpu.train.step import make_eval_step

        cfg, tc = halo_run["cfg"], halo_run["tc"]
        h, w = tc.image_size
        ev = make_eval_step(cfg, iters=4, mesh=halo_run["mesh_h"],
                            compute_sharding="halo")
        # contract: variables arrive in STORAGE layout (the train
        # state's own shardings), not gathered copies
        variables = {"params": halo_run["state_h"].params}
        b = halo_run["batches"](1)[0]
        flow_init = jnp.zeros((8, h // 8, w // 8, 2), jnp.float32)
        fl, fu = ev(variables, b["image1"], b["image2"], flow_init)

        model = RAFT(cfg)
        rl, ru = jax.jit(
            lambda v, a, bb: model.apply(v, a, bb, iters=4, train=False,
                                         test_mode=True))(
            {"params": jax.tree.map(jnp.asarray, halo_run["params_h"])},
            b["image1"], b["image2"])
        d_low = float(np.max(np.abs(np.asarray(fl) - np.asarray(rl))))
        d_up = float(np.max(np.abs(np.asarray(fu) - np.asarray(ru))))
        assert d_low < 1e-3 and d_up < 1e-3, (
            f"halo eval diverges: low={d_low:.3e} up={d_up:.3e}")


# --------------------------------------------------------------------------
# per-block gather schedule
# --------------------------------------------------------------------------


class TestParamBlockSchedule:
    def test_blocks_are_top_level_modules(self):
        """The gather→use→drop schedule partitions the tree by top-level
        module key; every param leaf must belong to exactly one block
        (a new top-level module automatically becomes its own block —
        the schedule can't silently skip one)."""
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.models.raft import RAFT
        from dexiraft_tpu.parallel.layout import param_block_names

        cfg, _ = _ok_setup()
        model = RAFT(cfg)
        abstract = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 48, 64, 3), jnp.float32),
                               jnp.zeros((1, 48, 64, 3), jnp.float32),
                               iters=1, train=False))
        params = abstract["params"]
        blocks = param_block_names(params)
        assert set(blocks) == {"fnet", "cnet", "ScanRAFTStep_0"}
        assert blocks == tuple(params), "schedule must follow tree order"
