"""DexiNed standalone workload: losses, datasets, train/test CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.dexined.losses import (
    bdcn_loss2,
    bdcn_loss_ori,
    cats_loss,
    hed_loss2,
    rcf_loss,
    weighted_multiscale_loss,
)


def _logits_targets(key, shape=(2, 16, 16, 1), p_edge=0.1):
    k1, k2 = jax.random.split(key)
    logits = jax.random.normal(k1, shape)
    targets = (jax.random.uniform(k2, shape) < p_edge).astype(jnp.float32)
    return logits, targets


class TestLosses:
    def test_bdcn_positive_scalar_and_grad(self):
        logits, targets = _logits_targets(jax.random.PRNGKey(0))
        loss = bdcn_loss2(logits, targets)
        assert loss.shape == () and float(loss) > 0
        g = jax.grad(lambda l: bdcn_loss2(l, targets))(logits)
        assert np.isfinite(np.asarray(g)).all()

    def test_bdcn_class_balance(self):
        """With rare positives, a missed positive must cost more than an
        equally-confident false positive (num_neg >> num_pos weighting)."""
        targets = jnp.zeros((1, 8, 8, 1)).at[0, 4, 4, 0].set(1.0)
        base = jnp.zeros((1, 8, 8, 1))
        miss = base.at[0, 4, 4, 0].set(-4.0)  # confident wrong on the edge
        fp = base.at[0, 2, 2, 0].set(4.0)     # confident wrong on background
        assert float(bdcn_loss2(miss, targets)) > float(bdcn_loss2(fp, targets))

    def test_bdcn_ori_per_sample_balance(self):
        """bdcn_lossORI (losses.py:37-58) balances per sample: a batch of
        one dense-edge and one sparse-edge image must weigh them
        differently, so the loss differs from pooled-batch balancing on
        the same data; fractional targets get zero weight."""
        key = jax.random.PRNGKey(3)
        k1, k2, k3 = jax.random.split(key, 3)
        logits = jax.random.normal(k1, (2, 16, 16, 1))
        dense = (jax.random.uniform(k2, (1, 16, 16, 1)) < 0.5)
        sparse = (jax.random.uniform(k3, (1, 16, 16, 1)) < 0.05)
        targets = jnp.concatenate([dense, sparse]).astype(jnp.float32)
        loss = bdcn_loss_ori(logits, targets)
        assert float(loss) > 0.0 and np.isfinite(float(loss))
        g = jax.grad(lambda l: bdcn_loss_ori(l, targets))(logits)
        assert np.isfinite(np.asarray(g)).all()
        # fractional annotations carry zero weight (torch fills only the
        # ==1 and ==0 masks of a zeros array)
        frac = jnp.full((2, 16, 16, 1), 0.5)
        assert float(bdcn_loss_ori(logits, frac)) == 0.0

    def test_hed_and_rcf_finite(self):
        logits, targets = _logits_targets(jax.random.PRNGKey(1))
        assert np.isfinite(float(hed_loss2(logits, targets)))
        assert np.isfinite(float(rcf_loss(logits, targets)))

    def test_rcf_ignores_dontcare(self):
        logits = jnp.zeros((1, 4, 4, 1))
        t_all2 = jnp.full((1, 4, 4, 1), 2.0)  # all don't-care
        assert float(rcf_loss(logits, t_all2)) == 0.0

    def test_cats_loss_components(self):
        logits, targets = _logits_targets(jax.random.PRNGKey(2))
        plain = cats_loss(logits, targets, (0.0, 0.0))
        full = cats_loss(logits, targets, (0.01, 4.0))
        assert np.isfinite(float(plain)) and np.isfinite(float(full))
        g = jax.grad(lambda l: cats_loss(l, targets, (0.01, 4.0)))(logits)
        assert np.isfinite(np.asarray(g)).all()

    def test_weighted_multiscale(self):
        logits, targets = _logits_targets(jax.random.PRNGKey(3))
        preds = [logits] * 7
        loss = weighted_multiscale_loss(preds, targets)
        single = bdcn_loss2(logits, targets, 1.0)
        np.testing.assert_allclose(float(loss),
                                   float(single) * (0.7 + 0.7 + 1.1 + 1.1
                                                    + 0.3 + 0.3 + 1.3),
                                   rtol=1e-5)


@pytest.fixture()
def biped_tree(tmp_path):
    import cv2

    rng = np.random.default_rng(0)
    img_dir = tmp_path / "imgs" / "train" / "rgbr" / "aug" / "seq0"
    gt_dir = tmp_path / "edge_maps" / "train" / "rgbr" / "aug" / "seq0"
    img_dir.mkdir(parents=True)
    gt_dir.mkdir(parents=True)
    for i in range(3):
        cv2.imwrite(str(img_dir / f"{i}.jpg"),
                    rng.integers(0, 256, (300, 300, 3), dtype=np.uint8))
        cv2.imwrite(str(gt_dir / f"{i}.png"),
                    rng.integers(0, 256, (300, 300), dtype=np.uint8))
    return tmp_path


class TestEdgeDatasets:
    def test_biped_sample(self, biped_tree):
        from dexiraft_tpu.dexined.data import BipedDataset

        ds = BipedDataset(str(biped_tree), img_size=64)
        assert len(ds) == 3
        s = ds.sample(0, np.random.default_rng(0))
        assert s["images"].shape == (64, 64, 3)
        assert s["labels"].shape == (64, 64, 1)
        assert 0.0 <= s["labels"].min() and s["labels"].max() <= 1.0
        # mean-subtracted: must have negative values
        assert s["images"].min() < 0

    def test_test_dataset_div16(self, biped_tree):
        import cv2

        from dexiraft_tpu.dexined.data import TestDataset

        d = biped_tree / "classic"
        d.mkdir()
        cv2.imwrite(str(d / "a.jpg"),
                    np.random.default_rng(1).integers(
                        0, 256, (100, 210, 3), dtype=np.uint8))
        ds = TestDataset(str(d))
        s = ds.sample(0)
        h, w = s["images"].shape[:2]
        assert h % 16 == 0 and w % 16 == 0
        assert s["image_shape"] == (100, 210)


def test_dexined_guard_rolls_back_then_aborts(biped_tree, tmp_path,
                                              monkeypatch):
    """Epoch-end divergence guard, single run: epoch 0 trains clean and
    checkpoints; a save hook then poisons the data (nan images -> nan
    loss), so epoch 1 rolls back to epoch 0's checkpoint and epoch 2
    exhausts the retry budget. The poisoned epochs never reach disk."""
    import dexiraft_tpu.dexined_cli as cli
    from dexiraft_tpu.dexined.data import BipedDataset
    from dexiraft_tpu.train import checkpoint as ckpt_io

    monkeypatch.chdir(tmp_path)
    ckpt = str(tmp_path / "ck")
    base = ["--train", "--data_root", str(biped_tree), "--batch_size", "2",
            "--img_size", "64", "--lr", "1e-4", "--steps_per_epoch", "2",
            "--checkpoint", ckpt]

    poisoned = {"on": False}
    orig_save = ckpt_io.save_checkpoint

    def save_then_poison(*a, **k):
        orig_save(*a, **k)
        poisoned["on"] = True

    monkeypatch.setattr(ckpt_io, "save_checkpoint", save_then_poison)
    orig_sample = BipedDataset.sample

    def sample(self, i, rng=None):
        s = orig_sample(self, i, rng)
        if poisoned["on"]:
            s = dict(s, images=np.full_like(s["images"], np.nan))
        return s

    monkeypatch.setattr(BipedDataset, "sample", sample)

    with pytest.raises(RuntimeError, match="diverged.*after 1 rollbacks"):
        cli.main(base + ["--epochs", "4", "--max_rollbacks", "1"])
    assert ckpt_io.latest_step(ckpt) == 2  # epoch 0 (2 steps); no poison


def test_dexined_guard_refuses_stale_checkpoints(biped_tree, tmp_path,
                                                 monkeypatch):
    """A fresh run that diverges before ITS OWN first checkpoint must
    abort — not silently splice in a previous experiment's weights that
    happen to live in the (default-constant) checkpoint dir."""
    import dexiraft_tpu.dexined_cli as cli
    from dexiraft_tpu.dexined.data import BipedDataset
    from dexiraft_tpu.train import checkpoint as ckpt_io

    monkeypatch.chdir(tmp_path)
    ckpt = str(tmp_path / "ck2")
    base = ["--train", "--data_root", str(biped_tree), "--batch_size", "2",
            "--img_size", "64", "--lr", "1e-4", "--steps_per_epoch", "2",
            "--checkpoint", ckpt]
    cli.main(base + ["--epochs", "1"])  # the "previous experiment"
    assert ckpt_io.latest_step(ckpt) is not None

    orig_sample = BipedDataset.sample
    monkeypatch.setattr(
        BipedDataset, "sample",
        lambda self, i, rng=None: dict(
            orig_sample(self, i, rng),
            images=np.full_like(orig_sample(self, i, rng)["images"],
                                np.nan)))
    with pytest.raises(RuntimeError,
                       match="before this run saved any checkpoint"):
        cli.main(base + ["--epochs", "2"])


def test_cli_train_then_test(biped_tree, tmp_path, monkeypatch):
    import cv2

    from dexiraft_tpu.dexined_cli import main

    monkeypatch.chdir(tmp_path)
    ckpt = str(tmp_path / "ck")
    main(["--train", "--data_root", str(biped_tree), "--epochs", "1",
          "--batch_size", "2", "--img_size", "64", "--lr", "1e-4",
          "--steps_per_epoch", "2", "--checkpoint", ckpt])

    classic = biped_tree / "classic"
    classic.mkdir(exist_ok=True)
    cv2.imwrite(str(classic / "t.jpg"),
                np.random.default_rng(2).integers(
                    0, 256, (64, 64, 3), dtype=np.uint8))
    # GT tree for the ODS/OIS/AP path (random GT -> just exercise wiring)
    gt_dir = tmp_path / "gt"
    gt_dir.mkdir()
    cv2.imwrite(str(gt_dir / "t.png"),
                (np.random.default_rng(3).random((64, 64)) < 0.05
                 ).astype(np.uint8) * 255)
    out = str(tmp_path / "res")
    main(["--test", "--data_root", str(classic), "--dataset", "CLASSIC",
          "--checkpoint", ckpt, "--output_dir", out,
          "--gt_root", str(gt_dir)])
    import os
    assert os.path.exists(os.path.join(out, "CLASSIC", "t.png"))


def test_cli_test_pich_channel_swap(biped_tree, tmp_path, monkeypatch):
    """testPich parity (main.py:149-187): channel-swap ensemble writes
    fusedCH/avgCH alongside the plain fused/avg protocol dirs."""
    import os

    import cv2

    from dexiraft_tpu.dexined_cli import main

    monkeypatch.chdir(tmp_path)
    ckpt = str(tmp_path / "ck")
    main(["--train", "--data_root", str(biped_tree), "--epochs", "1",
          "--batch_size", "2", "--img_size", "64", "--lr", "1e-4",
          "--steps_per_epoch", "1", "--checkpoint", ckpt])
    classic = biped_tree / "classic2"
    classic.mkdir(exist_ok=True)
    cv2.imwrite(str(classic / "p.jpg"),
                np.random.default_rng(4).integers(
                    0, 256, (64, 64, 3), dtype=np.uint8))
    out = str(tmp_path / "res2")
    main(["--test", "--test_pich", "--data_root", str(classic),
          "--dataset", "CLASSIC", "--checkpoint", ckpt,
          "--output_dir", out])
    for sub in ("fusedCH", "avgCH"):
        path = os.path.join(out, "CLASSIC", sub, "p.png")
        assert os.path.exists(path), path
        img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
        assert img.shape == (64, 64)


class TestSaturationStability:
    def test_bce_losses_finite_and_differentiable_at_saturation(self):
        # regression: the clipped-probability BCE NaN'd in fp32 once a
        # POSITIVE pixel's logit saturated (upper clip bound 1 - 1e-10
        # rounds to 1.0 in fp32, so (1-t)*log(1-p) = 0 * -inf = NaN) —
        # observed live at step ~316 of the CPU DexiNed demo. The
        # logits-space form must stay finite in value AND gradient for
        # arbitrarily large logits of either sign.
        import jax

        from dexiraft_tpu.dexined.losses import (
            bdcn_loss2,
            bdcn_loss_ori,
            cats_loss,
            hed_loss2,
            rcf_loss,
        )

        logits = jnp.array([[[[200.0], [-200.0]], [[75.0], [0.3]]]])
        targets = jnp.array([[[[1.0], [0.0]], [[0.0], [1.0]]]])
        for fn in (bdcn_loss2, hed_loss2, bdcn_loss_ori, rcf_loss,
                   lambda x, t: cats_loss(x, t, (0.1, 0.1))):
            val, grad = jax.value_and_grad(lambda x: fn(x, targets))(logits)
            assert np.isfinite(float(val)), fn
            assert np.isfinite(np.asarray(grad)).all(), fn

    def test_logits_bce_matches_clipped_form_unsaturated(self):
        # in the unsaturated regime the stable form equals the clipped
        # -t log p - (1-t) log(1-p) it replaced
        from dexiraft_tpu.dexined.losses import bdcn_loss2

        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.normal(0, 3, (1, 8, 8, 1)).astype(np.float32))
        targets = jnp.asarray((rng.random((1, 8, 8, 1)) > 0.8)
                              .astype(np.float32))
        got = float(bdcn_loss2(logits, targets))
        p = np.clip(1.0 / (1.0 + np.exp(-np.asarray(logits, np.float64))),
                    1e-10, 1 - 1e-10)
        t = np.asarray(targets, np.float64)
        num_pos = t.sum()
        num_neg = t.size - num_pos
        w = np.where(t > 0, num_neg / t.size, 1.1 * num_pos / t.size)
        want = 1.1 * np.sum(w * -(t * np.log(p) + (1 - t) * np.log(1 - p)))
        np.testing.assert_allclose(got, want, rtol=1e-5)
