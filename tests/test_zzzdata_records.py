"""Packed-record data plane (dexiraft_tpu/data/records, docs/data_plane.md).

Pins the contracts the multi-host story stands on: pack->read
bit-exactness against FlowDataset.sample, CRC-corruption skip+count
through PR 4's retry discipline, seek-resume parity with the fresh-run
sequence, the two-host disjoint-cover property, the epoch permutation
as a pure function of (seed, epoch) ACROSS process restarts, the
packer's --verify audit, and the stream sidecar's loader_kind refusal.

Named zzz* to sort last (tier-1 budget discipline); everything runs on
a 6-pair synthetic chairs tree at 96x128 — seconds, not minutes.
"""

import json
import os.path as osp
import subprocess
import sys

import numpy as np
import pytest

from dexiraft_tpu.data.datasets import FlyingChairs
from dexiraft_tpu.data.flow_io import write_flo
from dexiraft_tpu.data.loader import Loader, epoch_permutation
from dexiraft_tpu.data.records import (
    RecordCorruptError,
    RecordLoader,
    RecordShardReader,
    load_manifest,
    open_records,
    pack_dataset,
    verify_records,
)
from dexiraft_tpu.resilience.stream import (
    LoaderKindMismatch,
    StreamPosition,
    load_position,
    save_position,
)

AUG = dict(crop_size=(64, 96), min_scale=-0.1, max_scale=1.0, do_flip=True)


def _make_chairs_tree(root, n=6, h=96, w=128):
    import imageio.v2 as imageio

    data = root / "data"
    data.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(n):
        for k in (1, 2):
            imageio.imwrite(data / f"{i:05d}_img{k}.ppm",
                            rng.integers(0, 256, (h, w, 3), dtype=np.uint8))
        write_flo(data / f"{i:05d}_flow.flo",
                  rng.normal(size=(h, w, 2)).astype(np.float32))
    (root / "chairs_split.txt").write_text("\n".join(["1"] * n))
    return data


@pytest.fixture(scope="module")
def packed(tmp_path_factory):
    """One shared (raw dataset, records_dir) pair for the module."""
    root = tmp_path_factory.mktemp("records_plane")
    data = _make_chairs_tree(root)
    ds = FlyingChairs(AUG, split="training", root=str(data))
    records_dir = root / "records"
    manifest = pack_dataset(2 * ds, str(records_dir), num_shards=3,
                            stage="chairs", image_size=AUG["crop_size"])
    return ds, str(records_dir), manifest


class TestPackRoundTrip:
    def test_bit_exact_vs_flow_dataset_sample(self, packed):
        """The tentpole contract: for any (index, rng) the record path
        returns byte-identical samples to the raw stage — repeats,
        augmentation, and derived valid masks included."""
        ds, records_dir, _ = packed
        raw_mix = 2 * ds
        rds = open_records(records_dir)
        assert len(rds) == len(raw_mix) == 12
        for i in range(len(rds)):
            a = raw_mix.sample(i, np.random.default_rng((7, 0, i)))
            b = rds.sample(i, np.random.default_rng((7, 0, i)))
            assert set(a) == set(b)
            for k in a:
                assert a[k].dtype == b[k].dtype
                np.testing.assert_array_equal(a[k], b[k])

    def test_unaugmented_raw_arrays_round_trip(self, packed):
        ds, records_dir, _ = packed
        rds = open_records(records_dir, augment=False)
        raw = ds._load_raw(3)
        rec = rds._load_raw(3)
        assert rec["image1"].dtype == np.uint8
        for k in raw:
            np.testing.assert_array_equal(raw[k], rec[k])

    def test_manifest_schema(self, packed):
        _, records_dir, manifest = packed
        m = load_manifest(records_dir)
        assert m.num_records == 6 and m.num_samples == 12
        assert m.stage == "chairs" and m.image_size == (64, 96)
        assert [s.records for s in m.shards] == [2, 2, 2]
        assert len(m.members) == 1
        mem = m.members[0]
        assert mem.records == (0, 6) and mem.repeat == 2 and not mem.sparse
        assert mem.aug == {"crop_size": [64, 96], "min_scale": -0.1,
                           "max_scale": 1.0, "do_flip": True}
        assert m.keys["image1"]["dtype"] == "uint8"
        assert m.keys["flow"]["dtype"] == "float32"
        assert m.fingerprint == manifest.fingerprint

    def test_reader_seek_and_random_access(self, packed):
        _, records_dir, manifest = packed
        path = osp.join(records_dir, manifest.shards[0].file)
        with RecordShardReader(path) as r:
            sequential = list(iter(r))
            assert len(sequential) == 2
            # random access matches sequential, any order
            for i in (1, 0, 1):
                np.testing.assert_array_equal(r.read(i)["flow"],
                                              sequential[i]["flow"])
            r.seek(1)  # O(1) reposition of the sequential cursor
            np.testing.assert_array_equal(next(iter(r))["image1"],
                                          sequential[1]["image1"])


class TestShardNaming:
    def test_of_count_matches_files_written(self, packed, tmp_path):
        """6 records at --shards 4 packs 3 shards of 2 — every file
        must say -of-00003, not lie about a fourth that never existed."""
        ds, _, _ = packed
        m = pack_dataset(ds, str(tmp_path / "uneven"), num_shards=4)
        assert [s.records for s in m.shards] == [2, 2, 2]
        assert all(s.file.endswith("-of-00003.rec") for s in m.shards)
        assert verify_records(str(tmp_path / "uneven")) == []


class TestVerify:
    def test_fresh_pack_verifies_clean(self, packed):
        _, records_dir, _ = packed
        assert verify_records(records_dir) == []

    def test_corruption_caught(self, packed, tmp_path):
        import shutil

        _, records_dir, manifest = packed
        bad_dir = tmp_path / "bad"
        shutil.copytree(records_dir, bad_dir)
        shard = bad_dir / manifest.shards[1].file
        blob = bytearray(shard.read_bytes())
        blob[200] ^= 0xFF  # flip one payload byte
        shard.write_bytes(bytes(blob))
        problems = verify_records(str(bad_dir))
        assert problems and any("CRC" in p or "record" in p
                                for p in problems)


class TestCorruptionDiscipline:
    def test_crc_failure_skips_and_counts(self, packed, tmp_path):
        """A flipped bit on disk degrades one sample (retry -> skip ->
        backfill) and shows up in records/* stats — never kills the run."""
        import shutil

        _, records_dir, manifest = packed
        bad_dir = tmp_path / "bad_loader"
        shutil.copytree(records_dir, bad_dir)
        shard = bad_dir / manifest.shards[0].file
        blob = bytearray(shard.read_bytes())
        blob[100] ^= 0xFF
        shard.write_bytes(bytes(blob))

        loader = RecordLoader(str(bad_dir), 12, seed=3, num_workers=2,
                              max_retries=1, retry_backoff_s=0.0)
        it = loader.batches()
        batch = next(it)  # every sample requested; corrupt one backfilled
        it.close()
        assert batch["image1"].shape[0] == 12
        assert loader.stats.record_crc_failures >= 1
        assert loader.stats.skipped_samples >= 1
        assert loader.stats.retries >= 1
        d = loader.stats.as_dict()
        assert d["records/crc_failures"] == loader.stats.record_crc_failures
        assert d["records/reads"] > 0
        assert "CRC" in loader.stats.summary()

    def test_reader_raises_record_corrupt(self, packed, tmp_path):
        import shutil

        _, records_dir, manifest = packed
        bad_dir = tmp_path / "bad_reader"
        shutil.copytree(records_dir, bad_dir)
        shard = bad_dir / manifest.shards[0].file
        blob = bytearray(shard.read_bytes())
        blob[100] ^= 0xFF
        shard.write_bytes(bytes(blob))
        r = RecordShardReader(str(shard))
        with pytest.raises(RecordCorruptError):
            for i in range(len(r)):
                r.read(i)


class TestResumeParity:
    def test_seek_resume_matches_fresh_sequence(self, packed):
        """batches(start_epoch=, start_offset=) over records reproduces
        the exact tail of an uninterrupted run — the sidecar's resume."""
        _, records_dir, _ = packed
        mk = lambda: RecordLoader(records_dir, 4, seed=11, num_workers=2)
        fresh = mk()
        it = fresh.batches()
        full = [next(it) for _ in range(7)]  # epoch = 3 batches: crosses
        positions = list(fresh.positions)
        it.close()

        resumed = mk()
        epoch, offset = positions[4]
        it = resumed.batches(start_epoch=epoch, start_offset=offset)
        for want in full[4:]:
            got = next(it)
            for k in want:
                np.testing.assert_array_equal(got[k], want[k])
        it.close()


class TestGlobalShuffleContract:
    def test_pure_function_of_seed_epoch(self):
        p1 = epoch_permutation(123, 4, 17)
        p2 = epoch_permutation(123, 4, 17)
        np.testing.assert_array_equal(p1, p2)
        assert not np.array_equal(p1, epoch_permutation(123, 5, 17))
        assert not np.array_equal(p1, epoch_permutation(124, 4, 17))
        assert sorted(p1.tolist()) == list(range(17))

    def test_stable_across_process_restart(self):
        """The multi-host + exact-resume keystone: a RESTARTED process
        (fresh interpreter, no shared state) derives the identical
        permutation from (seed, epoch)."""
        code = ("from dexiraft_tpu.data.loader import epoch_permutation;"
                "print(epoch_permutation(123, 4, 17).tolist())")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=120,
                             cwd=osp.dirname(osp.dirname(
                                 osp.abspath(__file__))))
        assert out.returncode == 0, out.stderr
        child = json.loads(out.stdout.strip())
        assert child == epoch_permutation(123, 4, 17).tolist()

    def test_two_host_slices_disjoint_and_exhaustive(self, packed):
        """Each epoch's global batches partition into per-host slices
        that are disjoint and together cover the usable prefix — the
        property multi-host feeding AND exact resume both lean on."""
        _, records_dir, _ = packed
        rds = open_records(records_dir, augment=False)
        n, global_batch = len(rds), 4
        order = epoch_permutation(11, 0, n)
        usable = len(order) // global_batch * global_batch

        hosts = [RecordLoader(records_dir, global_batch, seed=11,
                              process_index=h, process_count=2,
                              num_workers=1) for h in (0, 1)]
        # replicate submit_loop's slicing arithmetic per host
        seen = []
        for h, loader in enumerate(hosts):
            assert loader.local_batch == 2
            for b0 in range(0, usable, global_batch):
                lo = b0 + h * loader.local_batch
                seen.append(order[lo:lo + loader.local_batch])
        flat = np.concatenate(seen)
        assert len(flat) == usable == len(np.unique(flat))
        assert set(flat.tolist()) == set(order[:usable].tolist())

        # and through the real loaders: the two hosts' first global
        # batch halves are disjoint sample sets drawn from that order
        batches = []
        for loader in hosts:
            it = loader.batches()
            batches.append(next(it))
            it.close()
        assert not np.array_equal(batches[0]["image1"],
                                  batches[1]["image1"])


class TestElasticResliceContract:
    """The contract elastic membership (resilience.membership) leans
    on: a stream position (epoch, offset) addresses GLOBAL batches, so
    it is host-count-invariant — a world that shrinks or grows re-
    slices the same permutation windows at the new size instead of
    deriving a new sample order. Pure numpy pins, no loader spin-up."""

    SEED, GB, N = 7, 8, 32

    def _window(self, epoch: int, offset: int):
        order = epoch_permutation(self.SEED, epoch, self.N)
        return order[offset * self.GB:(offset + 1) * self.GB]

    def _slices(self, window, k: int):
        local = len(window) // k
        return [window[i * local:(i + 1) * local] for i in range(k)]

    def test_disjoint_exhaustive_at_every_host_count(self):
        for epoch in (0, 1):
            for off in range(self.N // self.GB):
                window = self._window(epoch, off)
                for k in (1, 2, 4, 8):
                    parts = self._slices(window, k)
                    flat = np.concatenate(parts)
                    # disjoint, exhaustive, and rank-ordered: the
                    # concatenation of per-rank slices IS the window
                    assert len(flat) == self.GB == len(np.unique(flat))
                    assert flat.tolist() == window.tolist()

    def test_world_change_replays_from_boundary_skips_nothing(self):
        """Shrink semantics: the old 2-host world consumed offsets 0-1
        of epoch 0 and the agreed checkpoint restores (epoch 0,
        offset 2). The new world — at ANY size — replays exactly the
        windows at offsets >= 2: no sample of the un-consumed tail is
        skipped, no already-consumed sample reappears in this epoch."""
        consumed = set(np.concatenate(
            [self._window(0, off) for off in (0, 1)]).tolist())
        tail = [self._window(0, off) for off in (2, 3)]
        for k in (1, 2, 4):
            replayed = [np.concatenate(self._slices(w, k)) for w in tail]
            # same global windows, independent of the new host count
            assert [r.tolist() for r in replayed] == \
                [w.tolist() for w in tail]
        tail_flat = set(np.concatenate(tail).tolist())
        assert not tail_flat & consumed
        assert tail_flat | consumed == set(range(self.N))

    def test_world_compatible_guard(self):
        from dexiraft_tpu.data.loader import world_compatible

        assert world_compatible(8, 1) is None
        assert world_compatible(8, 2) is None
        assert world_compatible(8, 8) is None
        assert "divide" in world_compatible(8, 3)
        assert "positive" in world_compatible(8, 0)


class TestLoaderKindSidecar:
    def test_mismatch_refused_with_actionable_error(self, tmp_path):
        save_position(str(tmp_path), 10, StreamPosition(2, 5), seed=1,
                      loader_kind="raw")
        with pytest.raises(LoaderKindMismatch) as exc:
            load_position(str(tmp_path), 10, seed=1, loader_kind="records")
        msg = str(exc.value)
        assert "'raw'" in msg and "'records'" in msg
        assert "--records_dir" in msg  # actionable

        save_position(str(tmp_path), 20, StreamPosition(0, 1), seed=1,
                      loader_kind="records")
        with pytest.raises(LoaderKindMismatch):
            load_position(str(tmp_path), 20, seed=1, loader_kind="raw")

    def test_pack_fingerprint_mismatch_refused(self, tmp_path):
        """records -> DIFFERENT records pack (repack, other mixture or
        crop recipe) is refused too — loader_kind alone can't tell."""
        save_position(str(tmp_path), 10, StreamPosition(1, 3), seed=1,
                      loader_kind="records", fingerprint="a" * 40)
        with pytest.raises(LoaderKindMismatch) as exc:
            load_position(str(tmp_path), 10, seed=1,
                          loader_kind="records", fingerprint="b" * 40)
        assert "fingerprint" in str(exc.value)
        # the original pack resumes
        assert load_position(str(tmp_path), 10, seed=1,
                             loader_kind="records",
                             fingerprint="a" * 40) == StreamPosition(1, 3)

    def test_crop_recipe_changes_fingerprint(self, packed, tmp_path):
        """Two packs of the same tree at different crop recipes must
        fingerprint differently (the sidecar check depends on it)."""
        ds, _, manifest = packed
        import copy

        other = copy.copy(ds)
        other.augmentor = type(ds.augmentor)(
            crop_size=(32, 48), min_scale=-0.1, max_scale=1.0,
            do_flip=True)
        m2 = pack_dataset(other, str(tmp_path / "repack"), num_shards=1)
        assert m2.fingerprint != manifest.fingerprint

    def test_matching_and_legacy_sidecars_resume(self, tmp_path):
        save_position(str(tmp_path), 10, StreamPosition(2, 5), seed=1,
                      loader_kind="records")
        pos = load_position(str(tmp_path), 10, seed=1,
                            loader_kind="records")
        assert pos == StreamPosition(2, 5)
        # pre-records sidecar (no loader_kind field): resumes either way
        save_position(str(tmp_path), 30, StreamPosition(1, 2), seed=1)
        assert load_position(str(tmp_path), 30, seed=1,
                             loader_kind="records") == StreamPosition(1, 2)
        assert load_position(str(tmp_path), 30, seed=1,
                             loader_kind="raw") == StreamPosition(1, 2)


class TestRawRecordsLoaderParity:
    def test_identical_batch_stream(self, packed):
        """The pack->train parity the acceptance pins at loader level:
        raw Loader and RecordLoader over the same logical dataset yield
        the identical batch sequence, including a mid-epoch resume."""
        ds, records_dir, _ = packed
        raw = Loader(2 * ds, 4, seed=5, num_workers=2)
        rec = RecordLoader(records_dir, 4, seed=5, num_workers=2)
        it_raw, it_rec = raw.batches(), rec.batches()
        try:
            for _ in range(4):
                a, b = next(it_raw), next(it_rec)
                for k in a:
                    np.testing.assert_array_equal(a[k], b[k])
        finally:
            it_raw.close()
            it_rec.close()

        # mid-epoch resume on BOTH planes lands on the same batch
        it_raw = raw.batches(start_epoch=1, start_offset=1)
        it_rec = rec.batches(start_epoch=1, start_offset=1)
        try:
            a, b = next(it_raw), next(it_rec)
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
        finally:
            it_raw.close()
            it_rec.close()


class TestBenchSchema:
    def test_records_ab_keys_pinned(self):
        """loader_bench --records writes the comparison record with the
        pinned schema (no subprocess: just the constant's contract)."""
        sys.path.insert(0, osp.join(osp.dirname(osp.dirname(
            osp.abspath(__file__))), "scripts"))
        try:
            import loader_bench
        finally:
            sys.path.pop(0)
        assert loader_bench.RECORDS_AB_KEYS[0] == "metric"
        assert "samples_per_sec_speedup" in loader_bench.RECORDS_AB_KEYS
        assert "resume_latency_speedup" in loader_bench.RECORDS_AB_KEYS
        assert "resume_latency_s" in loader_bench.RECORDS_SIDE_KEYS
