"""Parity tests for coordinate grids and bilinear sampling.

The bilinear sampler is parity-critical (SURVEY.md §7 hard part #2): it must
match torch grid_sample(align_corners=True, padding_mode='zeros') exactly,
because the correlation lookup and therefore EPE parity depend on it.
"""

import numpy as np
import pytest

from dexiraft_tpu.ops import (
    bilinear_sampler,
    coords_grid,
    resize_bilinear_align_corners,
    upflow8,
)

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402


def torch_bilinear_sampler(img_nchw, coords_xy):
    """The reference wrapper (core/utils/utils.py:57-71)."""
    H, W = img_nchw.shape[-2:]
    xgrid, ygrid = coords_xy.split([1, 1], dim=-1)
    xgrid = 2 * xgrid / (W - 1) - 1
    ygrid = 2 * ygrid / (H - 1) - 1
    grid = torch.cat([xgrid, ygrid], dim=-1)
    return F.grid_sample(img_nchw, grid, align_corners=True)


def test_coords_grid():
    g = np.asarray(coords_grid(2, 3, 4))
    assert g.shape == (2, 3, 4, 2)
    # channel 0 is x (varies along width), channel 1 is y
    np.testing.assert_array_equal(g[0, :, :, 0], np.tile(np.arange(4), (3, 1)))
    np.testing.assert_array_equal(g[0, :, :, 1], np.tile(np.arange(3)[:, None], (1, 4)))
    np.testing.assert_array_equal(g[0], g[1])


@pytest.mark.parametrize("seed", [0, 1])
def test_bilinear_sampler_matches_grid_sample(seed):
    rng = np.random.RandomState(seed)
    N, H, W, C = 2, 9, 13, 3
    h2, w2 = 5, 7
    img = rng.randn(N, H, W, C).astype(np.float32)
    # coords spanning in-bounds, boundary, and well out-of-bounds
    coords = rng.uniform(-3.0, max(H, W) + 2.0, size=(N, h2, w2, 2)).astype(np.float32)
    coords[0, 0, 0] = [0.0, 0.0]
    coords[0, 0, 1] = [W - 1.0, H - 1.0]
    coords[0, 0, 2] = [-0.5, -0.5]

    ours = np.asarray(bilinear_sampler(img, coords))

    t_img = torch.from_numpy(img.transpose(0, 3, 1, 2))
    t_coords = torch.from_numpy(coords)
    ref = torch_bilinear_sampler(t_img, t_coords).numpy().transpose(0, 2, 3, 1)

    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_resize_align_corners_matches_interpolate():
    rng = np.random.RandomState(3)
    img = rng.randn(2, 5, 6, 2).astype(np.float32)
    ours = np.asarray(resize_bilinear_align_corners(img, 15, 18))
    ref = (
        F.interpolate(
            torch.from_numpy(img.transpose(0, 3, 1, 2)),
            size=(15, 18),
            mode="bilinear",
            align_corners=True,
        )
        .numpy()
        .transpose(0, 2, 3, 1)
    )
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_upflow8_matches_reference():
    rng = np.random.RandomState(4)
    flow = rng.randn(1, 6, 8, 2).astype(np.float32)
    ours = np.asarray(upflow8(flow))
    t = torch.from_numpy(flow.transpose(0, 3, 1, 2))
    ref = (
        (8 * F.interpolate(t, size=(48, 64), mode="bilinear", align_corners=True))
        .numpy()
        .transpose(0, 2, 3, 1)
    )
    assert ours.shape == (1, 48, 64, 2)
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)
