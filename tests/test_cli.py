"""CLI integration: train a few steps on a synthetic chairs tree through
the real argparse surface, checkpoint, resume, then eval-restore."""

import numpy as np
import pytest

from dexiraft_tpu.data.flow_io import write_flo


@pytest.fixture()
def chairs_env(tmp_path, monkeypatch):
    import imageio.v2 as imageio

    root = tmp_path / "FlyingChairs_release"
    data = root / "data"
    data.mkdir(parents=True)
    rng = np.random.default_rng(0)
    n = 8
    for i in range(n):
        imageio.imwrite(data / f"{i:05d}_img1.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        imageio.imwrite(data / f"{i:05d}_img2.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        write_flo(data / f"{i:05d}_flow.flo",
                  rng.normal(size=(96, 128, 2)).astype(np.float32))
    (root / "chairs_split.txt").write_text("\n".join(["1"] * n))
    monkeypatch.setenv("DEXIRAFT_DATA_DIR", str(tmp_path))
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _train_args(tmp_path, steps, extra=()):
    return [
        "--name", "t", "--stage", "chairs", "--variant", "v1", "--small",
        "--num_steps", str(steps), "--batch_size", "2",
        "--image_size", "64", "64", "--iters", "2", "--lr", "1e-4",
        "--num_workers", "1", "--val_freq", "1000",
        "--output", str(tmp_path / "ckpts"),
        "--log_dir", str(tmp_path / "runs"),
        *extra,
    ]


def test_train_resume_eval_roundtrip(chairs_env):
    import jax

    from dexiraft_tpu.train_cli import main as train_main
    from dexiraft_tpu.train import checkpoint as ckpt

    tmp = chairs_env
    train_main(_train_args(tmp, 3))
    ckpt_dir = str(tmp / "ckpts" / "t")
    assert ckpt.latest_step(ckpt_dir) == 3
    assert (tmp / "runs" / "t" / "metrics.jsonl").exists()

    # resume continues the step counter (full-state restore)
    train_main(_train_args(tmp, 5, extra=["--resume"]))
    assert ckpt.latest_step(ckpt_dir) == 5

    # eval-restore path: variables load and the jitted test-mode forward runs
    from dexiraft_tpu.eval_cli import build_parser, load_variables
    from dexiraft_tpu.train.step import make_eval_step

    args = build_parser().parse_args(
        ["--model", ckpt_dir, "--variant", "v1", "--small",
         "--dataset", "chairs"])
    cfg, variables = load_variables(args)
    step = make_eval_step(cfg, iters=2)
    im = jax.numpy.zeros((1, 64, 64, 3))
    low, up = step(variables, im, im)
    assert up.shape == (1, 64, 64, 2)


def test_divergence_guard_rolls_back_then_aborts(chairs_env):
    """Elastic-recovery guard (absent in the reference, SURVEY.md §5:
    its v3 diverged and kept logging). Poison the dataset after a good
    checkpoint exists: the guard must roll back to it — never saving a
    poisoned state — retry up to --max_rollbacks, then abort loudly."""
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train_cli import main as train_main

    tmp = chairs_env
    train_main(_train_args(tmp, 2))
    ckpt_dir = str(tmp / "ckpts" / "t")
    assert ckpt.latest_step(ckpt_dir) == 2

    # poison every flow file -> every batch from here on yields nan loss
    data = tmp / "FlyingChairs_release" / "data"
    for f in data.glob("*_flow.flo"):
        write_flo(f, np.full((96, 128, 2), np.nan, np.float32))

    with pytest.raises(RuntimeError, match="diverged.*after 2 rollbacks"):
        train_main(_train_args(
            tmp, 6, extra=["--resume", "--guard_every", "1",
                           "--max_rollbacks", "2"]))
    # the poisoned steps never reached disk
    assert ckpt.latest_step(ckpt_dir) == 2


def test_guard_disabled_reproduces_reference_behavior(chairs_env):
    """--no_guard: nan losses train through to completion (what the
    reference always did) — the guard is an opt-out upgrade, not a
    behavior change for anyone who wants the old semantics."""
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train_cli import main as train_main

    tmp = chairs_env
    data = tmp / "FlyingChairs_release" / "data"
    for f in data.glob("*_flow.flo"):
        write_flo(f, np.full((96, 128, 2), np.nan, np.float32))
    train_main(_train_args(tmp, 2, extra=["--no_guard"]))
    assert ckpt.latest_step(str(tmp / "ckpts" / "t")) == 2


def test_eval_cli_edgesum_dispatch(chairs_env, capsys):
    """--dataset edgesum wires through the validator registry: the CLI
    builds the edge-pair chairs-val dataset from --edge_root and
    validate_edgesum runs the dual-pass summed validation."""
    import imageio.v2 as imageio

    tmp = chairs_env
    root = tmp / "FlyingChairs_release"
    # flip the split to validation ("2") and add a parallel edge tree
    (root / "chairs_split.txt").write_text("\n".join(["2"] * 8))
    edge_root = tmp / "edges"
    rng = np.random.default_rng(1)
    for i in range(8):
        for k in (1, 2):
            p = edge_root / "data" / f"{i:05d}_img{k}.png"
            p.parent.mkdir(parents=True, exist_ok=True)
            imageio.imwrite(p, rng.integers(0, 256, (96, 128, 3),
                                            dtype=np.uint8))

    from dexiraft_tpu.eval_cli import _edgesum_dataset
    from dexiraft_tpu.eval.validate import run_validation

    ds = _edgesum_dataset(str(edge_root / "data"))
    assert len(ds) == 8
    fake = lambda im1, im2, flow_init=None: (
        None, np.zeros(im1.shape[:3] + (2,), np.float32))
    out = run_validation("edgesum", fake, ds)
    assert "edgesum" in out and np.isfinite(out["edgesum"])

    # the guard the registry contract requires: no dataset -> clear error
    with pytest.raises(ValueError, match="edge-pair dataset"):
        run_validation("edgesum", fake)


def test_preset_resolution():
    from dexiraft_tpu.train_cli import build_parser, resolve_configs

    args = build_parser().parse_args(
        ["--stage", "sintel", "--preset", "standard", "--variant", "v5"])
    cfg, tc = resolve_configs(args)
    assert cfg.variant == "dual" and cfg.embed_dexined
    assert tc.gamma == 0.85 and tc.freeze_bn and tc.num_steps == 100_000
    assert tc.image_size == (368, 768)

    # explicit overrides win over the preset
    args = build_parser().parse_args(
        ["--stage", "sintel", "--preset", "standard", "--lr", "3e-4"])
    _, tc = resolve_configs(args)
    assert tc.lr == 3e-4
