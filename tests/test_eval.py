"""Eval layer tests: flow viz, warm-start interpolation, validators,
submission writers — all on synthetic data with stub eval functions."""

import numpy as np

from dexiraft_tpu.data.flow_io import read_flo, read_flow_kitti
from dexiraft_tpu.eval import (
    create_kitti_submission,
    create_sintel_submission,
    flow_to_image,
    forward_interpolate,
    validate_chairs,
    validate_kitti,
)


class _StubDense:
    """Dense dataset stub: ground-truth flow is constant (2, -1)."""

    def __init__(self, n=3, h=60, w=80):
        self.n, self.h, self.w = n, h, w

    def __len__(self):
        return self.n

    def sample(self, i, rng=None):
        r = np.random.default_rng(i)
        return {
            "image1": r.uniform(0, 255, (self.h, self.w, 3)).astype(np.float32),
            "image2": r.uniform(0, 255, (self.h, self.w, 3)).astype(np.float32),
            "flow": np.broadcast_to(np.float32([2.0, -1.0]),
                                    (self.h, self.w, 2)).copy(),
            "valid": np.ones((self.h, self.w), np.float32),
        }


def _perfect_eval_fn(im1, im2, flow_init=None):
    """Predicts exactly (2, -1) everywhere."""
    b, h, w = im1.shape[:3]
    up = np.broadcast_to(np.float32([2.0, -1.0]), (b, h, w, 2)).copy()
    low = np.broadcast_to(np.float32([0.25, -0.125]),
                          (b, h // 8, w // 8, 2)).copy()
    return low, up


class TestFlowViz:
    def test_shapes_and_dtype(self):
        flow = np.random.default_rng(0).normal(size=(32, 48, 2)).astype(np.float32)
        img = flow_to_image(flow)
        assert img.shape == (32, 48, 3) and img.dtype == np.uint8

    def test_zero_flow_is_white(self):
        img = flow_to_image(np.zeros((8, 8, 2), np.float32))
        assert (img > 250).all()  # zero magnitude -> center of wheel (white)

    def test_bgr_swaps_channels(self):
        flow = np.random.default_rng(1).normal(size=(8, 8, 2)).astype(np.float32)
        rgb = flow_to_image(flow)
        bgr = flow_to_image(flow, convert_to_bgr=True)
        np.testing.assert_array_equal(rgb[..., 0], bgr[..., 2])


class TestForwardInterpolate:
    def test_zero_flow_identity(self):
        flow = np.zeros((16, 20, 2), np.float32)
        out = np.asarray(forward_interpolate(flow))
        np.testing.assert_allclose(out, 0.0)

    def test_constant_flow_fills_everywhere(self):
        # every pixel moves +4 in x: splat covers x>=4, holes filled left
        flow = np.zeros((16, 20, 2), np.float32)
        flow[..., 0] = 4.0
        out = np.asarray(forward_interpolate(flow))
        np.testing.assert_allclose(out, np.broadcast_to([4.0, 0.0], out.shape),
                                   atol=1e-5)

    def test_out_of_frame_vectors_dropped(self):
        flow = np.full((8, 8, 2), 100.0, np.float32)  # all leave the frame
        out = np.asarray(forward_interpolate(flow))
        np.testing.assert_allclose(out, 0.0)  # nothing splatted -> zeros


def _scipy_forward_interpolate(flow):
    """The reference's semantics (core/utils/utils.py:26-54) re-derived
    channels-last: splat to continuous targets, strict interior filter,
    scipy griddata(nearest) re-grid, fill 0 when no points survive."""
    from scipy import interpolate

    dx, dy = flow[..., 0], flow[..., 1]
    ht, wd = dx.shape
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))
    x1 = (x0 + dx).reshape(-1)
    y1 = (y0 + dy).reshape(-1)
    dxr, dyr = dx.reshape(-1), dy.reshape(-1)
    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    if not valid.any():
        return np.zeros_like(flow)
    fx = interpolate.griddata((x1[valid], y1[valid]), dxr[valid], (x0, y0),
                              method="nearest", fill_value=0)
    fy = interpolate.griddata((x1[valid], y1[valid]), dyr[valid], (x0, y0),
                              method="nearest", fill_value=0)
    return np.stack([fx, fy], axis=-1).astype(np.float32)


def _smooth_flow(rng, h, w, mag=8.0):
    """Low-frequency smooth field like a real low-res RAFT output."""
    ys, xs = np.meshgrid(np.linspace(0, 2 * np.pi, h),
                         np.linspace(0, 2 * np.pi, w), indexing="ij")
    a, b, c, d = rng.uniform(0.5, 2.0, 4)
    fx = mag * np.sin(a * ys + rng.uniform(0, 6)) * np.cos(b * xs)
    fy = mag * np.cos(c * xs + rng.uniform(0, 6)) * np.sin(d * ys)
    return np.stack([fx, fy], axis=-1).astype(np.float32)


class TestWarmStartParity:
    """Quantified divergence vs the reference's scipy re-grid (VERDICT
    r3 item 7). Our jump-flood Voronoi fill computes the same
    nearest-point assignment griddata(nearest) does; residual deltas
    come from sub-1/4-px scatter collisions on occlusion folds
    (eval/interpolate.py module docstring, docs/parity.md)."""

    GEOM = (55, 128)  # sintel flow_low geometry (440/8, 1024/8)

    def test_divergence_bounded_on_smooth_fields(self):
        h, w = self.GEOM
        means, fracs = [], []
        for seed in range(4):
            flow = _smooth_flow(np.random.default_rng(seed), h, w)
            ours = np.asarray(forward_interpolate(flow))
            ref = _scipy_forward_interpolate(flow)
            d = np.linalg.norm(ours - ref, axis=-1)
            means.append(d.mean())
            fracs.append((d > 0.5).mean())
        # measured r4 (S=4 supersampling): mean 0.016 px, frac 0.3%
        assert np.mean(means) < 0.05, means
        assert np.mean(fracs) < 0.01, fracs

    def test_exact_match_without_folds(self):
        """Fields whose splat has no scatter collisions reproduce scipy
        EXACTLY (seeds measured exact in r4; tolerance covers nearest
        tie-breaks, whose value delta is tiny on fold-free fields)."""
        h, w = self.GEOM
        for seed in (0, 2):
            flow = _smooth_flow(np.random.default_rng(seed), h, w)
            ours = np.asarray(forward_interpolate(flow))
            ref = _scipy_forward_interpolate(flow)
            assert np.abs(ours - ref).max() < 0.5

    def test_downstream_delta_with_trained_v5(self):
        """The bound VERDICT r3 asked for: warm-starting the next
        frame's refinement with our field vs the reference's scipy field
        moves the OUTPUT flow by ~0.1 px mean (measured r4: in 0.024/
        0.031 px mean -> out 0.097/0.125 px mean, max 2.4 px) through
        the 400-step-trained v5 checkpoint. Gated on the local trained
        checkpoint (3.2 GB, gitignored); skipped where absent."""
        import os.path as osp

        import jax
        import jax.numpy as jnp
        import pytest

        ck = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                      "logs", "v5_cpu_ck")
        if not osp.isdir(ck):
            pytest.skip("trained v5 checkpoint not present (gitignored)")

        from dexiraft_tpu.config import TrainConfig, raft_v5
        from dexiraft_tpu.train.checkpoint import restore_checkpoint
        from dexiraft_tpu.train.state import create_state
        from dexiraft_tpu.train.step import make_eval_step

        h, w = 96, 128  # the checkpoint's training geometry
        cfg = raft_v5(remat=True)
        tc = TrainConfig(name="demo", num_steps=400, batch_size=2,
                         image_size=(h, w), iters=12, lr=2e-4, wdecay=1e-5)
        state = restore_checkpoint(
            ck, create_state(jax.random.PRNGKey(1234), cfg, tc))
        variables = {"params": state.params,
                     "batch_stats": state.batch_stats}
        step = make_eval_step(cfg, iters=6)
        img1 = jax.random.uniform(jax.random.PRNGKey(0), (1, h, w, 3),
                                  jnp.float32, 0, 255)
        img2 = jax.random.uniform(jax.random.PRNGKey(1), (1, h, w, 3),
                                  jnp.float32, 0, 255)
        for seed in (0, 1):
            fl = _smooth_flow(np.random.default_rng(seed), h // 8, w // 8,
                              mag=3.0)
            ours = np.asarray(forward_interpolate(fl))[None]
            ref = _scipy_forward_interpolate(fl)[None]
            _, up_ours = step(variables, img1, img2,
                              flow_init=jnp.asarray(ours))
            _, up_ref = step(variables, img1, img2,
                             flow_init=jnp.asarray(ref))
            d = np.linalg.norm(np.asarray(up_ours) - np.asarray(up_ref),
                               axis=-1)
            assert d.mean() < 0.3, d.mean()
            assert d.max() < 5.0, d.max()


class TestValidators:
    def test_chairs_perfect(self):
        res = validate_chairs(_perfect_eval_fn, dataset=_StubDense())
        assert res["chairs"] < 1e-5

    def test_chairs_known_error(self):
        def off_by_one(im1, im2, flow_init=None):
            low, up = _perfect_eval_fn(im1, im2)
            return low, up + np.float32([1.0, 0.0])

        res = validate_chairs(off_by_one, dataset=_StubDense())
        np.testing.assert_allclose(res["chairs"], 1.0, atol=1e-5)

    def test_kitti_f1_counts_outliers(self):
        class SparseStub(_StubDense):
            def __init__(self):
                super().__init__(n=3, h=64, w=80)  # stride-8: no pad shift

            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                # large GT so epe/mag stays under 5% for inliers
                s["flow"] = np.broadcast_to(np.float32([90.0, 0.0]),
                                            (self.h, self.w, 2)).copy()
                s["valid"] = np.ones((self.h, self.w), np.float32)
                return s

        def half_outliers(im1, im2, flow_init=None):
            b, h, w = im1.shape[:3]
            up = np.broadcast_to(np.float32([90.0, 0.0]), (b, h, w, 2)).copy()
            up[:, : h // 2] += np.float32([20.0, 0.0])  # epe 20 > 3, ratio .22
            return _perfect_eval_fn(im1, im2)[0], up

        res = validate_kitti(half_outliers, dataset=SparseStub())
        np.testing.assert_allclose(res["kitti-f1"], 50.0, atol=1.0)


class TestSubmissions:
    def test_sintel_submission_tree(self, tmp_path):
        class SintelStub(_StubDense):
            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                s["extra_info"] = ("alley_1", i)
                return {"image1": s["image1"], "image2": s["image2"],
                        "extra_info": s["extra_info"]}

        out = tmp_path / "sub"
        create_sintel_submission(_perfect_eval_fn, output_path=str(out),
                                 warm_start=True,
                                 datasets={"clean": SintelStub(n=2)})
        f = out / "clean" / "alley_1" / "frame0001.flo"
        assert f.exists()
        flow = read_flo(f)
        np.testing.assert_allclose(flow[..., 0], 2.0, atol=1e-5)

    def test_kitti_submission_pngs(self, tmp_path):
        class KittiStub(_StubDense):
            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                return {"image1": s["image1"], "image2": s["image2"],
                        "extra_info": [f"{i:06d}_10.png"]}

        out = tmp_path / "kitti"
        create_kitti_submission(_perfect_eval_fn, output_path=str(out),
                                dataset=KittiStub(n=2))
        flow, valid = read_flow_kitti(out / "000000_10.png")
        np.testing.assert_allclose(flow[..., 0], 2.0, atol=1 / 64)
        assert valid.min() == 1.0


class TestEdgeSumValidator:
    def test_sum_fusion_epe(self):
        """alt/evaluate_1.py:84-94: flows from the image pair and the
        edge pair are summed before EPE. A model predicting exactly half
        the GT on each pass scores zero after summation."""
        from dexiraft_tpu.eval.validate import validate_edgesum

        class EdgeStub(_StubDense):
            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                s["edges1"] = s["image1"] * 0.5
                s["edges2"] = s["image2"] * 0.5
                return s

        def half_eval_fn(im1, im2, flow_init=None):
            low, up = _perfect_eval_fn(im1, im2)
            return low * 0.5, up * 0.5

        res = validate_edgesum(half_eval_fn, EdgeStub())
        assert res["edgesum"] < 1e-5

        res_full = validate_edgesum(_perfect_eval_fn, EdgeStub())
        np.testing.assert_allclose(res_full["edgesum"],
                                   np.hypot(2.0, 1.0), atol=1e-4)
