"""Eval layer tests: flow viz, warm-start interpolation, validators,
submission writers — all on synthetic data with stub eval functions."""

import numpy as np

from dexiraft_tpu.data.flow_io import read_flo, read_flow_kitti
from dexiraft_tpu.eval import (
    create_kitti_submission,
    create_sintel_submission,
    flow_to_image,
    forward_interpolate,
    validate_chairs,
    validate_kitti,
)


class _StubDense:
    """Dense dataset stub: ground-truth flow is constant (2, -1)."""

    def __init__(self, n=3, h=60, w=80):
        self.n, self.h, self.w = n, h, w

    def __len__(self):
        return self.n

    def sample(self, i, rng=None):
        r = np.random.default_rng(i)
        return {
            "image1": r.uniform(0, 255, (self.h, self.w, 3)).astype(np.float32),
            "image2": r.uniform(0, 255, (self.h, self.w, 3)).astype(np.float32),
            "flow": np.broadcast_to(np.float32([2.0, -1.0]),
                                    (self.h, self.w, 2)).copy(),
            "valid": np.ones((self.h, self.w), np.float32),
        }


def _perfect_eval_fn(im1, im2, flow_init=None):
    """Predicts exactly (2, -1) everywhere."""
    b, h, w = im1.shape[:3]
    up = np.broadcast_to(np.float32([2.0, -1.0]), (b, h, w, 2)).copy()
    low = np.broadcast_to(np.float32([0.25, -0.125]),
                          (b, h // 8, w // 8, 2)).copy()
    return low, up


class TestFlowViz:
    def test_shapes_and_dtype(self):
        flow = np.random.default_rng(0).normal(size=(32, 48, 2)).astype(np.float32)
        img = flow_to_image(flow)
        assert img.shape == (32, 48, 3) and img.dtype == np.uint8

    def test_zero_flow_is_white(self):
        img = flow_to_image(np.zeros((8, 8, 2), np.float32))
        assert (img > 250).all()  # zero magnitude -> center of wheel (white)

    def test_bgr_swaps_channels(self):
        flow = np.random.default_rng(1).normal(size=(8, 8, 2)).astype(np.float32)
        rgb = flow_to_image(flow)
        bgr = flow_to_image(flow, convert_to_bgr=True)
        np.testing.assert_array_equal(rgb[..., 0], bgr[..., 2])


class TestForwardInterpolate:
    def test_zero_flow_identity(self):
        flow = np.zeros((16, 20, 2), np.float32)
        out = np.asarray(forward_interpolate(flow))
        np.testing.assert_allclose(out, 0.0)

    def test_constant_flow_fills_everywhere(self):
        # every pixel moves +4 in x: splat covers x>=4, holes filled left
        flow = np.zeros((16, 20, 2), np.float32)
        flow[..., 0] = 4.0
        out = np.asarray(forward_interpolate(flow))
        np.testing.assert_allclose(out, np.broadcast_to([4.0, 0.0], out.shape),
                                   atol=1e-5)

    def test_out_of_frame_vectors_dropped(self):
        flow = np.full((8, 8, 2), 100.0, np.float32)  # all leave the frame
        out = np.asarray(forward_interpolate(flow))
        np.testing.assert_allclose(out, 0.0)  # nothing splatted -> zeros


class TestValidators:
    def test_chairs_perfect(self):
        res = validate_chairs(_perfect_eval_fn, dataset=_StubDense())
        assert res["chairs"] < 1e-5

    def test_chairs_known_error(self):
        def off_by_one(im1, im2, flow_init=None):
            low, up = _perfect_eval_fn(im1, im2)
            return low, up + np.float32([1.0, 0.0])

        res = validate_chairs(off_by_one, dataset=_StubDense())
        np.testing.assert_allclose(res["chairs"], 1.0, atol=1e-5)

    def test_kitti_f1_counts_outliers(self):
        class SparseStub(_StubDense):
            def __init__(self):
                super().__init__(n=3, h=64, w=80)  # stride-8: no pad shift

            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                # large GT so epe/mag stays under 5% for inliers
                s["flow"] = np.broadcast_to(np.float32([90.0, 0.0]),
                                            (self.h, self.w, 2)).copy()
                s["valid"] = np.ones((self.h, self.w), np.float32)
                return s

        def half_outliers(im1, im2, flow_init=None):
            b, h, w = im1.shape[:3]
            up = np.broadcast_to(np.float32([90.0, 0.0]), (b, h, w, 2)).copy()
            up[:, : h // 2] += np.float32([20.0, 0.0])  # epe 20 > 3, ratio .22
            return _perfect_eval_fn(im1, im2)[0], up

        res = validate_kitti(half_outliers, dataset=SparseStub())
        np.testing.assert_allclose(res["kitti-f1"], 50.0, atol=1.0)


class TestSubmissions:
    def test_sintel_submission_tree(self, tmp_path):
        class SintelStub(_StubDense):
            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                s["extra_info"] = ("alley_1", i)
                return {"image1": s["image1"], "image2": s["image2"],
                        "extra_info": s["extra_info"]}

        out = tmp_path / "sub"
        create_sintel_submission(_perfect_eval_fn, output_path=str(out),
                                 warm_start=True,
                                 datasets={"clean": SintelStub(n=2)})
        f = out / "clean" / "alley_1" / "frame0001.flo"
        assert f.exists()
        flow = read_flo(f)
        np.testing.assert_allclose(flow[..., 0], 2.0, atol=1e-5)

    def test_kitti_submission_pngs(self, tmp_path):
        class KittiStub(_StubDense):
            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                return {"image1": s["image1"], "image2": s["image2"],
                        "extra_info": [f"{i:06d}_10.png"]}

        out = tmp_path / "kitti"
        create_kitti_submission(_perfect_eval_fn, output_path=str(out),
                                dataset=KittiStub(n=2))
        flow, valid = read_flow_kitti(out / "000000_10.png")
        np.testing.assert_allclose(flow[..., 0], 2.0, atol=1 / 64)
        assert valid.min() == 1.0


class TestEdgeSumValidator:
    def test_sum_fusion_epe(self):
        """alt/evaluate_1.py:84-94: flows from the image pair and the
        edge pair are summed before EPE. A model predicting exactly half
        the GT on each pass scores zero after summation."""
        from dexiraft_tpu.eval.validate import validate_edgesum

        class EdgeStub(_StubDense):
            def sample(self, i, rng=None):
                s = super().sample(i, rng)
                s["edges1"] = s["image1"] * 0.5
                s["edges2"] = s["image2"] * 0.5
                return s

        def half_eval_fn(im1, im2, flow_init=None):
            low, up = _perfect_eval_fn(im1, im2)
            return low * 0.5, up * 0.5

        res = validate_edgesum(half_eval_fn, EdgeStub())
        assert res["edgesum"] < 1e-5

        res_full = validate_edgesum(_perfect_eval_fn, EdgeStub())
        np.testing.assert_allclose(res_full["edgesum"],
                                   np.hypot(2.0, 1.0), atol=1e-4)
