"""Stub FlowService replica for the fleet-router subprocess tests.

A REAL serve process (HTTP listener, scheduler, sessions — the full
service stack) over the numpy stub eval_fn, so it boots in ~a second
(no model, no checkpoint, no compile) and SIGKILLing it is a genuine
process death: connections reset, the port goes dark, warm session
carries vanish. tests/test_zzfleet_router.py and nothing else runs
this.

Usage: python tests/serve_replica_child.py PORT
"""

import sys

import numpy as np

from dexiraft_tpu.serve import FlowService, InferenceEngine, ServeConfig


def stub_eval(im1, im2, flow_init=None):
    """test_zzserve_service's carry-accumulating stub: constant
    (2, -1) flow; warm rows add their flow_init so affinity is
    OBSERVABLE in the responses, not just in counters."""
    b, h, w = im1.shape[:3]
    up = np.broadcast_to(np.float32([2.0, -1.0]), (b, h, w, 2)).copy()
    low = np.full((b, h // 8, w // 8, 2), 0.5, np.float32)
    if flow_init is not None:
        fi = np.asarray(flow_init)
        up = up + np.repeat(np.repeat(fi, 8, 1), 8, 2)
        low = low + fi
    return low, up


def main() -> None:
    port = int(sys.argv[1])
    svc = FlowService(
        InferenceEngine(stub_eval,
                        ServeConfig(batch_size=2, warm_start=True),
                        put=lambda t: t),
        host="127.0.0.1", port=port, slo_ms=30.0, max_queue=32,
        session_ttl_s=60.0)
    svc.install_signal_handlers()
    svc.start()
    print(f"[replica] listening on {svc.url}", flush=True)
    while not svc.stopped.wait(0.5):
        pass


if __name__ == "__main__":
    main()
