"""Test configuration: force CPU with 8 virtual devices.

Multi-chip sharding logic is exercised on a virtual CPU mesh (no TPU
needed). The environment pins JAX_PLATFORMS=axon (the TPU tunnel) via a
site hook, so setting the env var alone is not enough — we also update the
jax config after import, before any computation runs.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
