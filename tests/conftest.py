"""Test configuration: force CPU with 8 virtual devices + tier-1 budget guard.

Multi-chip sharding logic is exercised on a virtual CPU mesh (no TPU
needed). The environment pins JAX_PLATFORMS=axon (the TPU tunnel) via a
site hook, so setting the env var alone is not enough — we also update the
jax config after import, before any computation runs.

Budget guard: the tier-1 suite runs under a hard 870 s wall-clock cap
(ROADMAP.md), so one inadvertently expensive test silently evicts the
tests scheduled after it. Every run records per-test call durations to
logs/test_durations.json (rewritten after each test, so a timeout-killed
session still leaves the completed prefix). At COLLECTION time the next
run fails loudly if any collected test not marked `slow` exceeded the
per-test ceiling last time — the author finds out immediately, not by
watching DOTS_PASSED sag. Ceiling: DEXIRAFT_TEST_CEILING_S (default 420:
the heaviest legitimate test — a CLI guard-rollback training loop — has
measured 149-234s across runs (±30% machine-weather variance), so the
tripwire sits at ~1.8x the worst observed while still catching any new
multi-minute test; 0 disables). `scripts/test_slowest.py` prints the
top offenders.
"""

import json
import os
import os.path as osp

import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# arm the lock-order runtime for the whole suite (analysis/locks): any
# rank inversion or ABBA acquisition cycle in the serve/resilience
# thread fabric RAISES at the offending acquisition instead of warning
# — every threaded tier-1 test doubles as a lock-discipline canary
# (the armed-replication-canary idiom). Seeded-violation tests use
# private LockRegistry instances, so the global registry stays clean.
from dexiraft_tpu.analysis import locks as _locks  # noqa: E402

_locks.set_strict(True)

DURATIONS_PATH = osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                          "logs", "test_durations.json")
CEILING_S = float(os.environ.get("DEXIRAFT_TEST_CEILING_S", "420"))

_durations: dict = {}


def _last_durations() -> dict:
    try:
        with open(DURATIONS_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def pytest_collection_modifyitems(config, items):
    if CEILING_S <= 0:
        return
    last = _last_durations()
    over = [(it.nodeid, last[it.nodeid]) for it in items
            if "slow" not in it.keywords and last.get(it.nodeid, 0) > CEILING_S]
    if over:
        detail = "\n".join(f"  {d:7.1f}s  {nid}" for nid, d in
                           sorted(over, key=lambda x: -x[1]))
        raise pytest.UsageError(
            f"tier-1 budget guard: {len(over)} unmarked test(s) exceeded "
            f"the {CEILING_S:.0f}s per-test ceiling on the last recorded "
            f"run (logs/test_durations.json). Mark them `slow` or make "
            f"them cheaper — then delete logs/test_durations.json (or "
            f"run once with DEXIRAFT_TEST_CEILING_S=0) so the next run "
            f"re-records fresh timings:\n{detail}")


_seen_this_run: set = set()


def pytest_runtest_logreport(report):
    # sum ALL phases (setup + call + teardown): module/session-scoped
    # fixtures charge their cost to the first requesting test's setup,
    # and a 500s fixture evicts tail tests from the budget window just
    # as surely as a 500s test body would
    if report.when not in ("setup", "call", "teardown"):
        return
    if not _durations:
        # merge into the previous record so a partial invocation (one
        # file, -k filter) doesn't erase the rest of the suite's data
        _durations.update(_last_durations())
    if report.nodeid not in _seen_this_run:
        _seen_this_run.add(report.nodeid)
        _durations[report.nodeid] = 0.0
    _durations[report.nodeid] = round(
        _durations[report.nodeid] + report.duration, 3)
    if report.when != "teardown":
        return  # write once per test, at its last phase
    # rewrite after every test: the tier-1 runner kills the session at
    # the 870 s cap, and the completed prefix must survive the kill
    try:
        os.makedirs(osp.dirname(DURATIONS_PATH), exist_ok=True)
        tmp = DURATIONS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_durations, f, indent=0, sort_keys=True)
        os.replace(tmp, DURATIONS_PATH)
    except OSError:
        pass
