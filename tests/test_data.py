"""Data pipeline tests: I/O round-trips, augmentor semantics, datasets,
loader determinism and host sharding, padding."""

import numpy as np
import pytest

from dexiraft_tpu.data import (
    FlowAugmentor,
    FlyingChairs,
    InputPadder,
    KITTI,
    Loader,
    MpiSintel,
    SparseFlowAugmentor,
    read_flo,
    read_flow_kitti,
    write_flo,
    write_flow_kitti,
)
from dexiraft_tpu.data.flow_io import read_pfm, write_pfm


def _rand_img(rng, h, w):
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


class TestFlowIO:
    def test_flo_roundtrip(self, tmp_path):
        flow = np.random.default_rng(0).normal(size=(13, 17, 2)).astype(np.float32)
        p = tmp_path / "a.flo"
        write_flo(p, flow)
        np.testing.assert_array_equal(read_flo(p), flow)

    def test_pfm_roundtrip(self, tmp_path):
        rng = np.random.default_rng(1)
        for shape in [(7, 9), (7, 9, 3)]:
            data = rng.normal(size=shape).astype(np.float32)
            p = tmp_path / "a.pfm"
            write_pfm(p, data)
            np.testing.assert_array_equal(read_pfm(p), data)

    def test_kitti_roundtrip(self, tmp_path):
        # representable values: multiples of 1/64 within +-512
        flow = (np.random.default_rng(2)
                .integers(-2000, 2000, (11, 19, 2)) / 64.0).astype(np.float32)
        p = tmp_path / "f.png"
        write_flow_kitti(p, flow)
        back, valid = read_flow_kitti(p)
        np.testing.assert_allclose(back, flow, atol=1e-6)
        assert valid.min() == 1.0


class TestAugmentors:
    def test_dense_shapes_and_determinism(self):
        rng_img = np.random.default_rng(0)
        img1 = _rand_img(rng_img, 120, 160)
        img2 = _rand_img(rng_img, 120, 160)
        flow = rng_img.normal(size=(120, 160, 2)).astype(np.float32)
        aug = FlowAugmentor(crop_size=(64, 96), min_scale=-0.2, max_scale=0.5)

        o1 = aug(np.random.default_rng(42), img1, img2, flow)
        o2 = aug(np.random.default_rng(42), img1, img2, flow)
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(a, b)
        a1, a2, af = o1
        assert a1.shape == (64, 96, 3) and af.shape == (64, 96, 2)

    def test_dense_lockstep_edges(self):
        rng_img = np.random.default_rng(0)
        img1 = _rand_img(rng_img, 100, 140)
        img2 = _rand_img(rng_img, 100, 140)
        flow = np.zeros((100, 140, 2), np.float32)
        aug = FlowAugmentor(crop_size=(64, 96))
        # identical inputs for images and edges -> identical spatial result
        i1, i2, _, e1, e2 = aug(np.random.default_rng(7), img1, img2, flow,
                                edges=(img1.copy(), img2.copy()))
        assert e1.shape == i1.shape
        # photometric aug applies to images only; spatial transforms match,
        # so edges equal the un-jittered images' crop of the original
        assert e1.dtype == np.uint8

    def test_sparse_resize_respats_valid(self):
        flow = np.zeros((40, 60, 2), np.float32)
        valid = np.zeros((40, 60), np.float32)
        flow[10, 20] = (3.0, -2.0)
        valid[10, 20] = 1.0
        out_flow, out_valid = SparseFlowAugmentor.resize_sparse_flow_map(
            flow, valid, fx=2.0, fy=2.0)
        assert out_flow.shape == (80, 120, 2)
        assert out_valid.sum() == 1.0
        yy, xx = np.argwhere(out_valid == 1)[0]
        assert (yy, xx) == (20, 40)
        np.testing.assert_allclose(out_flow[yy, xx], [6.0, -4.0])

    def test_sparse_shapes(self):
        rng_img = np.random.default_rng(3)
        img1 = _rand_img(rng_img, 120, 200)
        img2 = _rand_img(rng_img, 120, 200)
        flow = rng_img.normal(size=(120, 200, 2)).astype(np.float32)
        valid = (rng_img.random((120, 200)) > 0.5).astype(np.float32)
        aug = SparseFlowAugmentor(crop_size=(96, 160), do_flip=True)
        a1, a2, af, av = aug(np.random.default_rng(11), img1, img2, flow, valid)
        assert a1.shape == (96, 160, 3)
        assert af.shape == (96, 160, 2) and av.shape == (96, 160)
        assert set(np.unique(av)).issubset({0.0, 1.0})

    def test_jitter_lut_matches_blend(self):
        # brightness/contrast run through cv2.LUT for speed; the
        # PRODUCTION ColorJitter must reproduce the float-blend
        # formulation (torchvision semantics: f32 multiply-add, clip,
        # truncating uint8 cast) bit-for-bit. The expected side replays
        # the jitter's own RNG draws through an independent blend-based
        # reference.
        import cv2

        from dexiraft_tpu.data.augment import ColorJitter

        def blend(img, other, f):
            out = f * img.astype(np.float32) + (1.0 - f) * other
            return np.clip(out, 0, 255).astype(np.uint8)

        base = np.random.default_rng(3).integers(
            0, 256, (64, 64, 3), dtype=np.uint8)
        for seed in range(20):
            # brightness-only: one op, factor replayed from the same seed
            cj = ColorJitter(brightness=0.4)
            got = cj(np.random.default_rng(seed), base)
            r = np.random.default_rng(seed)
            f = r.uniform(0.6, 1.4)
            np.testing.assert_array_equal(
                got, blend(base, np.float32(0.0), f))

            # contrast-only
            cj = ColorJitter(contrast=0.4)
            got = cj(np.random.default_rng(seed), base)
            r = np.random.default_rng(seed)
            f = r.uniform(0.6, 1.4)
            gm = cv2.cvtColor(base, cv2.COLOR_RGB2GRAY).mean()
            np.testing.assert_array_equal(
                got, blend(base, np.float32(gm), f))

    def test_hue_jitter_no_uint8_wrap(self):
        from dexiraft_tpu.data.augment import ColorJitter

        # high hue values + large shift: uint8 addition would wrap at 256
        img = np.full((8, 8, 3), 0, np.uint8)
        img[..., 0] = 200  # reddish -> high cv2 hue after conversion
        jit = ColorJitter(hue=0.45)
        out = jit(np.random.default_rng(0), img.copy())
        assert out.dtype == np.uint8  # and no crash / silent corruption
        # determinism sanity
        out2 = jit(np.random.default_rng(0), img.copy())
        np.testing.assert_array_equal(out, out2)

    def test_hflip_negates_u(self):
        rng_img = np.random.default_rng(4)
        img = _rand_img(rng_img, 80, 80)
        flow = np.full((80, 80, 2), 5.0, np.float32)
        aug = FlowAugmentor(crop_size=(72, 72), do_flip=True)
        aug.spatial_aug_prob = 0.0  # isolate flips
        aug.v_flip_prob = 0.0
        aug.h_flip_prob = 1.0
        _, _, f, _ = aug.spatial_transform(np.random.default_rng(0), img, img, flow)
        np.testing.assert_allclose(f[..., 0], -5.0)
        np.testing.assert_allclose(f[..., 1], 5.0)


def _make_chairs_tree(root, n=6, h=96, w=128):
    import imageio.v2 as imageio

    data = root / "data"
    data.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(n):
        imageio.imwrite(data / f"{i:05d}_img1.ppm", _rand_img(rng, h, w))
        imageio.imwrite(data / f"{i:05d}_img2.ppm", _rand_img(rng, h, w))
        write_flo(data / f"{i:05d}_flow.flo",
                  rng.normal(size=(h, w, 2)).astype(np.float32))
    split = [1, 1, 2, 1, 2, 1][:n]
    (root / "chairs_split.txt").write_text("\n".join(map(str, split)))
    return data


class TestDatasets:
    def test_flying_chairs(self, tmp_path):
        data = _make_chairs_tree(tmp_path)
        train = FlyingChairs(dict(crop_size=(64, 96)), split="training", root=str(data))
        val = FlyingChairs(None, split="validation", root=str(data))
        assert len(train) == 4 and len(val) == 2
        s = train.sample(0, np.random.default_rng(0))
        assert s["image1"].shape == (64, 96, 3)
        assert s["flow"].shape == (64, 96, 2)
        assert s["valid"].shape == (64, 96)
        v = val.sample(1)
        assert v["image1"].shape == (96, 128, 3)

    def test_replication_and_concat(self, tmp_path):
        data = _make_chairs_tree(tmp_path)
        a = FlyingChairs(None, split="training", root=str(data))
        b = FlyingChairs(None, split="validation", root=str(data))
        mix = 3 * a + b
        assert len(mix) == 3 * 4 + 2
        # index past the replicated block reaches b
        s = mix.sample(13)
        assert s["image1"].shape == (96, 128, 3)

    def test_replication_has_value_semantics(self, tmp_path):
        data = _make_chairs_tree(tmp_path)
        a = FlyingChairs(None, split="training", root=str(data))
        m1 = 100 * a
        m2 = 5 * a  # must NOT see m1's factor
        assert len(a) == 4
        assert len(m1) == 400 and len(m2) == 20

    def test_sintel_walk(self, tmp_path):
        import imageio.v2 as imageio

        rng = np.random.default_rng(0)
        for scene in ["alley_1", "market_2"]:
            img_dir = tmp_path / "training" / "clean" / scene
            flow_dir = tmp_path / "training" / "flow" / scene
            img_dir.mkdir(parents=True)
            flow_dir.mkdir(parents=True)
            for i in range(3):
                imageio.imwrite(img_dir / f"frame_{i:04d}.png", _rand_img(rng, 64, 64))
            for i in range(2):
                write_flo(flow_dir / f"frame_{i:04d}.flo",
                          np.zeros((64, 64, 2), np.float32))
        ds = MpiSintel(None, split="training", root=str(tmp_path), dstype="clean")
        assert len(ds) == 4  # 2 scenes x 2 consecutive pairs
        one = MpiSintel(None, split="training", root=str(tmp_path),
                        dstype="clean", scene="market_2")
        assert len(one) == 2
        # qualitative single-scene mode (core/datasets_sub.py): test-style
        # samples from a training scene for visualization runs
        q = MpiSintel(None, split="training", root=str(tmp_path),
                      dstype="clean", scene="market_2", qualitative=True)
        s = q.sample(0)
        assert "flow" not in s and s["extra_info"] == ("market_2", 0)

    def test_kitti_sparse(self, tmp_path):
        import imageio.v2 as imageio

        root = tmp_path / "data_scene_flow" / "training"
        (root / "image_2").mkdir(parents=True)
        (root / "flow_occ").mkdir(parents=True)
        rng = np.random.default_rng(0)
        for i in range(2):
            imageio.imwrite(root / "image_2" / f"{i:06d}_10.png", _rand_img(rng, 80, 120))
            imageio.imwrite(root / "image_2" / f"{i:06d}_11.png", _rand_img(rng, 80, 120))
            write_flow_kitti(root / "flow_occ" / f"{i:06d}_10.png",
                             rng.integers(-100, 100, (80, 120, 2)) / 64.0)
        ds = KITTI(None, split="training", root=str(tmp_path))
        assert len(ds) == 2 and ds.sparse
        s = ds.sample(0)
        assert s["valid"].shape == (80, 120)


class TestLoader:
    def test_batches_and_determinism(self, tmp_path):
        data = _make_chairs_tree(tmp_path)
        ds = FlyingChairs(dict(crop_size=(64, 96)), split="training", root=str(data))
        mk = lambda: Loader(ds, batch_size=2, seed=7, num_workers=2)
        it1, it2 = iter(mk()), iter(mk())
        b1, b2 = next(it1), next(it2)
        assert b1["image1"].shape == (2, 64, 96, 3)
        for k in b1:
            np.testing.assert_array_equal(b1[k], b2[k])

    def test_process_workers_match_thread_workers(self, tmp_path):
        # decoding is a pure function of (seed, epoch, index), so a
        # process pool must yield bit-identical batches to the thread
        # pool — the GIL-free path cannot change the data
        data = _make_chairs_tree(tmp_path)
        ds = FlyingChairs(dict(crop_size=(64, 96)), split="training", root=str(data))
        # spawn, not fork: this pytest process has jax/XLA initialized
        # (conftest + earlier modules), and forking after XLA's thread
        # pools exist can deadlock the worker
        it_t = iter(Loader(ds, 2, seed=7, num_workers=2, worker_mode="thread"))
        it_p = iter(Loader(ds, 2, seed=7, num_workers=2, worker_mode="process",
                           mp_start_method="spawn"))
        try:
            for _ in range(3):
                bt, bp = next(it_t), next(it_p)
                assert set(bt) == set(bp)
                for k in bt:
                    np.testing.assert_array_equal(bt[k], bp[k])
        finally:
            it_t.close()
            it_p.close()

    def test_host_sharding_disjoint(self, tmp_path):
        data = _make_chairs_tree(tmp_path)
        ds = FlyingChairs(None, split="training", root=str(data))
        h0 = next(iter(Loader(ds, 4, seed=3, shuffle=True,
                              process_index=0, process_count=2)))
        h1 = next(iter(Loader(ds, 4, seed=3, shuffle=True,
                              process_index=1, process_count=2)))
        assert h0["image1"].shape[0] == 2 and h1["image1"].shape[0] == 2
        # slices of one global batch: no overlapping samples
        assert not np.array_equal(h0["image1"], h1["image1"])


class TestInputPadder:
    @pytest.mark.parametrize("mode", ["sintel", "kitti"])
    def test_pad_unpad_roundtrip(self, mode):
        x = np.random.default_rng(0).normal(size=(1, 436, 1024, 3)).astype(np.float32)
        padder = InputPadder(x.shape, mode=mode)
        (y,) = padder.pad(x)
        assert y.shape[1] % 8 == 0 and y.shape[2] % 8 == 0
        assert y.shape[1] == 440
        np.testing.assert_array_equal(padder.unpad(y), x)

    def test_no_pad_needed(self):
        x = np.zeros((1, 64, 64, 3), np.float32)
        padder = InputPadder(x.shape)
        (y,) = padder.pad(x)
        assert y.shape == x.shape
        np.testing.assert_array_equal(padder.unpad(y), x)
