"""Adaptive-iteration inference (models/raft._adaptive_refine +
serve scheduler budgets): convergence-gated early exit in the
refinement loop, SLO-driven iteration budgets in the serve tier.

Layers covered, cheapest first:
  * model — converge_tol=0 + full budget is BIT-EXACT vs the fixed
    nn.scan driver (the gate strictly `dn < tol` never fires at 0);
    budget clamp; per-item freeze independence in a mixed batch (the
    damped contraction fixture, docs/perf.md);
  * engine/scheduler/service — numpy stub eval_fn (no jax): Result
    plumbing, budget refusal on fixed engines, the SLO/pressure budget
    policy on a fake clock, conditional stats keys, wire headers;
  * compile discipline — a second dispatch at a different budget rides
    the SAME executable (the traced-int32-scalar contract), proven via
    the engine's RecompileWatch;
  * record schemas — serve_bench ADAPTIVE_* and eval_cli FRONTIER_*
    pins, plus the watchdog stderr filter (bench.make_stderr_filter).

Real-model tests share one module-scoped fixture (v1-small, 40x56,
iters=4 — a handful of tiny CPU compiles). Named test_zzz* to sort
with the tail tests (tier-1 870 s budget convention).
"""

import dataclasses
import json
import os.path as osp
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))

from dexiraft_tpu.serve import (FlowService, InferenceEngine, Scheduler,
                                ServeConfig)
from dexiraft_tpu.serve.server import encode_request

H, W = 40, 56
ITERS = 4


# ---- module fixture: one tiny real model, shared compiles ---------------


@pytest.fixture(scope="module")
def setup():
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_eval_step

    cfg = raft_v1(small=True)
    state = create_state(jax.random.PRNGKey(0), cfg, TrainConfig())
    variables = {"params": state.params,
                 "batch_stats": state.batch_stats}

    # the contraction fixture (docs/perf.md): random-init refinement
    # updates do not contract, so the convergence gate never fires;
    # damping the flow head's params x0.01 gives the converging plateau
    # a trained model has, without shipping a checkpoint
    from jax.tree_util import tree_map_with_path

    def _damp(path, leaf):
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        return leaf * 0.01 if "FlowHead_0" in keys else leaf

    damped = {"params": tree_map_with_path(_damp, variables["params"]),
              "batch_stats": variables["batch_stats"]}

    fixed = make_eval_step(cfg, iters=ITERS)
    adapt0 = make_eval_step(
        dataclasses.replace(cfg, converge_tol=0.0), iters=ITERS,
        adaptive=True)
    adapt = make_eval_step(cfg, iters=ITERS, adaptive=True)  # tol 0.02

    rng = np.random.default_rng(0)

    def frame(seed):
        r = np.random.default_rng(seed)
        return r.uniform(0, 255, (H, W, 3)).astype(np.float32)

    del rng
    return dict(cfg=cfg, variables=variables, damped=damped,
                fixed=fixed, adapt0=adapt0, adapt=adapt, frame=frame)


def _get(x):
    import jax

    return jax.device_get(x)


# ---- model: parity, clamp, freeze ---------------------------------------


class TestAdaptiveRefine:
    def test_tol_zero_full_budget_bit_exact_vs_scan(self, setup):
        a, b = setup["frame"](1)[None], setup["frame"](2)[None]
        low_f, up_f = setup["fixed"](setup["variables"], a, b)
        low_a, up_a, iu, fd = setup["adapt0"](
            setup["variables"], a, b, iter_budget=np.int32(ITERS))
        # strict `dn < tol` with tol=0 NEVER fires: every item runs the
        # full budget and the while_loop must reproduce the scan's
        # arithmetic exactly — parity is the correctness anchor the
        # whole perf win hangs off
        assert np.array_equal(_get(up_f), _get(up_a))
        assert np.array_equal(_get(low_f), _get(low_a))
        assert _get(iu).tolist() == [ITERS]
        assert float(_get(fd)[0]) > 0.0

    def test_budget_clamped_to_configured_iters(self, setup):
        a, b = setup["frame"](1)[None], setup["frame"](2)[None]
        _, up_full, iu_full, _ = setup["adapt0"](
            setup["variables"], a, b, iter_budget=np.int32(ITERS))
        _, up_hi, iu_hi, _ = setup["adapt0"](
            setup["variables"], a, b, iter_budget=np.int32(100))
        assert _get(iu_hi).tolist() == [ITERS]   # clamped, not overrun
        assert np.array_equal(_get(up_full), _get(up_hi))

    def test_partial_budget_runs_exactly_budget_iters(self, setup):
        a, b = setup["frame"](1)[None], setup["frame"](2)[None]
        _, up2, iu, _ = setup["adapt0"](
            setup["variables"], a, b, iter_budget=np.int32(2))
        assert _get(iu).tolist() == [2]
        _, up4, _, _ = setup["adapt0"](
            setup["variables"], a, b, iter_budget=np.int32(ITERS))
        # fewer refinement steps = a genuinely different flow
        assert not np.array_equal(_get(up2), _get(up4))

    def test_converged_item_freezes_early(self, setup):
        # damped params converge below tol=0.02 after one update (the
        # measured plateau is ~4e-5) — the gate must stop the loop and
        # leave the flow exactly where iteration 1 put it
        a, b = setup["frame"](1)[None], setup["frame"](2)[None]
        _, up, iu, fd = setup["adapt"](
            setup["damped"], a, b, iter_budget=np.int32(ITERS))
        used = int(_get(iu)[0])
        assert used < ITERS, "early exit never fired"
        assert float(_get(fd)[0]) < setup["cfg"].converge_tol
        _, up_ref, _, _ = setup["adapt0"](
            setup["damped"], a, b, iter_budget=np.int32(used))
        np.testing.assert_allclose(_get(up), _get(up_ref),
                                   rtol=0, atol=1e-6)

    def test_mixed_batch_rows_freeze_independently(self, setup):
        # per-row done mask: batching two items must reproduce each
        # item's solo convergence (iterations applied AND flow) — a
        # leaked freeze mask would let a done row keep integrating or
        # stop its neighbor
        f1, f2 = setup["frame"](1), setup["frame"](2)
        f3, f4 = setup["frame"](3), setup["frame"](4)
        solo = [setup["adapt"](setup["damped"], x[None], y[None],
                               iter_budget=np.int32(ITERS))
                for x, y in ((f1, f2), (f3, f4))]
        _, up_b, iu_b, fd_b = setup["adapt"](
            setup["damped"], np.stack([f1, f3]), np.stack([f2, f4]),
            iter_budget=np.int32(ITERS))
        for row in range(2):
            _, up_s, iu_s, fd_s = solo[row]
            assert int(_get(iu_b)[row]) == int(_get(iu_s)[0])
            np.testing.assert_allclose(float(_get(fd_b)[row]),
                                       float(_get(fd_s)[0]), atol=1e-6)
            np.testing.assert_allclose(_get(up_b)[row], _get(up_s)[0],
                                       rtol=0, atol=1e-4)

    def test_config_rejects_negative_tol(self):
        from dataclasses import replace

        from dexiraft_tpu.config import raft_v1

        with pytest.raises(ValueError):
            replace(raft_v1(small=True), converge_tol=-0.1)


# ---- engine/scheduler/service: numpy stub, no jax -----------------------


_FULL = 8


def _stub_fixed(im1, im2, flow_init=None):
    b, h, w = im1.shape[:3]
    up = np.broadcast_to(np.float32([2.0, -1.0]), (b, h, w, 2)).copy()
    low = np.zeros((b, h // 8, w // 8, 2), np.float32)
    return low, up


def _stub_adaptive(im1, im2, flow_init=None, iter_budget=None):
    low, up = _stub_fixed(im1, im2, flow_init)
    b = im1.shape[0]
    n = _FULL if iter_budget is None else int(iter_budget)
    return (low, up, np.full((b,), n, np.int32),
            np.full((b,), 1e-4, np.float32))


def _item(seed=0):
    rng = np.random.default_rng(seed)
    return {"image1": rng.uniform(0, 255, (H, W, 3)).astype(np.float32),
            "image2": rng.uniform(0, 255, (H, W, 3)).astype(np.float32)}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestAdaptiveEngine:
    def test_results_carry_convergence_evidence(self):
        eng = InferenceEngine(_stub_adaptive,
                              ServeConfig(batch_size=2, adaptive=True))
        r1, r2 = eng.run_batch([_item(), _item(1)])
        assert r1.iters_used == _FULL and r2.iters_used == _FULL
        assert abs(r1.final_delta - 1e-4) < 1e-9
        (r3,) = eng.run_batch([_item()], iter_budget=3)
        assert r3.iters_used == 3
        rec = eng.stats_record()
        assert rec["adaptive"] is True
        assert rec["iters_used_mean"] > 0
        assert {"iters_used_p50", "iters_used_p99", "final_delta_p50",
                "final_delta_p99"} <= set(rec)

    def test_fixed_engine_refuses_budget_and_stays_schema_clean(self):
        eng = InferenceEngine(_stub_fixed, ServeConfig(batch_size=1))
        with pytest.raises(ValueError):
            eng.run_batch([_item()], iter_budget=4)
        (r,) = eng.run_batch([_item()])
        assert r.iters_used is None and r.final_delta is None
        # fixed-path stats are byte-identical to pre-adaptive records
        assert "adaptive" not in eng.stats_record()

    def test_stream_threads_budget_through(self):
        eng = InferenceEngine(_stub_adaptive,
                              ServeConfig(batch_size=2, adaptive=True))
        out = list(eng.stream([_item(i) for i in range(4)], iter_budget=5))
        assert [r.iters_used for r in out] == [5] * 4


class TestBudgetPolicy:
    def _sched(self, clock, calls, **kw):
        def timed(im1, im2, flow_init=None, iter_budget=None):
            calls.append(None if iter_budget is None else int(iter_budget))
            clock.advance(0.07)   # measured service time, fake-clock
            return _stub_adaptive(im1, im2, flow_init, iter_budget)

        eng = InferenceEngine(timed,
                              ServeConfig(batch_size=1, adaptive=True))
        kw.setdefault("slo_ms", 100.0)
        kw.setdefault("max_queue", 8)
        return Scheduler(eng, adaptive=True, max_iters=_FULL, min_iters=2,
                         clock=clock, **kw)

    def test_unlearned_bucket_runs_full_depth(self):
        clock, calls = FakeClock(), []
        s = self._sched(clock, calls)
        s.submit_async(_item())
        assert s.poll_once()
        # no per-iteration estimate yet: degrading on a guess would
        # teach the EWMA a degraded cost forever
        assert calls == [_FULL]

    def test_slo_exhausted_head_floors_at_min_iters(self):
        clock, calls = FakeClock(), []
        s = self._sched(clock, calls)
        s.submit_async(_item())
        assert s.poll_once()                  # learn ~8.75 ms/iter
        s.submit_async(_item())
        clock.advance(0.095)                  # 95 of the 100 ms burned
        assert s.poll_once()
        assert calls[-1] == 2                 # the min_iters floor holds

    def test_queue_pressure_degrades_smoothly(self):
        clock, calls = FakeClock(), []
        s = self._sched(clock, calls)
        s.submit_async(_item())
        assert s.poll_once()                  # learn the estimate
        for i in range(4):                    # pending=4 of max_queue=8
            s.submit_async(_item(i))
        assert s.poll_once()
        # between the floor and full depth: the soft valve, not a cliff
        assert 2 < calls[-1] < _FULL
        rec = s.stats_record()
        assert rec["adaptive"] is True
        assert rec["min_iters"] == 2 and rec["max_iters"] == _FULL
        assert {"iter_budget_p50", "iter_budget_p99",
                "iter_est_ms"} <= set(rec)

    def test_adaptive_scheduler_needs_adaptive_engine(self):
        eng = InferenceEngine(_stub_fixed, ServeConfig(batch_size=1))
        with pytest.raises(ValueError):
            Scheduler(eng, adaptive=True, clock=FakeClock())
        eng_a = InferenceEngine(_stub_adaptive,
                                ServeConfig(batch_size=1, adaptive=True))
        with pytest.raises(ValueError):
            Scheduler(eng_a, adaptive=True, max_iters=4, min_iters=9,
                      clock=FakeClock())

    def test_fixed_scheduler_schema_unchanged(self):
        eng = InferenceEngine(_stub_fixed, ServeConfig(batch_size=1))
        rec = Scheduler(eng, clock=FakeClock()).stats_record()
        assert "adaptive" not in rec and "iter_budget_p50" not in rec


class TestServiceWire:
    def test_headers_and_stats_expose_convergence(self):
        svc = FlowService(
            InferenceEngine(_stub_adaptive,
                            ServeConfig(batch_size=1, adaptive=True)),
            port=0, slo_ms=50.0, max_queue=8, session_ttl_s=0.0,
            max_iters=_FULL, min_iters=2).start()
        try:
            body = encode_request(**_item())
            req = urllib.request.Request(
                svc.url + "/v1/flow", data=body,
                headers={"Content-Type": "application/x-npz"})
            with urllib.request.urlopen(req, timeout=10.0) as r:
                assert r.status == 200
                hdr = dict(r.headers)
                r.read()
            assert int(hdr["X-Iters-Used"]) == _FULL
            assert abs(float(hdr["X-Final-Delta"]) - 1e-4) < 1e-6
            with urllib.request.urlopen(svc.url + "/stats",
                                        timeout=10.0) as r:
                stats = json.load(r)
            assert stats["service"]["adaptive"] is True
            assert stats["engine"]["adaptive"] is True
            assert stats["engine"]["iters_used_mean"] == float(_FULL)
            assert stats["scheduler"]["adaptive"] is True
            assert stats["scheduler"]["max_iters"] == _FULL
        finally:
            svc.drain_and_stop(timeout=10.0)


# ---- compile discipline: one executable serves every budget -------------


class TestCompileFlat:
    def test_budget_change_is_not_a_recompile(self, setup):
        # the serve_cli --warmup contract (satellite): after the warmup
        # dispatch, a dispatch at a DIFFERENT budget must ride the same
        # executable — the budget is a traced int32 scalar, so a --strict
        # boot would fail loudly if it ever re-specialized
        import jax

        step = setup["adapt"]
        variables = setup["damped"]

        def eval_fn(a, b, fi, ib=None):
            put = jax.device_put
            return step(variables, put(a), put(b),
                        flow_init=None if fi is None else put(fi),
                        iter_budget=np.int32(ITERS if ib is None else ib))

        eng = InferenceEngine(
            eval_fn, ServeConfig(batch_size=1, bucket_multiple=8,
                                 adaptive=True))
        (r1,) = eng.run_batch([_item()])            # warmup, baseline set
        (r2,) = eng.run_batch([_item()], iter_budget=1)
        (r3,) = eng.run_batch([_item()], iter_budget=3)
        eng.watch.check()                           # raises on drift
        assert eng.registry.compiles == 1
        assert r1.iters_used is not None
        assert r2.iters_used is not None and r2.iters_used <= 1


# ---- record schemas + watchdog stderr hygiene ---------------------------


def test_adaptive_bench_record_schema_pinned():
    sys.path.insert(0, osp.join(REPO, "scripts"))
    try:
        from serve_bench import (ADAPTIVE_OVERLOAD_KEYS,
                                 ADAPTIVE_RECORD_KEYS, OVERLOAD_KEYS)
    finally:
        sys.path.pop(0)
    assert {"metric", "converge_tol", "min_iters", "epe_vs_fixed_px",
            "mean_iters_used", "p99_iters_used", "iters_drop_pct",
            "mean_final_delta", "fixed_ms_per_pair",
            "adaptive_ms_per_pair", "overload_fixed", "overload_adaptive",
            "overload_goodput_ratio"} <= ADAPTIVE_RECORD_KEYS
    assert OVERLOAD_KEYS < ADAPTIVE_OVERLOAD_KEYS
    assert {"iter_budget_p50", "iter_budget_p99",
            "iters_used_mean"} <= ADAPTIVE_OVERLOAD_KEYS


def test_frontier_record_schema_pinned():
    from dexiraft_tpu.eval_cli import (FRONTIER_LEG_KEYS,
                                       FRONTIER_RECORD_KEYS)

    assert FRONTIER_RECORD_KEYS == {"record", "dataset", "iters",
                                    "converge_tol", "fixed", "sweep"}
    assert {"budget", "wall_s", "mean_iters_used", "p99_iters_used",
            "mean_final_delta"} <= FRONTIER_LEG_KEYS


def test_stderr_filter_diverts_xla_host_warning(tmp_path):
    sys.path.insert(0, REPO)
    try:
        from bench import XLA_HOST_WARNING_MARKER, make_stderr_filter
    finally:
        sys.path.pop(0)
    log = tmp_path / "xla_warn.log"
    filt = make_stderr_filter(log_path=str(log), tag="t")
    assert filt(b"ordinary progress line\n") == b"ordinary progress line\n"
    warn = b"W000 cpu_client.cc] " + XLA_HOST_WARNING_MARKER + b".\n"
    note = filt(warn)
    assert note is not None and b"suppressed" in note
    assert XLA_HOST_WARNING_MARKER not in note     # tail stays clean
    assert filt(warn) is None                      # repeats vanish
    assert warn in log.read_bytes()                # full text preserved
    # the record line the driver greps must always pass through
    rec = b'{"metric": "serve_adaptive"}\n'
    assert filt(rec) == rec
