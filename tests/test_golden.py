"""Golden-value regression: frozen-PRNG forward checksums.

The torch-parity tests (test_torch_interop.py) require the reference
repo mounted; these goldens guard the model math standalone. Values
recorded on the CPU backend with PRNGKey(0) init and a deterministic
ramp input; loose rtol absorbs cross-version XLA fusion differences
while still catching any real change to the forward semantics (a wrong
window ordering, a dropped stream, a changed update rule all shift
these sums by orders more than 1e-2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.config import raft_v1, raft_v2, raft_v5
from dexiraft_tpu.models.raft import RAFT

GOLDEN = {
    # name: (|flow_up| sum, |flow_low| sum) at iters=4, 48x64 ramp input.
    # Regenerated 2026-08 on this container's CPU backend (jax 0.4.37):
    # the seed-era values came from a different jax/flax build whose
    # PRNG fold-in and init orders differ, so every parametrization had
    # failed tier-1 since the seed tree. The guard property is
    # unchanged — any real change to the forward semantics (window
    # ordering, dropped stream, update rule) moves these sums by orders
    # more than the 1e-2 rtol.
    "v1_small": (86368.0, 162.9525),
    "v1": (51996.5, 127.7661),
    "v2": (56658.2, 135.0296),
    "v5": (95791.4, 239.4710),
}


def _forward(cfg, with_edges):
    model = RAFT(cfg)
    img = jnp.asarray(
        np.linspace(0, 255, 1 * 48 * 64 * 3, dtype=np.float32)
        .reshape(1, 48, 64, 3))
    img2 = img[:, :, ::-1, :]
    kw = dict(edges1=img / 2, edges2=img2 / 2) if with_edges else {}
    v = model.init(jax.random.PRNGKey(0), img, img2, iters=1,
                   train=False, **kw)
    low, up = model.apply(v, img, img2, iters=4, train=False,
                          test_mode=True, **kw)
    return float(jnp.sum(jnp.abs(up))), float(jnp.sum(jnp.abs(low)))


@pytest.mark.parametrize("name,cfg,with_edges", [
    ("v1_small", raft_v1(small=True), False),
    ("v1", raft_v1(), False),
    ("v2", raft_v2(), True),
    ("v5", raft_v5(), False),
])
def test_forward_matches_golden(name, cfg, with_edges):
    up, low = _forward(cfg, with_edges)
    g_up, g_low = GOLDEN[name]
    np.testing.assert_allclose(up, g_up, rtol=1e-2)
    np.testing.assert_allclose(low, g_low, rtol=1e-2)
