"""One virtual host of the multi-host resilience test (kill-one-host /
one-host-poison / coordinated resume).

Spawned (not imported) by tests/test_zzmultihost_resilience.py and by
scripts/chaos_smoke.py, twice per scenario: each child owns 2 virtual
CPU devices, joins its peer over jax.distributed, and runs a tiny but
REAL resilient train loop — the same primitives train_cli wires:
async checkpoint saves with wait_pending barriers (train.checkpoint),
host-consensus verdicts (resilience.coord), verified agreed restore
(resilience.verify), and the hang watchdog (resilience.watchdog).

The model is deliberately tiny (one dense matrix, SGD): the scenarios
pin COORDINATION semantics — same rollback step on every host, a dead
peer bounded by the watchdog instead of a hung collective, bit-exact
resume from the agreed step — not model numerics, and the suite's
870 s budget cannot afford a RAFT compile per child here.

Each host runs the step REPLICATED (full global batch, locally): this
container's CPU backend implements no cross-process XLA at all
("Multiprocess computations aren't implemented"), so the sharded-step
half of the multi-host story lives in tests/test_multiprocess.py (and
on real hardware), while THESE scenarios pin everything that is
host-side — consensus, async checkpointing through orbax's real
multiprocess path (via _mp_common.patch_orbax_kv_barriers), verified
agreed restore, and the watchdog. Replicated compute is exactly what
those layers see on a pod anyway: identical state, identical verdicts.

Fault injection:
  --poison_step N --poison_host K   host K's LOCAL verdict says
      poisoned after step N (a host-local fault by construction: the
      loss itself is replicated, so only a local verdict can prove the
      consensus path) — every host must roll back to the same step.
  --die_step N --die_host K         host K os._exit(3)s after step N;
      the survivor must exit nonzero via watchdog/collective error,
      never hang.

Any exception exits via os._exit(97): atexit would otherwise run the
checkpoint barrier against a dead peer and hang the "no hang" test.
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys
import traceback

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

GLOBAL_BATCH = 8
FEATURES = 16
COLLECTIVE_ERROR_EXIT = 97


def global_batch(step: int):
    """Deterministic pure function of the GLOBAL step index — the
    bit-exact-resume property needs nothing else."""
    r = np.random.default_rng(900 + step)
    x = r.normal(size=(GLOBAL_BATCH, FEATURES)).astype(np.float32)
    y = r.normal(size=(GLOBAL_BATCH, FEATURES)).astype(np.float32)
    return x, y


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--out", required=True)
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--num_steps", type=int, default=8)
    ap.add_argument("--save_every", type=int, default=2)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--poison_step", type=int, default=None)
    ap.add_argument("--poison_host", type=int, default=0)
    ap.add_argument("--die_step", type=int, default=None)
    ap.add_argument("--die_host", type=int, default=1)
    ap.add_argument("--stall_timeout", type=float, default=25.0)
    args = ap.parse_args()

    from dexiraft_tpu.parallel.distributed import initialize

    initialize(coordinator_address=f"127.0.0.1:{args.port}",
               num_processes=args.num_processes,
               process_id=args.process_id)
    pid = jax.process_index()

    import optax

    from tests._mp_common import patch_orbax_kv_barriers
    from dexiraft_tpu.resilience import Coordinator, HangWatchdog, \
        restore_verified
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import TrainState

    # the CPU backend has no XLA process sync; orbax's real multiprocess
    # barriers ride the coordination service instead (see _mp_common)
    patch_orbax_kv_barriers()

    tx = optax.sgd(0.05)
    w0 = np.asarray(
        jax.random.normal(jax.random.PRNGKey(7), (FEATURES, FEATURES)),
        np.float32)
    params = {"w": jnp.asarray(w0)}
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       batch_stats={}, opt_state=tx.init(params),
                       rng=jax.random.PRNGKey(0))

    @jax.jit
    def step_fn(state, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), loss

    coord = Coordinator()
    wd = HangWatchdog(args.stall_timeout, label=f"mpchild{pid}").start()
    coord.warmup()

    start = 0
    last_saved = None
    events = []
    if args.resume:
        # agreed resume: every host lands on the SAME verified step
        state, start = coord.agree_step(
            lambda b: restore_verified(args.ckpt_dir, state, step=b,
                                       verbose=False,
                                       clean_debris=True), None)
        last_saved = start
        events.append({"resumed": start})

    losses = []
    for step in range(start + 1, args.num_steps + 1):
        wd.arm(step)
        x, y = global_batch(step)
        state, loss = step_fn(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(jax.device_get(loss)))

        if args.die_step is not None and step == args.die_step \
                and pid == args.die_host:
            print(f"[chaos] host {pid} dying at step {step}",
                  flush=True)
            os._exit(3)

        # host-LOCAL poison verdict -> collective decision
        poisoned_here = (args.poison_step is not None
                         and step == args.poison_step
                         and pid == args.poison_host)
        if coord.any_flag(poisoned_here):
            agreed = coord.min_int(
                last_saved if last_saved is not None else -1)
            target = None if agreed < 0 else agreed
            state, restored = coord.agree_step(
                lambda b: restore_verified(args.ckpt_dir, state,
                                           step=b, verbose=False,
                                           clean_debris=True),
                target)
            last_saved = restored
            events.append({"rollback_at": step, "restored": restored,
                           "poisoned_here": bool(poisoned_here)})
        elif step % args.save_every == 0:
            # async save: the flush overlaps the following steps;
            # the next save (or exit) takes the barrier
            ckpt.save_checkpoint(args.ckpt_dir, state, step=step,
                                 block=False)
            last_saved = step
        wd.disarm()

    info = ckpt.wait_pending(args.ckpt_dir)  # exit barrier
    wd.stop()
    norm = float(np.sqrt(sum(
        float(np.sum(np.asarray(x) ** 2))
        for x in jax.tree.leaves(jax.device_get(state.params)))))
    result = {
        "process_id": pid,
        "losses": losses,
        "events": events,
        "param_norm": norm,
        "final_w": np.asarray(jax.device_get(state.params["w"])).tolist(),
        "saved_steps": ckpt.all_steps(args.ckpt_dir),
        "last_flush": None if info is None else
            {k: info[k] for k in ("step", "blocked_s", "flush_s")},
    }
    with open(args.out, "w") as f:
        json.dump(result, f)
    print("child done", json.dumps(result)[:160], flush=True)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException:
        # never let atexit (checkpoint barrier against a possibly dead
        # peer) turn an error into a hang — report and leave hard
        traceback.print_exc()
        sys.stderr.flush()
        os._exit(COLLECTIVE_ERROR_EXIT)
