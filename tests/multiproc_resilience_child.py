"""One virtual host of the multi-host resilience test (kill-one-host /
one-host-poison / coordinated resume).

Spawned (not imported) by tests/test_zzmultihost_resilience.py and by
scripts/chaos_smoke.py, twice per scenario: each child owns 2 virtual
CPU devices, joins its peer over jax.distributed, and runs a tiny but
REAL resilient train loop — the same primitives train_cli wires:
async checkpoint saves with wait_pending barriers (train.checkpoint),
host-consensus verdicts (resilience.coord), verified agreed restore
(resilience.verify), and the hang watchdog (resilience.watchdog).

The model is deliberately tiny (one dense matrix, SGD): the scenarios
pin COORDINATION semantics — same rollback step on every host, a dead
peer bounded by the watchdog instead of a hung collective, bit-exact
resume from the agreed step — not model numerics, and the suite's
870 s budget cannot afford a RAFT compile per child here.

Each host runs the step REPLICATED (full global batch, locally): this
container's CPU backend implements no cross-process XLA at all
("Multiprocess computations aren't implemented"), so the sharded-step
half of the multi-host story lives in tests/test_multiprocess.py (and
on real hardware), while THESE scenarios pin everything that is
host-side — consensus, async checkpointing through orbax's real
multiprocess path (via _mp_common.patch_orbax_kv_barriers), verified
agreed restore, and the watchdog. Replicated compute is exactly what
those layers see on a pod anyway: identical state, identical verdicts.

Fault injection:
  --poison_step N --poison_host K   host K's LOCAL verdict says
      poisoned after step N (a host-local fault by construction: the
      loss itself is replicated, so only a local verdict can prove the
      consensus path) — every host must roll back to the same step.
  --die_step N --die_host K         host K os._exit(3)s after step N;
      the survivor must exit nonzero via watchdog/collective error,
      never hang.
  --diverge_step N --diverge_host K host K issues an EXTRA collective
      (a min_int round) at step N that its peer never runs — the
      collective flight recorder's in-band lockstep check must raise
      CollectiveDivergence naming the first divergent (host, round,
      op) on both sides, in seconds, NOT a CoordinatorTimeout after
      the full timeout window.

Elastic mode (--elastic): the same faults, a different contract — the
survivor CONTINUES instead of exiting. The child then runs the full
membership runtime (resilience.membership): heartbeat leases, epoch
reconfiguration, dead-peer-safe runtime teardown/re-init, and a
per-world MESH POLICY chosen so reconfiguration genuinely crosses mesh
shapes on this backend: a pair keeps state replicated (the CPU backend
has no cross-process XLA), a solo world shards the 80x80 elastic model
fsdp=2 over its two virtual devices — so the shrink restore lands a
replicated checkpoint on an fsdp template and the grow restore does
the reverse (the PR 13 template-resharding mechanic, exercised across
real world changes). --join NAME makes the child a replacement host:
it posts a join intent on the FileBoard and enters the world at the
epoch the incumbents announce. Alongside each step's loss the elastic
child records the epoch_permutation slice its world assigns it
(epoch/offset/ids), which is what pins the re-slice contract in the
parent test.

Any exception exits via os._exit(97): atexit would otherwise run the
checkpoint barrier against a dead peer and hang the "no hang" test.
ElasticFallback exits 98 — the watchdog's restart-the-pod contract.
"""

from __future__ import annotations

import argparse
import json
import os
import os.path as osp
import sys
import time
import traceback

sys.path.insert(0, osp.dirname(osp.dirname(osp.abspath(__file__))))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

GLOBAL_BATCH = 8
FEATURES = 16
COLLECTIVE_ERROR_EXIT = 97

# elastic-mode geometry: the matrix must clear layout's
# FSDP_MIN_LEAF_SIZE (4096) so the solo world's fsdp=2 mesh actually
# shards it, and the virtual dataset must give a few global batches per
# epoch so the re-slice records cross an epoch boundary
E_FEATURES = 80
E_DATASET_N = 32
E_SEED = 7
E_BATCHES_PER_EPOCH = E_DATASET_N // GLOBAL_BATCH


def global_batch(step: int):
    """Deterministic pure function of the GLOBAL step index — the
    bit-exact-resume property needs nothing else."""
    r = np.random.default_rng(900 + step)
    x = r.normal(size=(GLOBAL_BATCH, FEATURES)).astype(np.float32)
    y = r.normal(size=(GLOBAL_BATCH, FEATURES)).astype(np.float32)
    return x, y


def elastic_batch(step: int):
    """Elastic-mode batch: same purity contract, E_FEATURES-wide."""
    r = np.random.default_rng(1700 + step)
    x = r.normal(size=(GLOBAL_BATCH, E_FEATURES)).astype(np.float32)
    y = r.normal(size=(GLOBAL_BATCH, E_FEATURES)).astype(np.float32)
    return x, y


def slice_record(pos, size: int, index: int) -> dict:
    """The data slice THIS member would decode at stream position
    ``pos`` in a ``size``-member world — the epoch_permutation re-slice
    contract (data.loader), recorded per step so the parent test can
    assert disjoint+exhaustive coverage across world changes."""
    from dexiraft_tpu.data.loader import epoch_permutation

    order = epoch_permutation(E_SEED, pos.epoch, E_DATASET_N)
    lo = pos.offset * GLOBAL_BATCH
    window = order[lo:lo + GLOBAL_BATCH]
    local = GLOBAL_BATCH // size
    mine = window[index * local:(index + 1) * local]
    return {"epoch": int(pos.epoch), "offset": int(pos.offset),
            "size": size, "ids": [int(i) for i in mine]}


def run_elastic(args) -> None:
    """The elastic-membership child: one member (or joiner) of an
    epoch-numbered world. See module docstring for the mesh policy and
    what each scenario proves."""
    import optax

    from tests._mp_common import patch_orbax_kv_barriers
    from dexiraft_tpu.data.loader import world_compatible
    from dexiraft_tpu.parallel import layout
    from dexiraft_tpu.resilience import (
        Coordinator,
        CoordinatorTimeout,
        ElasticConfig,
        ElasticFallback,
        HangWatchdog,
        MembershipRuntime,
        ReconfigureNeeded,
        StreamPosition,
        load_position,
        prune_steps_above,
        restore_verified,
        save_position,
    )
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import TrainState

    # cap the orbax barrier timeout well under the reconfiguration
    # budget: a flush barrier against a dead peer must fail fast, or a
    # wedged flush pins the next boundary's wait_pending for orbax's
    # default 300 s and the membership verdict never gets control
    patch_orbax_kv_barriers(cap_timeout_s=6.0)
    cfg = ElasticConfig(
        host="127.0.0.1",
        board_dir=osp.join(args.ckpt_dir, "membership"),
        min_hosts=args.min_hosts,
        global_batch=GLOBAL_BATCH,
        lease_interval_s=0.25,
        lease_timeout_s=2.0,
        probe_timeout_s=0.5,
        reconfig_timeout_s=15.0,
        join_poll_s=0.2,
    )
    mrt = MembershipRuntime(cfg)
    if args.join:
        info = mrt.join(args.join)
    else:
        info = mrt.bootstrap(f"127.0.0.1:{args.port}",
                             args.num_processes, args.process_id)
    orig_pid = args.process_id
    tx = optax.sgd(0.05)

    def build_world():
        """Mesh + fresh template state + per-epoch Coordinator for the
        CURRENT world (called after every epoch install)."""
        mesh = (layout.make_train_mesh(GLOBAL_BATCH, fsdp=2)
                if mrt.size == 1 else None)
        w0 = np.asarray(
            jax.random.normal(jax.random.PRNGKey(7),
                              (E_FEATURES, E_FEATURES)), np.float32)
        params = {"w": jnp.asarray(w0)}
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           batch_stats={}, opt_state=tx.init(params),
                           rng=jax.random.PRNGKey(0))
        if mesh is not None:
            state = layout.shard_state(state, mesh)
        coord = Coordinator(namespace=mrt.coord_namespace(),
                            timeout_s=args.coord_timeout)
        return mesh, state, coord

    @jax.jit
    def step_fn(state, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state,
                                       state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), loss

    mesh, state, coord = build_world()
    wd = HangWatchdog(args.stall_timeout,
                      label=f"mpchild{orig_pid}").start()
    wd.on_stall = mrt.notify_stall
    coord.warmup()

    events = []
    losses = {}
    slices = {}
    start = 0
    pos = StreamPosition(0, 0)
    last_saved = None

    def agreed_restore(bound):
        nonlocal state, start, pos, last_saved
        state, start = coord.agree_step(
            lambda b: restore_verified(args.ckpt_dir, state, step=b,
                                       verbose=False, clean_debris=True),
            bound)
        pos = load_position(args.ckpt_dir, start) or StreamPosition(0, 0)
        last_saved = start
        return start

    if args.resume or args.join:
        bound = args.resume_bound if args.resume_bound >= 0 else None
        agreed_restore(bound)
        events.append({"resumed": start, "epoch": mrt.epoch})

    step = start
    while step < args.num_steps:
        try:
            step += 1
            wd.arm(step)
            mrt.poll()
            x, y = elastic_batch(step)
            state, loss = step_fn(state, jnp.asarray(x), jnp.asarray(y))
            losses[str(step)] = float(jax.device_get(loss))
            slices[str(step)] = slice_record(pos, mrt.size, mrt.index)
            pos = pos.advance(1, E_BATCHES_PER_EPOCH)

            if args.die_step is not None and step == args.die_step \
                    and orig_pid == args.die_host:
                # drain this host's own flush first: the commit barrier
                # rendezvoused, so the survivor's copy of the last save
                # is committed too — the parity assertion needs the
                # agreed restore step to be deterministic, not a race
                # between the flush threads and os._exit
                ckpt.wait_pending(args.ckpt_dir)
                print(f"[chaos] host {orig_pid} dying at step {step}",
                      flush=True)
                os._exit(3)

            if args.save_every and step % args.save_every == 0:
                ckpt.save_checkpoint(args.ckpt_dir, state, step=step,
                                     block=False)
                save_position(args.ckpt_dir, step, pos, seed=E_SEED)
                last_saved = step
                if args.wait_join_at == step:
                    # test determinism only: absorb at THIS boundary,
                    # so block until the joiner's intent is posted
                    deadline = time.monotonic() + 120.0
                    while not mrt.pending_joins() \
                            and time.monotonic() < deadline:
                        time.sleep(0.2)
                # checkpoint boundary: absorb pending joiners — a
                # collective decision, so every incumbent grows at the
                # same boundary. Poll first: a suspect flagged while
                # this step computed turns into the (better-attributed)
                # ReconfigureNeeded instead of a CoordinatorTimeout.
                mrt.poll()
                if coord.any_flag(bool(mrt.pending_joins())):
                    wd.arm(step, "grow-reconfigure", steady=False)
                    ckpt.wait_pending(args.ckpt_dir)
                    info = mrt.absorb_joins()
                    mesh, state, coord = build_world()
                    coord.warmup()
                    agreed_restore(None)
                    wd.reset_stall_handoff()
                    step = start
                    events.append({"grew_to": mrt.size,
                                   "epoch": mrt.epoch,
                                   "restored": start})
            wd.disarm()
        except (ReconfigureNeeded, CoordinatorTimeout) as verdict:
            wd.disarm(feed_ewma=False)
            wd.arm(step, "shrink-reconfigure", steady=False)
            events.append({"verdict": type(verdict).__name__,
                           "detail": str(verdict)[:200], "at_step": step})
            info = mrt.reconfigure(dead=getattr(verdict, "dead", None))
            reason = world_compatible(GLOBAL_BATCH, info.size)
            if reason is not None:  # pre-checked by config; belt+braces
                raise ElasticFallback(reason)
            mesh, state, coord = build_world()
            coord.warmup()
            agreed_restore(None)
            # a zombie flush from the old world must not leave steps
            # above the agreement for a later restore to land on
            prune_steps_above(args.ckpt_dir, start, verbose=False)
            wd.reset_stall_handoff()
            wd.disarm()
            step = start
            events.append({"reconfigured": mrt.epoch, "size": mrt.size,
                           "restored": start,
                           "recovery_s": mrt.events[-1]["recovery_s"]})

    if args.save_every:
        ckpt.wait_pending(args.ckpt_dir)
    mrt.close()
    wd.stop()
    norm = float(np.sqrt(sum(
        float(np.sum(np.asarray(jax.device_get(x)) ** 2))
        for x in jax.tree.leaves(state.params))))
    try:
        saved = sorted(int(n) for n in os.listdir(args.ckpt_dir)
                       if n.isdigit())
    except OSError:
        saved = []
    from dexiraft_tpu.analysis import collective_trace, locks

    lrec = locks.stats_record()
    result = {
        "process_id": orig_pid,
        "mode": "elastic",
        # THIS process ran the lease thread + flush executor + watchdog
        # lock fabric through a real reconfiguration — its lock-order
        # verdict is what the chaos-smoke shrink phase pins
        "locks": {"order_violations": lrec["order_violations"],
                  "cycles": lrec["cycles"]},
        # ... and every consensus round / membership epoch / orbax
        # barrier through its flight recorder: the shrink scenario pins
        # divergences == 0 across the reconfiguration
        "collective_trace": collective_trace.recorder().snapshot(),
        "losses": losses,
        "slices": slices,
        "events": events,
        "membership_events": mrt.events,
        "final_epoch": {"epoch": mrt.epoch, "size": mrt.size,
                        "index": mrt.index},
        "param_norm": norm,
        "final_w": np.asarray(
            jax.device_get(state.params["w"])).tolist(),
        "saved_steps": saved,
    }
    with open(args.out, "w") as f:
        json.dump(result, f)
    print("child done", json.dumps(result)[:160], flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--out", required=True)
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--num_steps", type=int, default=8)
    ap.add_argument("--save_every", type=int, default=2)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--poison_step", type=int, default=None)
    ap.add_argument("--poison_host", type=int, default=0)
    ap.add_argument("--die_step", type=int, default=None)
    ap.add_argument("--die_host", type=int, default=1)
    ap.add_argument("--diverge_step", type=int, default=None,
                    help="seeded lockstep divergence: at this step the "
                         "diverge_host issues an extra min_int round "
                         "its peer never runs — the flight recorder's "
                         "in-band check must name the split, fast, "
                         "instead of a CoordinatorTimeout")
    ap.add_argument("--diverge_host", type=int, default=1)
    ap.add_argument("--stall_timeout", type=float, default=25.0)
    ap.add_argument("--elastic", action="store_true")
    ap.add_argument("--join", default=None,
                    help="join an elastic world as a replacement host "
                         "under this name (implies --elastic)")
    ap.add_argument("--min_hosts", type=int, default=1)
    ap.add_argument("--coord_timeout", type=float, default=6.0)
    ap.add_argument("--resume_bound", type=int, default=-1,
                    help="elastic resume: restore at or below this step")
    ap.add_argument("--wait_join_at", type=int, default=None,
                    help="elastic: at this save boundary, wait for a "
                         "join intent before the absorb check")
    args = ap.parse_args()

    # the flight recorder carries THIS virtual host's id before the
    # first collective (lazy install would default every child to host 0
    # and the published stamps could not be attributed)
    from dexiraft_tpu.analysis import collective_trace

    collective_trace.install(host=args.process_id)

    if args.elastic or args.join:
        from dexiraft_tpu.resilience import ElasticFallback
        from dexiraft_tpu.resilience.watchdog import STALL_EXIT_CODE

        try:
            run_elastic(args)
        except ElasticFallback as e:
            # the cases elastic cannot absorb keep the watchdog's
            # exit-98-and-restart contract
            print(f"[elastic] fallback to pod restart: {e}", flush=True)
            os._exit(STALL_EXIT_CODE)
        return

    from dexiraft_tpu.parallel.distributed import initialize

    initialize(coordinator_address=f"127.0.0.1:{args.port}",
               num_processes=args.num_processes,
               process_id=args.process_id)
    pid = jax.process_index()

    import optax

    from tests._mp_common import patch_orbax_kv_barriers
    from dexiraft_tpu.resilience import Coordinator, HangWatchdog, \
        restore_verified
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import TrainState

    # the CPU backend has no XLA process sync; orbax's real multiprocess
    # barriers ride the coordination service instead (see _mp_common)
    patch_orbax_kv_barriers()

    tx = optax.sgd(0.05)
    w0 = np.asarray(
        jax.random.normal(jax.random.PRNGKey(7), (FEATURES, FEATURES)),
        np.float32)
    params = {"w": jnp.asarray(w0)}
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       batch_stats={}, opt_state=tx.init(params),
                       rng=jax.random.PRNGKey(0))

    @jax.jit
    def step_fn(state, x, y):
        def loss_fn(p):
            return jnp.mean((x @ p["w"] - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return state.replace(step=state.step + 1, params=params,
                             opt_state=opt_state), loss

    coord = Coordinator()
    wd = HangWatchdog(args.stall_timeout, label=f"mpchild{pid}").start()
    coord.warmup()

    start = 0
    last_saved = None
    events = []
    if args.resume:
        # agreed resume: every host lands on the SAME verified step
        state, start = coord.agree_step(
            lambda b: restore_verified(args.ckpt_dir, state, step=b,
                                       verbose=False,
                                       clean_debris=True), None)
        last_saved = start
        events.append({"resumed": start})

    losses = []
    for step in range(start + 1, args.num_steps + 1):
        wd.arm(step)
        x, y = global_batch(step)
        state, loss = step_fn(state, jnp.asarray(x), jnp.asarray(y))
        losses.append(float(jax.device_get(loss)))

        if args.die_step is not None and step == args.die_step \
                and pid == args.die_host:
            print(f"[chaos] host {pid} dying at step {step}",
                  flush=True)
            os._exit(3)

        # seeded lockstep divergence: this host runs an EXTRA collective
        # its peer never issues, splitting the round sequences — the
        # stamp check must raise CollectiveDivergence naming this exact
        # (round, op) on BOTH sides, well inside the coord timeout
        if args.diverge_step is not None and step == args.diverge_step \
                and pid == args.diverge_host:
            print(f"[chaos] host {pid} diverging at step {step}: "
                  f"extra min_int round", flush=True)
            coord.min_int(0)

        # host-LOCAL poison verdict -> collective decision
        poisoned_here = (args.poison_step is not None
                         and step == args.poison_step
                         and pid == args.poison_host)
        if coord.any_flag(poisoned_here):
            agreed = coord.min_int(
                last_saved if last_saved is not None else -1)
            target = None if agreed < 0 else agreed
            state, restored = coord.agree_step(
                lambda b: restore_verified(args.ckpt_dir, state,
                                           step=b, verbose=False,
                                           clean_debris=True),
                target)
            last_saved = restored
            events.append({"rollback_at": step, "restored": restored,
                           "poisoned_here": bool(poisoned_here)})
        elif step % args.save_every == 0:
            # async save: the flush overlaps the following steps;
            # the next save (or exit) takes the barrier
            ckpt.save_checkpoint(args.ckpt_dir, state, step=step,
                                 block=False)
            last_saved = step
        wd.disarm()

    info = ckpt.wait_pending(args.ckpt_dir)  # exit barrier
    wd.stop()
    norm = float(np.sqrt(sum(
        float(np.sum(np.asarray(x) ** 2))
        for x in jax.tree.leaves(jax.device_get(state.params)))))
    from dexiraft_tpu.analysis import collective_trace

    result = {
        "process_id": pid,
        "losses": losses,
        "events": events,
        "collective_trace": collective_trace.recorder().snapshot(),
        "param_norm": norm,
        "final_w": np.asarray(jax.device_get(state.params["w"])).tolist(),
        "saved_steps": ckpt.all_steps(args.ckpt_dir),
        "last_flush": None if info is None else
            {k: info[k] for k in ("step", "blocked_s", "flush_s")},
    }
    with open(args.out, "w") as f:
        json.dump(result, f)
    print("child done", json.dumps(result)[:160], flush=True)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException:
        # never let atexit (checkpoint barrier against a possibly dead
        # peer) turn an error into a hang — report and leave hard
        traceback.print_exc()
        sys.stderr.flush()
        os._exit(COLLECTIVE_ERROR_EXIT)
