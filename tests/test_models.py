"""Model parity tests.

The strongest cheap parity signal: exact parameter-count matches against
the reference (counted from /root/reference logs line 2 and verified by
instantiating the torch modules — see BASELINE.md):

  v1 vanilla RAFT (full)           5,257,536
  v2 early fusion 6-ch             5,276,352
  v4 early fusion 10-ch + DexiNed  40,483,149
  v5 dual stream + DexiNed         42,600,909
  raft-small (v1 small)              990,162
  DexiNed alone                    35,181,709
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.config import RAFTConfig, raft_v1, raft_v2, raft_v3, raft_v4, raft_v5
from dexiraft_tpu.models import DexiNed, RAFT


def n_params(tree):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(tree))


def init_raft(cfg: RAFTConfig, h=64, w=64, with_edges=False):
    model = RAFT(cfg)
    rng = jax.random.PRNGKey(0)
    img = jnp.zeros((1, h, w, 3))
    kwargs = {}
    if with_edges:
        kwargs = dict(edges1=img, edges2=img)
    variables = model.init(rng, img, img, iters=1, **kwargs)
    return model, variables


@pytest.mark.parametrize(
    "cfg,expected",
    [
        (raft_v1(), 5_257_536),
        (raft_v2(), 5_276_352),
        (raft_v4(), 40_483_149),
        (raft_v5(), 42_600_909),
        (raft_v1(small=True), 990_162),
    ],
    ids=["v1", "v2", "v4", "v5", "small"],
)
def test_param_count_parity(cfg, expected):
    _, variables = init_raft(cfg, with_edges=cfg.variant == "early" and not cfg.embed_dexined)
    assert n_params(variables["params"]) == expected


def test_param_count_v3_corrected_refineflow():
    # reference v3 counts 5,257,541 with its buggy 4->1 RefineFlow (5 params);
    # ours is corrected to 4->2 (10 params): 5,257,546.
    _, variables = init_raft(raft_v3(), with_edges=True)
    assert n_params(variables["params"]) == 5_257_546


def test_dexined_param_count_and_shapes():
    model = DexiNed()
    x = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    assert n_params(variables["params"]) == 35_181_709

    outs = model.apply(variables, x)
    assert len(outs) == 7  # 6 scales + fused (core/DexiNed/model.py:260-268)
    for o in outs:
        assert o.shape == (1, 64, 64, 1)


def test_dexined_cofusion_head():
    # the reference's defined-but-unused CoFusion (core/DexiNed/model.py:25-47)
    # is a live option here; its output is a per-pixel convex combination of
    # the 6 scale maps, so it must lie within their pointwise min/max.
    model = DexiNed(fusion="cofusion")
    x = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    outs = model.apply(variables, x)
    assert len(outs) == 7
    scales = jnp.concatenate(outs[:6], axis=-1)
    fused = outs[6][..., 0]
    assert bool(jnp.all(fused <= scales.max(axis=-1) + 1e-5))
    assert bool(jnp.all(fused >= scales.min(axis=-1) - 1e-5))


def test_conv_transpose_matches_torch_geometry():
    torch = pytest.importorskip("torch")
    import flax.linen as nn

    from dexiraft_tpu.models.dexined import _conv_transpose_torchlike

    for up_scale, pad in [(1, 0), (2, 1), (3, 3), (4, 7)]:
        k = 2**up_scale
        t = torch.nn.ConvTranspose2d(3, 3, k, stride=2, padding=pad)
        t_out = t(torch.zeros(1, 3, 10, 10)).shape[-2:]
        m = _conv_transpose_torchlike(3, k, pad, jnp.float32)
        v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 10, 10, 3)))
        j_out = m.apply(v, jnp.zeros((1, 10, 10, 3))).shape[1:3]
        assert tuple(t_out) == tuple(j_out) == (20, 20)


def test_subpixel_conv_transpose_equivalent():
    # the phase-decomposed form is the SAME linear operator as
    # lax.conv_transpose — same params (tree and values), same outputs —
    # for every (kernel, padding) geometry DexiNed uses
    from dexiraft_tpu.models.dexined import _conv_transpose_torchlike

    for up_scale, pad in [(1, 0), (2, 1), (3, 3), (4, 7)]:
        k = 2**up_scale
        x = jax.random.normal(jax.random.PRNGKey(up_scale), (2, 9, 11, 5))
        ref = _conv_transpose_torchlike(4, k, pad, jnp.float32,
                                        name="ConvTranspose_0")
        sub = _conv_transpose_torchlike(4, k, pad, jnp.float32,
                                        impl="subpixel",
                                        name="ConvTranspose_0")
        v = ref.init(jax.random.PRNGKey(0), x)
        v2 = sub.init(jax.random.PRNGKey(0), x)
        assert jax.tree_util.tree_structure(v) == jax.tree_util.tree_structure(v2)
        out_ref = ref.apply(v, x)
        out_sub = sub.apply(v, x)  # reference params through subpixel math
        assert out_ref.shape == out_sub.shape == (2, 18, 22, 4)
        np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_sub),
                                   rtol=1e-5, atol=1e-5)


def test_subpixel_conv_transpose_grad_equivalent():
    # the standalone DexiNed CLI trains through the upsamplers, so the
    # backward pass must agree between impls too
    from dexiraft_tpu.models.dexined import _conv_transpose_torchlike

    x = jax.random.normal(jax.random.PRNGKey(2), (1, 7, 9, 3))
    ref = _conv_transpose_torchlike(2, 4, 1, jnp.float32, name="ConvTranspose_0")
    sub = _conv_transpose_torchlike(2, 4, 1, jnp.float32, impl="subpixel",
                                    name="ConvTranspose_0")
    v = ref.init(jax.random.PRNGKey(0), x)

    def loss(model, variables, inp):
        return jnp.sum(jnp.sin(model.apply(variables, inp)))

    g_ref = jax.grad(lambda vv: loss(ref, vv, x))(v)
    g_sub = jax.grad(lambda vv: loss(sub, vv, x))(v)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g_ref, g_sub)


def test_dexined_upconv_impls_equivalent():
    # whole-model check incl. checkpoint interop: variables initialized by
    # the transpose impl drive the subpixel impl to the same 7 maps
    x = jax.random.uniform(jax.random.PRNGKey(1), (1, 48, 64, 3), maxval=255.0)
    m_t = DexiNed(upconv="transpose")
    m_s = DexiNed(upconv="subpixel")
    variables = m_t.init(jax.random.PRNGKey(0), x)
    out_t = m_t.apply(variables, x)
    out_s = m_s.apply(variables, x)
    for a, b in zip(out_t, out_s):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_forward_shapes_and_test_mode():
    cfg = raft_v1(small=True)
    model, variables = init_raft(cfg)
    img = jnp.ones((2, 64, 72, 3)) * 127.0

    preds = model.apply(variables, img, img, iters=3)
    assert preds.shape == (3, 2, 64, 72, 2)

    flow_low, flow_up = model.apply(variables, img, img, iters=3, test_mode=True)
    assert flow_low.shape == (2, 8, 9, 2)
    assert flow_up.shape == (2, 64, 72, 2)
    # test-mode upsamples once after the scan; the train path upsamples
    # inside the compiled scan body — same math, different fusion, so
    # allow reassociation-level noise
    np.testing.assert_allclose(np.asarray(preds[-1]), np.asarray(flow_up),
                               rtol=1e-5, atol=1e-4)


def test_scan_unroll_identical():
    # unroll is an XLA pipelining knob: same params tree, same outputs
    cfg = raft_v1(small=True)
    model, variables = init_raft(cfg)
    from dexiraft_tpu.models.raft import RAFT

    model_u = RAFT(raft_v1(small=True, scan_unroll=4))
    img = jnp.asarray(np.random.RandomState(5).rand(1, 64, 64, 3) * 255.0)
    a = model.apply(variables, img, img, iters=6, test_mode=True)
    b = model_u.apply(variables, img, img, iters=6, test_mode=True)
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-6)


def test_forward_identical_images_small_flow():
    # identical frames => the model should keep flow near its zero init
    cfg = raft_v1(small=True)
    model, variables = init_raft(cfg)
    rng = np.random.RandomState(0)
    img = jnp.asarray(rng.rand(1, 64, 64, 3) * 255.0)
    preds = model.apply(variables, img, img, iters=4)
    assert np.isfinite(np.asarray(preds)).all()


def test_flow_init_warm_start_shifts_result():
    cfg = raft_v1(small=True)
    model, variables = init_raft(cfg)
    img = jnp.ones((1, 64, 64, 3)) * 100.0
    flow_init = jnp.ones((1, 8, 8, 2)) * 2.0
    low0, _ = model.apply(variables, img, img, iters=1, test_mode=True)
    low1, _ = model.apply(variables, img, img, iters=1, flow_init=flow_init, test_mode=True)
    # warm start must move the starting coords (core/raft.py:165-166)
    assert float(jnp.abs(low1 - low0).max()) > 0.5


def test_dual_stream_jit_and_grad():
    cfg = raft_v5(small=True)
    model, variables = init_raft(cfg)
    img = jnp.ones((1, 64, 64, 3)) * 127.0

    @jax.jit
    def run(v, a, b):
        return model.apply(v, a, b, iters=2)

    preds = run(variables, img, img)
    assert preds.shape == (2, 1, 64, 64, 2)

    # gradients must NOT flow into the frozen DexiNed (no_grad contract)
    def loss(params):
        p = model.apply({"params": params, **{k: v for k, v in variables.items() if k != "params"}},
                        img, img, iters=2)
        return jnp.abs(p).sum()

    grads = jax.grad(loss)(variables["params"])
    dexi_grad = grads["dexined"] if "dexined" in grads else grads["DexiNed_0"]
    assert max(float(jnp.abs(g).max()) for g in jax.tree_util.tree_leaves(dexi_grad)) == 0.0
    fnet_grad = grads["fnet"]
    assert max(float(jnp.abs(g).max()) for g in jax.tree_util.tree_leaves(fnet_grad)) > 0.0


def test_mixed_precision_runs_bf16():
    cfg = raft_v1(small=True, mixed_precision=True)
    model, variables = init_raft(cfg)
    img = jnp.ones((1, 64, 64, 3)) * 127.0
    preds = model.apply(variables, img, img, iters=2)
    # predictions come back fp32 (corr + coords path stays fp32)
    assert preds.dtype == jnp.float32
    assert np.isfinite(np.asarray(preds)).all()
