"""threadlint (JL020+) + OrderedLock lock-order runtime coverage.

One positive + one negative fixture per lock-discipline rule (incl.
suppression and lock-attr discovery), the OrderedLock runtime's
order-graph / cycle / rank-inversion / held-too-long semantics on a
fake clock, a seeded two-lock ABBA cycle caught at the SECOND
acquisition (not by timeout), the /stats ``locks``-block schema pin,
and the static-mirror == runtime-registry pin for LOCK_ORDER.

Named zzz to sort LAST (tier-1 budget convention); everything here is
pure-stdlib lock plumbing + AST fixtures — target well under 5 s.
"""

from __future__ import annotations

import os.path as osp
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from dexiraft_tpu.analysis import jaxlint, locks, threadlint
from dexiraft_tpu.analysis.locks import (LockOrderViolation, LockRegistry,
                                         OrderedLock)

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
GATE = osp.join(REPO, "scripts", "lint_gate.py")


def rules_of(src: str, path: str = "dexiraft_tpu/serve/fixture.py"):
    return {f.rule for f in jaxlint.lint_source(textwrap.dedent(src), path)}


# --------------------------------------------------------------------------
# static rules: one positive + one negative fixture per rule
# --------------------------------------------------------------------------


class TestRuleFixtures:
    def test_jl020_unlocked_shared_write(self):
        pos = """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.mode = "idle"

                def locked_set(self, m):
                    with self._lock:
                        self.mode = m

                def racy_set(self, m):
                    self.mode = m
        """
        assert "JL020" in rules_of(pos)
        # every write under the lock: clean
        neg = """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.mode = "idle"

                def locked_set(self, m):
                    with self._lock:
                        self.mode = m
        """
        assert "JL020" not in rules_of(neg)
        # an attr the class NEVER locks carries no contract (config
        # fields, single-thread state): not tracked, not flagged
        neg2 = """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.mode = "idle"

                def set_mode(self, m):
                    self.mode = m
        """
        assert "JL020" not in rules_of(neg2)

    def test_jl020_scopes_to_lock_owning_classes(self):
        # no lock in the class -> callers own the locking; out of reach
        neg = """
            class Plain:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
        """
        assert not {"JL020", "JL021"} & rules_of(neg)

    def test_jl021_unlocked_rmw(self):
        pos = """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1

                def undercount(self):
                    self.n += 1
        """
        assert "JL021" in rules_of(pos)
        # deque/dict mutation shapes count as RMW too
        pos2 = """
            import threading

            class Window:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.samples = []
                    self.by_key = {}

                def locked_note(self, x):
                    with self._lock:
                        self.samples.append(x)
                        self.by_key[x] = x

                def racy_note(self, x):
                    self.samples.append(x)
                    self.by_key[x] = x
        """
        assert "JL021" in rules_of(pos2)
        neg = """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1
        """
        assert "JL021" not in rules_of(neg)

    def test_jl021_resolves_the_stats_alias_idiom(self):
        """`st = self.stats; st.n += 1` is the same shared state as
        `self.stats.n += 1` — the exact spelling of the scheduler's
        dispatcher-side counter bumps."""
        pos = """
            import threading

            class Sched:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.stats = make_stats()

                def admitted(self):
                    with self._lock:
                        self.stats.submitted += 1

                def dispatched(self):
                    st = self.stats
                    st.completed += 1
        """
        assert "JL021" in rules_of(pos)

    def test_jl02x_lock_held_helper_fixpoint(self):
        """A helper whose EVERY intra-class call site holds the lock is
        lock-held (the _sweep/_note_affinity idiom) — its mutations are
        sanctioned AND establish the tracking contract."""
        neg = """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.expired = 0

                def _sweep(self):
                    self.expired += 1

                def get(self):
                    with self._lock:
                        self._sweep()

                def put(self):
                    with self._lock:
                        self._sweep()
        """
        assert "JL021" not in rules_of(neg)
        # ...but a helper ALSO called unlocked is not exempt
        pos = """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.expired = 0

                def _sweep(self):
                    self.expired += 1

                def get(self):
                    with self._lock:
                        self._sweep()

                def racy(self):
                    self._sweep()
        """
        assert "JL021" in rules_of(pos)

    def test_jl022_manual_acquire(self):
        pos = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    self._lock.acquire()
                    work()
                    self._lock.release()
        """
        assert "JL022" in rules_of(pos)
        # the sanctioned manual form: release in a finally
        neg = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def guarded(self):
                    if not self._lock.acquire(blocking=False):
                        return False
                    try:
                        work()
                    finally:
                        self._lock.release()
                    return True
        """
        assert "JL022" not in rules_of(neg)

    def test_jl023_blocking_under_lock(self):
        pos = """
            import threading
            import time

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        time.sleep(1.0)
        """
        assert "JL023" in rules_of(pos)
        # subprocess wait under the lock (the supervisor-respawn bug)
        pos2 = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.procs = {}

                def respawn(self, rid):
                    with self._lock:
                        self.procs[rid].wait(timeout=60.0)
        """
        assert "JL023" in rules_of(pos2)
        neg = """
            import threading
            import time

            class T:
                def __init__(self):
                    self._lock = threading.Lock()

                def good(self):
                    with self._lock:
                        snapshot = make()
                    time.sleep(1.0)
        """
        assert "JL023" not in rules_of(neg)

    def test_jl023_cv_wait_is_exempt(self):
        """Condition.wait RELEASES the held lock while waiting — the
        one sanctioned blocking wait under a lock (the scheduler's
        dispatch loop)."""
        neg = """
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()
                    self.pending = 0

                def loop(self):
                    with self._cv:
                        while self.pending == 0:
                            self._cv.wait(timeout=0.05)
        """
        assert "JL023" not in rules_of(neg)

    def test_jl023_str_join_not_confused_with_thread_join(self):
        neg = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.names = []

                def render(self):
                    with self._lock:
                        return ", ".join(self.names)
        """
        assert "JL023" not in rules_of(neg)
        pos = """
            import threading

            class T:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=run)

                def stop(self):
                    with self._lock:
                        self._thread.join(timeout=5)
        """
        assert "JL023" in rules_of(pos)

    def test_jl024_nested_order(self):
        # declared registry order (chunk rank < stats rank): clean —
        # and proves lock-attr discovery resolves OrderedLock names
        neg = """
            import threading

            from dexiraft_tpu.analysis.locks import OrderedLock

            class V:
                def __init__(self):
                    self._lock = OrderedLock("serve.video.chunk")
                    self._stats_lock = OrderedLock("serve.video.stats")

                def run(self):
                    with self._lock:
                        with self._stats_lock:
                            pass
        """
        assert "JL024" not in rules_of(neg)
        # inverted nesting of declared locks
        pos = """
            import threading

            from dexiraft_tpu.analysis.locks import OrderedLock

            class V:
                def __init__(self):
                    self._lock = OrderedLock("serve.video.chunk")
                    self._stats_lock = OrderedLock("serve.video.stats")

                def run(self):
                    with self._stats_lock:
                        with self._lock:
                            pass
        """
        assert "JL024" in rules_of(pos)
        # anonymous locks may not nest at all
        pos2 = """
            import threading

            class V:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def run(self):
                    with self._a:
                        with self._b:
                            pass
        """
        assert "JL024" in rules_of(pos2)
        # a name missing from the central registry
        pos3 = """
            from dexiraft_tpu.analysis.locks import OrderedLock

            class V:
                def __init__(self):
                    self._a = OrderedLock("serve.video.chunk")
                    self._b = OrderedLock("not.in.registry")

                def run(self):
                    with self._a:
                        with self._b:
                            pass
        """
        assert "JL024" in rules_of(pos3)

    def test_jl024_condition_wrapped_lock_discovered(self):
        """Condition(OrderedLock(...)) carries the inner lock's name —
        the scheduler-cv spelling."""
        neg = """
            import threading

            from dexiraft_tpu.analysis.locks import OrderedLock

            class S:
                def __init__(self):
                    self._cv = threading.Condition(
                        OrderedLock("serve.scheduler.cv", reentrant=True))
                    self._stats_lock = OrderedLock("serve.video.stats")

                def run(self):
                    with self._cv:
                        with self._stats_lock:
                            pass
        """
        assert "JL024" not in rules_of(neg)

    def test_inline_suppression(self):
        src = """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1

                def undercount(self):
                    self.n += 1  # jaxlint: disable=JL021
        """
        assert "JL021" not in rules_of(src)


# --------------------------------------------------------------------------
# the gate trips on every injected-footgun fixture (one invocation)
# --------------------------------------------------------------------------


_FOOTGUNS = {
    "JL020": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.mode = 0

            def a(self):
                with self._lock:
                    self.mode = 1

            def b(self):
                self.mode = 2
    """,
    "JL021": """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def a(self):
                with self._lock:
                    self.n += 1

            def b(self):
                self.n += 1
    """,
    "JL022": """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                self._lock.acquire()
                self._lock.release()
    """,
    "JL023": """
        import threading
        import time

        class T:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)
    """,
    "JL024": """
        import threading

        class T:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def bad(self):
                with self._a:
                    with self._b:
                        pass
    """,
}


def test_gate_trips_on_each_rule_fixture(tmp_path):
    """Acceptance pin: lint_gate exits nonzero on every JL02x footgun
    (all five fixtures in ONE gate run to stay inside the test budget),
    and --json reports the same verdict machine-readably."""
    rels = []
    for rule, src in _FOOTGUNS.items():
        p = tmp_path / f"fixture_{rule.lower()}.py"
        p.write_text(textwrap.dedent(src))
        rels.append(osp.relpath(str(p), REPO))
    r = subprocess.run([sys.executable, GATE, "--json", *rels], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    import json

    blob = json.loads(r.stdout)
    assert blob["ok"] is False
    fired = {f["rule"] for f in blob["findings"]}
    assert set(_FOOTGUNS) <= fired, (set(_FOOTGUNS) - fired, blob)
    for rule in _FOOTGUNS:
        assert blob["per_rule"][rule]["findings"] >= 1


# --------------------------------------------------------------------------
# static mirror == runtime registry
# --------------------------------------------------------------------------


def test_lock_order_mirror_matches_runtime():
    """threadlint must stay package-import-free, so it mirrors the
    runtime's LOCK_ORDER — this pin is what lets the mirror exist
    (the shardlint LAYOUT_AXES idiom)."""
    assert tuple(threadlint.LOCK_ORDER) == tuple(locks.LOCK_ORDER)


# --------------------------------------------------------------------------
# OrderedLock runtime semantics (private registries, fake clocks)
# --------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestOrderedLockRuntime:
    def test_rank_order_respected_is_clean(self):
        reg = LockRegistry(order=("t.a", "t.b"), strict=True)
        a = OrderedLock("t.a", registry=reg)
        b = OrderedLock("t.b", registry=reg)
        with a:
            with b:
                pass
        rec = reg.stats_record()
        assert rec["order_violations"] == 0 and rec["cycles"] == 0

    def test_rank_inversion_raises_under_strict(self):
        reg = LockRegistry(order=("t.a", "t.b"), strict=True)
        a = OrderedLock("t.a", registry=reg)
        b = OrderedLock("t.b", registry=reg)
        with b:
            with pytest.raises(LockOrderViolation, match="rank"):
                a.acquire()
        assert reg.stats_record()["order_violations"] == 1

    def test_seeded_abba_cycle_caught_at_second_acquisition(self):
        """The acceptance pin: thread 1 HOLDS A; this thread holds B
        and tries A. OrderedLock raises at that second acquisition —
        before blocking — so the detection is immediate, not a
        timeout on an actually-deadlocked pair."""
        reg = LockRegistry(order=("t.a", "t.b"), strict=True)
        a = OrderedLock("t.a", registry=reg)
        b = OrderedLock("t.b", registry=reg)
        holding = threading.Event()
        release = threading.Event()

        def hold_a():
            with a:
                holding.set()
                release.wait(10)

        t = threading.Thread(target=hold_a, daemon=True)
        t.start()
        assert holding.wait(10)
        t0 = time.monotonic()
        try:
            with b:
                with pytest.raises(LockOrderViolation):
                    a.acquire()   # A is HELD by t: blocking would deadlock
        finally:
            release.set()
            t.join(10)
        # caught by the order check, not by waiting out the holder
        assert time.monotonic() - t0 < 2.0
        assert not t.is_alive()

    def test_unranked_cycle_detected_from_acquisition_graph(self):
        """Locks outside LOCK_ORDER have no ranks — the graph still
        catches an ABBA pair: A->B taught by one path, B->A closes the
        cycle."""
        reg = LockRegistry(order=(), strict=True)
        a = OrderedLock("t.alpha", registry=reg)
        b = OrderedLock("t.beta", registry=reg)
        with a:
            with b:
                pass          # records edge alpha -> beta
        with b:
            with pytest.raises(LockOrderViolation, match="cycle"):
                a.acquire()   # beta -> alpha would close the loop
        assert reg.stats_record()["cycles"] == 1

    def test_non_strict_counts_and_proceeds(self, capsys):
        reg = LockRegistry(order=("t.a", "t.b"), strict=False)
        a = OrderedLock("t.a", registry=reg)
        b = OrderedLock("t.b", registry=reg)
        with b:
            with a:               # inversion: warned, not raised
                pass
        with b:
            with a:               # same edge: warn-once stays quiet
                pass
        rec = reg.stats_record()
        assert rec["order_violations"] == 2   # every occurrence counted
        assert rec["violations"]              # ...and retained for /stats
        err = capsys.readouterr().err
        assert err.count("rank-inversion") == 1   # printed once

    def test_reentrant_reacquire_is_not_a_violation(self):
        reg = LockRegistry(order=("t.r",), strict=True)
        r = OrderedLock("t.r", reentrant=True, registry=reg)
        with r:
            with r:
                pass
        rec = reg.stats_record()
        assert rec["order_violations"] == 0 and rec["cycles"] == 0
        # one SPAN, not two: the inner re-acquire is depth bookkeeping
        assert rec["by_lock"]["t.r"]["acquisitions"] == 1

    def test_nonreentrant_self_reacquire_raises_always(self):
        reg = LockRegistry(order=(), strict=False)   # even non-strict
        lk = OrderedLock("t.sd", registry=reg)
        lk.acquire()
        try:
            with pytest.raises(LockOrderViolation, match="self-deadlock"):
                lk.acquire()
            # a non-blocking probe by the OWNING thread answers False
            # (threading.Condition's default _is_owned protocol)
            assert lk.acquire(blocking=False) is False
        finally:
            lk.release()

    def test_held_too_long_and_max_held_on_fake_clock(self):
        clock = FakeClock()
        reg = LockRegistry(order=("t.h",), strict=True,
                           held_warn_ms=10.0, clock=clock)
        h = OrderedLock("t.h", registry=reg)
        with h:
            clock.t += 0.5          # 500 ms held
        with h:
            clock.t += 0.002        # 2 ms: under the threshold
        rec = reg.stats_record()["by_lock"]["t.h"]
        assert rec["max_held_ms"] == 500.0
        assert rec["held_too_long"] == 1
        assert rec["acquisitions"] == 2

    def test_contended_acquisition_counted(self):
        reg = LockRegistry(order=("t.c",), strict=True)
        c = OrderedLock("t.c", registry=reg)
        held = threading.Event()

        def holder():
            with c:
                held.set()
                time.sleep(0.05)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert held.wait(10)
        with c:                     # blocks ~50 ms behind the holder
            pass
        t.join(10)
        assert c.contended == 1

    def test_same_name_instance_nesting_flagged(self):
        """Two instances sharing one registry name cannot be ranked by
        the name order — nesting them is an undetectable-ABBA hazard
        and is flagged AT the nesting (no silent blind spot for e.g.
        two same-class stores)."""
        reg = LockRegistry(order=("t.twin",), strict=True)
        a = OrderedLock("t.twin", registry=reg)
        b = OrderedLock("t.twin", registry=reg)
        with a:
            with pytest.raises(LockOrderViolation, match="same-name"):
                b.acquire()
        assert reg.stats_record()["order_violations"] == 1

    def test_reentrant_locked_reports_owner_held(self):
        reg = LockRegistry(order=("t.rl",), strict=True)
        r = OrderedLock("t.rl", reentrant=True, registry=reg)
        assert r.locked() is False
        with r:
            # a bare RLock probe would succeed reentrantly and claim
            # "unlocked" to the very thread holding it
            assert r.locked() is True
        assert r.locked() is False

    def test_release_by_non_owner_raises(self):
        """Cross-thread release would strand the acquirer's held-stack
        entry (phantom nesting -> false violations forever) — the
        misuse raises instead of corrupting the bookkeeping."""
        reg = LockRegistry(order=(), strict=False)
        lk = OrderedLock("t.handoff", registry=reg)
        lk.acquire()
        errs: list = []

        def other():
            try:
                lk.release()
            except RuntimeError as e:
                errs.append(e)

        t = threading.Thread(target=other, daemon=True)
        t.start()
        t.join(10)
        assert errs and "does not hold it" in str(errs[0])
        lk.release()           # the owner's release still works
        with lk:
            pass               # and the lock stays usable

    def test_condition_over_ordered_lock(self):
        """The scheduler-cv integration: wait releases the lock (and
        the held-stack entry with it), notify hands it back."""
        reg = LockRegistry(order=("t.cv",), strict=True)
        cv = threading.Condition(
            OrderedLock("t.cv", reentrant=True, registry=reg))
        done: list = []
        woke = threading.Event()

        def waiter():
            with cv:
                while not done:
                    cv.wait(timeout=1.0)
                woke.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        with cv:
            done.append(1)
            cv.notify_all()
        assert woke.wait(10)
        t.join(10)
        assert not t.is_alive()
        assert reg.stats_record()["order_violations"] == 0


# --------------------------------------------------------------------------
# the locks stats block: schema pin (what /stats and chaos_smoke consume)
# --------------------------------------------------------------------------


def test_locks_stats_block_schema_pin():
    reg = LockRegistry(order=("t.pin",), strict=False)
    lk = OrderedLock("t.pin", registry=reg)
    with lk:
        pass
    rec = reg.stats_record()
    assert set(rec) == {"strict", "order_violations", "cycles",
                        "held_too_long", "violations", "by_lock"}
    assert set(rec["by_lock"]["t.pin"]) == {
        "acquisitions", "contended", "max_held_ms", "held_too_long"}
    # the module-level block (what FlowService/Router /stats embed)
    glob = locks.stats_record()
    assert set(glob) == set(rec)


def test_global_registry_is_clean_and_strict_under_tests():
    """The suite-wide canary: conftest arms strict mode, and no tier-1
    test may leave a violation behind (a seeded-violation test that
    touched the GLOBAL registry would trip this)."""
    rec = locks.stats_record()
    assert rec["strict"] is True
    assert rec["order_violations"] == 0
    assert rec["cycles"] == 0
