"""The training demo's synthetic ground truth must be exact: its flow
supervision is only correct if image1[x] == image2[x + flow[x]] by the
same bilinear convention the model is trained against."""

import os.path as osp
import sys

import numpy as np

sys.path.insert(0, osp.join(osp.dirname(osp.dirname(osp.abspath(__file__))),
                            "scripts"))

from train_demo import make_batch, make_pair, smooth_noise  # noqa: E402


def test_constant_shift_pair_is_exact():
    # force a constant integer flow: with order-1 map_coordinates the
    # warp is then an exact pixel shift, so the pair/flow contract is
    # verifiable bit-for-bit away from the border
    rng = np.random.default_rng(0)
    h, w = 48, 64
    img2 = np.stack([smooth_noise(rng, (h, w), grid=12, lo=0, hi=255)
                     for _ in range(3)], axis=-1)
    flow = np.full((h, w, 2), 0.0, np.float32)
    flow[..., 0] = 3.0  # x shift
    flow[..., 1] = -2.0  # y shift
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    from scipy import ndimage

    img1 = np.stack([
        ndimage.map_coordinates(img2[..., c],
                                [yy + flow[..., 1], xx + flow[..., 0]],
                                order=1, mode="nearest")
        for c in range(3)], axis=-1)
    # interior: image1[y, x] == image2[y - 2, x + 3]
    np.testing.assert_allclose(img1[4:-4, 4:-4], img2[2:-6, 7:-1],
                               rtol=0, atol=1e-10)


def test_make_pair_residual_epe_near_zero():
    # the generated flow must explain image1 from image2: warping image2
    # by the stored flow reproduces image1 (up to interpolation noise,
    # which is tiny for smooth textures)
    rng = np.random.default_rng(1)
    h, w = 64, 96
    img1, img2, flow = make_pair(rng, h, w, max_disp=4.0)
    from scipy import ndimage

    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    rewarp = np.stack([
        ndimage.map_coordinates(img2[..., c],
                                [yy + flow[..., 1], xx + flow[..., 0]],
                                order=1, mode="nearest")
        for c in range(3)], axis=-1)
    assert np.abs(rewarp - img1).max() < 1e-8
    # cubic zoom overshoots the coarse-grid range a little; bound loosely
    assert np.abs(flow).max() <= 4.0 * 1.25


def test_make_batch_shapes_and_dtypes():
    b = make_batch(np.random.default_rng(2), batch=2, h=32, w=48)
    assert b["image1"].shape == (2, 32, 48, 3)
    assert b["flow"].shape == (2, 32, 48, 2)
    assert b["valid"].shape == (2, 32, 48)
    assert str(b["image1"].dtype) == "float32"


def test_checkpoint_resume_continues_run(tmp_path, monkeypatch, capsys):
    """--ckpt_dir resume: a killed demo run must continue from its last
    checkpoint (full state, so the OneCycle schedule continues too) and
    append to the same transcript — this protects the multi-hour v5 CPU
    insurance transcript from session kills."""
    import train_demo

    log = str(tmp_path / "t.log")
    ck = str(tmp_path / "ck")
    base = ["train_demo.py", "--cpu", "--variant", "small", "--batch", "1",
            "--size", "64", "64", "--pool", "2", "--ckpt_dir", ck,
            "--ckpt_every", "2", "--log", log]
    monkeypatch.setattr(sys, "argv", base + ["--steps", "4"])
    train_demo.main()
    first = open(log).read()
    assert "[    3]" in first  # completed the declared run

    monkeypatch.setattr(sys, "argv", base + ["--steps", "6"])
    train_demo.main()
    full = open(log).read()
    assert full.startswith(first)  # appended, not rewritten
    assert "# resumed from" in full
    assert "[    5]" in full
