"""Sharding-contract layer tests: SpecLayout, shardlint JL010+, shard
audit golden machinery.

Named to sort LAST (tier-1 870 s budget convention): everything here is
cheap — AST fixtures, pure diff functions, and spec pins on the virtual
8-device CPU mesh. The expensive compile-based audit itself runs in the
tier-1 verify command (scripts/shard_audit.py, before pytest), so these
tests cover the logic around it, not the compile.
"""

from __future__ import annotations

import copy
import dataclasses
import importlib.util
import json
import os.path as osp
import subprocess
import sys
import textwrap

import pytest

from dexiraft_tpu.analysis import jaxlint, shardaudit, shardlint

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
GATE = osp.join(REPO, "scripts", "lint_gate.py")


def _lint(src: str, path: str = "dexiraft_tpu/somefile.py"):
    return jaxlint.lint_source(textwrap.dedent(src), path)


def _rules(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------------
# shardlint rules: positive + negative fixtures per rule
# --------------------------------------------------------------------------


class TestJL010InlineSpec:
    def test_partition_spec_literal_flagged(self):
        fs = _lint("""
            from jax.sharding import PartitionSpec as P
            spec = P("x", None)
        """)
        assert "JL010" in _rules(fs)

    def test_named_sharding_literal_flagged(self):
        fs = _lint("""
            from jax.sharding import NamedSharding, PartitionSpec
            ns = NamedSharding(mesh, PartitionSpec())
        """)
        assert [f for f in fs if f.rule == "JL010"]

    def test_layout_module_exempt(self):
        fs = _lint("""
            from jax.sharding import PartitionSpec as P
            spec = P("data")
        """, path=shardlint.LAYOUT_PATH)
        assert "JL010" not in _rules(fs)

    def test_layout_drawn_spec_clean(self):
        fs = _lint("""
            from dexiraft_tpu.parallel.layout import LAYOUT, named
            s = named(mesh, LAYOUT.batch_spatial())
        """)
        assert "JL010" not in _rules(fs)

    def test_suppression_comment(self):
        fs = _lint("""
            from jax.sharding import PartitionSpec as P
            spec = P("x")  # jaxlint: disable=JL010
        """)
        assert "JL010" not in _rules(fs)


class TestJL011AdhocMeshAxis:
    def test_mesh_ctor_flagged(self):
        fs = _lint("""
            from jax.sharding import Mesh
            import numpy as np
            m = Mesh(np.asarray(devs), ("x",))
        """)
        assert "JL011" in _rules(fs)

    def test_axis_name_string_in_collective_flagged(self):
        fs = _lint("""
            import jax
            def f():
                return jax.lax.axis_index("seq")
        """)
        assert "JL011" in _rules(fs)

    def test_axis_keyword_string_flagged(self):
        fs = _lint("""
            import jax
            def f(x):
                return jax.lax.psum(x, axis_name="data")
        """)
        assert "JL011" in _rules(fs)

    def test_unrelated_data_string_clean(self):
        # 'data' as a filesystem path component is NOT an axis name
        fs = _lint("""
            import os
            root = os.path.join(base, "data")
            d = {"data": 1}
        """)
        assert "JL011" not in _rules(fs)

    def test_layout_constant_clean(self):
        fs = _lint("""
            import jax
            from dexiraft_tpu.parallel.layout import SEQ_AXIS
            def f():
                return jax.lax.axis_index(SEQ_AXIS)
        """)
        assert "JL011" not in _rules(fs)

    def test_layout_module_exempt(self):
        fs = _lint("""
            from jax.sharding import Mesh
            import numpy as np
            m = Mesh(np.asarray(devs), ("data",))
        """, path=shardlint.LAYOUT_PATH)
        assert "JL011" not in _rules(fs)


class TestJL012RawSpecConstraint:
    def test_inline_spec_flagged(self):
        fs = _lint("""
            import jax
            from jax.sharding import PartitionSpec as P
            def f(x):
                return jax.lax.with_sharding_constraint(x, P("x"))
        """)
        assert "JL012" in _rules(fs)

    def test_named_spec_clean(self):
        fs = _lint("""
            import jax
            def f(x, spec):
                return jax.lax.with_sharding_constraint(x, spec)
        """)
        assert "JL012" not in _rules(fs)


class TestJL013UnpinnedMeshJit:
    def test_unpinned_state_jit_on_mesh_path_flagged(self):
        fs = _lint("""
            import jax
            def make_step(cfg, mesh=None):
                def step(state, batch):
                    return state
                return jax.jit(step, donate_argnums=0)
        """)
        assert "JL013" in _rules(fs)

    def test_mesh_none_branch_exempt(self):
        fs = _lint("""
            import jax
            def make_step(cfg, mesh=None):
                def step(state, batch):
                    return state
                if mesh is None:
                    return jax.jit(step, donate_argnums=0)
                return jax.jit(step, in_shardings=(a, b),
                               out_shardings=(a, a), donate_argnums=0)
        """)
        assert "JL013" not in _rules(fs)

    def test_variables_threading_covered(self):
        fs = _lint("""
            import jax
            def make_eval(cfg, mesh=None):
                def step(variables, image1):
                    return image1
                return jax.jit(step)
        """)
        assert "JL013" in _rules(fs)

    def test_no_mesh_param_exempt(self):
        # single-chip builders (dexined_cli style) have no mesh concept
        fs = _lint("""
            import jax
            def make_step(cfg):
                def step(state, batch):
                    return state
                return jax.jit(step, donate_argnums=0)
        """)
        assert "JL013" not in _rules(fs)

    def test_partial_pin_flagged(self):
        fs = _lint("""
            import jax
            def make_step(cfg, mesh=None):
                def step(state, batch):
                    return state
                return jax.jit(step, in_shardings=(a, b), donate_argnums=0)
        """)
        assert "JL013" in _rules(fs)


class TestRuleRegistration:
    def test_rules_merged_into_jaxlint(self):
        for rule in shardlint.RULES:
            assert rule in jaxlint.RULES

    def test_axes_mirror_the_live_layout(self):
        """shardlint is jax-free so it pins the axis names; they must
        equal the real SpecLayout's axes."""
        from dexiraft_tpu.parallel.layout import LAYOUT

        live = {LAYOUT.data_axis, LAYOUT.fsdp_axis, LAYOUT.seq_axis}
        assert set(shardlint.LAYOUT_AXES) == live


# --------------------------------------------------------------------------
# SpecLayout pins
# --------------------------------------------------------------------------


class TestSpecLayout:
    def test_frozen(self):
        from dexiraft_tpu.parallel.layout import LAYOUT

        with pytest.raises(dataclasses.FrozenInstanceError):
            LAYOUT.data_axis = "other"

    def test_canonical_specs(self):
        from dexiraft_tpu.parallel.layout import LAYOUT, spec_str

        assert spec_str(LAYOUT.replicated()) == "P()"
        assert spec_str(LAYOUT.params()) == "P()"
        assert spec_str(LAYOUT.opt_state()) == "P()"
        assert spec_str(LAYOUT.batch()) == "P('data')"
        assert spec_str(LAYOUT.batch_spatial()) == "P('data', 'seq')"
        assert spec_str(LAYOUT.batch_spatial_compute()) == \
            "P('data', 'seq')"
        assert spec_str(LAYOUT.carry()) == "P('data')"
        assert spec_str(LAYOUT.corr_query_rows()) == \
            "P(None, 'seq', None, None)"
        assert spec_str(LAYOUT.fsdp_params()) == "P('fsdp')"

    def test_complete_coverage(self):
        """Every canonical spec surface the audit golden accounts for —
        adding one means extending the golden + docs too."""
        from dexiraft_tpu.parallel.layout import SpecLayout

        expected = {"replicated", "params", "opt_state", "fsdp_params",
                    "param_leaf_spec", "batch", "batch_spatial",
                    "batch_spatial_compute", "carry",
                    "corr_query_rows", "batch_for", "corr_volume",
                    "corr_fmaps", "data_size", "has_seq", "has_fsdp",
                    "fsdp_size", "seq_size"}
        public = {n for n in dir(SpecLayout) if not n.startswith("_")
                  and callable(getattr(SpecLayout, n))}
        assert public == expected

    def test_mesh_dependent_specs(self):
        from dexiraft_tpu.parallel.layout import (
            LAYOUT,
            make_mesh,
            make_mesh_2d,
            spec_str,
        )

        m1 = make_mesh()
        m2 = make_mesh_2d(4, 2)
        assert spec_str(LAYOUT.batch_for(m1)) == "P('data')"
        assert spec_str(LAYOUT.batch_for(m2)) == "P('data', 'seq')"
        assert spec_str(LAYOUT.corr_volume(m2)) == "P('data', 'seq')"
        assert spec_str(LAYOUT.corr_fmaps(m2)) == "P('data', 'seq')"
        assert LAYOUT.data_size(m2) == 4
        assert LAYOUT.has_seq(m2) and not LAYOUT.has_seq(m1)
        assert LAYOUT.seq_size(m2) == 2 and LAYOUT.seq_size(m1) == 1

    def test_make_train_mesh_policy(self):
        """The glue that used to live inline in train_cli: largest
        device count dividing the batch."""
        from dexiraft_tpu.parallel.layout import make_train_mesh

        assert make_train_mesh(8).size == 8
        assert make_train_mesh(6).size == 6
        assert make_train_mesh(3).size == 3
        assert make_train_mesh(7).size == 7

    def test_mesh_compat_surface(self):
        """parallel.mesh re-exports the layout's implementations."""
        from dexiraft_tpu.parallel import layout, mesh

        assert mesh.make_mesh is layout.make_mesh
        assert mesh.batch_putter is layout.batch_putter
        assert mesh.LAYOUT is layout.LAYOUT
        assert mesh.DATA_AXIS == layout.LAYOUT.data_axis

    def test_replicated_ok_covers_state_groups(self):
        """Since the fsdp axis went live, params/opt_state carry NO
        replicated-by-design exemption — the size canary is armed on
        them (tests/test_zzzfsdp.py exercises it); only the genuinely
        global groups stay pinned."""
        from dexiraft_tpu.parallel.layout import REPLICATED_OK

        assert "batch_stats" in REPLICATED_OK
        assert "params" not in REPLICATED_OK
        assert "opt_state" not in REPLICATED_OK


# --------------------------------------------------------------------------
# shard audit: golden machinery (pure — no compiles)
# --------------------------------------------------------------------------


def _golden() -> dict:
    return shardaudit.load_golden()


class TestGoldenFile:
    def test_shipped_golden_loads_and_covers_all_steps(self):
        g = _golden()
        # serve_encode / serve_refine: the split-model streaming
        # signatures (PR 14) audited beside the monolithic serve step
        assert set(g["steps"]) == {"train", "eval", "serve",
                                   "serve_encode", "serve_refine"}
        from dexiraft_tpu.parallel.layout import LAYOUT

        assert g["axes"] == {"data": LAYOUT.data_axis,
                             "fsdp": LAYOUT.fsdp_axis,
                             "seq": LAYOUT.seq_axis}
        assert g["steps"]["train"]["mesh"] == shardaudit.TRAIN_MESH
        assert g["steps"]["serve"]["mesh"] == shardaudit.SERVE_MESH
        assert g["steps"]["serve_encode"]["mesh"] == shardaudit.SERVE_MESH
        assert g["steps"]["serve_refine"]["mesh"] == shardaudit.SERVE_MESH

    def test_volume_free_golden_with_fmap_canary(self):
        """ISSUE 12 pin: the production eval/serve config is the flash-
        blocked kernel, so the audit passes WITHOUT the materialized
        all-pairs volume — the corr_volume declared group is gone, and
        the canary is armed on the streamed fmap set instead (still
        over the 64 MB tripwire if ever pinned replicated)."""
        declared = _golden()["declared"]
        assert "corr_volume" not in declared
        g = declared["corr_fmaps"]
        assert not g["replicated"] and not g["flagged"]
        assert g["total_mb"] > shardaudit.DEFAULT_THRESHOLD_MB
        # the remaining groups keep the tripwire armed
        assert {"batch", "carry", "params", "opt_state"} <= set(declared)

    def test_params_replicated_by_design(self):
        g = _golden()["declared"]["params"]
        assert g["replicated"] and not g["flagged"]

    def test_golden_hash_stable(self):
        h1 = shardaudit.golden_hash()
        h2 = shardaudit.golden_hash()
        assert h1 == h2 and len(h1) == 40


class TestGoldenDiff:
    def test_identity_is_clean(self):
        g = _golden()
        assert shardaudit.diff_golden(copy.deepcopy(g), g) == []

    def test_spec_mutation_is_drift(self):
        g = _golden()
        mutated = copy.deepcopy(g)
        grp = next(iter(mutated["steps"]["train"]["in"].values()))
        grp["specs"] = ["P('data', None)"]
        drift = shardaudit.diff_golden(mutated, g)
        assert drift and any("specs" in d for d in drift)

    def test_vanished_group_is_drift(self):
        g = _golden()
        mutated = copy.deepcopy(g)
        mutated["steps"]["serve"]["in"].popitem()
        assert shardaudit.diff_golden(mutated, g)

    def test_new_group_is_drift(self):
        g = _golden()
        mutated = copy.deepcopy(g)
        mutated["steps"]["serve"]["out"]["[9]"] = {
            "specs": ["P()"], "leaves": 1, "bytes": 4,
            "max_leaf_bytes": 4}
        assert shardaudit.diff_golden(mutated, g)

    def test_partial_report_compares_only_its_steps(self):
        g = _golden()
        partial = copy.deepcopy(g)
        del partial["steps"]["train"], partial["steps"]["eval"]
        assert shardaudit.diff_golden(partial, g) == []

    def test_declared_replication_change_is_drift(self):
        g = _golden()
        mutated = copy.deepcopy(g)
        mutated["declared"]["corr_fmaps"]["spec"] = "P()"
        mutated["declared"]["corr_fmaps"]["replicated"] = True
        assert shardaudit.diff_golden(mutated, g)

    def test_flagged_groups(self):
        report = {"declared": {
            "corr_fmaps": {"spec": "P()", "total_mb": 128.1,
                           "per_device_mb": 128.1, "replicated": True,
                           "flagged": True},
            "params": {"spec": "P()", "total_mb": 20.0,
                       "per_device_mb": 20.0, "replicated": True,
                       "flagged": False},
        }}
        flagged = shardaudit.flagged_groups(report)
        assert len(flagged) == 1 and "corr_fmaps" in flagged[0]


class TestAuditCLI:
    """Exit-code wiring of scripts/shard_audit.py, with the expensive
    compile stages (both legs — the fsdp one runs by default since the
    axis went live) monkeypatched to replay the shipped goldens — the
    real compiles run in the tier-1 verify command itself."""

    @staticmethod
    def _main():
        spec = importlib.util.spec_from_file_location(
            "_shard_audit_cli", osp.join(REPO, "scripts", "shard_audit.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main

    @staticmethod
    def _patch_fsdp(monkeypatch):
        # the fsdp AND halo legs answer from their goldens so the CLI
        # tests exercise gate plumbing, not three step compiles
        fsdp_golden = shardaudit.load_golden(shardaudit.FSDP_GOLDEN_PATH)
        monkeypatch.setattr(
            shardaudit, "run_audit_fsdp",
            lambda steps, threshold_mb: copy.deepcopy(fsdp_golden))
        halo_golden = shardaudit.load_golden(shardaudit.HALO_GOLDEN_PATH)
        monkeypatch.setattr(
            shardaudit, "run_audit_halo",
            lambda steps, threshold_mb: copy.deepcopy(halo_golden))

    def test_clean_report_exits_zero(self, monkeypatch):
        main = self._main()
        self._patch_fsdp(monkeypatch)
        monkeypatch.setattr(shardaudit, "run_audit",
                            lambda steps, threshold_mb: copy.deepcopy(
                                _golden()))
        assert main([]) == 0

    def test_spec_drift_exits_nonzero(self, monkeypatch, capsys):
        main = self._main()
        self._patch_fsdp(monkeypatch)

        def mutated(steps, threshold_mb):
            r = copy.deepcopy(_golden())
            grp = next(iter(r["steps"]["train"]["in"].values()))
            grp["specs"] = ["P(None, 'seq')"]
            return r

        monkeypatch.setattr(shardaudit, "run_audit", mutated)
        assert main([]) == 1
        assert "DRIFT" in capsys.readouterr().out

    def test_flagged_replication_exits_nonzero(self, monkeypatch):
        main = self._main()
        self._patch_fsdp(monkeypatch)

        def flagged(steps, threshold_mb):
            r = copy.deepcopy(_golden())
            r["declared"]["corr_fmaps"].update(
                spec="P()", replicated=True, flagged=True)
            return r

        monkeypatch.setattr(shardaudit, "run_audit", flagged)
        assert main([]) == 1


# --------------------------------------------------------------------------
# lint gate satellites: --stats + stale-exclude detection
# --------------------------------------------------------------------------


class TestGateHygiene:
    def test_stats_mode(self):
        r = subprocess.run([sys.executable, GATE, "--stats"], cwd=REPO,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "rule" in r.stdout and "baseline-entries" in r.stdout

    def test_stale_exclude_detected(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "live.py").write_text("x = 1\n")
        bl = jaxlint.Baseline(exclude=["pkg/gone.py", "pkg/live.py"])
        _, _, _, stats = jaxlint.lint_tree(str(tmp_path), subdirs=("pkg",),
                                           baseline=bl)
        assert stats["stale_excludes"] == ["pkg/gone.py"]

    def test_shipped_baseline_has_no_stale_excludes(self):
        bl = jaxlint.Baseline.load(osp.join(
            REPO, "dexiraft_tpu", "analysis", "baseline.json"))
        _, _, _, stats = jaxlint.lint_tree(REPO, baseline=bl)
        assert stats["stale_excludes"] == []
        assert stats["missing_scope"] == []

    def test_missing_scope_file_detected(self, tmp_path):
        """A vanished explicit .py scope entry must surface, not
        silently shrink the gate's coverage."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "live.py").write_text("x = 1\n")
        (tmp_path / "entry.py").write_text("y = 2\n")
        _, _, _, stats = jaxlint.lint_tree(
            str(tmp_path), subdirs=("pkg", "entry.py", "gone.py"),
            baseline=jaxlint.Baseline())
        assert stats["missing_scope"] == ["gone.py"]
        assert stats["files"] == 2
