"""Remat option and the edge-pair (v2/v3 data-edge) training path."""

import numpy as np
import pytest

from dexiraft_tpu.data.flow_io import write_flo


class TestRemat:
    @pytest.mark.parametrize("kwarg", ["remat", "remat_lookup"])
    def test_remat_matches_plain(self, kwarg):
        """Full-iteration remat AND the selective lookup remat (which
        drops the stored hat matrices) must both leave loss and every
        gradient leaf numerically identical to the plain path."""
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        img = jax.random.uniform(jax.random.PRNGKey(1), (1, 64, 64, 3),
                                 jnp.float32, 0, 255)
        outs = {}
        for flag in (False, True):
            cfg = raft_v1(small=True, **{kwarg: flag})
            model = RAFT(cfg)
            variables = model.init(jax.random.PRNGKey(0), img, img,
                                   iters=1, train=False)

            def loss(v):
                preds = model.apply(v, img, img, iters=3, train=False)
                return jnp.sum(preds ** 2)

            outs[flag] = (float(loss(variables)),
                          jax.tree.leaves(jax.grad(loss)(variables)))
        np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-5)
        # recompute reorders fp32 fusions; conv biases directly followed
        # by InstanceNorm have a TRUE gradient of zero (the norm subtracts
        # the mean), so their computed grads are cancellation residue of
        # ~global-magnitude terms — tolerance must scale with the global
        # gradient magnitude, not the (near-zero) leaf's own
        gmax = max(float(np.abs(np.asarray(b)).max())
                   for b in outs[False][1])
        for a, b in zip(outs[True][1], outs[False][1]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4 * gmax)


class TestFreezeBN:
    def test_freeze_bn_stops_stat_updates(self):
        """freeze_bn=True (post-chairs stages, train.py:149-150) must run
        BN on running stats and leave them untouched."""
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        cfg = raft_v1()  # full model: cnet uses batch norm
        model = RAFT(cfg)
        img = jax.random.uniform(jax.random.PRNGKey(0), (1, 64, 64, 3),
                                 jnp.float32, 0, 255)
        variables = model.init(jax.random.PRNGKey(1), img, img,
                               iters=1, train=False)
        stats0 = variables["batch_stats"]

        def run(freeze):
            _, mut = model.apply(
                variables, img, img, iters=1, train=True, freeze_bn=freeze,
                mutable=["batch_stats"])
            return mut["batch_stats"]

        frozen = run(True)
        for a, b in zip(jax.tree.leaves(stats0), jax.tree.leaves(frozen)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        live = run(False)
        changed = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(stats0), jax.tree.leaves(live)))
        assert changed, "train-mode BN must update running stats"


@pytest.fixture()
def chairs_with_edges(tmp_path, monkeypatch):
    import imageio.v2 as imageio

    root = tmp_path / "FlyingChairs_release"
    data = root / "data"
    edges = tmp_path / "edges"
    data.mkdir(parents=True)
    edges.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(4):
        for suffix in ("img1", "img2"):
            img = rng.integers(0, 256, (96, 128, 3), dtype=np.uint8)
            imageio.imwrite(data / f"{i:05d}_{suffix}.ppm", img)
            imageio.imwrite(edges / f"{i:05d}_{suffix}.png", img)
        write_flo(data / f"{i:05d}_flow.flo",
                  rng.normal(size=(96, 128, 2)).astype(np.float32))
    (root / "chairs_split.txt").write_text("\n".join(["1"] * 4))
    monkeypatch.setenv("DEXIRAFT_DATA_DIR", str(tmp_path))
    return tmp_path, str(edges)


class TestEdgePairPath:
    def test_fetch_dataset_with_edge_root(self, chairs_with_edges):
        from dexiraft_tpu.data.datasets import fetch_dataset

        _, edge_root = chairs_with_edges
        ds = fetch_dataset("chairs", (64, 64), edge_root=edge_root)
        s = ds.sample(0, np.random.default_rng(0))
        assert s["edges1"].shape == (64, 64, 3)
        assert s["image1"].shape == (64, 64, 3)

    def test_v2_training_through_cli(self, chairs_with_edges, monkeypatch):
        from dexiraft_tpu.train_cli import main
        from dexiraft_tpu.train import checkpoint as ckpt

        tmp, edge_root = chairs_with_edges
        monkeypatch.chdir(tmp)
        main(["--name", "e", "--stage", "chairs", "--variant", "v2",
              "--small", "--num_steps", "2", "--batch_size", "2",
              "--image_size", "64", "64", "--iters", "2",
              "--num_workers", "1", "--edge_root", edge_root,
              "--output", str(tmp / "ck"), "--log_dir", str(tmp / "runs")])
        assert ckpt.latest_step(str(tmp / "ck" / "e")) == 2
