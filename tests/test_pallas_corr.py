"""Pallas local-corr kernel vs the XLA gather formulation.

Runs in interpreter mode so parity holds on the CPU test mesh; the same
kernel compiles for TPU (exercised by bench/eval on hardware).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.ops.local_corr import local_corr_level
from dexiraft_tpu.ops.pallas_corr import pallas_local_corr_level


@pytest.fixture(autouse=True, params=["loop", "batched"])
def _kernel_variant(request, monkeypatch):
    """Every parity/grad case runs against BOTH kernel shapes (the
    per-pixel loop and the staged-patches batched reduce) — the variant
    is a trace-time env switch, ops/pallas_corr.py:_variant."""
    monkeypatch.setenv("DEXIRAFT_PALLAS_VARIANT", request.param)
    return request.param


def _setup(key, b=1, h=8, w=16, c=128, noise=3.0):
    k1, k2, k3 = jax.random.split(key, 3)
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    base = jnp.stack([xs, ys], axis=-1)[None].repeat(b, 0)
    coords = base + jax.random.uniform(k3, (b, h, w, 2), jnp.float32,
                                       -noise, noise)
    return f1, f2, coords


@pytest.mark.parametrize("radius", [3, 4])
def test_parity_with_xla_gather(radius):
    f1, f2, coords = _setup(jax.random.PRNGKey(0))
    ref = local_corr_level(f1, f2, coords, radius)
    out = pallas_local_corr_level(f1, f2, coords, radius, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_boundary_windows_match():
    """Centers right at the frame edge exercise the clip+mask path."""
    f1, f2, _ = _setup(jax.random.PRNGKey(1))
    b, h, w, _ = f1.shape
    coords = jnp.stack(
        [jnp.full((b, h, w), -0.4), jnp.full((b, h, w), float(h) - 0.6)],
        axis=-1)
    ref = local_corr_level(f1, f2, coords, 4)
    out = pallas_local_corr_level(f1, f2, coords, 4, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_far_out_of_frame_zero():
    f1, f2, _ = _setup(jax.random.PRNGKey(2))
    b, h, w, _ = f1.shape
    for val in (-500.0, 500.0):
        coords = jnp.full((b, h, w, 2), val)
        out = pallas_local_corr_level(f1, f2, coords, 4, True)
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_nonsquare_level_shapes():
    """fmap2 at a coarser pyramid level than the query grid."""
    key = jax.random.PRNGKey(3)
    k1, k2, k3 = jax.random.split(key, 3)
    b, h, w, c = 1, 8, 8, 128
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h // 2, w // 2, c), jnp.float32)
    coords = jax.random.uniform(k3, (b, h, w, 2), jnp.float32, 0.0, 4.0)
    ref = local_corr_level(f1, f2, coords, 3)
    out = pallas_local_corr_level(f1, f2, coords, 3, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_custom_vjp_grads():
    f1, f2, coords = _setup(jax.random.PRNGKey(4), h=4, w=8, c=128)

    def loss_pallas(a, b_, c_):
        return jnp.sum(pallas_local_corr_level(a, b_, c_, 2, True) ** 2)

    def loss_ref(a, b_, c_):
        return jnp.sum(local_corr_level(a, b_, c_, 2) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(f1, f2, coords)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(f1, f2, coords)
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gr[0]),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gr[1]),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gp[2]), 0.0)


def test_pixel_block_override_identical(monkeypatch):
    # the tuning knob (DEXIRAFT_PALLAS_PIXEL_BLOCK, swept on-chip by
    # tpu_smoke) must only change the grid partition, never the values
    monkeypatch.delenv("DEXIRAFT_PALLAS_PIXEL_BLOCK", raising=False)
    f1, f2, coords = _setup(jax.random.PRNGKey(2))
    ref = pallas_local_corr_level(f1, f2, coords, 4, True)
    monkeypatch.setenv("DEXIRAFT_PALLAS_PIXEL_BLOCK", "64")
    out = pallas_local_corr_level(f1, f2, coords, 4, True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
