"""Flash-blocked correlation kernel (ISSUE 12).

Interpret-mode parity of flash_fused_step / flash_local_corr_level
against the unfused XLA references (forward AND gradients, including
through bf16/int8-quantized levels), blocked-tiling vs single-block and
vs the per-pixel split-path equivalence, the whole-model flash path on
shared parameters, config-time refusals, and the compile-time
memory_analysis pin that the flash executable's temp footprint is
O(fmaps) — not O(volume) — at a geometry where the all-pairs volume
dominates.

Named to sort last (870s tier-1 budget convention); every fixture is
tiny because interpret-mode Pallas pays per traced grid step.
"""

import importlib.util
import os.path as osp

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
from dexiraft_tpu.ops.local_corr import build_local_corr, local_corr_level
from dexiraft_tpu.ops.pallas_corr import (
    flash_fused_step,
    flash_local_corr_level,
    fused_reference,
    pallas_fused_step,
)

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


@pytest.fixture(autouse=True)
def _small_flash_blocks(monkeypatch):
    """Interpret mode traces the kernel once per grid step and pays per
    padded pixel: tiny fixtures want tiny blocks (the knobs never change
    values — test_rows_block_equivalence pins that)."""
    monkeypatch.setenv("DEXIRAFT_FLASH_PIXEL_BLOCK", "16")
    monkeypatch.setenv("DEXIRAFT_FLASH_ROWS", "2")


def _setup(key, b=1, h=6, w=8, c=32, levels=3, radius=2):
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    coords = (jnp.stack([xs, ys], axis=-1)[None].repeat(b, 0)
              + jax.random.uniform(k3, (b, h, w, 2), jnp.float32, -2, 2))
    win = 2 * radius + 1
    feat = 16
    weight = jax.random.normal(k4, (levels * win * win, feat),
                               jnp.float32) * 0.05
    bias = jax.random.normal(k5, (feat,), jnp.float32) * 0.1
    return f1, f2, coords, weight, bias


class TestFlashKernelParity:
    @pytest.mark.parametrize("radius", [2, 4])
    def test_fused_forward_matches_reference(self, radius):
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(0),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        out = flash_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                               weight, bias, radius, True)
        ref = fused_reference(lc.fmap1, lc.fmap2_pyramid, coords,
                              weight, bias, radius)
        # acceptance pin: fwd <= 1e-3 (measured ~1e-6 — same dots,
        # different accumulation order over row blocks)
        assert float(jnp.max(jnp.abs(out - ref))) <= 1e-3
        assert out.shape == (1, 6, 8, weight.shape[1])

    def test_lookup_level_matches_reference(self):
        radius = 2
        f1, f2, coords, _, _ = _setup(jax.random.PRNGKey(3), radius=radius)
        out = flash_local_corr_level(f1, f2, coords, radius, True)
        ref = local_corr_level(f1, f2, coords, radius)
        assert float(jnp.max(jnp.abs(out - ref))) <= 1e-3

    def test_far_out_of_frame_coords_are_zero(self):
        """Divergent-flow robustness: coords far outside the frame must
        produce all-zero windows (hat support empty), with every row
        block skipped rather than sliced out of range — flash needs no
        coordinate clipping."""
        radius = 2
        f1, f2, coords, _, _ = _setup(jax.random.PRNGKey(4), radius=radius)
        far = coords + 1000.0
        out = flash_local_corr_level(f1, f2, far, radius, True)
        np.testing.assert_array_equal(np.asarray(out), 0.0)
        ref = local_corr_level(f1, f2, far, radius)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_gradients_match_reference(self):
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(1),
                                              h=4, w=6, c=16, radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)

        def loss_flash(f1_, f2s_, co_, w_, b_):
            return jnp.sum(
                flash_fused_step(f1_, f2s_, co_, w_, b_, radius, True) ** 2)

        def loss_ref(f1_, f2s_, co_, w_, b_):
            return jnp.sum(
                fused_reference(f1_, f2s_, co_, w_, b_, radius) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3, 4))(
            lc.fmap1, lc.fmap2_pyramid, coords, weight, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(
            lc.fmap1, lc.fmap2_pyramid, coords, weight, bias)
        for a, b_ in zip(jax.tree_util.tree_leaves(gf),
                         jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-3, atol=1e-3)
        # zero coords gradient — the CUDA-kernel semantics every corr
        # path shares (custom-VJP contract)
        np.testing.assert_allclose(np.asarray(gf[2]), 0.0)

    def test_gradients_through_bf16_levels(self):
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(2),
                                              h=4, w=6, c=16, radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius,
                              dtype="bf16")

        def loss_flash(f1_, f2s_, w_, b_):
            return jnp.sum(flash_fused_step(f1_, f2s_, coords, w_, b_,
                                            radius, True) ** 2)

        def loss_ref(f1_, f2s_, w_, b_):
            return jnp.sum(fused_reference(f1_, f2s_, coords, w_, b_,
                                           radius) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(
            lc.fmap1, lc.fmap2_pyramid, weight, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(
            lc.fmap1, lc.fmap2_pyramid, weight, bias)
        for a, b_ in zip(jax.tree_util.tree_leaves(gf),
                         jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a, dtype=np.float32),
                                       np.asarray(b_, dtype=np.float32),
                                       rtol=1e-2, atol=1e-2)

    def test_gradients_through_int8_levels(self):
        """int8 levels are non-differentiable by construction (float0
        cotangents); grads to fmap1/weight/bias must still match the
        reference recompute to 1e-3."""
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(5),
                                              h=4, w=6, c=16, radius=radius)
        lc8 = build_local_corr(f1, f2, num_levels=3, radius=radius,
                               dtype="int8")

        def loss_flash(f1_, w_, b_):
            return jnp.sum(flash_fused_step(
                f1_, lc8.fmap2_pyramid, coords, w_, b_, radius, True) ** 2)

        def loss_ref(f1_, w_, b_):
            return jnp.sum(fused_reference(
                f1_, lc8.fmap2_pyramid, coords, w_, b_, radius) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(lc8.fmap1, weight, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(lc8.fmap1, weight, bias)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-3, atol=1e-3)

    def test_quantized_levels_through_flash_kernel(self):
        """int8-stored levels + scale-folded weights stay within the
        quantization error bound of the fp32 flash output."""
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(6),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        lc8 = build_local_corr(f1, f2, num_levels=3, radius=radius,
                               dtype="int8")
        win = 2 * radius + 1
        ww = win * win
        w8 = jnp.concatenate(
            [weight[i * ww:(i + 1) * ww] * lc8.scales[i] for i in range(3)],
            axis=0)
        ref = flash_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                               weight, bias, radius, True)
        out8 = flash_fused_step(lc8.fmap1, lc8.fmap2_pyramid, coords,
                                w8, bias, radius, True)
        bound = 0.05 * float(jnp.max(jnp.abs(ref)))
        assert float(jnp.max(jnp.abs(out8 - ref))) <= max(bound, 1e-3)


class TestBlockedTilingEquivalence:
    """The split-path equivalence satellite: one big block vs fine row
    tiling vs the per-pixel fused kernel's VMEM-budget split — all the
    same sum, associativity aside."""

    def test_rows_block_equivalence(self, monkeypatch):
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(7),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        monkeypatch.setenv("DEXIRAFT_FLASH_ROWS", "64")  # single block
        one = flash_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                               weight, bias, radius, True)
        monkeypatch.setenv("DEXIRAFT_FLASH_ROWS", "1")  # finest tiling
        many = flash_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                                weight, bias, radius, True)
        assert float(jnp.max(jnp.abs(one - many))) <= 1e-4

    def test_matches_per_pixel_split_path(self, monkeypatch):
        """flash vs the per-pixel fused kernel forced through ITS
        VMEM-budget per-level split: identical up to summation order."""
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(8),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        flash = flash_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                                 weight, bias, radius, True)
        from dexiraft_tpu.ops import pallas_corr

        monkeypatch.setenv("DEXIRAFT_PALLAS_PIXEL_BLOCK", "16")
        monkeypatch.setattr(pallas_corr, "_FUSED_LEVELS_VMEM_BYTES", 1)
        split = pallas_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                                  weight, bias, radius, True)
        assert float(jnp.max(jnp.abs(flash - split))) <= 1e-3

    def test_pixel_block_override_identical(self, monkeypatch):
        radius = 2
        f1, f2, coords, _, _ = _setup(jax.random.PRNGKey(9), radius=radius)
        a = flash_local_corr_level(f1, f2, coords, radius, True)
        monkeypatch.setenv("DEXIRAFT_FLASH_PIXEL_BLOCK", "64")
        b = flash_local_corr_level(f1, f2, coords, radius, True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


class TestFlashModel:
    """Whole-model flash vs the unfused path, SAME parameters — the
    checkpoint-interchange contract of FusedCorrEncoder extends to the
    flash kernel unchanged."""

    @pytest.fixture(scope="class")
    def fixture(self):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        img = jnp.zeros((1, 32, 32, 3), jnp.float32)
        im1 = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3),
                                 jnp.float32, 0, 255)
        im2 = jax.random.uniform(jax.random.PRNGKey(2), (1, 32, 32, 3),
                                 jnp.float32, 0, 255)
        cfg_l = raft_v1(small=True, corr_impl="local")
        variables = RAFT(cfg_l).init(jax.random.PRNGKey(0), img, img,
                                     iters=1, train=False)
        ref = RAFT(cfg_l).apply(variables, im1, im2, iters=2, train=False)
        return im1, im2, variables, ref

    def test_param_tree_identical(self, fixture, monkeypatch):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        img = jnp.zeros((1, 32, 32, 3), jnp.float32)
        _, _, variables, _ = fixture
        cfg_f = raft_v1(small=True, corr_impl="flash", fused_update=True)
        v_f = RAFT(cfg_f).init(jax.random.PRNGKey(0), img, img,
                               iters=1, train=False)
        assert (jax.tree_util.tree_structure(v_f)
                == jax.tree_util.tree_structure(variables))
        assert (jax.tree_util.tree_map(lambda x: x.shape, v_f)
                == jax.tree_util.tree_map(lambda x: x.shape, variables))

    def test_flash_fused_matches_unfused_same_params(self, fixture,
                                                     monkeypatch):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        im1, im2, variables, ref = fixture
        cfg_f = raft_v1(small=True, corr_impl="flash", fused_update=True)
        out = RAFT(cfg_f).apply(variables, im1, im2, iters=2, train=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_flash_unfused_lookup_matches(self, fixture, monkeypatch):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        im1, im2, variables, ref = fixture
        cfg_u = raft_v1(small=True, corr_impl="flash")
        out = RAFT(cfg_u).apply(variables, im1, im2, iters=2, train=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_flash_trains(self, fixture, monkeypatch):
        """flash is trainable (what licenses train_cli --corr_impl
        flash): whole-model param grads through the scanned fused step
        match the unfused path's grads — the VJP recomputes through
        fused_reference, so this is the same backward graph."""
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        im1, im2, variables, _ = fixture

        def loss(cfg):
            def f(params):
                out = RAFT(cfg).apply(
                    {**variables, "params": params}, im1, im2, iters=1,
                    train=False)
                return jnp.mean(out ** 2)
            return f

        g_flash = jax.grad(loss(raft_v1(small=True, corr_impl="flash",
                                        fused_update=True)))(
            variables["params"])
        g_ref = jax.grad(loss(raft_v1(small=True, corr_impl="local")))(
            variables["params"])
        flat_f = jax.tree_util.tree_leaves(g_flash)
        flat_r = jax.tree_util.tree_leaves(g_ref)
        for a, b in zip(flat_f, flat_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-3)
        # and they are not trivially zero
        assert max(float(jnp.abs(a).max()) for a in flat_f) > 0


class TestConfigTimeRefusals:
    """ISSUE 12 satellite: unknown combinations die at RAFTConfig
    construction, not deep in build_local_corr mid-trace."""

    def test_unknown_corr_impl_refused(self):
        from dexiraft_tpu.config import raft_v1

        with pytest.raises(ValueError, match="unknown corr_impl"):
            raft_v1(corr_impl="cuda")

    def test_unknown_corr_dtype_refused(self):
        from dexiraft_tpu.config import raft_v1

        with pytest.raises(ValueError, match="unknown corr_dtype"):
            raft_v1(corr_dtype="fp16")

    def test_fused_requires_flash_or_pallas_names_flash(self):
        from dexiraft_tpu.config import raft_v1

        with pytest.raises(ValueError, match="fused_update.*flash"):
            raft_v1(fused_update=True)  # default allpairs
        with pytest.raises(ValueError, match="fused_update.*flash"):
            raft_v1(corr_impl="local", fused_update=True)
        # the sanctioned combos construct fine
        raft_v1(corr_impl="flash", fused_update=True)
        raft_v1(corr_impl="pallas", fused_update=True)

    def test_resolve_corr_impl(self):
        from dexiraft_tpu.config import resolve_corr_impl

        assert resolve_corr_impl("auto", "tpu") == ("flash", True)
        assert resolve_corr_impl("auto", "cpu") == ("allpairs", False)
        assert resolve_corr_impl("pallas", "tpu") == ("pallas", False)
        assert resolve_corr_impl("flash", "cpu") == ("flash", False)

    def test_build_local_corr_unknown_kernel_refused(self):
        f1 = jnp.zeros((1, 4, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="unknown local-corr kernel"):
            build_local_corr(f1, f1, 2, 2, kernel="cuda")

    def test_fused_levels_budget_env_validation(self):
        from dexiraft_tpu.ops.pallas_corr import _parse_positive_int_env

        assert _parse_positive_int_env("DEXIRAFT_TEST_UNSET_VAR", 7) == 7
        import os

        os.environ["DEXIRAFT_TEST_BUDGET_VAR"] = "12MB"
        try:
            with pytest.raises(ValueError, match="not an integer"):
                _parse_positive_int_env("DEXIRAFT_TEST_BUDGET_VAR", 7)
            os.environ["DEXIRAFT_TEST_BUDGET_VAR"] = "-4"
            with pytest.raises(ValueError, match="positive"):
                _parse_positive_int_env("DEXIRAFT_TEST_BUDGET_VAR", 7)
        finally:
            del os.environ["DEXIRAFT_TEST_BUDGET_VAR"]


class TestMemoryFootprint:
    """The compile-time pin: at a geometry where the all-pairs volume
    dominates everything else, the flash executable's temp footprint is
    a small multiple of the fmaps — not the volume."""

    def test_flash_temp_is_o_fmaps_not_o_volume(self, monkeypatch):
        # big enough that N^2 >> N*C, small enough to trace fast:
        # N = 2560 queries, C = 64 -> level-0 volume 26 MB vs fmaps 1.3 MB
        monkeypatch.setenv("DEXIRAFT_FLASH_PIXEL_BLOCK", "512")
        monkeypatch.setenv("DEXIRAFT_FLASH_ROWS", "8")
        h8, w8, c, radius, levels = 40, 64, 64, 4, 4
        n = h8 * w8
        f1 = jax.random.normal(jax.random.PRNGKey(0), (1, h8, w8, c),
                               jnp.float32)
        f2 = jax.random.normal(jax.random.PRNGKey(1), (1, h8, w8, c),
                               jnp.float32)
        ys, xs = jnp.meshgrid(jnp.arange(h8, dtype=jnp.float32),
                              jnp.arange(w8, dtype=jnp.float32),
                              indexing="ij")
        coords = jnp.stack([xs, ys], axis=-1)[None]
        win = 2 * radius + 1
        weight = jnp.ones((levels * win * win, 64), jnp.float32) * 0.01
        bias = jnp.zeros((64,), jnp.float32)

        def flash(f1_, f2_, co_):
            lc = build_local_corr(f1_, f2_, levels, radius, kernel="flash")
            return flash_fused_step(lc.fmap1, lc.fmap2_pyramid, co_,
                                    weight, bias, radius, True)

        def allpairs(f1_, f2_, co_):
            pyr = build_corr_pyramid(f1_, f2_, levels, radius)
            corr = corr_lookup(pyr, co_)
            return jnp.einsum("bhwc,cf->bhwf", corr, weight) + bias

        def temp_bytes(fn):
            compiled = jax.jit(fn).lower(f1, f2, coords).compile()
            ma = compiled.memory_analysis()
            if ma is None:  # backend declined — nothing to pin
                pytest.skip("memory_analysis unavailable on this backend")
            return float(ma.temp_size_in_bytes)

        flash_temp = temp_bytes(flash)
        allpairs_temp = temp_bytes(allpairs)
        volume_bytes = n * n * 4  # level 0 alone
        fmap_bytes = 2 * n * c * 4
        # the allpairs executable really does carry the volume...
        assert allpairs_temp >= volume_bytes
        # ...and the flash executable carries only fmap-scale buffers:
        # padded fmaps + pyramid + per-tile transients. 8x fmaps is
        # comfortable headroom; the volume is 20x fmaps here, so the
        # assertion genuinely separates O(fmaps) from O(volume)
        assert flash_temp <= 8 * fmap_bytes
        assert flash_temp < allpairs_temp / 2


class TestHighresProbeSchema:
    """Record schema pin for scripts/highres_probe.py (the bench
    validate_record convention — drift fails, silently shifted records
    cannot happen)."""

    @staticmethod
    def _mod():
        spec = importlib.util.spec_from_file_location(
            "_highres_probe", osp.join(REPO, "scripts", "highres_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_validate_record_roundtrip(self):
        hp = self._mod()
        leg = {k: None for k in hp.EVAL_LEG_KEYS}
        rec = {
            "metric": "flash_correlation_memory_probe", "platform": "cpu",
            "model": "raft_v1_full", "strict": True, "iters": 2,
            "eval_geometry": [440, 1024], "eval_ab": [leg],
            "highres_geometry": [1088, 1920],
            "highres": {k: None for k in hp.HIGHRES_KEYS},
            "chained": {k: None for k in hp.CHAINED_KEYS},
        }
        hp.validate_record(rec)  # passes
        with pytest.raises(ValueError, match="drifted"):
            hp.validate_record({**rec, "extra": 1})
        bad = dict(rec)
        del bad["chained"]
        with pytest.raises(ValueError, match="drifted"):
            hp.validate_record(bad)

    def test_bench_schema_covers_flash(self):
        spec = importlib.util.spec_from_file_location(
            "_bench_flash", osp.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        assert "flash_corr_iters_per_sec" in bench.BENCH_RECORD_KEYS
        assert "flash" in bench.BENCH_DIAG_PREFIXES
