"""Fleet router (dexiraft_tpu/serve/router.py): hash-ring bounded
remapping, the circuit-breaker state machine, drain-waits-for-inflight,
failover-retry-once semantics (all fake-clock / fake-prober — no
sockets, deterministic), the /stats record schemas, and ONE real
router-over-2-subprocess-replicas HTTP test (SIGKILL a replica under
session traffic: zero 5xx beyond the in-flight window, sessions remap).

Named test_zz* to sort after the long-standing tail tests (870 s
budget convention); the subprocess test is the only non-instant piece
and stays well under the per-test ceiling.
"""

import json
import os
import os.path as osp
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dexiraft_tpu.serve.router import (CLOSED, HALF_OPEN, OPEN, HashRing,
                                       NoHealthyReplica, ReplicaPool,
                                       Router, RouterConfig)

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---- hash ring: bounded remapping ---------------------------------------


KEYS = [f"session-{i}" for i in range(256)]


class TestHashRing:
    def test_lookup_deterministic_and_covers_members(self):
        ring = HashRing(["a", "b", "c"])
        owners = {k: ring.lookup(k) for k in KEYS}
        assert owners == {k: ring.lookup(k) for k in KEYS}  # stable
        assert set(owners.values()) == {"a", "b", "c"}      # all used

    def test_add_moves_only_a_bounded_share_and_only_to_the_new_member(self):
        ring = HashRing(["a", "b", "c"])
        before = {k: ring.lookup(k) for k in KEYS}
        ring.add("d")
        after = {k: ring.lookup(k) for k in KEYS}
        moved = [k for k in KEYS if before[k] != after[k]]
        # consistent hashing's defining property: every moved key moved
        # TO the new member (nothing reshuffles between survivors) …
        assert all(after[k] == "d" for k in moved)
        # … and the moved share is ~1/(N+1), strictly bounded below 1/2
        assert 0 < len(moved) / len(KEYS) < 0.5

    def test_remove_moves_only_the_departed_members_keys(self):
        ring = HashRing(["a", "b", "c"])
        before = {k: ring.lookup(k) for k in KEYS}
        ring.remove("b")
        after = {k: ring.lookup(k) for k in KEYS}
        for k in KEYS:
            if before[k] != "b":
                assert after[k] == before[k]    # survivors keep theirs
            else:
                assert after[k] in ("a", "c")   # b's keys re-home
        # add it back: its keys return (sessions come home after a
        # replica recovers)
        ring.add("b")
        assert {k: ring.lookup(k) for k in KEYS} == before

    def test_chain_starts_at_owner_and_covers_all(self):
        ring = HashRing(["a", "b", "c"])
        for k in KEYS[:16]:
            chain = ring.chain(k)
            assert chain[0] == ring.lookup(k)
            assert sorted(chain) == ["a", "b", "c"]

    def test_empty_ring(self):
        ring = HashRing()
        assert ring.lookup("x") is None and ring.chain("x") == []


# ---- pool: breaker state machine (fake clock, fake prober) --------------


def make_pool(n=2, *, payloads=None, **cfg_kw):
    """Pool over fake replicas; `payloads[rid]` is the prober's answer
    (a dict) or an Exception to raise. Tests mutate it live."""
    clock = FakeClock()
    payloads = payloads if payloads is not None else {
        f"r{i}": {"_status": 200, "draining": False, "inflight": 0}
        for i in range(n)}

    def prober(replica):
        v = payloads[replica.rid]
        if isinstance(v, Exception):
            raise v
        return dict(v)

    pool = ReplicaPool(
        {f"r{i}": f"127.0.0.1:{9000 + i}" for i in range(n)},
        RouterConfig(fail_threshold=3, cooldown_s=5.0,
                     probe_interval_s=1.0, vnodes=16),
        clock=clock, prober=prober, sleep=lambda s: clock.advance(s))
    return pool, clock, payloads


class TestCircuitBreaker:
    def test_opens_after_threshold_consecutive_failures(self):
        pool, clock, _ = make_pool()
        pool.mark_failure("r0")
        pool.mark_failure("r0")
        assert pool.replicas["r0"].state == CLOSED    # 2 < threshold 3
        assert "r0" in pool.ring.members
        pool.mark_failure("r0")
        assert pool.replicas["r0"].state == OPEN
        assert "r0" not in pool.ring.members          # out of assignment
        assert pool.breaker_opens == 1

    def test_success_resets_the_consecutive_count(self):
        pool, clock, payloads = make_pool()
        pool.mark_failure("r0")
        pool.mark_failure("r0")
        pool.mark_alive("r0", payloads["r0"])
        pool.mark_failure("r0")
        pool.mark_failure("r0")
        assert pool.replicas["r0"].state == CLOSED    # count restarted

    def test_open_cooldown_then_half_open_probe_decides(self):
        pool, clock, payloads = make_pool()
        payloads["r0"] = ConnectionRefusedError("down")
        for _ in range(3):
            pool.mark_failure("r0")
        assert pool.replicas["r0"].state == OPEN
        opened_at = pool.replicas["r0"].opened_at

        # inside the cooldown: probe sweeps must NOT touch it
        clock.advance(1.0)
        pool.probe_once()
        assert pool.replicas["r0"].state == OPEN
        assert pool.replicas["r0"].opened_at == opened_at

        # cooldown over: the half-open trial probe fails -> re-open
        # with a FRESH cooldown window
        clock.advance(5.0)
        pool.probe_once()
        assert pool.replicas["r0"].state == OPEN
        assert pool.replicas["r0"].opened_at > opened_at

        # next cooldown: the trial succeeds -> closed, back in the ring
        payloads["r0"] = {"_status": 200, "draining": False, "inflight": 0}
        clock.advance(5.5)
        pool.probe_once()
        assert pool.replicas["r0"].state == CLOSED
        assert "r0" in pool.ring.members

    def test_half_open_receives_no_client_traffic(self):
        pool, clock, payloads = make_pool()
        for _ in range(3):
            pool.mark_failure("r0")
        clock.advance(6.0)
        pool.replicas["r0"].state = HALF_OPEN   # mid-trial snapshot
        for _ in range(8):
            assert pool.route(None).rid == "r1"

    def test_draining_replica_is_alive_but_not_routable(self):
        pool, clock, _ = make_pool()
        pool.mark_alive("r0", {"_status": 503, "draining": True,
                               "inflight": 4})
        r = pool.replicas["r0"]
        assert r.state == CLOSED and not r.ready and not r.routable()
        assert "r0" not in pool.ring.members
        assert r.fails == 0         # deliberate drain != failure
        # readiness returns -> routable again
        pool.mark_alive("r0", {"_status": 200, "draining": False,
                               "inflight": 0})
        assert pool.replicas["r0"].routable()
        assert "r0" in pool.ring.members

    def test_probe_interval_respected(self):
        pool, clock, payloads = make_pool()
        calls = []
        orig = pool.prober

        def counting(replica):
            calls.append(replica.rid)
            return orig(replica)

        pool.prober = counting
        pool.probe_once()
        pool.probe_once()               # same instant: nothing due
        assert len(calls) == 2          # one sweep probed both once
        clock.advance(1.1)
        pool.probe_once()
        assert len(calls) == 4


class TestRoutingAffinity:
    def test_session_routes_to_ring_owner_until_it_dies(self):
        pool, clock, _ = make_pool(3)
        sid = "cam-0"
        owner = pool.route(sid).rid
        for _ in range(4):
            assert pool.route(sid).rid == owner
        assert pool.affinity_hits == 4 and pool.sticky_misses == 0

        for _ in range(3):              # owner dies
            pool.mark_failure(owner)
        moved = pool.route(sid).rid
        assert moved != owner
        assert pool.sticky_misses == 1  # cold restart elsewhere, counted
        assert pool.route(sid).rid == moved
        assert pool.affinity_hits == 5  # sticky again on the new home

    def test_stateless_round_robin(self):
        pool, clock, _ = make_pool(3)
        seen = {pool.route(None).rid for _ in range(6)}
        assert seen == {"r0", "r1", "r2"}

    def test_no_healthy_raises(self):
        pool, clock, _ = make_pool(2)
        for rid in ("r0", "r1"):
            for _ in range(3):
                pool.mark_failure(rid)
        with pytest.raises(NoHealthyReplica):
            pool.route("cam-0")

    def test_alternate_excludes_and_follows_chain(self):
        pool, clock, _ = make_pool(3)
        sid = "cam-1"
        chain = pool.ring.chain(sid)
        alt = pool.alternate(chain[0], sid)
        assert alt is not None and alt.rid == chain[1]
        assert pool.alternate("r0", None).rid != "r0"


class TestDrain:
    def test_drain_waits_for_inflight_then_restarts(self):
        pool, clock, payloads = make_pool()
        inflight = [3, 2, 1, 0]
        restarted = []
        pool.replicas["r0"].restart = lambda: restarted.append(clock())

        def draining_prober(replica):
            n = inflight.pop(0) if inflight else 0
            return {"_status": 503, "draining": True, "inflight": n}

        pool.prober = draining_prober
        out = pool.drain("r0", timeout_s=60.0, poll_s=1.0)
        assert out["drained"] is True
        assert out["inflight_last"] == 0
        assert restarted == [out["waited_s"]]   # hook ran AFTER inflight 0
        assert out["waited_s"] == 3.0           # three 1 s polls
        r = pool.replicas["r0"]
        assert not r.draining                   # lifecycle flag released
        assert "r0" not in pool.ring.members    # until it probes ready
        pool.mark_alive("r0", {"_status": 200, "draining": False,
                               "inflight": 0})
        assert "r0" in pool.ring.members

    def test_drain_timeout_never_restarts_busy_replica(self):
        pool, clock, payloads = make_pool()
        restarted = []
        pool.replicas["r0"].restart = lambda: restarted.append(1)
        pool.prober = lambda r: {"_status": 200, "draining": False,
                                 "inflight": 5}
        out = pool.drain("r0", timeout_s=3.0, poll_s=1.0)
        assert out["drained"] is False and out["inflight_last"] == 5
        assert restarted == []      # zero-drop: no restart over live work

    def test_dead_replica_drains_immediately(self):
        pool, clock, payloads = make_pool()
        pool.prober = lambda r: (_ for _ in ()).throw(
            ConnectionRefusedError("gone"))
        out = pool.drain("r0", timeout_s=10.0)
        assert out["drained"] is True and out["waited_s"] == 0.0


# ---- failover-retry-once semantics (patched upstream, no sockets) -------


def make_router(n=2, *, clock=None, **cfg_kw):
    cfg_kw.setdefault("retry_backoff_s", 0.0)
    cfg_kw.setdefault("vnodes", 16)
    router = Router({f"r{i}": f"127.0.0.1:{9100 + i}" for i in range(n)},
                    port=0, config=RouterConfig(**cfg_kw),
                    clock=clock or time.monotonic)
    return router


class _Up:
    """Scripted upstream: pops the next outcome per call; an Exception
    outcome is raised. Records which replica each attempt hit."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)
        self.hits = []

    def __call__(self, replica, body, session_id, content_type, timeout):
        self.hits.append(replica.rid)
        out = self.outcomes.pop(0)
        if isinstance(out, Exception):
            raise out
        from dexiraft_tpu.serve.router import _UpstreamResult

        return _UpstreamResult(out, b"{}", {})


class TestFailoverRetryOnce:
    def test_connect_refused_retries_once_on_a_different_replica(self):
        router = make_router()
        try:
            up = _Up([ConnectionRefusedError("dead"), 200])
            router._upstream = up
            status, body, headers = router.proxy_flow(b"x", "cam-0",
                                                      "application/x-npz")
            assert status == 200
            assert len(up.hits) == 2 and up.hits[0] != up.hits[1]
            assert headers["X-Router-Retries"] == "1"
            assert headers["X-Replica"] == up.hits[1]
            rec = router.stats.record()
            assert rec["retries"] == 1 and rec["failovers"] == 1
            # the failed attempt fed the breaker (passive marking)
            assert router.pool.replicas[up.hits[0]].fails == 1
        finally:
            router._httpd.server_close()

    def test_exactly_one_retry_then_502(self):
        router = make_router()
        try:
            up = _Up([ConnectionRefusedError("a"),
                      ConnectionRefusedError("b"),
                      200])   # a third attempt would consume this
            router._upstream = up
            status, _, _ = router.proxy_flow(b"x", "cam-0",
                                             "application/x-npz")
            assert status == 502
            assert len(up.hits) == 2          # retry-ONCE, not until-success
            assert router.stats.record()["upstream_errors"] == 1
        finally:
            router._httpd.server_close()

    def test_both_replicas_shedding_surfaces_503_not_502(self):
        router = make_router()
        try:
            up = _Up([503, 503])
            router._upstream = up
            status, _, headers = router.proxy_flow(b"x", None,
                                                   "application/x-npz")
            assert status == 503
            assert headers.get("Retry-After") == "1"
            rec = router.stats.record()
            assert rec["shed_upstream"] == 1 and rec["upstream_errors"] == 0
            # shedding is load, not failure: no breaker input
            assert all(r.fails == 0
                       for r in router.pool.replicas.values())
        finally:
            router._httpd.server_close()

    def test_deadline_budget_exhausted_is_504(self):
        clock = FakeClock()
        router = make_router(clock=clock, deadline_s=1.0)
        try:
            def slow_upstream(replica, body, sid, ct, timeout):
                clock.advance(2.0)      # burn past the deadline
                raise ConnectionResetError("mid-flight kill")

            router._upstream = slow_upstream
            status, body, _ = router.proxy_flow(b"x", "cam-0",
                                                "application/x-npz")
            assert status == 504
            assert b"deadline" in body
        finally:
            router._httpd.server_close()

    def test_router_admission_bound_sheds_503(self):
        router = make_router(max_inflight=1)
        try:
            router._inflight = 1    # simulate one request parked inside
            status, _, headers = router.proxy_flow(b"x", None,
                                                   "application/x-npz")
            assert status == 503 and headers["Retry-After"] == "1"
            assert router.stats.record()["shed_router"] == 1
        finally:
            router._inflight = 0
            router._httpd.server_close()

    def test_no_healthy_replica_is_503(self):
        router = make_router()
        try:
            for rid in list(router.pool.replicas):
                for _ in range(3):
                    router.pool.mark_failure(rid)
            status, _, _ = router.proxy_flow(b"x", None,
                                             "application/x-npz")
            assert status == 503
            assert router.stats.record()["no_healthy"] == 1
        finally:
            router._httpd.server_close()


# ---- record schemas (the /stats and bench contracts) --------------------


ROUTER_KEYS = {"requests", "proxied_ok", "retries", "failovers",
               "shed_router", "shed_upstream", "upstream_errors",
               "no_healthy", "latency_p50_ms", "latency_p99_ms"}
POOL_KEYS = {"replicas", "healthy", "ring_members", "breaker_opens",
             "drains", "affinity"}
AFFINITY_KEYS = {"hits", "new", "sticky_misses", "hit_rate"}
AUTOSCALE_KEYS = {"recommendation", "healthy", "shed_window",
                  "queue_depths"}
REPLICA_KEYS = {"url", "state", "ready", "draining",
                "consecutive_failures", "health"}


def test_router_stats_schema_pinned():
    router = make_router()
    try:
        rec = router.stats_record()
        assert set(rec) == {"router", "pool", "autoscale", "locks"}
        # the lock-order runtime's verdict block: a healthy router
        # reads zero violations (tests run with strict armed anyway)
        assert rec["locks"]["order_violations"] == 0
        assert rec["locks"]["cycles"] == 0
        assert set(rec["router"]) == ROUTER_KEYS
        assert set(rec["pool"]) == POOL_KEYS
        assert set(rec["pool"]["affinity"]) == AFFINITY_KEYS
        assert set(rec["autoscale"]) == AUTOSCALE_KEYS
        for r in rec["pool"]["replicas"].values():
            assert set(r) == REPLICA_KEYS
    finally:
        router._httpd.server_close()


def test_autoscale_recommendation_rules():
    router = make_router()
    try:
        assert (router._autoscale_record()["recommendation"]
                == "scale_down")            # idle window, >1 routable
        router.stats.requests = 10
        assert router._autoscale_record()["recommendation"] == "steady"
        router.stats.shed_router = 1
        assert router._autoscale_record()["recommendation"] == "scale_up"
        # windows are SINCE-LAST-SCRAPE deltas, not lifetime counters:
        # one ancient shed must not latch scale_up forever, and an
        # idle window after traffic must still reach scale_down
        rec = router._autoscale_record()
        assert rec["recommendation"] == "scale_down"
        assert rec["shed_window"] == 0
    finally:
        router._httpd.server_close()


def test_fleet_bench_record_schemas_pinned():
    sys.path.insert(0, osp.join(REPO, "scripts"))
    try:
        from serve_bench import (FLEET_KILL_KEYS, FLEET_RECORD_KEYS,
                                 FLEET_SCALING_KEYS, LEVEL_KEYS)
    finally:
        sys.path.pop(0)
    assert {"metric", "replicas", "scaling", "kill",
            "goodput_scaling"} <= FLEET_RECORD_KEYS
    assert {"replicas", "goodput_rps", "affinity_hit_rate",
            "client_retries"} <= FLEET_SCALING_KEYS
    assert {"killed", "detect_s", "recovery_s", "zero_dropped",
            "sticky_misses", "affinity_hit_rate_before",
            "affinity_hit_rate_after"} <= FLEET_KILL_KEYS
    # the closed-loop client now reports restart-window retries
    # separately from errors
    assert "client_retries" in LEVEL_KEYS


def test_fleet_replica_args_forward_corr_config():
    """A fleet A/B of the fused config must spawn FUSED replicas:
    --corr_impl and --fused_update both ride the replica argv (explicit
    --corr_impl alone resolves fused=False in serve_cli)."""
    import argparse

    sys.path.insert(0, osp.join(REPO, "scripts"))
    try:
        from serve_bench import _fleet_serve_args
    finally:
        sys.path.pop(0)
    ns = argparse.Namespace(
        variant="v1", iters=2, batch=4, slo_ms=200, max_queue=32,
        bucket_multiple=None, corr_impl="flash", fused_update=True,
        size="64x96", small=True, cpu=True)
    sa = _fleet_serve_args(ns)
    assert "--fused_update" in sa
    assert sa[sa.index("--corr_impl") + 1] == "flash"
    ns.fused_update = False
    assert "--fused_update" not in _fleet_serve_args(ns)


# ---- the real thing: router over 2 subprocess replicas ------------------


def _free_ports(n):
    import socket

    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _post(url, body, session=None, timeout=15.0):
    headers = {"Content-Type": "application/x-npz"}
    if session:
        headers["X-Session-Id"] = session
    req = urllib.request.Request(url + "/v1/flow", data=body,
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers)


class TestRouterOverSubprocessReplicas:
    def test_kill_one_replica_sessions_remap_no_5xx(self):
        from dexiraft_tpu.router_cli import wait_ready
        from dexiraft_tpu.serve.server import encode_request

        child = osp.join(REPO, "tests", "serve_replica_child.py")
        env = {**os.environ,
               "PYTHONPATH": REPO + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        ports = _free_ports(2)
        procs = {f"r{i}": subprocess.Popen(
            [sys.executable, child, str(p)], env=env,
            start_new_session=True) for i, p in enumerate(ports)}
        router = None
        try:
            for i, p in enumerate(ports):
                assert wait_ready("127.0.0.1", p, 60.0), \
                    f"stub replica r{i} (port {p}) never became healthy"
            router = Router(
                {f"r{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)},
                port=0,
                config=RouterConfig(probe_interval_s=0.1, cooldown_s=0.5,
                                    fail_threshold=2,
                                    retry_backoff_s=0.01)).start()
            rng = np.random.default_rng(0)
            body = encode_request(
                rng.uniform(0, 255, (40, 56, 3)).astype(np.float32),
                rng.uniform(0, 255, (40, 56, 3)).astype(np.float32))

            sessions = [f"s-{i}" for i in range(4)]
            served_by = {}
            for k in range(3):
                for sid in sessions:
                    status, hdr = _post(router.url, body, session=sid)
                    assert status == 200
                    if k:   # same replica as last time = affinity held
                        assert hdr["X-Replica"] == served_by[sid]
                    served_by[sid] = hdr["X-Replica"]
            assert router.pool.affinity_record()["hit_rate"] == 1.0

            # SIGKILL the replica owning s-0: a REAL process death
            victim = served_by["s-0"]
            procs[victim].kill()
            procs[victim].wait()

            # every later request still answers 200 — the in-flight
            # window is absorbed by the router's failover retry
            survivor_serves = []
            for k in range(3):
                for sid in sessions:
                    status, hdr = _post(router.url, body, session=sid)
                    assert status == 200, \
                        f"5xx after the in-flight window ({sid}, {k})"
                    survivor_serves.append(hdr["X-Replica"])
            assert victim not in survivor_serves    # remapped away
            rec = router.stats.record()
            assert rec["upstream_errors"] == 0
            assert rec["failovers"] >= 1            # the kill was absorbed
            aff = router.pool.affinity_record()
            assert aff["sticky_misses"] >= 1        # remap counted
            assert router.pool.replicas[victim].state == OPEN

            # the router's own health stays green on the survivor
            with urllib.request.urlopen(router.url + "/healthz",
                                        timeout=5.0) as r:
                health = json.load(r)
            assert health["healthy"] == 1
        finally:
            if router is not None:
                router.stop()
            for p in procs.values():
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            for p in procs.values():
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
