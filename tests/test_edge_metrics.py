"""ODS/OIS/AP edge metrics."""

import numpy as np

from dexiraft_tpu.dexined.metrics import edge_counts, evaluate_edges


def _gt_line(h=64, w=64, row=32):
    gt = np.zeros((h, w), np.float32)
    gt[row] = 1.0
    return gt


class TestEdgeMetrics:
    def test_perfect_prediction(self):
        gt = _gt_line()
        res = evaluate_edges([gt.copy()], [gt])
        assert res["ODS"] > 0.99 and res["OIS"] > 0.99
        assert res["AP"] > 0.5  # PR curve is (1, 1) at all thresholds

    def test_shifted_within_tolerance_still_matches(self):
        gt = _gt_line(row=32)
        pred = _gt_line(row=33)  # 1 px off, diag tolerance ~1 px at 64x64
        res = evaluate_edges([pred], [gt])
        assert res["ODS"] > 0.99

    def test_garbage_prediction_scores_low(self):
        gt = _gt_line()
        rng = np.random.default_rng(0)
        pred = (rng.random(gt.shape) < 0.02).astype(np.float32)
        res = evaluate_edges([pred], [gt])
        assert res["ODS"] < 0.5

    def test_threshold_sweep_monotone_counts(self):
        gt = _gt_line()
        pred = np.linspace(0, 1, 64 * 64, dtype=np.float32).reshape(64, 64)
        counts = edge_counts(pred, gt)
        n_pred = counts[:, 1]
        assert (np.diff(n_pred) <= 0).all()  # higher threshold, fewer preds

    def test_ois_at_least_ods(self):
        rng = np.random.default_rng(1)
        gts = [_gt_line(row=r) for r in (16, 40)]
        preds = [np.clip(g + 0.3 * rng.random(g.shape), 0, 1) for g in gts]
        res = evaluate_edges(preds, gts)
        assert res["OIS"] >= res["ODS"] - 1e-9
