"""ODS/OIS/AP edge metrics — including validation of the assignment
matching against an independent brute-force implementation of the BSDS
correspondPixels count, and quantification of the dilation surrogate's
upward bias."""

import numpy as np
import pytest

from dexiraft_tpu.dexined.metrics import (
    edge_counts,
    evaluate_edges,
    match_count,
)


def _gt_line(h=64, w=64, row=32):
    gt = np.zeros((h, w), np.float32)
    gt[row] = 1.0
    return gt


def _brute_force_match_count(pred_mask, gt_mask, radius):
    """Independent MAX-CARDINALITY matching (0/big costs — cardinality
    only, NOT the distance-cost correspondPixels objective; for that see
    _min_cost_outlier_count below). Feasible only on tiny fixtures."""
    from scipy.optimize import linear_sum_assignment

    p = np.argwhere(pred_mask)
    g = np.argwhere(gt_mask)
    if len(p) == 0 or len(g) == 0:
        return 0
    d = np.linalg.norm(p[:, None, :] - g[None, :, :], axis=-1)
    # squares: matching an in-range pair always beats leaving both out
    big = d.shape[0] * d.shape[1] + 1.0
    cost = np.where(d <= radius, 0.0, big)
    rows, cols = linear_sum_assignment(cost)
    return int((d[rows, cols] <= radius).sum())


class TestEdgeMetrics:
    @pytest.mark.parametrize("matching", ["assignment", "dilation"])
    def test_perfect_prediction(self, matching):
        gt = _gt_line()
        res = evaluate_edges([gt.copy()], [gt], matching=matching)
        assert res["ODS"] > 0.99 and res["OIS"] > 0.99
        assert res["AP"] > 0.5  # PR curve is (1, 1) at all thresholds

    @pytest.mark.parametrize("matching", ["assignment", "dilation"])
    def test_shifted_within_tolerance_still_matches(self, matching):
        gt = _gt_line(row=32)
        pred = _gt_line(row=33)  # 1 px off, diag tolerance ~1 px at 64x64
        res = evaluate_edges([pred], [gt], matching=matching)
        assert res["ODS"] > 0.99

    def test_garbage_prediction_scores_low(self):
        gt = _gt_line()
        rng = np.random.default_rng(0)
        pred = (rng.random(gt.shape) < 0.02).astype(np.float32)
        res = evaluate_edges([pred], [gt])
        assert res["ODS"] < 0.5

    def test_threshold_sweep_monotone_counts(self):
        gt = _gt_line()
        pred = np.linspace(0, 1, 64 * 64, dtype=np.float32).reshape(64, 64)
        counts = edge_counts(pred, gt)
        n_pred = counts[:, 1]
        assert (np.diff(n_pred) <= 0).all()  # higher threshold, fewer preds

    def test_ois_at_least_ods(self):
        rng = np.random.default_rng(1)
        gts = [_gt_line(row=r) for r in (16, 40)]
        preds = [np.clip(g + 0.3 * rng.random(g.shape), 0, 1) for g in gts]
        res = evaluate_edges(preds, gts)
        assert res["OIS"] >= res["ODS"] - 1e-9


class TestAssignmentMatching:
    """The correspondPixels protocol itself."""

    def test_one_to_one_not_many_to_one(self):
        # 3 predicted pixels cluster around ONE GT pixel: the toolbox
        # counts exactly 1 TP; the dilation surrogate counts 3
        pred = np.zeros((16, 16), np.float32)
        gt = np.zeros((16, 16), np.float32)
        gt[8, 8] = 1.0
        pred[8, 7] = pred[8, 8] = pred[8, 9] = 1.0
        assert match_count(pred > 0, gt > 0, radius=1.5) == 1
        c_assign = edge_counts(pred, gt, np.array([0.5]), matching="assignment")
        c_dilate = edge_counts(pred, gt, np.array([0.5]), matching="dilation")
        assert c_assign[0, 0] == 1  # tp
        assert c_dilate[0, 0] == 3  # the documented upward bias
        assert c_assign[0, 2] == 1  # matched_gt (one-to-one)
        assert c_dilate[0, 2] == 1

    def test_out_of_radius_never_matches(self):
        pred = np.zeros((32, 32), np.float32)
        gt = np.zeros((32, 32), np.float32)
        pred[4, 4] = 1.0
        gt[20, 20] = 1.0
        assert match_count(pred > 0, gt > 0, radius=3.0) == 0

    def test_crossing_assignment_found(self):
        # p0 can only match g0; p1 could match either — a greedy pairing
        # of p1->g0 would strand p0, the maximum matching finds both
        pred = np.zeros((16, 16), np.float32)
        gt = np.zeros((16, 16), np.float32)
        pred[2, 2] = 1.0   # p0: only g0 (at 2,3) in range
        pred[2, 4] = 1.0   # p1: in range of g0 and g1
        gt[2, 3] = 1.0     # g0
        gt[2, 5] = 1.0     # g1
        assert match_count(pred > 0, gt > 0, radius=1.0) == 2

    @pytest.mark.parametrize("seed", range(6))
    def test_matches_brute_force_assignment(self, seed):
        # the sparse Hopcroft-Karp count must equal the literal min-cost
        # assignment formulation on random small masks, several radii
        rng = np.random.default_rng(seed)
        pred = rng.random((24, 24)) < 0.08
        gt = rng.random((24, 24)) < 0.08
        for radius in (1.0, 2.0, 3.5):
            assert match_count(pred, gt, radius) == \
                _brute_force_match_count(pred, gt, radius)

    def test_dilation_upper_bounds_assignment(self):
        # the surrogate can only inflate scores; measure the gap on a
        # noisy realistic-ish fixture (the number quoted in parity.md)
        rng = np.random.default_rng(3)
        gts, preds = [], []
        for _ in range(4):
            gt = np.zeros((64, 64), np.float32)
            for r in rng.integers(8, 56, 3):
                gt[r, 8:56] = 1.0
            # noisy thick responses around the true lines + clutter
            from scipy import ndimage

            prob = ndimage.gaussian_filter(gt, 1.0)
            prob = prob / prob.max() + 0.15 * rng.random(gt.shape)
            gts.append(gt)
            preds.append(np.clip(prob, 0, 1).astype(np.float32))
        res_a = evaluate_edges(preds, gts, matching="assignment")
        res_d = evaluate_edges(preds, gts, matching="dilation")
        for k in ("ODS", "OIS", "AP"):
            assert res_d[k] >= res_a[k] - 1e-9
        # the bias is not just nonnegative but material on thick
        # responses — the reason the surrogate is opt-in (parity.md
        # quantification, promoted from a session note to a pin)
        assert res_d["ODS"] - res_a["ODS"] > 0.02


def _min_cost_outlier_count(pred_mask, gt_mask, radius,
                            outlier_mult=100.0):
    """The LITERAL correspondPixels objective (BSDS benchmark,
    match.cc): min-total-cost assignment where an in-tolerance pair
    costs its Euclidean distance and an unmatched pixel costs
    outlierCost (the toolbox default is a large multiple of maxDist),
    built as the standard outlier-augmented square matrix and solved
    exactly. Returns the matched COUNT — the only quantity that enters
    precision/recall."""
    from scipy.optimize import linear_sum_assignment

    p = np.argwhere(pred_mask)
    g = np.argwhere(gt_mask)
    n_p, n_g = len(p), len(g)
    if n_p == 0 or n_g == 0:
        return 0
    d = np.linalg.norm(p[:, None, :] - g[None, :, :], axis=-1)
    oc = outlier_mult * radius
    forbid = 1e9
    cost = np.full((n_p + n_g, n_g + n_p), forbid)
    cost[:n_p, :n_g] = np.where(d <= radius, d, forbid)
    cost[:n_p, n_g:] = np.where(np.eye(n_p, dtype=bool), oc, forbid)
    cost[n_p:, :n_g] = np.where(np.eye(n_g, dtype=bool), oc, forbid)
    cost[n_p:, n_g:] = 0.0
    rows, cols = linear_sum_assignment(cost)
    return int(sum(1 for r, c in zip(rows, cols)
                   if r < n_p and c < n_g and d[r, c] <= radius))


class TestCorrespondPixelsObjective:
    """Demonstrates (not just argues) the docstring claim in
    dexined/metrics.py: the matched count of correspondPixels'
    min-cost-with-outlier objective equals the maximum-cardinality
    matching our KD-tree + Hopcroft-Karp matcher computes. The MATLAB
    toolbox itself cannot run here; this is the same objective solved
    by an independent exact solver on dense fixtures."""

    @pytest.mark.parametrize("seed", range(8))
    def test_count_equals_min_cost_outlier_objective(self, seed):
        rng = np.random.default_rng(100 + seed)
        pred = rng.random((20, 20)) < 0.1
        gt = rng.random((20, 20)) < 0.1
        for radius in (1.5, 3.0):
            assert match_count(pred, gt, radius) == \
                _min_cost_outlier_count(pred, gt, radius)

    def test_clustered_fixture(self):
        # dense clusters are where cost-vs-cardinality trades could
        # plausibly diverge: many near-equal distances, shared targets
        rng = np.random.default_rng(7)
        pred = np.zeros((24, 24), bool)
        gt = np.zeros((24, 24), bool)
        for cy, cx in ((6, 6), (6, 18), (18, 12)):
            for _ in range(8):
                py, px = rng.integers(-2, 3, 2)
                gy, gx = rng.integers(-2, 3, 2)
                pred[cy + py, cx + px] = True
                gt[cy + gy, cx + gx] = True
        for radius in (1.0, 2.0, 4.0):
            assert match_count(pred, gt, radius) == \
                _min_cost_outlier_count(pred, gt, radius)
