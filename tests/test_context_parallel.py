"""Context parallelism over the correlation volume, on the 8-device CPU
mesh: shard_map row-sharded lookup parity, and the GSPMD spatially-sharded
train step matching the 1-D data-parallel step numerically."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.config import TrainConfig, raft_v1
from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
from dexiraft_tpu.ops.grid import coords_grid
from dexiraft_tpu.parallel.context import (
    context_parallel_corr,
    ring_corr_lookup,
)
from dexiraft_tpu.parallel.mesh import (
    make_mesh,
    make_mesh_2d,
    shard_batch,
    shard_batch_spatial,
)
from dexiraft_tpu.train.state import create_state
from dexiraft_tpu.train.step import make_train_step


def _fmaps(key, b=2, h=16, w=16, c=32):
    k1, k2, k3 = jax.random.split(key, 3)
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    coords = coords_grid(b, h, w) + jax.random.uniform(
        k3, (b, h, w, 2), jnp.float32, -2.0, 2.0)
    return f1, f2, coords


class TestContextParallelCorr:
    def test_matches_unsharded(self):
        f1, f2, coords = _fmaps(jax.random.PRNGKey(0))
        mesh = make_mesh_2d(2, 4)
        out = context_parallel_corr(f1, f2, coords, mesh,
                                    num_levels=2, radius=3)
        pyr = build_corr_pyramid(f1, f2, num_levels=2, radius=3)
        ref = corr_lookup(pyr, coords)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_under_jit_with_sharded_inputs(self):
        f1, f2, coords = _fmaps(jax.random.PRNGKey(1))
        mesh = make_mesh_2d(1, 8)
        fn = jax.jit(lambda a, b, c: context_parallel_corr(
            a, b, c, mesh, num_levels=2, radius=3))
        out = fn(f1, f2, coords)
        pyr = build_corr_pyramid(f1, f2, num_levels=2, radius=3)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(corr_lookup(pyr, coords)),
                                   rtol=1e-4, atol=1e-4)


class TestRingCorrLookup:
    def test_matches_unsharded(self):
        """Ring-rotated target blocks (the ring-attention analog) must
        reproduce the unsharded lookup exactly: hat-stencil supports
        partition across blocks."""
        f1, f2, coords = _fmaps(jax.random.PRNGKey(2))
        mesh = make_mesh_2d(2, 4)  # H=16 over 4 ring chips -> blocks of 4
        out = ring_corr_lookup(f1, f2, coords, mesh,
                               num_levels=3, radius=3)
        pyr = build_corr_pyramid(f1, f2, num_levels=3, radius=3)
        ref = corr_lookup(pyr, coords)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_full_ring_under_jit(self):
        f1, f2, coords = _fmaps(jax.random.PRNGKey(3), h=32)
        mesh = make_mesh_2d(1, 8)  # blocks of 4 rows over an 8-ring
        fn = jax.jit(lambda a, b, c: ring_corr_lookup(
            a, b, c, mesh, num_levels=2, radius=4))
        out = fn(f1, f2, coords)
        pyr = build_corr_pyramid(f1, f2, num_levels=2, radius=4)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(corr_lookup(pyr, coords)),
                                   rtol=1e-4, atol=1e-4)

    def test_alignment_guard(self):
        import pytest

        f1, f2, coords = _fmaps(jax.random.PRNGKey(4), h=12)
        mesh = make_mesh_2d(2, 4)  # blocks of 3 rows: not 2^2-aligned
        with pytest.raises(ValueError, match="divisible"):
            ring_corr_lookup(f1, f2, coords, mesh, num_levels=3, radius=3)


class TestSpatiallyShardedTrainStep:
    @pytest.mark.skipif(
        jax.default_backend() == "cpu",
        reason="GSPMD miscompiles spatially-partitioned convolutions on the "
               "CPU backend: the fence train step on a mesh with a 'seq' "
               "axis computes a wrong loss (same class as the feature-dim "
               "conv miscompile in docs/perf.md; see docs/parallel.md). "
               "compute_sharding='halo' sidesteps GSPMD conv partitioning "
               "entirely and is parity-pinned in tests/test_zzzhalo.py.")
    def test_2d_mesh_matches_1d(self):
        cfg = raft_v1(small=True)
        tc = TrainConfig(name="cp", num_steps=10, batch_size=4,
                         image_size=(64, 64), iters=2)
        rng = np.random.default_rng(0)
        batch = {
            "image1": rng.uniform(0, 255, (4, 64, 64, 3)).astype(np.float32),
            "image2": rng.uniform(0, 255, (4, 64, 64, 3)).astype(np.float32),
            "flow": rng.normal(0, 1, (4, 64, 64, 2)).astype(np.float32),
            "valid": np.ones((4, 64, 64), np.float32),
        }

        losses = {}
        for name, mesh, shard in [
            ("dp", make_mesh(jax.devices()[:4]), shard_batch),
            ("dp_sp", make_mesh_2d(4, 2), shard_batch_spatial),
        ]:
            state = create_state(jax.random.PRNGKey(0), cfg, tc)
            step = make_train_step(cfg, tc, mesh=mesh)
            with mesh:
                state, metrics = step(state, shard(batch, mesh))
                losses[name] = float(metrics["loss"])
                assert np.isfinite(losses[name])

        # GSPMD partitioning must not change the math
        np.testing.assert_allclose(losses["dp_sp"], losses["dp"],
                                   rtol=2e-4, atol=2e-4)


class TestSpatiallyShardedEval:
    @pytest.mark.slow
    def test_sharded_eval_matches_unsharded(self):
        """Long-context inference: the test-mode forward with inputs
        sharded over a (data, seq) mesh — batch over 'data', image rows
        over 'seq', so each chip holds a row-block of the quadratic
        volume — must reproduce the unsharded flow exactly (jit
        propagates input shardings; make_eval_step docstring)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dexiraft_tpu.train.step import make_eval_step

        cfg = raft_v1(small=True)
        tc = TrainConfig(name="spe", num_steps=1, batch_size=2,
                         image_size=(64, 64), iters=2)
        state = create_state(jax.random.PRNGKey(0), cfg, tc)
        step = make_eval_step(cfg, iters=2)

        rng = np.random.default_rng(5)
        im1 = jnp.asarray(rng.uniform(0, 255, (2, 64, 64, 3)), jnp.float32)
        im2 = jnp.asarray(rng.uniform(0, 255, (2, 64, 64, 3)), jnp.float32)

        low_ref, up_ref = step(state.variables, im1, im2)

        mesh = make_mesh_2d(2, 2)
        sp = NamedSharding(mesh, P("data", "seq", None, None))
        with mesh:
            low_sh, up_sh = step(state.variables,
                                 jax.device_put(im1, sp),
                                 jax.device_put(im2, sp))
        np.testing.assert_allclose(np.asarray(up_sh), np.asarray(up_ref),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(low_sh), np.asarray(low_ref),
                                   rtol=2e-4, atol=2e-4)
