"""Resilience through the REAL train CLI: kill -TERM mid-run produces a
valid checkpoint and --resume continues the exact next sample (sequence
parity pinned bit-exactly against an uninterrupted run), and an injected
corrupt sample leaves the run alive with the skip counts in the logger
output.

Named test_zz* to sort after the whole existing suite (tier-1 budget
cap displaces the tail, which must be these, not the seed tests). The
three train_main invocations share one process, so the jitted step
compiles once.
"""

import numpy as np
import pytest

from dexiraft_tpu.data.flow_io import write_flo


@pytest.fixture()
def chairs_env(tmp_path, monkeypatch):
    import imageio.v2 as imageio

    root = tmp_path / "FlyingChairs_release"
    data = root / "data"
    data.mkdir(parents=True)
    rng = np.random.default_rng(0)
    n = 8
    for i in range(n):
        imageio.imwrite(data / f"{i:05d}_img1.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        imageio.imwrite(data / f"{i:05d}_img2.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        write_flo(data / f"{i:05d}_flow.flo",
                  rng.normal(size=(96, 128, 2)).astype(np.float32))
    (root / "chairs_split.txt").write_text("\n".join(["1"] * n))
    monkeypatch.setenv("DEXIRAFT_DATA_DIR", str(tmp_path))
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _train_args(tmp_path, name, steps, extra=()):
    return [
        "--name", name, "--stage", "chairs", "--variant", "v1", "--small",
        "--num_steps", str(steps), "--batch_size", "2",
        "--image_size", "64", "64", "--iters", "2", "--lr", "1e-4",
        "--num_workers", "1", "--val_freq", "1000",
        "--output", str(tmp_path / "ckpts"),
        "--log_dir", str(tmp_path / "runs"),
        *extra,
    ]


def _final_params(tmp_path, name, step):
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state

    template = create_state(jax.random.PRNGKey(0), raft_v1(small=True),
                            TrainConfig())
    state = ckpt.restore_checkpoint(str(tmp_path / "ckpts" / name), template,
                                    step=step)
    return [np.asarray(x) for x in jax.tree.leaves(state.params)]


def test_sigterm_emergency_save_then_exact_resume_parity(chairs_env):
    """The acceptance path end to end: a real SIGTERM (injected via
    --chaos at a pinned step, flowing through the installed handler
    exactly as `kill -TERM` would) triggers ONE emergency checkpoint
    with the data-stream position; --resume continues the exact sample
    sequence — final parameters BIT-EXACT vs an uninterrupted run. Any
    data-order or state divergence on resume breaks the equality."""
    from dexiraft_tpu.resilience import StreamPosition, load_position
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train_cli import main as train_main

    tmp = chairs_env
    train_main(_train_args(tmp, "ref", 6))
    assert ckpt.latest_step(str(tmp / "ckpts" / "ref")) == 6

    train_main(_train_args(tmp, "cut", 6, ["--chaos", "sigterm@3"]))
    cut_dir = str(tmp / "ckpts" / "cut")
    assert ckpt.latest_step(cut_dir) == 3  # emergency save, not step 6
    # the sidecar records the NEXT batch to consume: 3 of 4 per epoch
    assert load_position(cut_dir, 3) == StreamPosition(0, 3)

    train_main(_train_args(tmp, "cut", 6, ["--resume"]))
    assert ckpt.latest_step(cut_dir) == 6

    for a, b in zip(_final_params(tmp, "ref", 6),
                    _final_params(tmp, "cut", 6)):
        np.testing.assert_array_equal(a, b)


def test_corrupt_sample_keeps_run_alive_with_logged_skips(chairs_env, capsys):
    """Undecodable data (garbage bytes where a .flo should be) degrades
    the run, never kills it: training completes, and the skip counts are
    visible in the logger's emit line and the end-of-run summary."""
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train_cli import main as train_main

    tmp = chairs_env
    # corrupt 7 of 8 flow files -> every 2-sample batch hits >= 1 skip
    for i in range(1, 8):
        (tmp / "FlyingChairs_release" / "data"
         / f"{i:05d}_flow.flo").write_bytes(b"not a flow file")

    train_main(_train_args(tmp, "corrupt", 2, ["--sum_freq", "1"]))
    assert ckpt.latest_step(str(tmp / "ckpts" / "corrupt")) == 2
    out = capsys.readouterr().out
    assert "[pipeline:" in out      # per-emit logger suffix
    assert "skipped" in out
    assert "[pipeline]" in out      # end-of-run summary line
