"""Multi-host initialize(): env-driven modes and error paths."""

import pytest

from dexiraft_tpu.parallel.distributed import initialize


def test_noop_without_env(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_AUTO_DISTRIBUTED", raising=False)
    initialize()  # must not raise or touch jax.distributed


def test_coordinator_without_nproc_raises(monkeypatch):
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    with pytest.raises(ValueError, match="JAX_NUM_PROCESSES"):
        initialize(coordinator_address="10.0.0.1:1234")


def test_explicit_args_call_jax(monkeypatch):
    calls = {}

    def fake_init(**kw):
        calls.update(kw)

    import jax

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    initialize(coordinator_address="10.0.0.1:1234",
               num_processes=4, process_id=2)
    assert calls == {"coordinator_address": "10.0.0.1:1234",
                     "num_processes": 4, "process_id": 2}
