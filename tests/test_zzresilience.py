"""Resilience layer, component level: fault-tolerant loader (retry /
skip / worker-death recovery), exact stream positioning, verified
restore with truncated-checkpoint fallback, retention GC, guard
messages, serve-input validation, actionable missing-checkpoint errors.

Named test_zz* so the file sorts AFTER the whole existing suite: the
tier-1 870s wall-clock cap kills the tail of the run, and new tests must
be the ones displaced, never the seed suite's.
"""

import json
import os

import numpy as np
import pytest

from dexiraft_tpu.data.loader import Loader, PipelineStats
from dexiraft_tpu.resilience import chaos
from dexiraft_tpu.resilience.stream import (
    StreamPosition,
    load_position,
    save_position,
)

DS = chaos.SyntheticFlowDataset(n=8, size=(8, 8))


def _take(loader_iter, n):
    out = [next(loader_iter) for _ in range(n)]
    loader_iter.close()
    return out


def _assert_batches_equal(a, b):
    for x, y in zip(a, b):
        assert x.keys() == y.keys()
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])


class TestStreamPosition:
    def test_advance_wraps_epochs(self):
        p = StreamPosition(0, 0).advance(7, 4)
        assert (p.epoch, p.offset) == (1, 3)
        assert StreamPosition(2, 3).advance(1, 4) == StreamPosition(3, 0)

    def test_sidecar_roundtrip_and_missing(self, tmp_path):
        d = str(tmp_path)
        save_position(d, 500, StreamPosition(2, 7), seed=9)
        assert load_position(d, 500) == StreamPosition(2, 7)
        assert load_position(d, 123) is None  # absent -> epoch-0 resume

    def test_seed_mismatch_warns(self, tmp_path, capsys):
        d = str(tmp_path)
        save_position(d, 1, StreamPosition(0, 1), seed=1)
        assert load_position(d, 1, seed=2) == StreamPosition(0, 1)
        assert "seed" in capsys.readouterr().out


class TestLoaderExactPositioning:
    def test_start_offset_reproduces_stream(self):
        """batches(start_epoch=e, start_offset=o) must yield the EXACT
        continuation an uninterrupted stream produces — the property the
        checkpointed position relies on."""
        ref = _take(Loader(DS, 2, num_workers=1).batches(), 11)
        for consumed in (3, 4, 9):
            pos = StreamPosition().advance(consumed, 4)
            resumed = _take(
                Loader(DS, 2, num_workers=1).batches(
                    start_epoch=pos.epoch, start_offset=pos.offset),
                2)
            _assert_batches_equal(resumed, ref[consumed:consumed + 2])

    def test_offset_past_epoch_end_normalizes(self):
        ref = _take(Loader(DS, 2, num_workers=1).batches(), 7)
        resumed = _take(
            Loader(DS, 2, num_workers=1).batches(start_epoch=0,
                                                 start_offset=6), 1)
        _assert_batches_equal(resumed, ref[6:7])


class TestDecodeFaults:
    def test_permanent_corruption_skips_and_counts(self, capsys):
        bad = chaos.CorruptSampleDataset(DS, [0, 5])
        loader = Loader(bad, 2, num_workers=1, max_retries=1,
                        retry_backoff_s=0.001)
        got = _take(loader.batches(), 8)  # two epochs: both bad indices hit
        assert all(b["image1"].shape == (2, 8, 8, 3) for b in got)
        assert loader.stats.skipped_samples >= 2
        assert loader.stats.retries >= 2
        assert "skipping" in capsys.readouterr().out

    def test_transient_corruption_retries_to_bit_parity(self):
        flaky = chaos.CorruptSampleDataset(DS, [1, 6], fail_times=1)
        loader = Loader(flaky, 2, num_workers=1, max_retries=3,
                        retry_backoff_s=0.001)
        got = _take(loader.batches(), 4)
        assert loader.stats.retries >= 1
        assert loader.stats.skipped_samples == 0
        _assert_batches_equal(got, _take(Loader(DS, 2,
                                                num_workers=1).batches(), 4))

    def test_dropped_batch_never_desyncs_published_positions(self):
        """The loader publishes each yielded batch's true (epoch,
        offset); a dropped batch must NOT occupy a slot — resuming from
        the published position must reproduce the yielded stream (the
        trainer's exact-resume bookkeeping relies on this)."""
        # unshuffled, indices 0+1 corrupt -> every epoch's batch 0 dies
        # wholesale while batches 1..3 survive
        bad = chaos.CorruptSampleDataset(DS, [0, 1])
        loader = Loader(bad, 2, num_workers=1, shuffle=False,
                        max_retries=0, retry_backoff_s=0.001)
        it = loader.batches()
        got = [next(it) for _ in range(6)]
        positions = list(loader.positions)
        it.close()
        # unshuffled: indices 0,1 form batch (0,0) which drops entirely
        assert loader.stats.dropped_batches >= 1
        assert positions[0] == (0, 1)  # batch (0,0) never published
        assert len(positions) == len(got)
        # every published position replays to the exact same batch
        pos_epoch, pos_offset = positions[3]
        replay = _take(Loader(DS, 2, num_workers=1, shuffle=False).batches(
            start_epoch=pos_epoch, start_offset=pos_offset), 1)
        _assert_batches_equal(replay, got[3:4])

    def test_all_samples_failing_drops_batches_not_run(self):
        """Epoch 0 is entirely corrupt (every sample fails its single
        attempt); epoch 1 decodes fine. The stream must DROP the four
        doomed batches and keep going — the first batch that arrives is
        epoch 1's first."""
        bad = chaos.CorruptSampleDataset(DS, range(8), fail_times=1)
        loader = Loader(bad, 2, num_workers=1, max_retries=0,
                        retry_backoff_s=0.001)
        got = _take(loader.batches(), 2)
        assert loader.stats.dropped_batches == 4
        assert loader.stats.skipped_samples == 8
        ref = _take(Loader(DS, 2, num_workers=1).batches(start_epoch=1), 2)
        _assert_batches_equal(got, ref)

    def test_logger_surfaces_pipeline_counts(self, capsys):
        from dexiraft_tpu.train.logger import Logger

        stats = PipelineStats()
        stats.skipped_samples = 3
        stats.worker_restarts = 1
        stats.retries = 4
        logger = Logger(sum_freq=1, pipeline_stats=stats)
        logger.push({"loss": 1.0})
        out = capsys.readouterr().out
        assert "pipeline: 3 skipped" in out and "1 worker restarts" in out

    def test_logger_jsonl_carries_pipeline_fields(self, tmp_path):
        from dexiraft_tpu.train.logger import Logger

        stats = PipelineStats()
        stats.skipped_samples = 2
        logger = Logger(sum_freq=1, log_dir=str(tmp_path),
                        tensorboard=False, pipeline_stats=stats)
        logger.push({"loss": 1.0})
        logger.close()
        rec = json.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
        assert rec["pipeline/skipped_samples"] == 2


class TestWorkerDeath:
    def test_process_pool_rebuilds_and_batches_match(self):
        import tempfile

        with tempfile.TemporaryDirectory() as sentinels:
            killer = chaos.WorkerDeathDataset(DS, [1], sentinels)
            loader = Loader(killer, 2, num_workers=1, worker_mode="process",
                            mp_start_method="spawn", max_retries=3,
                            retry_backoff_s=0.01)
            got = _take(loader.batches(), 4)
        assert loader.stats.worker_restarts >= 1
        _assert_batches_equal(got, _take(Loader(DS, 2,
                                                num_workers=1).batches(), 4))


def _toy_state():
    """A real TrainState with toy leaves — checkpoint plumbing without a
    model init (keeps these tests off the 870s budget's radar)."""
    import jax.numpy as jnp

    from dexiraft_tpu.train.state import TrainState

    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params={"w": jnp.arange(512, dtype=jnp.float32).reshape(32, 16),
                "b": jnp.ones((16,), jnp.float32)},
        batch_stats={},
        opt_state={"m": jnp.zeros((32, 16), jnp.float32)},
        rng=jnp.zeros((2,), jnp.uint32),
    )


class TestVerifiedRestore:
    def test_truncated_newest_falls_back(self, tmp_path, capsys):
        from dexiraft_tpu.resilience import restore_verified
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        ckpt.save_checkpoint(d, state, step=1)
        ckpt.save_checkpoint(d, state.replace(
            params={"w": state.params["w"] + 1, "b": state.params["b"]}),
            step=2)
        assert chaos.truncate_checkpoint(d, 2)
        restored, got = restore_verified(d, state)
        assert got == 1
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(state.params["w"]))
        out = capsys.readouterr().out
        assert "failed verification" in out and "restored step 1" in out
        # the damaged step must be GONE: orbax save() onto an existing
        # step dir silently no-ops, so leaving it would swallow the
        # re-save when retraining reaches step 2 again
        assert ckpt.all_steps(d) == [1]
        ckpt.save_checkpoint(d, state, step=2)
        re_restored, got = restore_verified(d, state, verbose=False)
        assert got == 2
        np.testing.assert_array_equal(np.asarray(re_restored.params["w"]),
                                      np.asarray(state.params["w"]))

    def test_nonfinite_checkpoint_rejected(self, tmp_path):
        import jax.numpy as jnp

        from dexiraft_tpu.resilience import (CheckpointIntegrityError,
                                             restore_verified, verify_state)
        from dexiraft_tpu.train import checkpoint as ckpt

        state = _toy_state()
        poisoned = state.replace(
            params={"w": jnp.full((32, 16), jnp.nan, jnp.float32),
                    "b": state.params["b"]})
        with pytest.raises(CheckpointIntegrityError, match="non-finite"):
            verify_state(poisoned, state)

        d = str(tmp_path / "ck")
        ckpt.save_checkpoint(d, state, step=1)
        ckpt.save_checkpoint(d, poisoned, step=2)
        _, got = restore_verified(d, state, verbose=False)
        assert got == 1  # the poisoned newest step was skipped
        assert ckpt.all_steps(d) == [1]  # ...and deleted (re-savable)

    def test_all_bad_raises_integrity_error(self, tmp_path):
        from dexiraft_tpu.resilience import (CheckpointIntegrityError,
                                             restore_verified)
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        ckpt.save_checkpoint(d, state, step=1)
        assert chaos.truncate_checkpoint(d, 1)
        with pytest.raises(CheckpointIntegrityError, match="no restorable"):
            restore_verified(d, state, verbose=False)
        # total loss: nothing is deleted (forensics beat tidiness)
        assert os.path.isdir(os.path.join(d, "1"))


class TestRetention:
    def test_keep_window_and_sidecar_gc(self, tmp_path):
        from dexiraft_tpu.resilience import RetentionPolicy
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        for s in (1, 2, 3, 4):
            ckpt.save_checkpoint(d, state, step=s)
            save_position(d, s, StreamPosition(0, s))
        policy = RetentionPolicy(keep=2)
        deleted = policy.apply(d)
        assert deleted == [1, 2]
        assert ckpt.all_steps(d) == [3, 4]
        assert load_position(d, 1) is None
        assert load_position(d, 4) is not None

    def test_keep_best_survives_window(self, tmp_path):
        from dexiraft_tpu.resilience import RetentionPolicy
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        policy = RetentionPolicy(keep=1, keep_best=True)
        for s, epe in ((1, 5.0), (2, 1.0), (3, 9.0)):
            ckpt.save_checkpoint(d, state, step=s)
            policy.note_score(s, epe)
        policy.apply(d, protect=(3,))
        assert ckpt.all_steps(d) == [2, 3]  # best (2) + newest (3)

    def test_protect_beats_window(self, tmp_path):
        from dexiraft_tpu.resilience import RetentionPolicy
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        for s in (1, 2, 3):
            ckpt.save_checkpoint(d, state, step=s)
        RetentionPolicy(keep=1).apply(d, protect=(1,))
        assert ckpt.all_steps(d) == [1, 3]

    def test_keep_best_scores_survive_restart(self, tmp_path):
        """--keep_best is a promise about a multi-restart run: a policy
        rebuilt after preemption (fresh process, empty memory) must
        still protect the best step recorded BEFORE the restart."""
        from dexiraft_tpu.resilience import RetentionPolicy
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        first = RetentionPolicy(keep=1, keep_best=True, directory=d)
        for s, epe in ((1, 5.0), (2, 1.0)):
            ckpt.save_checkpoint(d, state, step=s)
            first.note_score(s, epe)

        # simulate the relaunch: a brand-new policy over the same dir
        resumed = RetentionPolicy(keep=1, keep_best=True, directory=d)
        assert resumed.best_step() == 2
        ckpt.save_checkpoint(d, state, step=3)
        resumed.apply(d, protect=(3,))
        assert ckpt.all_steps(d) == [2, 3]  # best survived the restart

    def test_pool_not_rebuilt_after_close(self):
        """Closing the batch stream while the feeder still has
        submissions in flight must not resurrect the worker pool (a
        leak) nor count phantom worker restarts."""
        from dexiraft_tpu.data.loader import _PoolManager

        loader = Loader(DS, 2, num_workers=1)
        pools = _PoolManager(loader)
        pools.shutdown()
        pools.rebuild(0)  # the race: a post-shutdown observer
        assert loader.stats.worker_restarts == 0
        fut = pools.submit(0, 0)  # must not spin up a fresh pool either
        with pytest.raises(Exception):
            fut.result()
        assert loader.stats.worker_restarts == 0

    def test_keep_zero_is_noop(self, tmp_path):
        from dexiraft_tpu.resilience import RetentionPolicy
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        ckpt.save_checkpoint(d, _toy_state(), step=1)
        assert RetentionPolicy(keep=0).apply(d) == []
        assert ckpt.all_steps(d) == [1]


class TestGuardMessages:
    def test_rollback_message_names_dir_and_step(self):
        from dexiraft_tpu.train.guard import DivergenceGuard

        g = DivergenceGuard(max_rollbacks=2)
        msg = g.consume_rollback(float("nan"), True, "step 7", 5,
                                 ckpt_dir="ckpts/run")
        assert "ckpts/run" in msg and "step 5" in msg and "1/2" in msg

    def test_abort_message_names_last_good_checkpoint(self):
        from dexiraft_tpu.train.guard import DivergenceGuard

        g = DivergenceGuard(max_rollbacks=0)
        with pytest.raises(RuntimeError,
                           match=r"ckpts/run step 5"):
            g.consume_rollback(1e9, True, "step 7", 5, ckpt_dir="ckpts/run")


class TestServeInputValidation:
    def _engine(self, batch_size=1):
        from dexiraft_tpu.serve import InferenceEngine, ServeConfig

        def fake_eval(im1, im2, fi):
            b, h, w, _ = np.asarray(im1).shape
            return (np.zeros((b, h // 8, w // 8, 2), np.float32),
                    np.zeros((b, h, w, 2), np.float32))

        return InferenceEngine(fake_eval,
                               ServeConfig(batch_size=batch_size),
                               put=lambda x: x)

    def test_good_item_passes(self):
        eng = self._engine()
        item = {"image1": np.zeros((16, 24, 3), np.float32),
                "image2": np.zeros((16, 24, 3), np.float32)}
        out = eng.run_batch([item])
        assert out[0].flow_up.shape == (16, 24, 2)

    def test_array_like_input_normalized_not_crashed(self):
        """A nested-list frame is a valid array-like: validation
        normalizes it in place (np.asarray written back) instead of
        letting it pass the checks and crash on `.shape` downstream."""
        eng = self._engine()
        frame = np.zeros((16, 24, 3), np.float32)
        item = {"image1": frame.tolist(), "image2": frame.tolist()}
        out = eng.run_batch([item])
        assert out[0].flow_up.shape == (16, 24, 2)

    @pytest.mark.parametrize("mutate,match", [
        (lambda it: it.pop("image2"), "missing"),
        (lambda it: it.update(image1=np.zeros((16, 24), np.float32)),
         "rank-3"),
        (lambda it: it.update(image2=np.zeros((16, 24, 4), np.float32)),
         "3 channels"),
        (lambda it: it.update(image1=np.zeros((16, 24, 3), bool)),
         "dtype"),
        (lambda it: it.update(image2=np.zeros((8, 24, 3), np.float32)),
         "must agree"),
        (lambda it: it.update(flow_init=np.zeros((2, 3, 7), np.float32)),
         "flow_init"),
    ])
    def test_malformed_items_rejected_up_front(self, mutate, match):
        eng = self._engine()
        item = {"image1": np.zeros((16, 24, 3), np.float32),
                "image2": np.zeros((16, 24, 3), np.float32)}
        mutate(item)
        with pytest.raises(ValueError, match=match):
            eng.run_batch([item])
        with pytest.raises(ValueError, match=match):
            list(eng.stream([item]))


class TestMissingCheckpointErrors:
    def test_require_checkpoints_lists_candidates(self, tmp_path):
        from dexiraft_tpu.train import checkpoint as ckpt

        good = tmp_path / "raft-chairs"
        (good / "100").mkdir(parents=True)
        with pytest.raises(FileNotFoundError) as ei:
            ckpt.require_checkpoints(str(tmp_path / "raft-chair"))
        msg = str(ei.value)
        assert "raft-chair" in msg and "raft-chairs" in msg
        assert "\n" not in msg  # ONE line, not a traceback wall
        # probing must not have created the missing dir
        assert not (tmp_path / "raft-chair").exists()

    def test_eval_cli_missing_model_exits_cleanly(self, tmp_path):
        from dexiraft_tpu.eval_cli import build_parser, load_variables

        args = build_parser().parse_args(
            ["--model", str(tmp_path / "nope"), "--dataset", "chairs"])
        with pytest.raises(SystemExit, match="no checkpoints under"):
            load_variables(args)


# --- pod-grade additions: async saves, consensus, watchdog ----------------


class TestAsyncCheckpoint:
    def test_async_save_returns_before_flush_commits(self, tmp_path):
        import threading

        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        hold = threading.Event()
        ckpt.flush_hold = hold
        try:
            ckpt.save_checkpoint(d, state, step=1, block=False)
            # the flush is provably in flight (held), yet save returned
            assert ckpt.pending_step(d) == 1
            threading.Timer(0.05, hold.set).start()
            info = ckpt.wait_pending(d)
        finally:
            ckpt.flush_hold = None
        assert info["step"] == 1 and info["error"] is None
        assert info["flush_s"] >= info["blocked_s"] > 0
        stats = ckpt.save_stats(d)
        assert stats["saves"] == 1 and stats["failed"] == 0
        assert ckpt.all_steps(d) == [1]

    def test_poisoned_verdict_during_inflight_flush(self, tmp_path):
        """The guard+save interleaving contract: a poisoned loss arriving
        while a previous (guard-checked, good) flush is still in flight
        neither commits the poisoned state nor orphans the in-flight
        save — the rollback barrier commits it, then restores it."""
        import threading

        import jax.numpy as jnp

        from dexiraft_tpu.resilience import restore_verified
        from dexiraft_tpu.train import checkpoint as ckpt
        from dexiraft_tpu.train.guard import DivergenceGuard

        d = str(tmp_path / "ck")
        good1 = _toy_state()
        good2 = good1.replace(
            step=jnp.int32(2),
            params={"w": good1.params["w"] + 1, "b": good1.params["b"]})
        ckpt.save_checkpoint(d, good1, step=1)  # committed baseline

        hold = threading.Event()
        ckpt.flush_hold = hold
        try:
            # step 2's guard verdict was taken BEFORE this handoff
            ckpt.save_checkpoint(d, good2, step=2, block=False)
            last_saved = 2
            # ... two steps later the loss explodes: train_cli's rollback
            # discipline — guard verdict, then barrier, then restore
            guard = DivergenceGuard(threshold=1e4)
            assert guard.poisoned(float("nan"), True)
            assert ckpt.pending_step(d) == 2  # flush genuinely in flight
            threading.Timer(0.05, hold.set).start()
            state, restored = restore_verified(d, good1, step=last_saved,
                                               verbose=False)
        finally:
            ckpt.flush_hold = None
        # the in-flight save was NOT orphaned: the barrier inside the
        # restore path committed it, and the rollback landed on it
        assert restored == 2
        np.testing.assert_array_equal(np.asarray(state.params["w"]),
                                      np.asarray(good2.params["w"]))
        # and the poisoned state never reached disk at all
        assert ckpt.all_steps(d) == [1, 2]

    def test_crash_mid_flush_debris_cleaned_and_prior_step_restores(
            self, tmp_path, capsys):
        from dexiraft_tpu.resilience import (
            restore_verified,
            uncommitted_flushes,
        )
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state()
        ckpt.save_checkpoint(d, state, step=3)
        # what a kill mid-flush leaves behind: an uncommitted orbax tmp
        # dir for the NEXT step (the rename-commit never happened)
        debris = tmp_path / "ck" / "4.orbax-checkpoint-tmp-123456"
        debris.mkdir()
        (debris / "partial").write_bytes(b"x" * 64)
        assert uncommitted_flushes(d) == [debris.name]
        # a READER (serve/eval) reports the debris but must never
        # delete it — it may be another process's live in-flight flush
        restored, got = restore_verified(d, state)
        assert got == 3
        assert uncommitted_flushes(d) == [debris.name]
        assert "left in place" in capsys.readouterr().out
        # the WRITER recovering its own directory sweeps it
        restored, got = restore_verified(d, state, clean_debris=True)
        assert got == 3  # the prior committed step is the latest
        assert uncommitted_flushes(d) == []  # debris reported + removed
        assert "uncommitted flush" in capsys.readouterr().out
        assert ckpt.all_steps(d) == [3]

    def test_failed_flush_reports_and_never_raises(self, tmp_path,
                                                   monkeypatch, capsys):
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")

        def boom(key, step, host_state, t0):
            raise OSError("disk on fire")

        monkeypatch.setattr(ckpt, "_flush", boom)
        ckpt.save_checkpoint(d, _toy_state(), step=5, block=False)
        info = ckpt.wait_pending(d)
        assert info["error"] and "disk on fire" in info["error"]
        assert "FAILED" in capsys.readouterr().out
        assert ckpt.save_stats(d)["failed"] == 1
        # a BLOCKING save keeps the historical contract: it raises at
        # the call site, so callers never bookkeep an uncommitted step
        with pytest.raises(OSError, match="disk on fire"):
            ckpt.save_checkpoint(d, _toy_state(), step=6, block=True)
        # the directory stays usable: nothing committed, reads work
        monkeypatch.undo()
        assert ckpt.latest_step(d) is None

    def test_typed_prng_key_roundtrips_dtype_preserving(self, tmp_path):
        import jax

        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        state = _toy_state().replace(rng=jax.random.key(3))
        ckpt.save_checkpoint(d, state, step=1)
        template = _toy_state().replace(rng=jax.random.key(0))
        restored = ckpt.restore_checkpoint(d, template)
        assert restored.rng.dtype == state.rng.dtype  # key<fry>, not u32
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(restored.rng)),
            np.asarray(jax.random.key_data(state.rng)))
        # and the old-style uint32 key path is untouched
        ckpt.save_checkpoint(d, _toy_state(), step=2)
        old = ckpt.restore_checkpoint(d, _toy_state(), step=2)
        assert old.rng.dtype == np.uint32

    def test_chaos_kill_mid_flush_spec_arms_once(self):
        from dexiraft_tpu.resilience import chaos as chaos_lib
        from dexiraft_tpu.train import checkpoint as ckpt

        fire = chaos_lib.parse_spec("kill_mid_flush@3")
        try:
            fire(2)
            assert not ckpt._chaos_kill_next_flush
            fire(3)
            assert ckpt._chaos_kill_next_flush
        finally:
            ckpt._chaos_kill_next_flush = False  # never kill this pytest


class TestDeleteStepLogging:
    def test_manager_refusal_names_step_and_dir(self, tmp_path, capsys):
        from dexiraft_tpu.train import checkpoint as ckpt

        d = str(tmp_path / "ck")
        ckpt.save_checkpoint(d, _toy_state(), step=1)
        ckpt.delete_step(d, 999)  # the manager has no step 999
        out = capsys.readouterr().out
        assert "999" in out and str(d) in out and "failed" in out


class TestPartialRestoreSkipReport:
    def test_full_skip_list_lands_in_sidecar(self, tmp_path, capsys):
        from dexiraft_tpu.train import checkpoint as ckpt

        params = {f"fresh_{i}": np.zeros((2,)) for i in range(12)}
        restored = {f"old_{i}": np.zeros((2,)) for i in range(3)}
        merged, skipped = ckpt.restore_params_into(
            params, restored, verbose=True,
            skipped_report_dir=str(tmp_path))
        assert len(skipped) == 15
        out = capsys.readouterr().out
        assert "15 leaves" in out
        report = tmp_path / "partial_restore_skipped.txt"
        assert str(report) in out
        lines = report.read_text().strip().splitlines()
        assert len(lines) == 15
        assert set(lines) == set(skipped)

    def test_small_skip_list_stays_inline(self, tmp_path, capsys):
        from dexiraft_tpu.train import checkpoint as ckpt

        params = {"a": np.zeros((2,)), "b": np.zeros((3,))}
        merged, skipped = ckpt.restore_params_into(
            params, {"a": np.zeros((5,))}, verbose=True,
            skipped_report_dir=str(tmp_path))
        # 'a' (shape mismatch) and 'b' (missing) both count, inline only
        assert "2 leaves" in capsys.readouterr().out
        assert not (tmp_path / "partial_restore_skipped.txt").exists()


class TestHangWatchdog:
    def _wd(self, tmp_path, timeout=10.0, **kw):
        import io

        from dexiraft_tpu.resilience import HangWatchdog

        clk = [0.0]
        exits = []
        out = open(tmp_path / "wd.log", "w+")
        wd = HangWatchdog(timeout, clock=lambda: clk[0],
                          exit_fn=exits.append, stream=out, **kw)
        return wd, clk, exits, out

    def test_stall_dumps_stacks_and_exits_nonzero(self, tmp_path):
        from dexiraft_tpu.resilience import STALL_EXIT_CODE

        wd, clk, exits, out = self._wd(tmp_path, timeout=10.0)
        wd.arm(42, "step+data")
        clk[0] = 9.0
        assert wd.check_once() is None
        clk[0] = 10.5
        assert wd.check_once() == "stall"
        assert exits == [STALL_EXIT_CODE] and wd.fired
        out.seek(0)
        dump = out.read()
        out.close()
        assert "step 42" in dump and "step+data" in dump
        assert "Thread" in dump  # faulthandler live-stack dump

    def test_straggler_warns_once_on_ewma(self, tmp_path):
        wd, clk, exits, out = self._wd(tmp_path, timeout=100.0,
                                       straggler_factor=10.0)
        # four 1s steps -> EWMA 1s
        for step in range(4):
            wd.arm(step)
            clk[0] += 1.0
            wd.disarm()
        assert wd.ewma_s == pytest.approx(1.0)
        wd.arm(5)
        clk[0] += 11.0  # > 10x EWMA, < timeout
        assert wd.check_once() == "straggler"
        assert wd.check_once() is None  # once per armed region
        assert wd.straggler_warnings == 1 and not exits
        out.seek(0)
        assert "straggler" in out.read()
        out.close()

    def test_sanctioned_windows_stay_out_of_ewma_and_straggler(
            self, tmp_path):
        wd, clk, exits, out = self._wd(tmp_path, timeout=100.0)
        # seed the EWMA with fast steady steps
        for step in range(3):
            wd.arm(step)
            clk[0] += 0.5
            wd.disarm()
        # a sanctioned slow region: no EWMA feed, no straggler warning,
        # and the stall bound is scaled by slow_region_factor (10x) —
        # a legitimate 2-minute validation sweep must not be killed by
        # a step-sized timeout
        wd.arm(9, "checkpoint+validation", steady=False)
        clk[0] += 500.0  # 1000x the EWMA, 5x timeout, < 10x timeout
        assert wd.check_once() is None
        assert wd.straggler_warnings == 0
        assert wd.disarm() is not None
        assert wd.ewma_s == pytest.approx(0.5)
        wd.arm(10, "checkpoint+validation", steady=False)
        clk[0] += 1001.0  # past 10x the timeout: still fires
        assert wd.check_once() == "stall"
        assert exits  # the stall bound is scaled, never waived
        out.close()

    def test_timeout_zero_is_inert(self, tmp_path):
        wd, clk, exits, out = self._wd(tmp_path, timeout=0.0)
        assert not wd.enabled
        wd.arm(1)
        clk[0] = 1e9
        assert wd.check_once() is None and not exits
        assert wd.start()._thread is None  # no monitor thread either
        out.close()


class TestCoordinator:
    def test_single_process_is_identity(self):
        from dexiraft_tpu.resilience import Coordinator

        calls = []
        coord = Coordinator(size=1, index=0,
                            allgather_fn=lambda v: calls.append(v))
        assert coord.any_flag(True) is True
        assert coord.any_flag(False) is False
        assert coord.min_int(7) == 7
        state, step = coord.agree_step(
            lambda b: (("state", b), 4), None)
        assert (state, step) == (("state", None), 4)
        coord.warmup()
        assert calls == []  # never a collective

    def test_any_flag_and_min_over_hosts(self):
        from dexiraft_tpu.resilience import Coordinator

        peers = {"flags": [False, True], "steps": [40, 20]}

        def fake_allgather(v):
            import numpy as _np

            if v.dtype == bool:
                return _np.asarray([[f] for f in peers["flags"]])
            return _np.asarray([[s] for s in peers["steps"]])

        coord = Coordinator(size=2, index=0, allgather_fn=fake_allgather)
        assert coord.any_flag(False) is True  # the PEER's verdict wins
        assert coord.min_int(40) == 20

    def test_agree_step_converges_to_global_min(self):
        from dexiraft_tpu.resilience import Coordinator

        # this host restored 4, the peer only has 2: round 1 agrees on
        # 2, round 2 this host re-restores at 2 and everyone matches
        script = iter([
            np.asarray([[4], [2]]),          # min_int round 1 -> 2
            np.asarray([[True], [False]]),   # any_flag: mismatch
            np.asarray([[2], [2]]),          # min_int round 2 -> 2
            np.asarray([[False], [False]]),  # any_flag: agreed
        ])
        coord = Coordinator(size=2, index=0,
                            allgather_fn=lambda v: next(script))
        restores = []

        def restore_fn(bound):
            restores.append(bound)
            step = 4 if bound is None else min(4, bound)
            return f"state@{step}", step

        state, step = coord.agree_step(restore_fn, None)
        assert (state, step) == ("state@2", 2)
        assert restores == [None, 2]  # re-restored at the agreed min

    def test_agree_step_gives_up_after_max_rounds(self):
        from dexiraft_tpu.resilience import Coordinator

        coord = Coordinator(
            size=2, index=1,
            allgather_fn=lambda v: (np.asarray([[True], [True]])
                                    if v.dtype == bool
                                    else np.asarray([[0], [1]])))
        with pytest.raises(RuntimeError, match="no checkpoint step"):
            coord.agree_step(lambda b: ("s", 1), None, max_rounds=2)
