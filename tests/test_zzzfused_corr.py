"""Fused Pallas refinement-step kernel + quantized correlation pyramid.

Interpret-mode parity of pallas_fused_step against the unfused XLA
reference (forward AND gradients), the int8/bf16 pyramid accuracy bounds
(corr-value max-abs error and end-to-end flow drift on a tiny fixture),
and the whole-model fused path — ISSUE 8's test satellite.

Named to sort last (tier-1 budget convention): everything here is
CPU-only and tiny, but interpret-mode pallas is per-pixel slow, so the
fixtures stay small.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dexiraft_tpu.ops.corr import build_corr_pyramid, corr_lookup
from dexiraft_tpu.ops.local_corr import build_local_corr
from dexiraft_tpu.ops.pallas_corr import fused_reference, pallas_fused_step
from dexiraft_tpu.ops.quant import (
    corr_dtype_bytes,
    dequantize,
    quantize_symmetric,
)


@pytest.fixture(autouse=True)
def _small_pixel_block(monkeypatch):
    """The interpret-mode kernel pays per PADDED pixel: these fixtures
    have 16-80 real pixels, so the production 256-pixel block would make
    interpret spend >80% of its time on padding (test_pixel_block_
    override_identical pins that the knob never changes values)."""
    monkeypatch.setenv("DEXIRAFT_PALLAS_PIXEL_BLOCK", "16")


def _setup(key, b=1, h=6, w=8, c=32, levels=3, radius=2):
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    f1 = jax.random.normal(k1, (b, h, w, c), jnp.float32)
    f2 = jax.random.normal(k2, (b, h, w, c), jnp.float32)
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    coords = (jnp.stack([xs, ys], axis=-1)[None].repeat(b, 0)
              + jax.random.uniform(k3, (b, h, w, 2), jnp.float32, -2, 2))
    win = 2 * radius + 1
    feat = 16
    weight = jax.random.normal(k4, (levels * win * win, feat),
                               jnp.float32) * 0.05
    bias = jax.random.normal(k5, (feat,), jnp.float32) * 0.1
    return f1, f2, coords, weight, bias


class TestFusedKernelParity:
    @pytest.mark.parametrize("radius", [2, 4])
    def test_forward_matches_reference(self, radius):
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(0),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        out = pallas_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                                weight, bias, radius, True)
        ref = fused_reference(lc.fmap1, lc.fmap2_pyramid, coords,
                              weight, bias, radius)
        # acceptance pin: fwd <= 1e-3 max-abs on fp32 (actual ~1e-6 —
        # same dots, different accumulation order)
        assert float(jnp.max(jnp.abs(out - ref))) <= 1e-3
        assert out.shape == (1, 6, 8, weight.shape[1])

    def test_gradients_match_reference(self):
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(1),
                                              h=4, w=6, c=16, radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)

        def loss_fused(f1_, f2s_, co_, w_, b_):
            return jnp.sum(
                pallas_fused_step(f1_, f2s_, co_, w_, b_, radius, True) ** 2)

        def loss_ref(f1_, f2s_, co_, w_, b_):
            return jnp.sum(
                fused_reference(f1_, f2s_, co_, w_, b_, radius) ** 2)

        gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(
            lc.fmap1, lc.fmap2_pyramid, coords, weight, bias)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(
            lc.fmap1, lc.fmap2_pyramid, coords, weight, bias)
        for a, b_ in zip(jax.tree_util.tree_leaves(gf),
                         jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-3, atol=1e-3)
        # coords gradient is exactly zero (the CUDA-kernel semantics
        # every corr path shares)
        np.testing.assert_allclose(np.asarray(gf[2]), 0.0)

    def test_vmem_level_split_parity(self, monkeypatch):
        """Over the staged-levels VMEM budget the fused forward splits
        into one fused call per level (the fp32-at-eval-geometry path);
        a 1-byte budget forces the split on the tiny fixture, and the
        result must match the unfused reference exactly like the
        single-call path (pure summation-order difference)."""
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(7),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        ref = fused_reference(lc.fmap1, lc.fmap2_pyramid, coords,
                              weight, bias, radius)
        # the env override is parsed once at module load (ISSUE 12
        # satellite) — tests force the split via the module constant
        from dexiraft_tpu.ops import pallas_corr

        monkeypatch.setattr(pallas_corr, "_FUSED_LEVELS_VMEM_BYTES", 1)
        out = pallas_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                                weight, bias, radius, True)
        assert float(jnp.max(jnp.abs(out - ref))) <= 1e-3

    def test_quantized_levels_through_fused_kernel(self):
        """int8-stored levels + scale-folded weights stay within the
        quantization error bound of the fp32 fused output."""
        radius = 2
        f1, f2, coords, weight, bias = _setup(jax.random.PRNGKey(2),
                                              radius=radius)
        lc = build_local_corr(f1, f2, num_levels=3, radius=radius)
        lc8 = build_local_corr(f1, f2, num_levels=3, radius=radius,
                               dtype="int8")
        win = 2 * radius + 1
        ww = win * win
        w8 = jnp.concatenate(
            [weight[i * ww:(i + 1) * ww] * lc8.scales[i] for i in range(3)],
            axis=0)
        ref = pallas_fused_step(lc.fmap1, lc.fmap2_pyramid, coords,
                                weight, bias, radius, True)
        out8 = pallas_fused_step(lc8.fmap1, lc8.fmap2_pyramid, coords,
                                 w8, bias, radius, True)
        # fmap2 quant error <= scale/2 per element; after the C-dim dot,
        # the bilinear blend (convex) and the small conv weights, the
        # output error stays well under 5% of the output range
        bound = 0.05 * float(jnp.max(jnp.abs(ref)))
        assert float(jnp.max(jnp.abs(out8 - ref))) <= max(bound, 1e-3)


class TestQuantizedPyramid:
    def test_quantize_roundtrip_bound(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (7, 9, 5), jnp.float32)
        q, scale = quantize_symmetric(x)
        assert q.dtype == jnp.int8
        err = jnp.max(jnp.abs(dequantize(q, scale) - x))
        # symmetric round-to-nearest: error <= scale/2 (+ eps)
        assert float(err) <= float(scale) * 0.5 + 1e-7

    def test_zero_size_level_quantizes(self):
        q, scale = quantize_symmetric(jnp.zeros((4, 0, 3), jnp.float32))
        assert q.shape == (4, 0, 3) and q.dtype == jnp.int8
        assert float(scale) == 1.0

    def test_corr_dtype_bytes(self):
        assert (corr_dtype_bytes("fp32"), corr_dtype_bytes("bf16"),
                corr_dtype_bytes("int8")) == (4, 2, 1)
        with pytest.raises(ValueError):
            corr_dtype_bytes("fp16")

    @pytest.mark.parametrize("dtype,tol_frac", [("bf16", 0.01),
                                                ("int8", 0.02)])
    def test_allpairs_lookup_error_bound(self, dtype, tol_frac):
        """corr-value max-abs error of the quantized allpairs pyramid,
        relative to the fp32 lookup's value range."""
        f1, f2, coords, _, _ = _setup(jax.random.PRNGKey(4), h=8, w=10)
        ref = corr_lookup(build_corr_pyramid(f1, f2, 4, 4), coords)
        out = corr_lookup(build_corr_pyramid(f1, f2, 4, 4, dtype=dtype),
                          coords)
        err = float(jnp.max(jnp.abs(out - ref)))
        assert err <= tol_frac * float(jnp.max(jnp.abs(ref)))

    @pytest.mark.parametrize("dtype", ["bf16", "int8"])
    def test_local_lookup_error_bound(self, dtype):
        f1, f2, coords, _, _ = _setup(jax.random.PRNGKey(5), h=8, w=10)
        ref = build_local_corr(f1, f2, 4, 4)(coords)
        out = build_local_corr(f1, f2, 4, 4, dtype=dtype)(coords)
        err = float(jnp.max(jnp.abs(out - ref)))
        # the on-demand path quantizes fmap2 BEFORE the C-dim dot, so the
        # error grows ~sqrt(C); still small relative to the corr range
        assert err <= 0.05 * float(jnp.max(jnp.abs(ref)))

    def test_bf16_pyramid_gradients_flow(self):
        """bf16 storage must stay trainable (the astype is
        differentiable); this is what licenses --corr_dtype bf16 on
        train_cli."""
        f1, f2, coords, _, _ = _setup(jax.random.PRNGKey(6), h=6, w=6, c=8)

        def loss(f1_, f2_):
            lc = build_local_corr(f1_, f2_, 2, 2, dtype="bf16")
            return jnp.sum(lc(coords) ** 2)

        g1, g2 = jax.grad(loss, argnums=(0, 1))(f1, f2)
        assert float(jnp.abs(g1).max()) > 0
        assert float(jnp.abs(g2).max()) > 0


class TestModelFusedPath:
    """Whole-model fused step vs the unfused path, SAME parameters —
    the checkpoint-interchange contract of FusedCorrEncoder."""

    @pytest.fixture(scope="class")
    def fixture(self):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        img = jnp.zeros((1, 32, 32, 3), jnp.float32)
        im1 = jax.random.uniform(jax.random.PRNGKey(1), (1, 32, 32, 3),
                                 jnp.float32, 0, 255)
        im2 = jax.random.uniform(jax.random.PRNGKey(2), (1, 32, 32, 3),
                                 jnp.float32, 0, 255)
        cfg_l = raft_v1(small=True, corr_impl="local")
        variables = RAFT(cfg_l).init(jax.random.PRNGKey(0), img, img,
                                     iters=1, train=False)
        ref = RAFT(cfg_l).apply(variables, im1, im2, iters=2, train=False)
        return im1, im2, variables, ref

    def test_param_tree_identical(self, fixture, monkeypatch):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        img = jnp.zeros((1, 32, 32, 3), jnp.float32)
        _, _, variables, _ = fixture
        cfg_f = raft_v1(small=True, corr_impl="pallas", fused_update=True)
        v_f = RAFT(cfg_f).init(jax.random.PRNGKey(0), img, img,
                               iters=1, train=False)
        assert (jax.tree_util.tree_structure(v_f)
                == jax.tree_util.tree_structure(variables))
        assert (jax.tree_util.tree_map(lambda x: x.shape, v_f)
                == jax.tree_util.tree_map(lambda x: x.shape, variables))

    def test_fused_forward_matches_unfused(self, fixture, monkeypatch):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        monkeypatch.setenv("DEXIRAFT_PALLAS_INTERPRET", "1")
        im1, im2, variables, ref = fixture
        cfg_f = raft_v1(small=True, corr_impl="pallas", fused_update=True)
        out = RAFT(cfg_f).apply(variables, im1, im2, iters=2, train=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("dtype,px_bound", [("bf16", 0.05),
                                                ("int8", 0.25)])
    def test_quantized_flow_drift_bounded(self, fixture, dtype, px_bound):
        """End-to-end flow drift of the quantized pyramid on the tiny
        fixture (allpairs path — no interpret-mode kernel, so cheap).
        Measured: bf16 ~0.016 px max, int8 ~0.041 px max at 2 iters;
        bounds leave headroom for rng/platform wiggle without ever
        letting a broken dequant (errors >> 1 px) pass."""
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        im1, im2, variables, ref = fixture
        cfg_q = raft_v1(small=True, corr_dtype=dtype)
        out = RAFT(cfg_q).apply(variables, im1, im2, iters=2, train=False)
        drift = float(jnp.max(jnp.abs(out - ref)))
        assert drift <= px_bound, f"{dtype} flow drift {drift} px"

    def test_int8_train_refused(self, fixture):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        im1, im2, variables, _ = fixture
        with pytest.raises(ValueError, match="int8.*inference"):
            RAFT(raft_v1(small=True, corr_dtype="int8")).apply(
                variables, im1, im2, iters=1, train=True)

    def test_fused_requires_pallas(self, fixture):
        from dexiraft_tpu.config import raft_v1
        from dexiraft_tpu.models.raft import RAFT

        im1, im2, variables, _ = fixture
        with pytest.raises(ValueError, match="fused_update.*pallas"):
            RAFT(raft_v1(small=True, fused_update=True)).apply(
                variables, im1, im2, iters=1, train=False)
