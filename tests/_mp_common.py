"""Shared fixtures for the true multi-process distributed test.

Lives outside test_*.py so both the pytest parent and the spawned child
processes (tests/multiproc_child.py) import the exact same dataset and
model configuration — the grad-parity assertion is only meaningful if
every process derives identical samples and identical initial state.
"""

from __future__ import annotations

import numpy as np

from dexiraft_tpu.config import TrainConfig, raft_v1

GLOBAL_BATCH = 8
IMAGE_SIZE = (48, 64)
SEED = 7
N_STEPS = 3

# --- cross-process context-parallel (ring) test geometry ---------------------
# H must divide by n_seq * 2^(CP_LEVELS-1) = 4 * 4 (ring_corr_lookup's
# pooling-alignment requirement)
CP_B, CP_H, CP_W, CP_C = 1, 16, 16, 16
CP_LEVELS, CP_RADIUS = 3, 3


def cp_full_inputs():
    """Deterministic full-size ring-test inputs — identical in every
    child process and in the parent's unsharded reference."""
    rng = np.random.default_rng(42)
    f1 = rng.normal(size=(CP_B, CP_H, CP_W, CP_C)).astype(np.float32)
    f2 = rng.normal(size=(CP_B, CP_H, CP_W, CP_C)).astype(np.float32)
    ys, xs = np.meshgrid(np.arange(CP_H), np.arange(CP_W), indexing="ij")
    base = np.stack([xs, ys], axis=-1)[None].astype(np.float32)
    coords = base + rng.uniform(
        -2, 2, size=(CP_B, CP_H, CP_W, 2)).astype(np.float32)
    return f1, f2, coords


class SyntheticFlowDataset:
    """Deterministic function of the sample index alone (the loader's
    counter-based aug rng is deliberately ignored): any process can
    reproduce any sample, which is what lets the parent rebuild the
    children's global batches exactly. Each sample also carries its own
    index so the test can verify WHICH samples each host decoded."""

    def __init__(self, n: int = 32, size=IMAGE_SIZE):
        self.n = n
        self.h, self.w = size

    def __len__(self) -> int:
        return self.n

    def sample(self, index: int, rng) -> dict:
        del rng
        r = np.random.default_rng(1000 + index)
        img2 = r.uniform(0, 255, (self.h, self.w, 3)).astype(np.float32)
        # small smooth flow; image1 as a plain shift keeps this cheap —
        # convergence is not under test here, numerics parity is
        flow = np.broadcast_to(
            r.uniform(-2, 2, (1, 1, 2)), (self.h, self.w, 2)
        ).astype(np.float32)
        img1 = np.roll(img2, (1, 1), axis=(0, 1))
        return {
            "image1": img1,
            "image2": img2,
            "flow": np.ascontiguousarray(flow),
            "valid": np.ones((self.h, self.w), np.float32),
            "index": np.asarray(index, np.int32),
        }


def make_configs():
    cfg = raft_v1(small=True, mixed_precision=False)
    tc = TrainConfig(name="mp-test", num_steps=16, batch_size=GLOBAL_BATCH,
                     image_size=IMAGE_SIZE, iters=2, lr=1e-4, wdecay=1e-5)
    return cfg, tc


def spawn_child_pair(child_path, outs, ckpt_dir, extra=(),
                     timeout: float = 300.0):
    """Two spawned children, one rendezvous port; returns
    ([rc0, rc1], [log0, log1], wall_s).

    Shared by tests/test_zzmultihost_resilience.py and
    scripts/chaos_smoke.py (multihost phase) so the pair orchestration
    cannot drift between the suite and the smoke. Never raises on a
    hung child: it is killed and reaped, its log slot is the
    '<killed: timed out>' placeholder, and its returncode reports the
    kill — callers assert on exit codes with the surviving logs
    attached, which is exactly the diagnosis a hang needs.
    XLA_FLAGS is stripped so children control their own virtual device
    count."""
    import os
    import socket
    import subprocess
    import sys
    import time

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    t0 = time.perf_counter()
    procs = [subprocess.Popen(
        [sys.executable, str(child_path), "--port", str(port),
         "--process_id", str(pid), "--out", str(out),
         "--ckpt_dir", str(ckpt_dir), *[str(a) for a in extra]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid, out in enumerate(outs)]
    logs = []
    try:
        for p in procs:
            try:
                logs.append(p.communicate(timeout=timeout)[0]
                            .decode(errors="replace"))
            except subprocess.TimeoutExpired:
                logs.append("<killed: timed out>")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    return [p.returncode for p in procs], logs, time.perf_counter() - t0


def free_port() -> int:
    """An OS-assigned free TCP port for a coordination service."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_child(child_path, out, ckpt_dir, port, process_id, extra=()):
    """Popen ONE child. The elastic scenarios need heterogeneous
    worlds — a solo incumbent plus a later --join replacement, or a
    parity-reference rerun — which the symmetric pair launcher cannot
    express. Same CLI surface and XLA_FLAGS hygiene as
    spawn_child_pair; reap with reap_children."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    return subprocess.Popen(
        [sys.executable, str(child_path), "--port", str(port),
         "--process_id", str(process_id), "--out", str(out),
         "--ckpt_dir", str(ckpt_dir), *[str(a) for a in extra]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def reap_children(procs, timeout: float = 300.0):
    """Collect launch_child processes: ([rc...], [log...], wall_s),
    with the same never-raise/kill-on-timeout contract as
    spawn_child_pair."""
    import subprocess
    import time

    t0 = time.perf_counter()
    logs = []
    try:
        for p in procs:
            try:
                logs.append(p.communicate(timeout=timeout)[0]
                            .decode(errors="replace"))
            except subprocess.TimeoutExpired:
                logs.append("<killed: timed out>")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
    return [p.returncode for p in procs], logs, time.perf_counter() - t0


def patch_orbax_kv_barriers(cap_timeout_s=None) -> None:
    """Reroute orbax's process-sync onto its distributed-client barrier.

    orbax 0.7.0's ``sync_global_processes`` defaults to an XLA allgather
    (``multihost_utils.sync_global_devices``) that this container's CPU
    backend cannot run ("Multiprocess computations aren't implemented on
    the CPU backend") — but orbax already ships the non-XLA alternative,
    ``get_barrier_sync_fn`` over the jax.distributed coordination
    service (the path newer orbax versions default to). Semantically the
    same barrier, carried by gRPC instead of a compiled collective.

    Called by the multiprocess resilience children (and the chaos-smoke
    multihost phase): on real TPU pods the XLA barrier exists and this
    shim is unnecessary; on the 2-process virtual CPU mesh it is the
    difference between exercising the real multiprocess checkpoint path
    and not testing it at all.

    cap_timeout_s caps every barrier's timeout (elastic children pass a
    few seconds): a checkpoint barrier against a DEAD peer then fails
    fast instead of pinning the flush — and with it anything behind the
    wait_pending barrier — for orbax's default 300 s, which would
    swallow the whole elastic recovery budget. Healthy barriers are
    unaffected: the elastic worlds rendezvous at consensus boundaries,
    so real flush skew is milliseconds.
    """
    from orbax.checkpoint import multihost as omh_pkg
    from orbax.checkpoint.multihost import utils as omh

    def kv_sync(name, *, timeout=None, processes=None,
                barrier_sync_fn=None):
        from jax._src import distributed

        if barrier_sync_fn is None and distributed.global_state.client \
                is None:
            return  # solo world (or mid-elastic-reconfig): nobody to sync
        fn = barrier_sync_fn or omh.get_barrier_sync_fn(
            processes=processes)
        timeout_s = timeout or 300
        if cap_timeout_s is not None:
            timeout_s = min(timeout_s, cap_timeout_s)
        # flight-recorder stamp: the barrier key is the protocol
        # identity (identical across hosts for a lockstep barrier)
        from dexiraft_tpu.analysis import collective_trace

        collective_trace.record(
            "dexiraft/barrier", "orbax_sync",
            digest=collective_trace.args_digest(str(name)))
        fn(key=name, timeout_ms=int(timeout_s * 1000))

    omh.sync_global_processes = kv_sync
    omh_pkg.sync_global_processes = kv_sync
