"""Shared fixtures for the true multi-process distributed test.

Lives outside test_*.py so both the pytest parent and the spawned child
processes (tests/multiproc_child.py) import the exact same dataset and
model configuration — the grad-parity assertion is only meaningful if
every process derives identical samples and identical initial state.
"""

from __future__ import annotations

import numpy as np

from dexiraft_tpu.config import TrainConfig, raft_v1

GLOBAL_BATCH = 8
IMAGE_SIZE = (48, 64)
SEED = 7
N_STEPS = 3

# --- cross-process context-parallel (ring) test geometry ---------------------
# H must divide by n_seq * 2^(CP_LEVELS-1) = 4 * 4 (ring_corr_lookup's
# pooling-alignment requirement)
CP_B, CP_H, CP_W, CP_C = 1, 16, 16, 16
CP_LEVELS, CP_RADIUS = 3, 3


def cp_full_inputs():
    """Deterministic full-size ring-test inputs — identical in every
    child process and in the parent's unsharded reference."""
    rng = np.random.default_rng(42)
    f1 = rng.normal(size=(CP_B, CP_H, CP_W, CP_C)).astype(np.float32)
    f2 = rng.normal(size=(CP_B, CP_H, CP_W, CP_C)).astype(np.float32)
    ys, xs = np.meshgrid(np.arange(CP_H), np.arange(CP_W), indexing="ij")
    base = np.stack([xs, ys], axis=-1)[None].astype(np.float32)
    coords = base + rng.uniform(
        -2, 2, size=(CP_B, CP_H, CP_W, 2)).astype(np.float32)
    return f1, f2, coords


class SyntheticFlowDataset:
    """Deterministic function of the sample index alone (the loader's
    counter-based aug rng is deliberately ignored): any process can
    reproduce any sample, which is what lets the parent rebuild the
    children's global batches exactly. Each sample also carries its own
    index so the test can verify WHICH samples each host decoded."""

    def __init__(self, n: int = 32, size=IMAGE_SIZE):
        self.n = n
        self.h, self.w = size

    def __len__(self) -> int:
        return self.n

    def sample(self, index: int, rng) -> dict:
        del rng
        r = np.random.default_rng(1000 + index)
        img2 = r.uniform(0, 255, (self.h, self.w, 3)).astype(np.float32)
        # small smooth flow; image1 as a plain shift keeps this cheap —
        # convergence is not under test here, numerics parity is
        flow = np.broadcast_to(
            r.uniform(-2, 2, (1, 1, 2)), (self.h, self.w, 2)
        ).astype(np.float32)
        img1 = np.roll(img2, (1, 1), axis=(0, 1))
        return {
            "image1": img1,
            "image2": img2,
            "flow": np.ascontiguousarray(flow),
            "valid": np.ones((self.h, self.w), np.float32),
            "index": np.asarray(index, np.int32),
        }


def make_configs():
    cfg = raft_v1(small=True, mixed_precision=False)
    tc = TrainConfig(name="mp-test", num_steps=16, batch_size=GLOBAL_BATCH,
                     image_size=IMAGE_SIZE, iters=2, lr=1e-4, wdecay=1e-5)
    return cfg, tc
