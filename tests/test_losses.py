"""Parity test for sequence_loss vs. the reference (train.py:48-73)."""

import numpy as np
import pytest

from dexiraft_tpu.ops import sequence_loss

torch = pytest.importorskip("torch")


def torch_sequence_loss(flow_preds, flow_gt, valid, gamma=0.8, max_flow=400.0):
    n_predictions = len(flow_preds)
    flow_loss = 0.0
    mag = torch.sum(flow_gt**2, dim=1).sqrt()
    valid = (valid >= 0.5) & (mag < max_flow)
    for i in range(n_predictions):
        i_weight = gamma ** (n_predictions - i - 1)
        i_loss = (flow_preds[i] - flow_gt).abs()
        flow_loss += i_weight * (valid[:, None] * i_loss).mean()
    epe = torch.sum((flow_preds[-1] - flow_gt) ** 2, dim=1).sqrt()
    epe = epe.view(-1)[valid.view(-1)]
    metrics = {
        "epe": epe.mean().item(),
        "1px": (epe < 1).float().mean().item(),
        "3px": (epe < 3).float().mean().item(),
        "5px": (epe < 5).float().mean().item(),
    }
    return flow_loss.item(), metrics


@pytest.mark.parametrize("gamma", [0.8, 0.85])
def test_sequence_loss_matches_reference(gamma):
    rng = np.random.RandomState(0)
    iters, B, H, W = 5, 2, 8, 10
    preds = rng.randn(iters, B, H, W, 2).astype(np.float32) * 3
    gt = rng.randn(B, H, W, 2).astype(np.float32) * 3
    # mix of valid/invalid and one huge-magnitude pixel to hit the mag mask
    valid = (rng.rand(B, H, W) > 0.3).astype(np.float32)
    gt[0, 0, 0] = [500.0, 0.0]

    loss, metrics = sequence_loss(preds, gt, valid, gamma=gamma)

    t_preds = [torch.from_numpy(p.transpose(0, 3, 1, 2)) for p in preds]
    ref_loss, ref_metrics = torch_sequence_loss(
        t_preds,
        torch.from_numpy(gt.transpose(0, 3, 1, 2)),
        torch.from_numpy(valid),
        gamma=gamma,
    )

    np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
    for k in ref_metrics:
        np.testing.assert_allclose(float(metrics[k]), ref_metrics[k], rtol=1e-4, atol=1e-5)


def test_sequence_loss_matches_reference_source():
    """Same parity, but against the reference's ACTUAL train.sequence_loss
    imported from the checkout (train.py:48-73) — the transcription above
    could drift; the source of truth cannot."""
    import os.path as osp
    import sys

    if not osp.isdir("/root/reference/core"):
        pytest.skip("reference checkout not mounted")
    import test_eval_stack_parity as parity

    parity._import_ref_evaluate()  # stubs torchvision, loads siblings
    for p in ("/root/reference", "/root/reference/core"):
        sys.path.insert(0, p)
    try:
        import train as ref_train
    finally:
        for p in ("/root/reference", "/root/reference/core"):
            sys.path.remove(p)

    rng = np.random.RandomState(7)
    iters, b, h, w = 4, 2, 8, 10
    preds = rng.randn(iters, b, h, w, 2).astype(np.float32) * 3
    gt = rng.randn(b, h, w, 2).astype(np.float32) * 3
    valid = (rng.rand(b, h, w) > 0.3).astype(np.float32)
    gt[0, 0, 0] = [500.0, 0.0]  # hits the MAX_FLOW magnitude mask

    loss, metrics = sequence_loss(preds, gt, valid)

    t_preds = [torch.from_numpy(p.transpose(0, 3, 1, 2)) for p in preds]
    ref_loss, ref_metrics = ref_train.sequence_loss(
        t_preds, torch.from_numpy(gt.transpose(0, 3, 1, 2)),
        torch.from_numpy(valid))

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ref_metrics:
        np.testing.assert_allclose(float(metrics[k]), float(ref_metrics[k]),
                                   rtol=1e-4, atol=1e-5)
