"""scripts/serve_bench.py: tiny-geometry CPU smoke with the JSON record
schema pinned, and the stall watchdog (the bench.py pattern — a
relay-tunnel death mid-measurement must never hang the driver's
round-end run; the parent kills a silent child and exits 8).

Named to sort LAST in collection (tier-1 870 s budget convention, see
test_zpipeline_async.py).
"""

import json
import os
import os.path as osp
import subprocess
import sys

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
BENCH = osp.join(REPO, "scripts", "serve_bench.py")


def test_cpu_smoke_record_schema_and_bucket_compiles():
    """One mixed-geometry stream, batch 1 vs 4: the record is
    self-describing (schema pinned here), every config compiles EXACTLY
    one executable per bucket, and the batched configuration beats
    batch-size-1 throughput."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, BENCH, "--variant", "v1", "--small", "--iters", "2",
         # 12 frames = exact batch multiples per bucket: no tail-pad
         # slots diluting the batched config's throughput, so the
         # speedup margin stays wide (measured 1.5-2.3x; 16 frames'
         # 25% tail waste thinned it into 2-core machine-weather noise)
         "--batch", "4", "--sizes", "40x56,44x60,62x70", "--frames", "12",
         "--bucket_multiple", "16", "--inflight", "2", "--no_compile_cache",
         "--cpu"],
        env=env, capture_output=True, timeout=420)
    assert r.returncode == 0, r.stderr.decode()
    line = [ln for ln in r.stdout.decode().splitlines()
            if ln.startswith('{"metric"')]
    assert line, r.stdout.decode()
    rec = json.loads(line[-1])

    # schema pin: the queue tooling greps these fields
    sys.path.insert(0, osp.dirname(BENCH))
    try:
        from serve_bench import CONFIG_KEYS, RECORD_KEYS
    finally:
        sys.path.pop(0)
    assert set(rec) == RECORD_KEYS, sorted(set(rec) ^ RECORD_KEYS)
    assert [c["batch_size"] for c in rec["configs"]] == [1, 4]
    for c in rec["configs"]:
        assert set(c) == CONFIG_KEYS, sorted(set(c) ^ CONFIG_KEYS)
        # 40x56/44x60 -> 48x64, 62x70 -> 64x80 at multiple=16
        assert c["bucket_count"] == 2
        assert c["compiles"] == c["bucket_count"]  # exactly one per bucket
        assert c["frame_pairs_per_sec"] > 0
    assert rec["platform"] == "cpu"
    # the acceptance signal: micro-batching amortizes the prelude and
    # per-dispatch overhead, so batched throughput must win — but only
    # where there is a second core to amortize INTO; on a 1-core box the
    # larger batched working set loses to cache pressure (measured
    # 0.65x), so the perf pin holds the schema/compile assertions above
    # and stands down on single-core runners
    if (os.cpu_count() or 1) >= 2:
        assert rec["speedup_batched_over_b1"] > 1.0, rec


def test_watchdog_kills_stalled_child():
    # the fake child prints one line as soon as it is up (no jax
    # import on its path), then blocks forever; the stall threshold
    # only needs to outlast interpreter startup
    env = dict(os.environ, JAX_PLATFORMS="cpu", SERVE_BENCH_FAKE_HANG="1",
               SERVE_BENCH_STALL_S="20")
    r = subprocess.run([sys.executable, BENCH], env=env,
                       capture_output=True, timeout=180)
    assert r.returncode == 8, r.stderr.decode()
    assert b"stalled" in r.stderr
    assert b"fake child hanging" in r.stderr
