"""Throughput-mode inference engine (dexiraft_tpu.serve): bucket
pad/unpad round-trips, partial-batch tail masking, eval-forward batch
invariance, engine-vs-per-image metric parity, per-item warm-start
carry, and the empty-valid-mask sparse-metrics fix.

Named to sort LAST in collection (the test_zpipeline_async.py
convention): the tier-1 suite runs under a hard 870 s wall-clock cap
(ROADMAP.md), and inserting new files mid-order would displace the
long-standing tail tests out of the budget window.
"""

import numpy as np
import pytest

from dexiraft_tpu.data.padder import InputPadder
from dexiraft_tpu.serve import InferenceEngine, ServeConfig, bucket_shape


def _stub_eval(im1, im2, flow_init=None):
    """Constant (2, -1) prediction at any batch/geometry; warm-start
    rows add their (upsampled-by-repeat) flow_init so per-item carry is
    observable. flow_low is a PER-ITEM constant derived from the input
    (sub-pixel, so forward_interpolate round-trips it) — a zero
    flow_low would make every warm-start carry vanish and leave the
    carry ROUTING (which row feeds which sequence) unpinned."""
    b, h, w = im1.shape[:3]
    up = np.broadcast_to(np.float32([2.0, -1.0]), (b, h, w, 2)).copy()
    if flow_init is not None:
        up = up + np.repeat(np.repeat(np.asarray(flow_init), 8, 1), 8, 2)
    means = np.asarray(im1).reshape(b, -1).mean(axis=1) / 255.0  # (0, 1)
    low = np.zeros((b, h // 8, w // 8, 2), np.float32)
    low[..., 0] = means[:, None, None] * 0.4
    low[..., 1] = -0.2 * means[:, None, None]
    return low, up


def _items(geoms, seed=0):
    rng = np.random.default_rng(seed)
    return [{"image1": rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
             "image2": rng.uniform(0, 255, (h, w, 3)).astype(np.float32)}
            for h, w in geoms]


class TestBuckets:
    def test_bucket_shape_quantizes_up(self):
        assert bucket_shape(30, 41) == (32, 48)          # stride default
        assert bucket_shape(32, 48) == (32, 48)          # aligned unchanged
        assert bucket_shape(33, 49, multiple=16) == (48, 64)
        with pytest.raises(ValueError):
            bucket_shape(30, 41, multiple=12)            # not stride-aligned

    def test_padder_target_roundtrip_both_modes(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(37, 53, 2)).astype(np.float32)
        for mode in ("sintel", "kitti"):
            p = InputPadder(x.shape, mode=mode, target=(48, 64))
            (px,) = p.pad(x)
            assert px.shape == (48, 64, 2) and p.padded_shape == (48, 64)
            np.testing.assert_array_equal(p.unpad(px), x)

    def test_padder_target_matches_reference_when_stride_aligned(self):
        # target = next stride multiple reproduces the reference pad
        # placement bit for bit (the metric-parity configuration)
        x = np.arange(30 * 41 * 3, dtype=np.float32).reshape(30, 41, 3)
        ref = InputPadder(x.shape, mode="sintel")
        gen = InputPadder(x.shape, mode="sintel", target=(32, 48))
        np.testing.assert_array_equal(ref.pad(x)[0], gen.pad(x)[0])

    def test_padder_rejects_bad_targets(self):
        with pytest.raises(ValueError):
            InputPadder((40, 56, 3), target=(32, 56))    # smaller than input
        with pytest.raises(ValueError):
            InputPadder((40, 56, 3), target=(44, 56))    # not stride-aligned


class TestEngineStream:
    def test_partial_batch_tail_masked(self):
        # 5 frames over 2 buckets at batch 2: tails pad up to the batch
        # shape on device but yield EXACTLY the dataset back
        items = _items([(30, 41), (30, 41), (30, 41), (62, 70), (62, 70)])
        eng = InferenceEngine(_stub_eval, ServeConfig(batch_size=2))
        got = sorted(eng.stream(items), key=lambda r: r.index)
        assert [r.index for r in got] == [0, 1, 2, 3, 4]
        for r, it in zip(got, items):
            assert r.flow_up.shape == it["image1"].shape[:2] + (2,)
            np.testing.assert_allclose(r.flow_up, np.float32([2.0, -1.0])
                                       * np.ones_like(r.flow_up))
        assert eng.stats.frames == 5
        assert eng.stats.pad_frames == 1                 # the 30x41 tail
        assert eng.registry.stats()["bucket_count"] == 2
        assert eng.registry.compiles == 2                # one per bucket

    def test_bucket_multiple_bounds_executables(self):
        # three geometries collapse into one bucket at multiple=16
        items = _items([(40, 56), (44, 60), (36, 52), (40, 56)])
        eng = InferenceEngine(
            _stub_eval, ServeConfig(batch_size=2, bucket_multiple=16))
        got = list(eng.stream(items))
        assert len(got) == 4
        assert eng.registry.stats()["buckets"] == {"48x64": 4}
        assert eng.registry.compiles == 1

    def test_inflight_window_respected(self):
        items = _items([(30, 41)] * 7)
        eng = InferenceEngine(
            _stub_eval, ServeConfig(batch_size=1, inflight=3))
        assert len(list(eng.stream(items))) == 7
        assert eng.stats.peak_inflight == 3

    def test_run_batch_rejects_leftover_inflight(self):
        # silently fetching (and discarding) an unfinished stream()'s
        # tickets would lose frames — the engine must refuse instead
        items = _items([(30, 41)] * 4)
        eng = InferenceEngine(_stub_eval,
                              ServeConfig(batch_size=1, inflight=2))
        it = eng.stream(items)
        next(it)  # leaves dispatched tickets behind
        with pytest.raises(RuntimeError, match="in flight"):
            eng.run_batch([items[0]])

    def test_per_item_flow_init_rows(self):
        # one warm row + one cold row ride the same batch; zeros == cold
        items = _items([(32, 48), (32, 48)])
        items[0]["flow_init"] = np.full((4, 6, 2), 0.5, np.float32)
        eng = InferenceEngine(
            _stub_eval, ServeConfig(batch_size=2, warm_start=True))
        out = eng.run_batch(items)
        np.testing.assert_allclose(out[0].flow_up[0, 0], [2.5, -0.5])
        np.testing.assert_allclose(out[1].flow_up[0, 0], [2.0, -1.0])
        assert eng.registry.compiles == 1                # one signature


@pytest.fixture(scope="module")
def small_eval():
    """Real small-RAFT eval step + variables (one init, many tests)."""
    import jax

    from dexiraft_tpu.config import TrainConfig, raft_v1
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_eval_step

    cfg = raft_v1(small=True)
    tc = TrainConfig(num_steps=10, batch_size=2, image_size=(40, 56), iters=2)
    state = create_state(jax.random.PRNGKey(0), cfg, tc)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    step = make_eval_step(cfg, iters=2)
    return dict(
        cfg=cfg,
        variables=variables,
        step=step,
        fn=lambda a, b, flow_init=None: step(variables, a, b,
                                             flow_init=flow_init),
    )


class TestRealModel:
    def test_eval_forward_batch_invariant(self, small_eval):
        # batch of 3 == 3 batches of 1: eval-mode BN normalizes with
        # running stats, so no cross-item coupling survives
        rng = np.random.default_rng(1)
        im1 = rng.uniform(0, 255, (3, 40, 56, 3)).astype(np.float32)
        im2 = rng.uniform(0, 255, (3, 40, 56, 3)).astype(np.float32)
        _, up_batched = small_eval["fn"](im1, im2)
        for i in range(3):
            _, up_one = small_eval["fn"](im1[i:i + 1], im2[i:i + 1])
            np.testing.assert_allclose(np.asarray(up_batched)[i],
                                       np.asarray(up_one)[0], atol=1e-4)

    def test_engine_matches_per_image_metrics(self, small_eval):
        # the acceptance pin: --batch_size N metrics == batch-size-1
        # metrics (fp32 tolerance) on a tiny synthetic dataset
        from dexiraft_tpu.eval.validate import validate_chairs

        class DS:
            def __len__(self):
                return 3

            def sample(self, i, rng=None):
                r = np.random.default_rng(i)
                return {
                    "image1": r.uniform(0, 255, (37, 53, 3)).astype(np.float32),
                    "image2": r.uniform(0, 255, (37, 53, 3)).astype(np.float32),
                    "flow": np.broadcast_to(np.float32([2.0, -1.0]),
                                            (37, 53, 2)).copy(),
                    "valid": np.ones((37, 53), np.float32),
                }

        ref = validate_chairs(small_eval["fn"], DS())
        batched = validate_chairs(small_eval["fn"], DS(), batch_size=2)
        np.testing.assert_allclose(batched["chairs"], ref["chairs"],
                                   rtol=1e-5, atol=1e-5)

    def test_data_parallel_engine_matches_single_chip(self, small_eval):
        # the first multi-chip eval path: batch sharded over 'data',
        # pinned in_shardings, per-item results identical
        from dexiraft_tpu.parallel.mesh import make_serve_mesh
        from dexiraft_tpu.train.step import make_eval_step

        mesh = make_serve_mesh(2)
        stepm = make_eval_step(small_eval["cfg"], iters=2, mesh=mesh)
        variables = small_eval["variables"]
        items = _items([(37, 53)] * 3, seed=2)
        single = InferenceEngine(
            lambda a, b, fi: small_eval["step"](variables, a, b,
                                                flow_init=fi),
            ServeConfig(batch_size=2))
        sharded = InferenceEngine(
            lambda a, b, fi: stepm(variables, a, b, None, None, fi),
            ServeConfig(batch_size=2), mesh=mesh)
        ref = {r.index: r.flow_up
               for r in single.stream(dict(it) for it in items)}
        got = {r.index: r.flow_up
               for r in sharded.stream(dict(it) for it in items)}
        for i in ref:
            np.testing.assert_allclose(got[i], ref[i], atol=1e-4)

    def test_engine_rejects_indivisible_mesh_batch(self, small_eval):
        from dexiraft_tpu.parallel.mesh import make_serve_mesh

        with pytest.raises(ValueError, match="divisible"):
            InferenceEngine(small_eval["fn"], ServeConfig(batch_size=3),
                            mesh=make_serve_mesh(2))


class TestSparseMetricsFix:
    def _ds(self, empty_frames=()):
        class DS:
            def __len__(self):
                return 3

            def sample(self, i, rng=None):
                r = np.random.default_rng(i)
                s = {
                    "image1": r.uniform(0, 255, (32, 48, 3)).astype(np.float32),
                    "image2": r.uniform(0, 255, (32, 48, 3)).astype(np.float32),
                    "flow": np.broadcast_to(np.float32([2.0, -1.0]),
                                            (32, 48, 2)).copy(),
                    "valid": np.zeros((32, 48), np.float32)
                    if i in empty_frames
                    else np.ones((32, 48), np.float32),
                }
                return s

        return DS()

    def test_empty_mask_frame_skipped_not_nan(self, capsys):
        from dexiraft_tpu.eval.validate import validate_kitti

        res = validate_kitti(_stub_eval, self._ds(empty_frames=(1,)))
        assert np.isfinite(res["kitti-epe"])             # NaN before the fix
        np.testing.assert_allclose(res["kitti-epe"], 0.0, atol=1e-5)
        assert "1 empty-mask frames skipped" in capsys.readouterr().out

    def test_all_empty_raises(self):
        from dexiraft_tpu.eval.validate import _sparse_metrics

        with pytest.raises(ValueError, match="empty valid mask"):
            _sparse_metrics(_stub_eval, self._ds(empty_frames=(0, 1, 2)),
                            "kitti")

    def test_batched_sparse_matches_per_image(self):
        from dexiraft_tpu.eval.validate import validate_kitti

        ref = validate_kitti(_stub_eval, self._ds(empty_frames=(2,)))
        got = validate_kitti(_stub_eval, self._ds(empty_frames=(2,)),
                             batch_size=2)
        np.testing.assert_allclose(got["kitti-epe"], ref["kitti-epe"],
                                   atol=1e-6)
        np.testing.assert_allclose(got["kitti-f1"], ref["kitti-f1"],
                                   atol=1e-6)


class TestBatchedSubmission:
    def test_sintel_batched_equals_per_frame(self, tmp_path):
        """Two sequences abreast with per-item warm-start carry write
        byte-identical .flo trees to the reference per-frame loop."""
        from dexiraft_tpu.data.flow_io import read_flo
        from dexiraft_tpu.eval.submission import create_sintel_submission

        class SintelStub:
            def __init__(self, lens=(3, 2)):
                self.extra_info = [(f"seq_{s}", j)
                                   for s, n in enumerate(lens)
                                   for j in range(n)]

            def __len__(self):
                return len(self.extra_info)

            def sample(self, i, rng=None):
                r = np.random.default_rng(i)
                return {"image1": r.uniform(0, 255, (36, 48, 3))
                        .astype(np.float32),
                        "image2": r.uniform(0, 255, (36, 48, 3))
                        .astype(np.float32),
                        "extra_info": self.extra_info[i]}

        for warm in (True, False):  # False = the pipelined stream() path
            outs = {}
            for bs in (1, 2):
                out = tmp_path / f"sub_w{warm}_b{bs}"
                create_sintel_submission(
                    _stub_eval, output_path=str(out), warm_start=warm,
                    datasets={"clean": SintelStub()}, batch_size=bs)
                outs[bs] = {p.relative_to(out): read_flo(p)
                            for p in sorted(out.rglob("*.flo"))}
            assert set(outs[1]) == set(outs[2]) and len(outs[1]) == 5
            for name in outs[1]:
                np.testing.assert_allclose(outs[2][name], outs[1][name],
                                           atol=1e-5, err_msg=str(name))

    def test_kitti_batched_equals_per_frame(self, tmp_path):
        from dexiraft_tpu.data.flow_io import read_flow_kitti
        from dexiraft_tpu.eval.submission import create_kitti_submission

        class KittiStub:
            def __len__(self):
                return 3

            def sample(self, i, rng=None):
                r = np.random.default_rng(i)
                return {"image1": r.uniform(0, 255, (30, 41, 3))
                        .astype(np.float32),
                        "image2": r.uniform(0, 255, (30, 41, 3))
                        .astype(np.float32),
                        "extra_info": [f"{i:06d}_10.png"]}

        for bs in (1, 2):
            create_kitti_submission(_stub_eval,
                                    output_path=str(tmp_path / f"k{bs}"),
                                    dataset=KittiStub(), batch_size=bs)
        for i in range(3):
            a, _ = read_flow_kitti(tmp_path / "k1" / f"{i:06d}_10.png")
            b, _ = read_flow_kitti(tmp_path / "k2" / f"{i:06d}_10.png")
            np.testing.assert_allclose(b, a, atol=1e-6)
