"""Parity tests for the all-pairs correlation volume against the reference
CorrBlock semantics (core/corr.py:12-60), re-implemented here in torch.
"""

import numpy as np
import pytest

from dexiraft_tpu.ops import build_corr_pyramid, corr_lookup

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402


class TorchCorrBlock:
    """Reference CorrBlock (core/corr.py) including its transposed window
    ordering (meshgrid(dy, dx) stacked onto (x, y) centroids,
    core/corr.py:37-43) — our implementation matches it bit-for-bit so
    reference-trained checkpoints load (see ops/corr.py:_window_delta and
    tests/test_torch_interop.py for the real-reference check)."""

    def __init__(self, fmap1, fmap2, num_levels=4, radius=4):
        self.num_levels = num_levels
        self.radius = radius
        b, dim, h, w = fmap1.shape
        f1 = fmap1.view(b, dim, h * w)
        f2 = fmap2.view(b, dim, h * w)
        corr = torch.matmul(f1.transpose(1, 2), f2) / (dim**0.5)
        corr = corr.view(b * h * w, 1, h, w)
        self.batch, self.h, self.w = b, h, w
        self.pyramid = [corr]
        for _ in range(num_levels - 1):
            corr = F.avg_pool2d(corr, 2, stride=2)
            self.pyramid.append(corr)

    def __call__(self, coords):  # coords (B, 2, H, W), channels (x, y)
        r = self.radius
        coords = coords.permute(0, 2, 3, 1)
        b, h, w, _ = coords.shape
        out = []
        for i, corr in enumerate(self.pyramid):
            d = torch.linspace(-r, r, 2 * r + 1)
            di, dj = torch.meshgrid(d, d, indexing="ij")
            # reference ordering: axis-0 offset added to x, axis-1 to y
            delta = torch.stack([di, dj], dim=-1)
            centroid = coords.reshape(b * h * w, 1, 1, 2) / 2**i
            coords_lvl = centroid + delta.view(1, 2 * r + 1, 2 * r + 1, 2)

            H, W = corr.shape[-2:]
            xg, yg = coords_lvl.split([1, 1], dim=-1)
            xg = 2 * xg / (W - 1) - 1
            yg = 2 * yg / (H - 1) - 1
            sampled = F.grid_sample(
                corr, torch.cat([xg, yg], dim=-1), align_corners=True
            )
            out.append(sampled.view(b, h, w, -1))
        return torch.cat(out, dim=-1)


@pytest.mark.parametrize("radius,num_levels", [(4, 4), (3, 4), (2, 2)])
def test_corr_pyramid_and_lookup_match_torch(radius, num_levels):
    rng = np.random.RandomState(0)
    # keep every pyramid level >= 2 in both dims: torch's grid normalization
    # divides by (size-1) and NaNs out on singleton levels
    B, H, W, D = 2, 16, 24, 8
    f1 = rng.randn(B, H, W, D).astype(np.float32)
    f2 = rng.randn(B, H, W, D).astype(np.float32)
    coords = (
        np.stack(np.meshgrid(np.arange(W), np.arange(H)), axis=-1)[None]
        .repeat(B, axis=0)
        .astype(np.float32)
    )
    coords += rng.uniform(-2, 2, coords.shape).astype(np.float32)

    pyr = build_corr_pyramid(f1, f2, num_levels=num_levels, radius=radius)
    ours = np.asarray(corr_lookup(pyr, coords))

    tb = TorchCorrBlock(
        torch.from_numpy(f1.transpose(0, 3, 1, 2)),
        torch.from_numpy(f2.transpose(0, 3, 1, 2)),
        num_levels=num_levels,
        radius=radius,
    )
    ref = tb(torch.from_numpy(coords.transpose(0, 3, 1, 2))).numpy()

    assert ours.shape == (B, H, W, num_levels * (2 * radius + 1) ** 2)
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_pyramid_shapes_floor_division():
    # odd spatial dims must floor like avg_pool2d (e.g. Sintel 55x128 at 1/8)
    rng = np.random.RandomState(1)
    f = rng.randn(1, 55, 13, 4).astype(np.float32)
    pyr = build_corr_pyramid(f, f, num_levels=4, radius=4)
    shapes = [lvl.shape[1:3] for lvl in pyr.levels]
    assert shapes == [(55, 13), (27, 6), (13, 3), (6, 1)]


def test_lookup_finite_at_one_pixel_levels():
    """A pyramid level that collapses to a single row/col must still
    produce finite lookups. The reference's bilinear_sampler normalizes
    grid coords by (dim-1) (core/utils/utils.py:63-66), so a 1-pixel
    level divides by zero and floods the update block with nan (observed
    in tests/test_eval_stack_parity.py at 104x136 inputs). Our one-hot
    interpolation matmul uses absolute coords and stays finite at every
    size — small-image inference just works."""
    from dexiraft_tpu.ops import coords_grid

    rng = np.random.RandomState(3)
    f = rng.randn(1, 13, 17, 8).astype(np.float32)  # 104x136 at 1/8
    pyr = build_corr_pyramid(f, f, num_levels=4, radius=4)
    assert pyr.levels[-1].shape[1:3] == (1, 2)  # degenerate level hit
    out = corr_lookup(pyr, coords_grid(1, 13, 17))
    assert np.isfinite(np.asarray(out)).all()


def test_corr_pyramid_is_jit_safe_pytree():
    """Geometry ints are static aux data — jit/scan must not trace them."""
    import jax

    from dexiraft_tpu.ops import coords_grid

    rng = np.random.RandomState(2)
    f = rng.randn(1, 16, 16, 8).astype(np.float32)
    pyr = build_corr_pyramid(f, f)
    out = jax.jit(corr_lookup)(pyr, coords_grid(1, 16, 16))
    assert out.shape == (1, 16, 16, 324)
