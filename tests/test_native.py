"""Native C++ decoder parity with the Python codecs."""

import numpy as np
import pytest

from dexiraft_tpu.data import native
from dexiraft_tpu.data.flow_io import write_flo


@pytest.fixture(autouse=True)
def _require_native():
    """Lazy skip: only selected native tests pay the g++ build (a
    module-level skipif would compile during collection)."""
    if native.get_lib() is None:
        pytest.skip("native library unavailable")


def _write_ppm(path, img):
    import imageio.v2 as imageio

    imageio.imwrite(path, img)


def test_flo_parity(tmp_path):
    flow = np.random.default_rng(0).normal(size=(37, 53, 2)).astype(np.float32)
    p = tmp_path / "a.flo"
    write_flo(p, flow)
    out = native.read_flo_native(p)
    np.testing.assert_array_equal(out, flow)


def test_ppm_parity(tmp_path):
    import imageio.v2 as imageio

    img = np.random.default_rng(1).integers(0, 256, (41, 29, 3), dtype=np.uint8)
    p = tmp_path / "a.ppm"
    _write_ppm(p, img)
    out = native.read_ppm_native(p)
    np.testing.assert_array_equal(out, np.asarray(imageio.imread(p)))


def test_flo_batch(tmp_path):
    rng = np.random.default_rng(2)
    flows = [rng.normal(size=(16, 24, 2)).astype(np.float32) for _ in range(5)]
    paths = []
    for i, f in enumerate(flows):
        p = tmp_path / f"{i}.flo"
        write_flo(p, f)
        paths.append(str(p))
    out = native.read_flo_batch(paths, 16, 24, nthreads=4)
    np.testing.assert_array_equal(out, np.stack(flows))


def test_ppm_batch(tmp_path):
    rng = np.random.default_rng(3)
    imgs = [rng.integers(0, 256, (16, 24, 3), dtype=np.uint8) for _ in range(5)]
    paths = []
    for i, im in enumerate(imgs):
        p = tmp_path / f"{i}.ppm"
        _write_ppm(p, im)
        paths.append(str(p))
    out = native.read_ppm_batch(paths, 16, 24, nthreads=4)
    np.testing.assert_array_equal(out, np.stack(imgs))


def test_bad_file_declined_not_raised(tmp_path):
    """Single-file native decode declines gracefully (caller falls back to
    the Python codec, which owns the error reporting)."""
    p = tmp_path / "bad.flo"
    p.write_bytes(b"not a flo file at all")
    assert native.read_flo_native(p) is None
    from dexiraft_tpu.data.flow_io import read_flo

    with pytest.raises(ValueError):
        read_flo(p)  # Python codec raises the descriptive error


def test_dims_mismatch_in_batch(tmp_path):
    write_flo(tmp_path / "a.flo", np.zeros((8, 8, 2), np.float32))
    with pytest.raises(IOError):
        native.read_flo_batch([str(tmp_path / "a.flo")], 16, 16)


def test_flow_io_routes_through_native(tmp_path):
    """read_flo/read_image transparently use the native path."""
    from dexiraft_tpu.data.flow_io import read_flo, read_image

    flow = np.random.default_rng(4).normal(size=(9, 11, 2)).astype(np.float32)
    write_flo(tmp_path / "f.flo", flow)
    np.testing.assert_array_equal(read_flo(tmp_path / "f.flo"), flow)

    img = np.random.default_rng(5).integers(0, 256, (9, 11, 3), dtype=np.uint8)
    _write_ppm(tmp_path / "i.ppm", img)
    np.testing.assert_array_equal(read_image(tmp_path / "i.ppm"), img)
