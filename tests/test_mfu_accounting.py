"""MFU accounting helpers in bench.py (VERDICT r4 next-3).

The on-chip MFU number itself needs the real chip; what is testable here
is the accounting machinery: XLA's cost analysis yields a plausible FLOP
count for a known workload, and the chip-peak table is sane.
"""

import jax
import jax.numpy as jnp

import bench


def test_counted_flops_matches_matmul_arithmetic():
    n = 256
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((n, n), jnp.float32)
    flops = bench._counted_flops(f, a, a)
    assert flops is not None
    # one n^3 matmul = 2n^3 flops; XLA may count fused epilogue ops on
    # top, so bound loosely from both sides
    assert 0.5 * 2 * n**3 <= flops <= 4 * 2 * n**3


def test_counted_flops_never_raises_on_junk():
    # a non-jitted callable has no .lower — the helper must return None,
    # not propagate (the bench record may never fail over accounting)
    assert bench._counted_flops(lambda x: x, jnp.ones(3)) is None


def test_chip_peak_table_sane():
    assert all(1e13 < v < 1e16 for v in bench.CHIP_PEAK_BF16_FLOPS.values())
    # the chip this project benches on must be present under both the
    # device_kind spellings seen from jax
    assert "TPU v5 lite" in bench.CHIP_PEAK_BF16_FLOPS
    assert bench.CHIP_PEAK_BF16_FLOPS["TPU v5 lite"] == 197e12
