"""jaxlint + runtime guards coverage (docs/static_analysis.md).

One positive + one negative fixture per jaxlint rule, the suppression /
baseline / exclude mechanics, the lint-gate CLI contract (exit 0 on the
shipped tree, nonzero the moment a fixture footgun is introduced), and
the runtime half: compile_count / RecompileWatch / strict_mode.

Named zzz to sort LAST (tier-1 budget convention — the 870 s cap evicts
tail tests, and these are cheap: target well under 15 s total; the only
jax work is a handful of tiny CPU jits).
"""

from __future__ import annotations

import json
import os.path as osp
import subprocess
import sys
import textwrap

import pytest

from dexiraft_tpu.analysis import jaxlint

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))
GATE = osp.join(REPO, "scripts", "lint_gate.py")


def rules_of(src: str, path: str = "dexiraft_tpu/train/fixture.py"):
    """Set of rule ids jaxlint raises on a dedented fixture snippet."""
    return {f.rule for f in jaxlint.lint_source(textwrap.dedent(src), path)}


# --------------------------------------------------------------------------
# per-rule fixtures: positive (fires) + negative (sanctioned spelling)
# --------------------------------------------------------------------------


class TestRuleFixtures:
    def test_jl001_host_sync_in_jit(self):
        pos = """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x) + 1
        """
        assert "JL001" in rules_of(pos)
        # .item() on a tracer
        pos2 = """
            import jax

            @jax.jit
            def f(x):
                return x.sum().item()
        """
        assert "JL001" in rules_of(pos2)
        # float() on a traced argument
        pos3 = """
            import jax

            @jax.jit
            def f(x):
                return float(x)
        """
        assert "JL001" in rules_of(pos3)
        neg = """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return x + 1

            y = np.asarray(f(np.ones(3)))  # outside jit: JL007's domain
        """
        assert "JL001" not in rules_of(neg)

    def test_jl002_key_reuse(self):
        pos = """
            import jax
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
        """
        assert "JL002" in rules_of(pos)
        neg = """
            import jax
            key = jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (2,))
            b = jax.random.uniform(k2, (2,))
        """
        assert "JL002" not in rules_of(neg)

    def test_jl002_key_consumed_in_loop(self):
        pos = """
            import jax
            key = jax.random.PRNGKey(0)
            for i in range(3):
                x = jax.random.normal(key, (2,))
        """
        assert "JL002" in rules_of(pos)
        neg = """
            import jax
            key = jax.random.PRNGKey(0)
            for sub in jax.random.split(key, 3):
                x = jax.random.normal(sub, (2,))
        """
        assert "JL002" not in rules_of(neg)

    def test_jl003_tracer_branch(self):
        pos = """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """
        assert "JL003" in rules_of(pos)
        # shape/None checks are static at trace time — sanctioned
        neg = """
            import jax

            @jax.jit
            def f(x, flow_init=None):
                if x.shape[0] > 1 and flow_init is None:
                    return x
                return -x
        """
        assert "JL003" not in rules_of(neg)

    def test_jl003_static_argnums_exempt(self):
        neg = """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnums=(1,))
            def f(x, mode):
                if mode:
                    return x
                return -x
        """
        assert "JL003" not in rules_of(neg)

    def test_jl004_untimed_bench_span(self):
        pos = """
            import time
            import jax

            fn = jax.jit(lambda x: x)

            def bench(x):
                t0 = time.perf_counter()
                y = fn(x)
                dt = time.perf_counter() - t0
                return dt
        """
        path = "scripts/fixture_bench.py"
        assert "JL004" in rules_of(pos, path)
        neg = """
            import time
            import jax

            fn = jax.jit(lambda x: x)

            def bench(x):
                t0 = time.perf_counter()
                y = jax.block_until_ready(fn(x))
                dt = time.perf_counter() - t0
                return dt
        """
        assert "JL004" not in rules_of(neg, path)
        # the rule scopes to scripts/*bench*.py only
        assert "JL004" not in rules_of(pos, "dexiraft_tpu/train/x.py")

    def test_jl005_f64_literal(self):
        pos = """
            import jax
            import numpy as np
            x = np.zeros((2,), dtype=np.float64)
        """
        assert "JL005" in rules_of(pos)
        neg = """
            import jax
            import numpy as np
            x = np.zeros((2,), dtype=np.float32)
        """
        assert "JL005" not in rules_of(neg)
        # no jax import -> not our problem (plain numpy code may be f64)
        no_jax = """
            import numpy as np
            x = np.zeros((2,), dtype=np.float64)
        """
        assert "JL005" not in rules_of(no_jax)

    def test_jl006_jit_without_donation(self):
        pos = """
            import jax

            @jax.jit
            def step(state, batch):
                return state
        """
        assert "JL006" in rules_of(pos)
        neg = """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, batch):
                return state
        """
        assert "JL006" not in rules_of(neg)
        # non-state-threading signatures carry no donation obligation
        neg2 = """
            import jax

            @jax.jit
            def fwd(image1, image2):
                return image1 - image2
        """
        assert "JL006" not in rules_of(neg2)

    def test_jl007_implicit_fetch(self):
        pos = """
            import jax

            fn = jax.jit(lambda x: x)

            def run(x):
                loss = fn(x)
                return float(loss)
        """
        assert "JL007" in rules_of(pos)
        neg = """
            import jax

            fn = jax.jit(lambda x: x)

            def run(x):
                loss = fn(x)
                return float(jax.device_get(loss))
        """
        assert "JL007" not in rules_of(neg)

    def test_jl008_unconditional_loop_sync(self):
        pos = """
            import jax

            def loop(xs):
                out = []
                for x in xs:
                    out.append(jax.device_get(x))
                return out
        """
        path = "dexiraft_tpu/train/fixture.py"
        assert "JL008" in rules_of(pos, path)
        # cadence-gated syncs are the sanctioned shape
        neg = """
            import jax

            def loop(xs):
                for i, x in enumerate(xs):
                    if i % 10 == 0:
                        jax.device_get(x)
        """
        assert "JL008" not in rules_of(neg, path)
        # rule scopes to library train/eval/serve paths, not scripts
        assert "JL008" not in rules_of(pos, "scripts/fixture.py")

    def test_jl009_jit_in_loop(self):
        pos = """
            import jax
            for i in range(3):
                f = jax.jit(lambda x: x)
        """
        assert "JL009" in rules_of(pos)
        neg = """
            import jax
            f = jax.jit(lambda x: x)
            for i in range(3):
                y = f(i)
        """
        assert "JL009" not in rules_of(neg)

    def test_jl000_syntax_error(self):
        assert rules_of("def f(:\n") == {"JL000"}


# --------------------------------------------------------------------------
# suppression + baseline mechanics
# --------------------------------------------------------------------------


class TestSuppression:
    def test_inline_disable_comment(self):
        src = """
            import jax
            for i in range(3):
                f = jax.jit(lambda x: x)  # jaxlint: disable=JL009
        """
        assert "JL009" not in rules_of(src)

    def test_disable_is_rule_specific(self):
        src = """
            import jax
            for i in range(3):
                f = jax.jit(lambda x: x)  # jaxlint: disable=JL001
        """
        assert "JL009" in rules_of(src)


class TestBaseline:
    SRC = textwrap.dedent("""
        import jax
        for i in range(3):
            f = jax.jit(lambda x: x)
    """)

    def test_allow_matches_on_rule_path_snippet(self):
        findings = jaxlint.lint_source(self.SRC, "scripts/x.py")
        assert findings
        bl = jaxlint.Baseline(allow=[f.baseline_entry() for f in findings])
        kept, allowed, stale = bl.split(findings)
        assert not kept and not stale and len(allowed) == len(findings)

    def test_stale_entry_reported(self):
        bl = jaxlint.Baseline(allow=[{
            "rule": "JL009", "path": "scripts/x.py",
            "snippet": "gone = jax.jit(lambda x: x)", "reason": "old"}])
        kept, allowed, stale = bl.split(
            jaxlint.lint_source(self.SRC, "scripts/x.py"))
        assert kept and stale and not allowed

    def test_exclude_glob(self):
        bl = jaxlint.Baseline(exclude=["scripts/lookup_ab*.py"])
        assert bl.excludes("scripts/lookup_ab2.py")
        assert not bl.excludes("scripts/serve_bench.py")

    def test_shipped_baseline_is_valid_json_with_reasons(self):
        with open(osp.join(REPO, "dexiraft_tpu", "analysis",
                           "baseline.json")) as f:
            raw = json.load(f)
        for entry in raw["allow"]:
            assert entry["rule"] in jaxlint.RULES
            assert entry["reason"].strip()


# --------------------------------------------------------------------------
# the gate CLI: zero-findings pin on the shipped tree + teeth
# --------------------------------------------------------------------------


def _gate(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run([sys.executable, GATE, *args], cwd=REPO,
                          capture_output=True, text=True, timeout=120)


class TestLintGate:
    def test_shipped_tree_is_clean(self):
        """THE tier-1 pin: zero unallowlisted findings, zero stale
        allowlist entries, on every commit."""
        r = _gate()
        assert r.returncode == 0, r.stdout + r.stderr
        assert "0 finding(s)" in r.stdout

    def test_gate_trips_on_introduced_footgun(self, tmp_path):
        bad = tmp_path / "fixture_footgun.py"
        bad.write_text(textwrap.dedent("""
            import jax
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
        """))
        rel = osp.relpath(str(bad), REPO)
        r = _gate(rel)
        assert r.returncode == 1, r.stdout + r.stderr
        assert "JL002" in r.stdout

    def test_list_rules(self):
        r = _gate("--list-rules")
        assert r.returncode == 0
        for rule in jaxlint.RULES:
            assert rule in r.stdout


# --------------------------------------------------------------------------
# runtime guards: compile_count / RecompileWatch / strict_mode
# --------------------------------------------------------------------------


class TestGuards:
    def test_compile_count_flat_on_cache_hit(self):
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        f = jax.jit(lambda x: x + 1)
        x = jnp.ones((3,))
        f(x)
        c1 = guards.compile_count()
        f(x)  # same signature: executable-cache hit, no compile event
        assert guards.compile_count() == c1

    def test_watch_drift_and_once_only_warning(self, capsys):
        import io

        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        watch = guards.RecompileWatch("fixture")
        watch.mark_warm()
        assert watch.drift == 0
        jax.jit(lambda x: x * 3)(jnp.ones((7,)))  # an unplanned compile
        assert watch.drift >= 1
        buf = io.StringIO()
        assert watch.warn_if_drifted(file=buf)
        assert "recompile(s) after warmup" in buf.getvalue()
        buf2 = io.StringIO()
        watch.warn_if_drifted(file=buf2)  # once-only
        assert buf2.getvalue() == ""

    def test_sanctioned_window_absorbs_only_its_compiles(self):
        """The checkpoint-save sanction (train_cli.save_with_position):
        compiles INSIDE the window — the fsdp snapshot's one-time
        per-shape device copies — shift the baseline; compiles outside
        still count as drift."""
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        watch = guards.RecompileWatch("fixture")
        watch.mark_warm()
        with watch.sanctioned():
            jax.jit(lambda x: x / 7)(jnp.ones((13,)))  # planned: absorbed
        assert watch.drift == 0
        jax.jit(lambda x: x / 9)(jnp.ones((17,)))  # unplanned: counted
        assert watch.drift >= 1

    def test_overlapping_sanctioned_windows_absorb_once(self):
        """Two open windows sharing one watch (both engines compiling
        fresh buckets at once) must shift the baseline by the UNION
        span's compiles, not once per window — a double shift drives
        drift negative and silently swallows the next real retraces."""
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        watch = guards.RecompileWatch("fixture")
        watch.mark_warm()
        with watch.sanctioned():
            with watch.sanctioned():
                jax.jit(lambda x: x / 17)(jnp.ones((29,)))
        assert watch.drift == 0      # absorbed once — NOT -1
        jax.jit(lambda x: x / 19)(jnp.ones((31,)))
        assert watch.drift >= 1      # the next unplanned compile counts
                                     # (a double shift would swallow it)

    def test_check_defers_while_sanctioned_window_open(self):
        """The serve-tier race: the pair dispatcher and the streaming
        engine share ONE watch across threads — a check() landing while
        the OTHER thread's sanctioned cold-bucket compile is in progress
        must defer (the window's exit shifts the baseline), then regain
        its teeth."""
        import io

        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        watch = guards.RecompileWatch("fixture")
        watch.mark_warm()
        with watch.sanctioned():
            jax.jit(lambda x: x / 11)(jnp.ones((19,)))
            assert watch.drift >= 1       # counter already moved...
            watch.check()                 # ...but an open window defers
            assert not watch.warn_if_drifted(file=io.StringIO())
        assert watch.drift == 0           # exit absorbed the window
        jax.jit(lambda x: x / 13)(jnp.ones((23,)))  # unplanned
        with pytest.raises(guards.RecompileBudgetExceeded):
            watch.check()

    def test_strict_mode_raises_on_post_warmup_compile(self):
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        x = jnp.ones((9,))  # created OUTSIDE: eager ops transfer scalars
        f = jax.jit(lambda x: x - 2)
        with pytest.raises(guards.RecompileBudgetExceeded):
            with guards.strict_mode(label="fixture"):
                f(x)  # first call on this signature: compiles

    def test_strict_mode_budget_absorbs_expected_compile(self):
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        x = jnp.ones((11,))
        f = jax.jit(lambda x: x * 5)
        with guards.strict_mode(compile_budget=1, label="fixture"):
            f(x)  # the one planned compile
            f(x)  # cache hit

    def test_strict_mode_disallows_implicit_transfer(self):
        import numpy as np

        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        f = jax.jit(lambda x: x + 4)
        f(jnp.ones((5,), jnp.float32))  # warm outside the region
        with pytest.raises(Exception, match="[Tt]ransfer"):
            with guards.strict_mode(compile_budget=1, label="fixture"):
                f(np.ones((5,), np.float32))  # implicit h2d: rejected

    def test_strict_mode_allows_explicit_put_get(self):
        import numpy as np

        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        f = jax.jit(lambda x: x + 4)
        f(jnp.ones((5,), jnp.float32))
        with guards.strict_mode(compile_budget=1, label="fixture"):
            y = f(jax.device_put(np.ones((5,), np.float32)))
            host = jax.device_get(y)  # explicit d2h: sanctioned
        assert host.shape == (5,)

    def test_mark_warm_rebaselines_mid_region(self):
        import jax
        import jax.numpy as jnp

        from dexiraft_tpu.analysis import guards

        x = jnp.ones((13,))
        f = jax.jit(lambda x: x + 7)
        with guards.strict_mode(label="fixture") as watch:
            f(x)             # planned: a new geometry
            watch.mark_warm()  # absorb it
            f(x)             # cache hit — exit check stays clean


class TestEngineStrictKnob:
    def test_serve_config_strict_flag_and_watch(self):
        """InferenceEngine carries the drift watch even without --strict
        (the non-strict warning satellite) and honors strict=True."""
        from dexiraft_tpu.serve import InferenceEngine, ServeConfig

        cfg = ServeConfig(batch_size=1, strict=True)
        assert cfg.strict
        eng = InferenceEngine(lambda a, b, flow_init=None: (a, b),
                              ServeConfig(batch_size=1))
        assert hasattr(eng.watch, "warn_if_drifted")
