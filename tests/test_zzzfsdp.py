"""fsdp-axis tests: storage-sharded state, per-shard checkpoints, audit leg.

Named to sort LAST (tier-1 870 s budget convention). The expensive parts
— the fsdp train-step compile and the per-shard orbax round trip — run
ONCE in a module-scoped fixture and every test reads off it; the audit
CLI tests monkeypatch the compile stage and replay the shipped goldens
(the test_zzzshardlayout pattern), and the layout-policy pins are pure.

What is pinned here and why:

  * ``param_leaf_spec`` — the central divisibility-fallback policy
    (largest dividing dim; small leaves replicated). Call sites never
    decide, so the policy's edge cases live in one test class.
  * step-loss parity fsdp vs replicated — the fence pattern's whole
    claim is that fsdp is STORAGE only and the computed math is the
    replicated step's. This is also the regression tripwire for the
    GSPMD feature-dim-conv miscompilation that forced the fence design
    (conv-of-concat-of-cout-sharded-conv computes garbage on this
    backend; if a layout change ever lets fsdp shardings leak into the
    model, parity breaks loudly here).
  * bit-exact per-shard save -> restore -> resume on a virtual fsdp
    mesh (the PR 7/10 parity discipline on sharded state).
  * coordinated rollback (PR 10 consensus) landing every host on the
    same sharded step.
  * the fsdp audit golden and the armed (exemption-free) opt_state
    replication canary.
"""

from __future__ import annotations

import copy
import importlib.util
import os.path as osp
import shutil

import numpy as np
import pytest

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))

FSDP_N = 2  # fsdp ways used by the compiled fixtures (8-device mesh)


# --------------------------------------------------------------------------
# layout policy pins (pure — no compiles)
# --------------------------------------------------------------------------


class TestParamLeafSpec:
    @pytest.fixture()
    def mesh(self):
        from dexiraft_tpu.parallel.layout import make_mesh_fsdp

        return make_mesh_fsdp(2, 4)

    def test_largest_dividing_dim_wins(self, mesh):
        from dexiraft_tpu.parallel.layout import LAYOUT, spec_str

        # conv kernel HWIO: the channel dims divide, the 3x3 taps don't
        assert spec_str(LAYOUT.param_leaf_spec(mesh, (3, 3, 96, 160))) == \
            "P(None, None, None, 'fsdp')"
        # cin larger than cout: cin wins
        assert spec_str(LAYOUT.param_leaf_spec(mesh, (3, 3, 256, 96))) == \
            "P(None, None, 'fsdp', None)"

    def test_small_leaves_stay_replicated(self, mesh):
        from dexiraft_tpu.parallel.layout import LAYOUT, spec_str

        # biases, norm scales, scalars: under FSDP_MIN_LEAF_SIZE
        assert spec_str(LAYOUT.param_leaf_spec(mesh, (256,))) == "P()"
        assert spec_str(LAYOUT.param_leaf_spec(mesh, ())) == "P()"
        assert spec_str(LAYOUT.param_leaf_spec(mesh, (96,))) == "P()"

    def test_no_dividing_dim_falls_back(self, mesh):
        from dexiraft_tpu.parallel.layout import LAYOUT, spec_str

        # big enough, but no dim divides 4
        assert spec_str(LAYOUT.param_leaf_spec(mesh, (7, 7, 7, 31))) == \
            "P()"

    def test_no_fsdp_mesh_is_replicated(self):
        from dexiraft_tpu.parallel.layout import LAYOUT, make_mesh, spec_str

        m = make_mesh()
        assert spec_str(LAYOUT.param_leaf_spec(m, (3, 3, 96, 160))) == "P()"
        assert spec_str(LAYOUT.params(m)) == "P()"
        assert not LAYOUT.has_fsdp(m)

    def test_group_specs_resolve_by_mesh(self, mesh):
        from dexiraft_tpu.parallel.layout import LAYOUT, spec_str

        assert spec_str(LAYOUT.params(mesh)) == "P('fsdp')"
        assert spec_str(LAYOUT.opt_state(mesh)) == "P('fsdp')"
        assert spec_str(LAYOUT.params()) == "P()"
        assert LAYOUT.has_fsdp(mesh) and LAYOUT.fsdp_size(mesh) == 4


class TestMakeTrainMeshFsdp:
    def test_default_keeps_historical_mesh(self):
        from dexiraft_tpu.parallel.layout import make_train_mesh

        assert dict(make_train_mesh(8).shape) == {"data": 8}

    def test_explicit_fsdp_carves_first(self):
        from dexiraft_tpu.parallel.layout import make_train_mesh

        # 8 devices, batch 8, fsdp=4: data takes the largest batch
        # divisor of the remaining budget
        assert dict(make_train_mesh(8, fsdp=4).shape) == \
            {"data": 2, "fsdp": 4}

    def test_auto_grows_over_leftover_devices(self):
        from dexiraft_tpu.parallel.layout import make_train_mesh

        # a 2-batch on 8 chips: data-parallelism idles 6 of them today;
        # auto hands 4 to the fsdp axis (host-count-aware walk-down)
        m = make_train_mesh(2, fsdp="auto")
        assert dict(m.shape) == {"data": 2, "fsdp": 4}

    def test_auto_without_leftover_is_one_d(self):
        from dexiraft_tpu.parallel.layout import make_train_mesh

        assert dict(make_train_mesh(8, fsdp="auto").shape) == {"data": 8}

    def test_bad_fsdp_rejected(self):
        from dexiraft_tpu.parallel.layout import make_train_mesh

        with pytest.raises(ValueError, match="fsdp"):
            make_train_mesh(8, fsdp=16)


# --------------------------------------------------------------------------
# compiled fixtures: one fsdp step + one replicated step, shared by the
# parity / checkpoint / rollback tests below
# --------------------------------------------------------------------------


def _small_setup():
    from dexiraft_tpu.config import TrainConfig, raft_v1

    cfg = raft_v1(small=True)
    h, w = 48, 64
    tc = TrainConfig(name="fsdp-test", stage="chairs", num_steps=20,
                     batch_size=4, image_size=(h, w), iters=2)
    rng = np.random.default_rng(7)
    batch = {
        "image1": rng.uniform(0, 255, (4, h, w, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (4, h, w, 3)).astype(np.float32),
        "flow": rng.uniform(-5, 5, (4, h, w, 2)).astype(np.float32),
        "valid": np.ones((4, h, w), np.float32),
    }
    return cfg, tc, batch


@pytest.fixture(scope="module")
def fsdp_run(tmp_path_factory):
    """Everything the expensive tests share, computed once: 4 plain-mesh
    losses, 4 fsdp-mesh losses with a per-shard async checkpoint taken
    after step 2, and the artifacts (ckpt dir, step fn, template) the
    restore tests reuse."""
    import jax

    from dexiraft_tpu.parallel.layout import make_train_mesh, shard_state
    from dexiraft_tpu.train import checkpoint as ckpt
    from dexiraft_tpu.train.state import create_state
    from dexiraft_tpu.train.step import make_train_step

    cfg, tc, batch = _small_setup()
    ckpt_dir = str(tmp_path_factory.mktemp("fsdp") / "ck")

    def fresh_state():
        return jax.tree.map(np.asarray,
                            create_state(jax.random.PRNGKey(tc.seed),
                                         cfg, tc))

    # replicated reference: the historical mesh for this batch size
    mesh_p = make_train_mesh(tc.batch_size)
    step_p = make_train_step(cfg, tc, mesh=mesh_p)
    sp = fresh_state()
    losses_plain = []
    for _ in range(4):
        sp, m = step_p(sp, batch)
        losses_plain.append(float(jax.device_get(m["loss"])))

    # fsdp run: same data/seed, state stored sharded
    mesh_f = make_train_mesh(tc.batch_size, fsdp=FSDP_N)
    step_f = make_train_step(cfg, tc, mesh=mesh_f)
    sf = shard_state(fresh_state(), mesh_f)
    losses_fsdp = []
    for i in range(4):
        sf, m = step_f(sf, batch)
        losses_fsdp.append(float(jax.device_get(m["loss"])))
        if i == 1:  # async per-shard save of the step-2 state
            ckpt.save_checkpoint(ckpt_dir, sf, step=2, block=False)
    ckpt.wait_pending(ckpt_dir, raise_on_error=True)

    return dict(cfg=cfg, tc=tc, batch=batch, mesh_f=mesh_f, step_f=step_f,
                losses_plain=losses_plain, losses_fsdp=losses_fsdp,
                ckpt_dir=ckpt_dir, fresh_state=fresh_state,
                final_state=sf)


class TestFsdpStepParity:
    def test_mesh_shape(self, fsdp_run):
        from dexiraft_tpu.parallel.layout import LAYOUT

        assert LAYOUT.fsdp_size(fsdp_run["mesh_f"]) == FSDP_N

    def test_loss_parity_vs_replicated(self, fsdp_run):
        """fsdp is storage-only: identical data/seed must give the
        replicated step's losses (cross-mesh reduction-order drift
        only). A real divergence here is the GSPMD feature-dim conv
        miscompilation leaking past the fences."""
        lp, lf = fsdp_run["losses_plain"], fsdp_run["losses_fsdp"]
        assert np.allclose(lp, lf, rtol=1e-3, atol=1e-4), (lp, lf)

    def test_state_stored_sharded(self, fsdp_run):
        """The persistent (between-steps) layout is the storage win:
        big param/moment leaves carry an fsdp spec, small leaves the
        replicated fallback."""
        import jax

        from dexiraft_tpu.parallel.layout import LAYOUT

        state = fsdp_run["final_state"]
        leaves = jax.tree_util.tree_leaves(state.params)
        big = max(leaves, key=lambda x: x.size)
        assert LAYOUT.fsdp_axis in str(big.sharding.spec)
        # per-device bytes across params+opt_state land near 1/N plus
        # the replicated fallback leaves — well under the full size
        total = per_dev = 0
        for leaf in (jax.tree_util.tree_leaves(state.params)
                     + jax.tree_util.tree_leaves(state.opt_state)):
            nbytes = leaf.size * leaf.dtype.itemsize
            total += nbytes
            shard = leaf.sharding.shard_shape(np.shape(leaf))
            per_dev += int(np.prod(shard, dtype=np.int64)) * \
                leaf.dtype.itemsize
        assert per_dev < 0.75 * total  # N=2: ideal 0.5 + fallbacks

    def test_metrics_replicated(self, fsdp_run):
        import jax

        state = fsdp_run["final_state"]
        assert state.rng.is_fully_replicated
        assert state.step.is_fully_replicated
        assert int(jax.device_get(state.step)) == 4


class TestPerShardCheckpoint:
    def test_bit_exact_restore(self, fsdp_run):
        """Per-shard orbax round trip: restore into a sharded template
        and compare every leaf bit-for-bit against the live state that
        was saved (the fixture saved the step-2 state; replay it)."""
        import jax

        from dexiraft_tpu.parallel.layout import shard_state
        from dexiraft_tpu.train import checkpoint as ckpt

        template = shard_state(fsdp_run["fresh_state"](),
                               fsdp_run["mesh_f"])
        restored = ckpt.restore_checkpoint(fsdp_run["ckpt_dir"], template)
        assert int(jax.device_get(restored.step)) == 2
        big = max(jax.tree_util.tree_leaves(restored.params),
                  key=lambda x: x.size)
        assert "fsdp" in str(big.sharding.spec)

    def test_bit_exact_resume_loss_sequence(self, fsdp_run):
        """Train 2 steps -> per-shard checkpoint -> restore -> continue:
        the loss sequence must equal the uninterrupted run's EXACTLY
        (same mesh, same compiled program — the PR 7/10 discipline)."""
        import jax

        from dexiraft_tpu.parallel.layout import shard_state
        from dexiraft_tpu.train import checkpoint as ckpt

        template = shard_state(fsdp_run["fresh_state"](),
                               fsdp_run["mesh_f"])
        state = ckpt.restore_checkpoint(fsdp_run["ckpt_dir"], template)
        resumed = []
        for _ in range(2):
            state, m = fsdp_run["step_f"](state, fsdp_run["batch"])
            resumed.append(float(jax.device_get(m["loss"])))
        assert resumed == fsdp_run["losses_fsdp"][2:]

    def test_snapshot_keeps_shards_on_device(self, fsdp_run):
        """The donation-safe snapshot: sharded leaves become on-device
        copies (orbax then writes per shard), replicated leaves numpy —
        nothing ever gathers a sharded leaf to one host buffer."""
        import jax

        from dexiraft_tpu.train.checkpoint import (
            _host_snapshot,
            _keys_to_data,
        )

        snapped = _host_snapshot(_keys_to_data(fsdp_run["final_state"]))
        flat = jax.tree_util.tree_flatten_with_path(snapped)[0]
        saw_sharded = False
        for path, leaf in flat:
            field = getattr(path[0], "name", None)
            if isinstance(leaf, jax.Array):
                assert field in ("params", "opt_state")
                assert not leaf.is_fully_replicated
                saw_sharded = True
            else:
                assert isinstance(leaf, np.ndarray) or np.isscalar(leaf)
        assert saw_sharded

    def test_partial_restore_lands_on_template_sharding(self, fsdp_run):
        """restore_params_into on sharded templates: grafted leaves
        adopt the template leaf's resolved sharding, and the skip-list
        contract (PR 10) is untouched."""
        import jax

        from dexiraft_tpu.parallel.layout import shard_state
        from dexiraft_tpu.train import checkpoint as ckpt

        template = shard_state(fsdp_run["fresh_state"](),
                               fsdp_run["mesh_f"])
        prev = ckpt.restore_checkpoint(fsdp_run["ckpt_dir"], template)
        fresh = shard_state(fsdp_run["fresh_state"](), fsdp_run["mesh_f"])
        merged, skipped = ckpt.restore_params_into(fresh.params,
                                                   prev.params)
        assert skipped == []
        flat_m = jax.tree_util.tree_flatten_with_path(merged)[0]
        flat_f = {tuple(p): l.sharding for p, l in
                  jax.tree_util.tree_flatten_with_path(fresh.params)[0]}
        for path, leaf in flat_m:
            assert leaf.sharding == flat_f[tuple(path)]


class TestCoordinatedRollback:
    def test_hosts_agree_on_sharded_step(self, fsdp_run):
        """PR 10 consensus over sharded state: both (scripted) hosts
        run the verified restore and land on the SAME sharded step —
        the rollback path train_cli takes after a poisoned verdict."""
        import jax

        from dexiraft_tpu.parallel.layout import shard_state
        from dexiraft_tpu.resilience import Coordinator, restore_verified

        template = shard_state(fsdp_run["fresh_state"](),
                               fsdp_run["mesh_f"])
        script = iter([
            np.asarray([[2], [2]]),          # min_int: both restored 2
            np.asarray([[False], [False]]),  # any_flag: agreed
        ])
        coord = Coordinator(size=2, index=0,
                            allgather_fn=lambda v: next(script))
        state, step = coord.agree_step(
            lambda bound: restore_verified(fsdp_run["ckpt_dir"],
                                           template, step=bound),
            None)
        assert step == 2
        big = max(jax.tree_util.tree_leaves(state.params),
                  key=lambda x: x.size)
        assert "fsdp" in str(big.sharding.spec)

    def test_poisoned_peer_verdict_is_collective(self):
        from dexiraft_tpu.resilience import Coordinator

        coord = Coordinator(
            size=2, index=0,
            allgather_fn=lambda v: np.asarray([[False], [True]]))
        # the PEER's poison verdict reaches this host
        assert coord.any_flag(False) is True


# --------------------------------------------------------------------------
# CLI round trip: --fsdp through the real argparse surface
# --------------------------------------------------------------------------


@pytest.fixture()
def chairs_env(tmp_path, monkeypatch):
    """Synthetic chairs tree (the test_cli fixture pattern)."""
    import imageio.v2 as imageio

    from dexiraft_tpu.data.flow_io import write_flo

    root = tmp_path / "FlyingChairs_release"
    data = root / "data"
    data.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(8):
        imageio.imwrite(data / f"{i:05d}_img1.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        imageio.imwrite(data / f"{i:05d}_img2.ppm",
                        rng.integers(0, 256, (96, 128, 3), dtype=np.uint8))
        write_flo(data / f"{i:05d}_flow.flo",
                  rng.normal(size=(96, 128, 2)).astype(np.float32))
    (root / "chairs_split.txt").write_text("\n".join(["1"] * 8))
    monkeypatch.setenv("DEXIRAFT_DATA_DIR", str(tmp_path))
    monkeypatch.chdir(tmp_path)
    return tmp_path


class TestFsdpCLI:
    def test_train_fsdp_checkpoint_resume(self, chairs_env):
        """--fsdp 2 end to end: train, per-shard checkpoint, --resume
        restores the sharded state through the verified-restore +
        consensus path and continues the step counter."""
        from dexiraft_tpu.train import checkpoint as ckpt
        from dexiraft_tpu.train_cli import main as train_main

        tmp = chairs_env
        args = [
            "--name", "f", "--stage", "chairs", "--variant", "v1",
            "--small", "--num_steps", "2", "--batch_size", "2",
            "--image_size", "64", "64", "--iters", "2", "--lr", "1e-4",
            "--num_workers", "1", "--val_freq", "1000",
            "--output", str(tmp / "ckpts"), "--log_dir", str(tmp / "runs"),
            "--fsdp", "2",
        ]
        train_main(args)
        ckpt_dir = str(tmp / "ckpts" / "f")
        assert ckpt.latest_step(ckpt_dir) == 2
        resume = list(args)
        resume[resume.index("--num_steps") + 1] = "4"
        train_main(resume + ["--resume"])
        assert ckpt.latest_step(ckpt_dir) == 4


# --------------------------------------------------------------------------
# audit: fsdp golden + armed opt_state canary (compile monkeypatched)
# --------------------------------------------------------------------------


def _golden():
    from dexiraft_tpu.analysis import shardaudit

    return shardaudit.load_golden()


def _fsdp_golden():
    from dexiraft_tpu.analysis import shardaudit

    return shardaudit.load_golden(shardaudit.FSDP_GOLDEN_PATH)


class TestFsdpGoldenFile:
    def test_fsdp_golden_shape(self):
        from dexiraft_tpu.analysis import shardaudit

        g = _fsdp_golden()
        assert set(g["steps"]) == {"train_fsdp"}
        assert g["steps"]["train_fsdp"]["mesh"] == shardaudit.FSDP_MESH

    def test_state_resolved_to_fsdp_with_fallback(self):
        """The acceptance pin: params/opt_state resolve to fsdp specs,
        divisibility-fallback leaves replicated — visible as the spec
        SET {P(), P(..'fsdp'..)} on each state group."""
        g = _fsdp_golden()
        for group in ("[0].params", "[0].opt_state"):
            specs = g["steps"]["train_fsdp"]["in"][group]["specs"]
            assert any("'fsdp'" in s for s in specs), specs
            assert "P()" in specs  # the fallback leaves
        # batch stays compute-sharded only: fsdp is storage
        assert g["steps"]["train_fsdp"]["in"]["[1]['image1']"]["specs"] \
            == ["P('data', 'seq')"]

    def test_declared_state_sharded_not_exempt(self):
        g = _fsdp_golden()["declared"]
        for name in ("params", "opt_state"):
            assert g[name]["spec"] == "P('fsdp')"
            assert g[name]["replicated"] is False
            assert g[name]["flagged"] is False

    def test_canary_armed_no_exemption(self):
        """The exemption died with the reservation: params/opt_state
        are no longer in REPLICATED_OK, so an over-threshold replicated
        resolution FLAGS (exercised synthetically below)."""
        from dexiraft_tpu.analysis import shardaudit
        from dexiraft_tpu.parallel.layout import REPLICATED_OK

        assert "params" not in REPLICATED_OK
        assert "opt_state" not in REPLICATED_OK
        report = {"declared": {
            "opt_state": {"spec": "P()", "total_mb": 320.0,
                          "per_device_mb": 320.0, "replicated": True,
                          "flagged": True}}}
        flagged = shardaudit.flagged_groups(report)
        assert len(flagged) == 1 and "opt_state" in flagged[0]


class TestFsdpAuditCLI:
    """scripts/shard_audit.py runs the fsdp leg by default; the compile
    stages are monkeypatched to replay the shipped goldens."""

    @staticmethod
    def _main():
        spec = importlib.util.spec_from_file_location(
            "_shard_audit_cli_fsdp",
            osp.join(REPO, "scripts", "shard_audit.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main

    @staticmethod
    def _patch(monkeypatch, mutate_fsdp=None):
        from dexiraft_tpu.analysis import shardaudit

        monkeypatch.setattr(
            shardaudit, "run_audit",
            lambda steps, threshold_mb: copy.deepcopy(_golden()))

        def fsdp(steps, threshold_mb):
            r = copy.deepcopy(_fsdp_golden())
            if mutate_fsdp:
                mutate_fsdp(r)
            return r

        monkeypatch.setattr(shardaudit, "run_audit_fsdp", fsdp)

    def test_default_steps_include_fsdp_leg(self, monkeypatch, capsys):
        self._patch(monkeypatch)
        assert self._main()([]) == 0
        out = capsys.readouterr().out
        # 7 = the 5 base steps + fsdp + halo legs (PR 14/16 growth)
        assert "train_fsdp" in out and "7 step(s)" in out

    def test_fsdp_spec_drift_fails(self, monkeypatch, capsys):
        def mutate(r):
            grp = r["steps"]["train_fsdp"]["in"]["[0].params"]
            grp["specs"] = ["P()"]  # someone reverted the storage layout

        self._patch(monkeypatch, mutate)
        assert self._main()([]) == 1
        assert "DRIFT [fsdp]" in capsys.readouterr().out

    def test_replicated_opt_state_over_threshold_fails(self, monkeypatch,
                                                       capsys):
        def mutate(r):
            r["declared"]["opt_state"].update(
                spec="P()", replicated=True, flagged=True)

        self._patch(monkeypatch, mutate)
        assert self._main()([]) == 1
        assert "FLAGGED [fsdp]" in capsys.readouterr().out

    def test_fsdp_only_partial_run(self, monkeypatch):
        self._patch(monkeypatch)
        assert self._main()(["--steps", "train_fsdp"]) == 0
