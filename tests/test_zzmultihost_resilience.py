"""Multi-host failure handling over a REAL 2-process virtual mesh: the
consensus + watchdog + async-checkpoint story single-process tests
cannot cover (named test_zz* to sort after the seed suite per the
tier-1 budget convention).

Six scenarios against tests/multiproc_resilience_child.py (which runs
the same resilience primitives train_cli wires — coord, watchdog,
async checkpoint, verified agreed restore, elastic membership):

  * one-host poison: a verdict LOCAL to host 0 produces the SAME
    rollback step on BOTH hosts (consensus, not luck — the loss is
    replicated, only the verdict is local).
  * kill-one-host: host 1 os._exit()s mid-run; host 0 must exit
    NONZERO within the watchdog bound instead of hanging in the next
    collective forever.
  * coordinated resume: after the kill, a --resume pair agrees on one
    restored step and finishes with parameters BIT-EXACT equal to an
    uninterrupted reference run.
  * seeded divergence: one host issues an extra collective round its
    peer never runs; the collective flight recorder's in-band lockstep
    check (analysis/collective_trace via resilience/coord) must name
    the first divergent (host, round, op) in seconds — never a
    CoordinatorTimeout after the full window.
  * elastic shrink-and-continue: the same kill under --elastic, but the
    survivor CONTINUES — missed lease -> new membership epoch, solo
    mesh re-form, agreed-step restore, re-sliced data — and its
    post-shrink loss sequence is pinned bit-exact against a fresh solo
    run restored from the same agreed step.
  * elastic grow-at-checkpoint: a replacement host posts a join intent
    and is absorbed at the next checkpoint boundary; incumbent and
    joiner finish bit-identical with disjoint+exhaustive data slices.
"""

from __future__ import annotations

import json
import os.path as osp
import time

import pytest

from tests._mp_common import (
    free_port,
    launch_child,
    reap_children,
    spawn_child_pair,
)

_CHILD = osp.join(osp.dirname(osp.abspath(__file__)),
                  "multiproc_resilience_child.py")


def _spawn_pair(outs, ckpt_dir, extra=(), timeout=300.0):
    """Scenario pair over the shared orchestration helper (never
    raises on a hang — scenarios expect different exit codes)."""
    return spawn_child_pair(_CHILD, outs, ckpt_dir, extra=extra,
                            timeout=timeout)


def test_one_host_poison_rolls_back_all_hosts(tmp_path):
    outs = [tmp_path / f"c{i}.json" for i in range(2)]
    rcs, logs, _ = _spawn_pair(
        outs, tmp_path / "ck",
        extra=["--num_steps", "6", "--save_every", "2",
               "--poison_step", "3", "--poison_host", "0",
               "--stall_timeout", "60"])
    assert rcs == [0, 0], f"children failed:\n{logs[0][-2000:]}\n" \
                          f"{logs[1][-2000:]}"
    results = [json.loads(out.read_text()) for out in outs]
    rollbacks = [[e for e in r["events"] if "rollback_at" in e]
                 for r in results]
    # exactly one rollback each, at the same step, restoring the SAME
    # checkpoint — though only host 0 saw the local verdict
    assert [len(r) for r in rollbacks] == [1, 1]
    assert rollbacks[0][0]["rollback_at"] == rollbacks[1][0]["rollback_at"] == 3
    assert rollbacks[0][0]["restored"] == rollbacks[1][0]["restored"] == 2
    assert results[0]["events"][0]["poisoned_here"] is True
    assert results[1]["events"][0]["poisoned_here"] is False
    # the mesh kept training after the coordinated rollback: replicated
    # losses stayed identical across hosts
    assert results[0]["losses"] == pytest.approx(results[1]["losses"])


@pytest.fixture(scope="module")
def kill_and_reference(tmp_path_factory):
    """Reference pair (uninterrupted), then a pair with host 1 killed at
    step 5. Shared by the no-hang and resume-parity tests."""
    root = tmp_path_factory.mktemp("mpkill")
    ref_outs = [root / f"ref{i}.json" for i in range(2)]
    ref_rcs, ref_logs, _ = _spawn_pair(
        ref_outs, root / "ck_ref",
        extra=["--num_steps", "8", "--save_every", "2",
               "--stall_timeout", "60"])
    cut_outs = [root / f"cut{i}.json" for i in range(2)]
    cut_rcs, cut_logs, cut_wall = _spawn_pair(
        cut_outs, root / "ck_cut",
        extra=["--num_steps", "8", "--save_every", "2",
               "--die_step", "5", "--die_host", "1",
               "--stall_timeout", "20"], timeout=180.0)
    return dict(root=root, ref_outs=ref_outs, ref_rcs=ref_rcs,
                ref_logs=ref_logs, cut_rcs=cut_rcs, cut_logs=cut_logs,
                cut_wall=cut_wall)


def test_kill_one_host_coordinated_abort_no_hang(kill_and_reference):
    k = kill_and_reference
    assert k["ref_rcs"] == [0, 0], f"reference pair failed:\n" \
        f"{k['ref_logs'][0][-2000:]}\n{k['ref_logs'][1][-2000:]}"
    # the injected death exits 3; the survivor must exit NONZERO — via
    # the hang watchdog (98) or a collective error surfaced by the
    # child's hard-exit guard (97) — well inside the spawn timeout,
    # never hanging in the dead peer's collective
    assert k["cut_rcs"][1] == 3, k["cut_logs"][1][-2000:]
    assert k["cut_rcs"][0] not in (0, None), k["cut_logs"][0][-2000:]
    assert k["cut_wall"] < 150, f"survivor took {k['cut_wall']:.0f}s " \
        f"to abort — the watchdog did not bound the hang"


def test_resume_after_kill_is_bit_exact(kill_and_reference, tmp_path):
    k = kill_and_reference
    assert k["ref_rcs"] == [0, 0]
    outs = [tmp_path / f"res{i}.json" for i in range(2)]
    rcs, logs, _ = _spawn_pair(
        outs, k["root"] / "ck_cut",
        extra=["--num_steps", "8", "--save_every", "2", "--resume",
               "--stall_timeout", "60"])
    assert rcs == [0, 0], f"resume pair failed:\n{logs[0][-2000:]}\n" \
                          f"{logs[1][-2000:]}"
    results = [json.loads(out.read_text()) for out in outs]
    ref = [json.loads(out.read_text()) for out in k["ref_outs"]]
    # both hosts resumed from the SAME agreed step (the newest step the
    # kill run verifiably committed — the async flush racing the kill
    # may or may not have committed step 4, both are legal agreements)
    resumed = [r["events"][0]["resumed"] for r in results]
    assert resumed[0] == resumed[1]
    assert resumed[0] in (2, 4)
    # and finished BIT-EXACT equal to the uninterrupted reference
    assert results[0]["final_w"] == ref[0]["final_w"]
    assert results[1]["final_w"] == ref[1]["final_w"]
    assert results[0]["final_w"] == results[1]["final_w"]


def test_seeded_divergence_is_named_not_timed_out(tmp_path):
    """Host 1 issues an EXTRA min_int round at step 3 (--diverge_step)
    that host 0 never runs — the canonical lockstep bug distlint JL030/
    JL031 exists to prevent. The collective flight recorder's in-band
    stamp check must diagnose it: BOTH hosts raise CollectiveDivergence
    naming the first divergent (host, round, op) within seconds, NOT a
    CoordinatorTimeout after the full 60 s coord window."""
    outs = [tmp_path / f"d{i}.json" for i in range(2)]
    rcs, logs, wall = _spawn_pair(
        outs, tmp_path / "ck",
        extra=["--num_steps", "4", "--save_every", "2",
               "--diverge_step", "3", "--diverge_host", "1",
               "--coord_timeout", "60", "--stall_timeout", "120"],
        timeout=180.0)
    # both sides die via the hard-exit guard with the divergence raised
    assert rcs == [97, 97], f"rcs {rcs}:\n{logs[0][-2000:]}\n" \
                            f"{logs[1][-2000:]}"
    # diagnosed in seconds — NOT by pairing mismatched rounds until the
    # 60 s coord timeout (or the 120 s watchdog) expired
    assert wall < 45, f"divergence took {wall:.0f}s to surface — the " \
        f"in-band check did not fire before the timeout window"
    for i, log in enumerate(logs):
        assert "collective divergence" in log, (i, log[-2000:])
        assert "CoordinatorTimeout" not in log, (i, log[-2000:])
    # ... and NAMED: each side reports the peer host, the round, and
    # the expected-vs-seen ops of the first divergent call
    assert "host 1 issued 'min_int" in logs[0], logs[0][-2000:]
    assert "round 3" in logs[0] and "any_flag" in logs[0]
    assert "host 0 issued 'any_flag" in logs[1], logs[1][-2000:]


def test_elastic_shrink_and_continue(tmp_path):
    """Host 1 dies at step 3 under --elastic: host 0 must detect the
    missed lease, reconfigure into a solo epoch-1 world (smaller mesh,
    agreed-step restore, re-sliced stream), and FINISH the run with
    exit 0 — the elastic counterpart of the kill-one-host abort."""
    outs = [tmp_path / f"e{i}.json" for i in range(2)]
    ck = tmp_path / "ck"
    rcs, logs, wall = _spawn_pair(
        outs, ck,
        extra=["--elastic", "--die_step", "3", "--die_host", "1",
               "--num_steps", "8", "--stall_timeout", "25"],
        timeout=180.0)
    assert rcs == [0, 3], f"shrink pair:\n{logs[0][-2500:]}\n" \
                          f"{logs[1][-1500:]}"
    surv = json.loads(outs[0].read_text())
    shrinks = [e for e in surv["membership_events"]
               if e["kind"] == "shrink"]
    assert len(shrinks) == 1, surv["membership_events"]
    assert shrinks[0]["members"] == [0]
    assert 0 < shrinks[0]["recovery_s"] < 60
    assert surv["final_epoch"] == {"epoch": 1, "size": 1, "index": 0}
    rec = next(e for e in surv["events"] if "reconfigured" in e)
    # host 1 drained its step-2 flush before dying, so the agreed
    # restore step is exactly the last committed boundary
    assert rec["restored"] == 2
    # the solo world finished the remaining steps AND kept saving
    assert set(surv["saved_steps"]) >= {2, 4, 6, 8}
    # post-shrink the solo member owns every sample of each window
    assert surv["slices"]["8"]["size"] == 1
    assert len(surv["slices"]["8"]["ids"]) == 8
    # the collective flight recorder ran through the whole scenario —
    # pair consensus, the shrink reconfiguration, the solo epoch — and
    # lockstep verified CLEAN: a reconfiguration is exactly the kind of
    # protocol whose rounds could silently skew
    ct = surv["collective_trace"]
    assert ct["divergences"] == 0, ct
    assert ct["entries"] > 0 and ct["host"] == 0, ct

    # parity pin: a FRESH solo elastic run restoring the same agreed
    # step from the same directory (replicated pair checkpoint landing
    # on the solo world's fsdp=2 template — the cross-mesh restore)
    # must reproduce the survivor's post-shrink losses bit-exactly
    ref_out = tmp_path / "ref.json"
    proc = launch_child(
        _CHILD, ref_out, ck, free_port(), 0,
        extra=["--num_processes", "1", "--elastic", "--resume",
               "--resume_bound", str(rec["restored"]),
               "--save_every", "0", "--num_steps", "8"])
    (rc,), (log,), _ = reap_children([proc], timeout=120.0)
    assert rc == 0, log[-2500:]
    ref = json.loads(ref_out.read_text())
    assert ref["events"][0]["resumed"] == rec["restored"]
    for s in range(rec["restored"] + 1, 9):
        assert ref["losses"][str(s)] == surv["losses"][str(s)], \
            f"post-shrink loss diverged at step {s}"
    assert ref["param_norm"] == surv["param_norm"]


def test_elastic_grow_at_checkpoint(tmp_path):
    """A replacement host (--join) posts its intent on the FileBoard;
    the solo incumbent absorbs it at the next checkpoint boundary into
    an epoch-1 pair world. Both members restore the same step and must
    finish bit-identical with disjoint+exhaustive data slices."""
    ck = tmp_path / "ck"
    port = free_port()
    inc = launch_child(
        _CHILD, tmp_path / "inc.json", ck, port, 0,
        extra=["--num_processes", "1", "--elastic",
               "--wait_join_at", "2", "--num_steps", "8"])
    time.sleep(1.5)
    jon = launch_child(
        _CHILD, tmp_path / "jon.json", ck, port, 1,
        extra=["--num_processes", "1", "--join", "w1",
               "--num_steps", "8"])
    rcs, logs, _ = reap_children([inc, jon], timeout=180.0)
    assert rcs == [0, 0], f"grow pair:\n{logs[0][-2500:]}\n" \
                          f"{logs[1][-2500:]}"
    a = json.loads((tmp_path / "inc.json").read_text())
    b = json.loads((tmp_path / "jon.json").read_text())
    grows = [e for e in a["membership_events"] if e["kind"] == "grow"]
    assert len(grows) == 1, a["membership_events"]
    assert grows[0]["members"] == [0, 1]
    assert grows[0]["join_ranks"] == {"w1": 1}
    assert a["final_epoch"]["size"] == 2
    assert b["final_epoch"] == {"epoch": 1, "size": 2, "index": 1}
    # the joiner entered at the announced epoch and restored the same
    # boundary the incumbents agreed (the solo fsdp=2 checkpoint
    # landing on the pair's replicated template — the reverse
    # cross-mesh restore)
    assert b["events"][0] == {"resumed": 2, "epoch": 1}
    for s in range(3, 9):
        assert a["losses"][str(s)] == b["losses"][str(s)], \
            f"post-grow loss diverged at step {s}"
    assert a["final_w"] == b["final_w"]
    # post-grow re-slice contract: each window split disjointly and
    # exhaustively between the two members
    for s in range(3, 9):
        sa, sb = a["slices"][str(s)], b["slices"][str(s)]
        assert sa["size"] == sb["size"] == 2
        assert (sa["epoch"], sa["offset"]) == (sb["epoch"], sb["offset"])
        assert not set(sa["ids"]) & set(sb["ids"])
        assert len(sa["ids"]) + len(sb["ids"]) == 8
