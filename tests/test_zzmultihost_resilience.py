"""Multi-host failure handling over a REAL 2-process virtual mesh: the
consensus + watchdog + async-checkpoint story single-process tests
cannot cover (named test_zz* to sort after the seed suite per the
tier-1 budget convention).

Three scenarios against tests/multiproc_resilience_child.py (which runs
the same resilience primitives train_cli wires — coord, watchdog,
async checkpoint, verified agreed restore):

  * one-host poison: a verdict LOCAL to host 0 produces the SAME
    rollback step on BOTH hosts (consensus, not luck — the loss is
    replicated, only the verdict is local).
  * kill-one-host: host 1 os._exit()s mid-run; host 0 must exit
    NONZERO within the watchdog bound instead of hanging in the next
    collective forever.
  * coordinated resume: after the kill, a --resume pair agrees on one
    restored step and finishes with parameters BIT-EXACT equal to an
    uninterrupted reference run.
"""

from __future__ import annotations

import json
import os.path as osp

import pytest

from tests._mp_common import spawn_child_pair

_CHILD = osp.join(osp.dirname(osp.abspath(__file__)),
                  "multiproc_resilience_child.py")


def _spawn_pair(outs, ckpt_dir, extra=(), timeout=300.0):
    """Scenario pair over the shared orchestration helper (never
    raises on a hang — scenarios expect different exit codes)."""
    return spawn_child_pair(_CHILD, outs, ckpt_dir, extra=extra,
                            timeout=timeout)


def test_one_host_poison_rolls_back_all_hosts(tmp_path):
    outs = [tmp_path / f"c{i}.json" for i in range(2)]
    rcs, logs, _ = _spawn_pair(
        outs, tmp_path / "ck",
        extra=["--num_steps", "6", "--save_every", "2",
               "--poison_step", "3", "--poison_host", "0",
               "--stall_timeout", "60"])
    assert rcs == [0, 0], f"children failed:\n{logs[0][-2000:]}\n" \
                          f"{logs[1][-2000:]}"
    results = [json.loads(out.read_text()) for out in outs]
    rollbacks = [[e for e in r["events"] if "rollback_at" in e]
                 for r in results]
    # exactly one rollback each, at the same step, restoring the SAME
    # checkpoint — though only host 0 saw the local verdict
    assert [len(r) for r in rollbacks] == [1, 1]
    assert rollbacks[0][0]["rollback_at"] == rollbacks[1][0]["rollback_at"] == 3
    assert rollbacks[0][0]["restored"] == rollbacks[1][0]["restored"] == 2
    assert results[0]["events"][0]["poisoned_here"] is True
    assert results[1]["events"][0]["poisoned_here"] is False
    # the mesh kept training after the coordinated rollback: replicated
    # losses stayed identical across hosts
    assert results[0]["losses"] == pytest.approx(results[1]["losses"])


@pytest.fixture(scope="module")
def kill_and_reference(tmp_path_factory):
    """Reference pair (uninterrupted), then a pair with host 1 killed at
    step 5. Shared by the no-hang and resume-parity tests."""
    root = tmp_path_factory.mktemp("mpkill")
    ref_outs = [root / f"ref{i}.json" for i in range(2)]
    ref_rcs, ref_logs, _ = _spawn_pair(
        ref_outs, root / "ck_ref",
        extra=["--num_steps", "8", "--save_every", "2",
               "--stall_timeout", "60"])
    cut_outs = [root / f"cut{i}.json" for i in range(2)]
    cut_rcs, cut_logs, cut_wall = _spawn_pair(
        cut_outs, root / "ck_cut",
        extra=["--num_steps", "8", "--save_every", "2",
               "--die_step", "5", "--die_host", "1",
               "--stall_timeout", "20"], timeout=180.0)
    return dict(root=root, ref_outs=ref_outs, ref_rcs=ref_rcs,
                ref_logs=ref_logs, cut_rcs=cut_rcs, cut_logs=cut_logs,
                cut_wall=cut_wall)


def test_kill_one_host_coordinated_abort_no_hang(kill_and_reference):
    k = kill_and_reference
    assert k["ref_rcs"] == [0, 0], f"reference pair failed:\n" \
        f"{k['ref_logs'][0][-2000:]}\n{k['ref_logs'][1][-2000:]}"
    # the injected death exits 3; the survivor must exit NONZERO — via
    # the hang watchdog (98) or a collective error surfaced by the
    # child's hard-exit guard (97) — well inside the spawn timeout,
    # never hanging in the dead peer's collective
    assert k["cut_rcs"][1] == 3, k["cut_logs"][1][-2000:]
    assert k["cut_rcs"][0] not in (0, None), k["cut_logs"][0][-2000:]
    assert k["cut_wall"] < 150, f"survivor took {k['cut_wall']:.0f}s " \
        f"to abort — the watchdog did not bound the hang"


def test_resume_after_kill_is_bit_exact(kill_and_reference, tmp_path):
    k = kill_and_reference
    assert k["ref_rcs"] == [0, 0]
    outs = [tmp_path / f"res{i}.json" for i in range(2)]
    rcs, logs, _ = _spawn_pair(
        outs, k["root"] / "ck_cut",
        extra=["--num_steps", "8", "--save_every", "2", "--resume",
               "--stall_timeout", "60"])
    assert rcs == [0, 0], f"resume pair failed:\n{logs[0][-2000:]}\n" \
                          f"{logs[1][-2000:]}"
    results = [json.loads(out.read_text()) for out in outs]
    ref = [json.loads(out.read_text()) for out in k["ref_outs"]]
    # both hosts resumed from the SAME agreed step (the newest step the
    # kill run verifiably committed — the async flush racing the kill
    # may or may not have committed step 4, both are legal agreements)
    resumed = [r["events"][0]["resumed"] for r in results]
    assert resumed[0] == resumed[1]
    assert resumed[0] in (2, 4)
    # and finished BIT-EXACT equal to the uninterrupted reference
    assert results[0]["final_w"] == ref[0]["final_w"]
    assert results[1]["final_w"] == ref[1]["final_w"]
    assert results[0]["final_w"] == results[1]["final_w"]
