"""viz CLI and profiling utilities."""

import numpy as np

from dexiraft_tpu.data.flow_io import write_flo


def test_viz_cli_converts_tree(tmp_path):
    from dexiraft_tpu.viz_cli import main

    d = tmp_path / "flows" / "seq"
    d.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(2):
        write_flo(d / f"frame{i:04d}.flo",
                  rng.normal(size=(16, 24, 2)).astype(np.float32))
    out = tmp_path / "viz"
    main(["--input", str(tmp_path / "flows"), "--output", str(out)])
    import imageio.v2 as imageio

    # subdirectory structure is preserved (colliding frame names across
    # scenes must not overwrite)
    img = np.asarray(imageio.imread(out / "seq" / "frame0000.png"))
    assert img.shape == (16, 24, 3)


def test_step_timer_excludes_warmup():
    from dexiraft_tpu.profiling import StepTimer

    t = StepTimer(warmup=2)
    for _ in range(5):
        with t:
            pass
    assert len(t.times) == 3
    assert "3 laps" in t.summary()


def test_trace_context(tmp_path):
    import jax
    import jax.numpy as jnp

    from dexiraft_tpu.profiling import trace

    with trace(str(tmp_path)):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    # trace files land under the dir
    assert any(tmp_path.rglob("*"))
