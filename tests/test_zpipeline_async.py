"""Async training pipeline: device prefetch parity, gradient
accumulation vs the single-batch step, the bf16 master-weight policy,
and buffer donation.

Named to sort LAST in collection: the tier-1 suite runs under a hard
870 s wall-clock cap (ROADMAP.md), and inserting new files mid-order
would displace the long-standing tail tests out of the budget window.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dexiraft_tpu.config import TrainConfig, raft_v1
from dexiraft_tpu.data.loader import Loader
from dexiraft_tpu.data.prefetch import DevicePrefetcher, prefetch_to_device
from dexiraft_tpu.parallel.mesh import batch_input_sharding, make_mesh
from dexiraft_tpu.train.state import create_state
from dexiraft_tpu.train.step import make_train_step

SMALL = raft_v1(small=True)
TC = TrainConfig(num_steps=200, batch_size=4, iters=2, image_size=(64, 64),
                 lr=1e-4)


def synthetic_batch(rng, batch=4, size=(64, 64)):
    h, w = size
    base = rng.uniform(0, 255, (batch, h + 8, w + 8, 3)).astype(np.float32)
    flow = np.zeros((batch, h, w, 2), np.float32)
    flow[..., 0] = 2.0
    return {
        "image1": base[:, 4:4 + h, 4:4 + w],
        "image2": base[:, 4:4 + h, 2:2 + w],
        "flow": flow,
        "valid": np.ones((batch, h, w), np.float32),
    }


@pytest.fixture(scope="module")
def fp32_step():
    """One compiled fp32 step and its result — the baseline several
    tests compare against (module-scoped: one compile, many asserts).
    The freshly created state is donated into the step (same as
    production), so only state1 survives."""
    batch = synthetic_batch(np.random.default_rng(0))
    step = make_train_step(SMALL, TC)
    state1, metrics = step(create_state(jax.random.key(0), SMALL, TC), batch)
    return dict(batch=batch, step=step, state1=state1, metrics=metrics)


class _TinyDS:
    """In-memory dataset with the Loader's sample(index, rng) contract."""

    def __len__(self):
        return 8

    def sample(self, index, rng):
        h, w = 16, 24
        img = rng.normal(loc=index, size=(h, w, 3)).astype(np.float32)
        return {
            "image1": img,
            "image2": img + 1.0,
            "flow": np.full((h, w, 2), float(index), np.float32),
            "valid": np.ones((h, w), np.float32),
        }


class TestDevicePrefetch:
    def test_bit_identical_to_synchronous_loader(self):
        # decode is a pure function of (seed, epoch, index), so two
        # Loader instances emit identical streams; the device-put hop
        # must not perturb a single bit
        mk = lambda: Loader(_TinyDS(), batch_size=2, seed=11, num_workers=2)
        sync = iter(mk())
        pre = prefetch_to_device(mk(), depth=2)
        try:
            for _ in range(6):  # crosses an epoch boundary (8 samples / 2)
                host, dev = next(sync), next(pre)
                assert set(host) == set(dev)
                for k in host:
                    np.testing.assert_array_equal(host[k], np.asarray(dev[k]))
        finally:
            sync.close()
            pre.close()

    def test_stall_accounting_and_exhaustion(self):
        batches = [synthetic_batch(np.random.default_rng(i), batch=1,
                                   size=(16, 16)) for i in range(5)]
        pf = DevicePrefetcher(iter(batches), depth=2)
        got = list(pf)
        assert len(got) == 5
        assert pf.stats.batches == 5
        # instant in-memory iterator: the host never starves the chips —
        # zero STALLED yields (sub-epsilon next() calls must not count)
        assert pf.stats.stalls == 0
        assert pf.stats.stall_per_batch_s < 0.05

    def test_depth_zero_is_synchronous(self):
        batches = [synthetic_batch(np.random.default_rng(i), batch=1,
                                   size=(16, 16)) for i in range(3)]
        pf = DevicePrefetcher(iter(batches), depth=0)
        assert len(list(pf)) == 3

    def test_mesh_putter_lands_step_input_sharding(self):
        mesh = make_mesh()
        pf = prefetch_to_device(
            iter([synthetic_batch(np.random.default_rng(0), batch=8)]),
            mesh, depth=1)
        dev = next(pf)
        want = batch_input_sharding(mesh)
        for k, v in dev.items():
            assert v.sharding.is_equivalent_to(want, v.ndim), k


class TestGradAccum:
    def test_matches_single_batch_step(self, fp32_step):
        tc = TrainConfig(num_steps=200, batch_size=4, iters=2,
                         image_size=(64, 64), lr=1e-4, accum_steps=2)
        state = create_state(jax.random.key(0), SMALL, tc)
        state, metrics = make_train_step(SMALL, tc)(state, fp32_step["batch"])
        # mean of per-microbatch mean grads == full-batch mean grad, so
        # one accumulated step must match the single-batch step to fp32
        # round-off (the loss is a mean over pixels either way)
        np.testing.assert_allclose(float(metrics["loss"]),
                                   float(fp32_step["metrics"]["loss"]),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(fp32_step["state1"].params),
                        jax.tree.leaves(state.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)

    def test_sharded_accum_step_runs(self):
        # the risky composition: microbatch reshape of a data-sharded
        # batch inside the GSPMD-partitioned step. 4-way mesh so each
        # microbatch (8/2 = 4) still splits evenly over the data axis
        mesh = make_mesh(jax.devices()[:4])
        tc = TrainConfig(num_steps=200, batch_size=8, iters=1,
                         image_size=(64, 64), lr=1e-4, accum_steps=2)
        state = create_state(jax.random.key(0), SMALL, tc)
        step = make_train_step(SMALL, tc, mesh=mesh)
        batch = synthetic_batch(np.random.default_rng(2), batch=8)
        pf = prefetch_to_device(iter([batch]), mesh, depth=1)
        state, metrics = step(state, next(pf))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 1

    def test_sharded_accum_microbatch_must_split_over_mesh(self):
        # batch 8, accum 2 → microbatch 4 over an 8-way data axis: every
        # scan iteration would idle half the chips; refuse loudly
        mesh = make_mesh()
        tc = TrainConfig(num_steps=200, batch_size=8, iters=1,
                         image_size=(64, 64), lr=1e-4, accum_steps=2)
        state = create_state(jax.random.key(0), SMALL, tc)
        with pytest.raises(ValueError, match="data axis"):
            make_train_step(SMALL, tc, mesh=mesh)(
                state, synthetic_batch(np.random.default_rng(2), batch=8))

    def test_indivisible_batch_raises(self):
        tc = TrainConfig(num_steps=200, batch_size=4, iters=1,
                         image_size=(64, 64), lr=1e-4, accum_steps=3)
        state = create_state(jax.random.key(0), SMALL, tc)
        with pytest.raises(ValueError, match="not divisible"):
            make_train_step(SMALL, tc)(
                state, synthetic_batch(np.random.default_rng(0)))


class TestBf16Policy:
    def test_finite_loss_fp32_masters_and_optimizer(self, fp32_step):
        tc = TrainConfig(num_steps=200, batch_size=4, iters=2,
                         image_size=(64, 64), lr=1e-4, precision="bf16")
        state = create_state(jax.random.key(0), SMALL, tc)
        state, metrics = make_train_step(SMALL, tc)(state, fp32_step["batch"])
        loss = float(metrics["loss"])
        assert np.isfinite(loss)
        # bf16 is the COMPUTE dtype only: master weights, optimizer
        # moments, and BN stats all stay fp32 in the carried state
        for tree in (state.params, state.opt_state, state.batch_stats):
            for leaf in jax.tree.leaves(tree):
                leaf = jnp.asarray(leaf)
                if jnp.issubdtype(leaf.dtype, jnp.floating):
                    assert leaf.dtype == jnp.float32
        # and the bf16 forward tracks the fp32 one closely at step 0
        np.testing.assert_allclose(loss, float(fp32_step["metrics"]["loss"]),
                                   rtol=2e-2)

    def test_bad_precision_rejected(self):
        tc = TrainConfig(precision="fp16")
        with pytest.raises(ValueError, match="precision"):
            make_train_step(SMALL, tc)


class TestDonation:
    def test_stale_state_buffer_raises_after_step(self, fp32_step):
        # donate_argnums=0 must keep holding through the policy/accum
        # refactor: the consumed state's buffers are gone after the call
        state0 = create_state(jax.random.key(1), SMALL, TC)
        leaf = jax.tree.leaves(state0.params)[0]
        state1, _ = fp32_step["step"](state0, fp32_step["batch"])
        assert leaf.is_deleted()
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(leaf)
        # the returned state is live and usable
        assert np.isfinite(float(jnp.sum(jax.tree.leaves(state1.params)[0])))
