"""Serving subsystem: throughput-mode inference engine (ISSUE 3) plus
the persistent flow service around it (ISSUE 6) — SLO-aware request
scheduling, session warm-start affinity, and the stdlib HTTP tier.

Import layering: buckets/engine/scheduler/sessions import no jax at
module level (unit-testable with a numpy stub eval_fn); server pulls
them together; serve_cli owns the jax-heavy restore/step construction.
"""

from dexiraft_tpu.serve.buckets import BucketRegistry, bucket_shape
from dexiraft_tpu.serve.engine import (InferenceEngine, Result, ServeConfig,
                                       add_engine_args)
from dexiraft_tpu.serve.scheduler import (QueueFull, Scheduler,
                                          SchedulerClosed, SchedulerStats)
from dexiraft_tpu.serve.server import FlowService
from dexiraft_tpu.serve.sessions import SessionStore

__all__ = [
    "FlowService",
    "BucketRegistry",
    "bucket_shape",
    "InferenceEngine",
    "Result",
    "ServeConfig",
    "add_engine_args",
    "QueueFull",
    "Scheduler",
    "SchedulerClosed",
    "SchedulerStats",
    "SessionStore",
]
