"""Throughput-mode inference: shape buckets, micro-batching, async
in-flight dispatch, optional data-parallel serving (ISSUE 3 tentpole)."""

from dexiraft_tpu.serve.buckets import BucketRegistry, bucket_shape
from dexiraft_tpu.serve.engine import InferenceEngine, Result, ServeConfig

__all__ = [
    "BucketRegistry",
    "bucket_shape",
    "InferenceEngine",
    "Result",
    "ServeConfig",
]
