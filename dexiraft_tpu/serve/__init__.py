"""Serving subsystem: throughput-mode inference engine (ISSUE 3), the
persistent flow service around it (ISSUE 6) — SLO-aware request
scheduling, session warm-start affinity, the stdlib HTTP tier — the
fleet router over N replicas (ISSUE 11): health-checked circuit
breakers, consistent-hash session affinity, zero-drop failover — and
the split-encoder streaming tier (ISSUE 14): per-frame encode with
cross-frame feature reuse over a device-resident, byte-budgeted
session carry (POST /v1/flow/stream).

Import layering: buckets/engine/scheduler/sessions/video import no jax
at module level (unit-testable with numpy stub fns); server pulls them
together; router imports no jax at all (pure control plane); serve_cli
owns the jax-heavy restore/step construction.
"""

from dexiraft_tpu.serve.buckets import BucketRegistry, bucket_shape
from dexiraft_tpu.serve.engine import (InferenceEngine, Result, ServeConfig,
                                       add_engine_args)
from dexiraft_tpu.serve.router import (HashRing, NoHealthyReplica,
                                       ReplicaPool, Router, RouterConfig)
from dexiraft_tpu.serve.scheduler import (QueueFull, Scheduler,
                                          SchedulerClosed, SchedulerStats)
from dexiraft_tpu.serve.server import FlowService
from dexiraft_tpu.serve.sessions import DeviceSessionStore, SessionStore
from dexiraft_tpu.serve.video import ChunkResult, VideoEngine

__all__ = [
    "FlowService",
    "HashRing",
    "NoHealthyReplica",
    "ReplicaPool",
    "Router",
    "RouterConfig",
    "BucketRegistry",
    "bucket_shape",
    "InferenceEngine",
    "Result",
    "ServeConfig",
    "add_engine_args",
    "QueueFull",
    "Scheduler",
    "SchedulerClosed",
    "SchedulerStats",
    "SessionStore",
    "DeviceSessionStore",
    "VideoEngine",
    "ChunkResult",
]
